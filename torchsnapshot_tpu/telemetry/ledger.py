"""The run ledger: crash-safe, append-only record of a training run's
checkpoint economy.

Every telemetry surface before this one is per-op — one SnapshotReport,
trace, and heartbeat per take or restore — so nobody could answer the
question a training fleet actually asks: *what fraction of this run's
wall time did checkpointing eat, what did the last preemption cost in
lost work, and what does retention cost in bytes per step?* The ledger
is the substrate for that answer: one ``<root>/.ledger.jsonl`` per
manager root, to which the manager, the snapshot take/restore
envelopes, the tiered mirror, the preemption saver, and retention GC
post small typed events (the ``EVENT_`` constants in
``telemetry/names.py`` — snaplint's ``ledger-event-ids`` rule keeps
literal event strings out of post sites). ``telemetry/goodput.py``
folds the records into a run-level attribution; ``python -m
torchsnapshot_tpu.telemetry goodput <root>`` renders it.

Properties:

- **Crash-safe**: records append as ONE short write each; a kill
  mid-append leaves at most one torn final line, which
  :func:`load_ledger` skips. Trimming (the rolling bound) rewrites
  atomically (tmp + rename), so a reader never sees a torn document.
- **Resumable**: a restarted manager resumes the previous run id and
  increments the segment counter (:func:`open_run`), so one training
  run's identity survives preemptions and restarts.
- **Rank-0-only**: only the process whose manager opened the run (rank
  0) ever appends — post sites in rank-agnostic layers (snapshot
  envelopes, the mirror) route through :func:`post_event_for_snapshot`,
  which posts only for roots *this process* opened. A 2-process job
  writes exactly one stream of records.
- **Bounded**: the newest ``TORCHSNAPSHOT_TPU_LEDGER_MAX_RECORDS``
  records are kept (default 4096); the newest run-start always
  survives a trim so the active run's attribution keeps its anchor.
- **Best-effort**: a ledger write must never fail a checkpoint;
  failures log a warning and the operation proceeds.

Knobs: ``TORCHSNAPSHOT_TPU_LEDGER`` (default on; ``0`` disables) and
``TORCHSNAPSHOT_TPU_LEDGER_MAX_RECORDS``. The test conftest pins the
ledger off so tier-1 manager dirs stay deterministic. See
docs/goodput.md for the event schema and the attribution model.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
import uuid
from typing import Any, Dict, Iterable, List, Optional, Set

from .. import knobs
from . import names

logger: logging.Logger = logging.getLogger(__name__)

LEDGER_BASENAME = ".ledger.jsonl"

# Appends are short single writes; the bound is enforced by a trim pass
# every this-many appends per path (cheap against reading the whole
# file back on every post, tight enough that the file can only overrun
# the bound by a sliver).
TRIM_CHECK_EVERY = 64

_STEP_DIR_RE = re.compile(r"^step_(\d+)$")

# Serializes appends/trims within the process (async-save commit
# threads, the mirror worker, and the training thread all post).
# Re-entrant: the trim/prune paths load the ledger while holding it.
_LOCK = threading.RLock()
# Per-ledger-path append counter since the last trim check.
_APPENDS_SINCE_TRIM: Dict[str, int] = {}
# Parsed-record cache, path -> (file size, records). This process is
# the ledger's sole writer (the owned-root gate), so the per-step
# goodput refresh must not re-read and re-parse up to max-records
# lines of JSON on every save: appends extend the cached list in
# place, rewrites (trim/prune) replace it, and an out-of-band size
# mismatch (another writer, a test wiping the file) invalidates it.
_READ_CACHE: Dict[str, tuple] = {}
# Ledger paths THIS process opened a run for (rank 0's manager):
# the rank-0-only gate every snapshot-path post site routes through.
_OWNED: Set[str] = set()


def ledger_path_for(root: str) -> Optional[str]:
    """Where a manager root's run ledger lives, or None for object-store
    roots (no local append primitive — the ledger is a local operator
    aid, not a durability artifact; tiered roots use their fast tier,
    like the step history)."""
    from .sink import local_fs_root

    local = local_fs_root(root)
    if local is None:
        return None
    return os.path.join(local, LEDGER_BASENAME)


def step_from_path(snapshot_path: str) -> Optional[int]:
    """The manager step number a snapshot path encodes (its basename is
    ``step_<n>`` under a manager root), or None for free-form paths."""
    base = os.path.basename(snapshot_path.rstrip("/"))
    m = _STEP_DIR_RE.match(base)
    return int(m.group(1)) if m else None


def _ledger_path_for_snapshot(snapshot_path: str) -> Optional[str]:
    """Resolve a snapshot path to the ledger of the manager root that
    owns it: a ``step_<n>`` dir posts to its parent's ledger; anything
    else to its own directory's (covers diagnosing a root directly)."""
    from .sink import local_fs_root

    local = local_fs_root(snapshot_path)
    if local is None:
        return None
    local = local.rstrip("/") or local
    if _STEP_DIR_RE.match(os.path.basename(local)):
        local = os.path.dirname(local)
    if not local:
        return None
    return os.path.join(local, LEDGER_BASENAME)


def find_ledger_for(path: str) -> Optional[str]:
    """Read-side resolution (doctor, fsck, CLI): the existing ledger
    file a snapshot path or manager root maps to, or None. Probes the
    path's own directory first, then the step-dir parent."""
    from .sink import local_fs_root

    local = local_fs_root(path)
    if local is None:
        if os.path.isfile(path) and path.endswith(LEDGER_BASENAME):
            return path
        return None
    own = os.path.join(local, LEDGER_BASENAME)
    if os.path.exists(own):
        return own
    resolved = _ledger_path_for_snapshot(path)
    if resolved is not None and os.path.exists(resolved):
        return resolved
    return None


# ---------------------------------------------------------------------------
# Writing
# ---------------------------------------------------------------------------


def _append(path: str, record: Dict[str, Any]) -> Optional[str]:
    line = json.dumps(record, sort_keys=True) + "\n"
    with _LOCK:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # One write of one line in append mode: a kill mid-append
        # leaves at most one torn final line (skipped on load), never
        # an unparseable file. A previous crash's torn tail has no
        # newline — heal it with a leading one so the torn fragment
        # stays its own (skipped) line instead of corrupting ours.
        needs_newline = False
        size_before = 0
        try:
            size_before = os.path.getsize(path)
            if size_before > 0:
                with open(path, "rb") as rf:
                    rf.seek(-1, os.SEEK_END)
                    needs_newline = rf.read(1) != b"\n"
        except OSError:
            pass  # fresh file
        with open(path, "a", encoding="utf-8") as f:
            f.write(("\n" if needs_newline else "") + line)
        cached = _READ_CACHE.get(path)
        if cached is not None:
            if cached[0] == size_before:
                cached[1].append(record)
                _READ_CACHE[path] = (
                    size_before
                    + len(line.encode("utf-8"))
                    + (1 if needs_newline else 0),
                    cached[1],
                )
            else:
                # The file moved under us (external rewrite): reparse
                # on the next load rather than serve stale records.
                _READ_CACHE.pop(path, None)
        n = _APPENDS_SINCE_TRIM.get(path, 0) + 1
        if n >= TRIM_CHECK_EVERY:
            _trim_locked(path, knobs.get_ledger_max_records())
            n = 0
        _APPENDS_SINCE_TRIM[path] = n
    return path


def _rewrite_locked(path: str, records: List[Dict[str, Any]]) -> None:
    """Atomic full rewrite (caller holds _LOCK), keeping the read
    cache coherent with what just landed on disk."""
    from .sink import atomic_write_text

    atomic_write_text(
        path, "".join(json.dumps(r, sort_keys=True) + "\n" for r in records)
    )
    try:
        _READ_CACHE[path] = (os.path.getsize(path), list(records))
    except OSError:
        _READ_CACHE.pop(path, None)


def _trim_locked(path: str, max_records: int) -> None:
    """Enforce the rolling bound (caller holds _LOCK): keep the newest
    ``max_records``, re-anchoring the newest run-start at the front if
    the cut would drop it — goodput attribution needs the active
    segment's start to exist."""
    records = load_ledger(path)
    if len(records) <= max_records:
        return
    kept = records[-max_records:]
    if not any(r.get("event") == names.EVENT_RUN_START for r in kept):
        starts = [
            r
            for r in records[: -max_records or None]
            if r.get("event") == names.EVENT_RUN_START
        ]
        if starts:
            kept = [starts[-1], *kept[1:]]
    _rewrite_locked(path, kept)


def post_event(
    root: str, event: str, create: bool = False, **fields: Any
) -> Optional[str]:
    """Append one typed event to ``root``'s ledger; returns the ledger
    path, or None when disabled / non-local / (without ``create``) no
    ledger exists yet. ``event`` must be a ``names.EVENT_*`` constant
    (lint-enforced). ``unix_ts`` is stamped unless the caller provides
    one (injection tests, backfills). Best-effort: never raises."""
    if not knobs.is_ledger_enabled():
        return None
    path = ledger_path_for(root)
    if path is None:
        return None
    if not create and not os.path.exists(path):
        # Only roots a manager opened a run for carry a ledger; posting
        # elsewhere would scatter orphan files next to ad-hoc snapshots.
        return None
    record = {"event": event, "unix_ts": round(time.time(), 6), **fields}
    try:
        return _append(path, record)
    except Exception as e:  # noqa: BLE001 - the ledger must never fail an op
        logger.warning("ledger: could not append %r to %r: %r", event, path, e)
        return None


def post_event_for_snapshot(
    snapshot_path: str, event: str, **fields: Any
) -> Optional[str]:
    """Post an event about a snapshot path to its manager root's ledger
    — ONLY when this process opened the run (the rank-0-only gate for
    rank-agnostic layers: snapshot envelopes, the mirror). The step
    number is derived from the path and stamped unless provided."""
    if not knobs.is_ledger_enabled():
        return None
    path = _ledger_path_for_snapshot(snapshot_path)
    if path is None or os.path.abspath(path) not in _OWNED:
        return None
    step = step_from_path(snapshot_path)
    if step is not None:
        fields.setdefault("step", step)
    record = {"event": event, "unix_ts": round(time.time(), 6), **fields}
    try:
        return _append(path, record)
    except Exception as e:  # noqa: BLE001
        logger.warning("ledger: could not append %r to %r: %r", event, path, e)
        return None


def open_run(root: str, world_size: int = 1) -> Optional[str]:
    """Open (or resume) a run at ``root``: reuse the newest recorded
    run id with an incremented segment counter, or mint a fresh id for
    a first-ever run; post the run-start event and register this
    process as the root's ledger owner (subsequent snapshot-path posts
    from this process land; other ranks' never do). Rank-0 callers
    only — the manager gates. Returns the run id, or None when the
    ledger is disabled / the root is non-local. Best-effort."""
    if not knobs.is_ledger_enabled():
        return None
    path = ledger_path_for(root)
    if path is None:
        return None
    try:
        run_id: Optional[str] = None
        segment = 1
        for rec in load_ledger(path):
            if rec.get("event") == names.EVENT_RUN_START:
                run_id = rec.get("run_id")
                segment = int(rec.get("segment", 0)) + 1
        if run_id is None:
            run_id = uuid.uuid4().hex[:12]
            segment = 1
        post_event(
            root,
            names.EVENT_RUN_START,
            create=True,
            run_id=run_id,
            segment=segment,
            world_size=world_size,
        )
        _OWNED.add(os.path.abspath(path))
        return run_id
    except Exception as e:  # noqa: BLE001
        logger.warning("ledger: could not open run at %r: %r", root, e)
        return None


def reset_owned_roots() -> None:
    """Drop ownership registrations and the read cache (tests
    simulating a fresh process)."""
    with _LOCK:
        _OWNED.clear()
        _READ_CACHE.clear()


def owned_roots() -> List[str]:
    """Ledger paths this process opened runs for (abspaths). Rootless
    layers needing a capture target — the stall watchdog's incident
    bundle — resolve one here: owning the ledger is what makes this
    process the root's rank 0."""
    with _LOCK:
        return sorted(_OWNED)


def prune_steps(root: str, steps: Iterable[int]) -> Optional[str]:
    """Drop deleted steps' ``step-committed`` storage records (atomic
    rewrite) so the ledger's storage-cost view tracks what retention
    actually keeps. Time-attribution events (visible-stall, restores,
    drains) survive — that wall time was spent regardless of whether
    the bytes still exist. Called by the manager's GC; best-effort."""
    if not knobs.is_ledger_enabled():
        return None
    path = ledger_path_for(root)
    if path is None or not os.path.exists(path):
        return None
    dropped = {int(s) for s in steps}
    try:
        with _LOCK:
            records = load_ledger(path)
            kept = [
                r
                for r in records
                if not (
                    r.get("event") == names.EVENT_STEP_COMMITTED
                    and r.get("step") in dropped
                )
            ]
            if len(kept) == len(records):
                return path
            _rewrite_locked(path, kept)
        return path
    except Exception as e:  # noqa: BLE001 - GC must not fail a save
        logger.warning("ledger: could not prune steps at %r: %r", path, e)
        return None


# ---------------------------------------------------------------------------
# Typed post helpers (the event-shaping lives here, not at call sites)
# ---------------------------------------------------------------------------


def post_op_event(
    kind: str,
    path: str,
    report: Any,
    world_tier_split: Optional[Dict[str, int]] = None,
) -> None:
    """Ledger events for one completed snapshot operation, shaped from
    its SnapshotReport: takes post their training-visible stall (the
    whole wall for sync takes, return-to-caller for async ones) plus
    the overlapped background drain; restores post the recovery time
    served — with a ``tier`` field naming which tier of the peer RAM ->
    fast -> durable ladder dominated, and the full ``tier_split`` byte
    map when the restore ran the ladder (``world_tier_split``, summed
    across ranks by the report gather, wins over the rank-local split).
    Routed through the owned-root gate (rank 0 only)."""
    phases = report.phases or {}
    wall = max((float(v) for v in phases.values()), default=0.0)
    if kind in ("take", "async_take"):
        visible = (
            float(report.visible_s)
            if report.visible_s is not None
            else wall
        )
        post_event_for_snapshot(
            path,
            names.EVENT_VISIBLE_STALL,
            kind=kind,
            visible_s=round(visible, 6),
            wall_s=round(wall, 6),
            nbytes=int(report.bytes_moved),
        )
        if kind == "async_take" and report.staged_s is not None:
            staged = float(report.staged_s)
            post_event_for_snapshot(
                path,
                names.EVENT_STAGED_DRAIN,
                staged_s=round(staged, 6),
                drain_s=round(max(0.0, staged - visible), 6),
                nbytes=int(report.bytes_moved),
            )
    elif kind in ("restore", "async_restore"):
        fields: Dict[str, Any] = {
            "kind": kind,
            "restore_s": round(wall, 6),
            "nbytes": int(report.bytes_moved),
        }
        tier_split = world_tier_split or getattr(
            report, "tier_split", None
        )
        if tier_split:
            fields["tier_split"] = {
                k: int(v) for k, v in tier_split.items()
            }
            fields["tier"] = max(tier_split, key=lambda t: tier_split[t])
        peer = getattr(report, "peer", None) or {}
        if peer:
            fields["peer_failures"] = int(peer.get("failures", 0))
        post_event_for_snapshot(
            path, names.EVENT_RESTORE_SERVED, **fields
        )


def post_mirror_settled(
    fast_url: str,
    lag_s: float,
    nbytes: int,
    blobs: int,
    error: Optional[BaseException] = None,
) -> None:
    """One tiered mirror job settled: durability lag and bytes moved,
    posted to the manager root that owns the fast step dir (owned-root
    gate — co-hosted non-leader ranks' mirrors never post)."""
    post_event_for_snapshot(
        fast_url,
        names.EVENT_MIRROR_SETTLED,
        lag_s=round(float(lag_s), 3),
        nbytes=int(nbytes),
        blobs=int(blobs),
        error=repr(error) if error is not None else None,
    )


# ---------------------------------------------------------------------------
# Reading
# ---------------------------------------------------------------------------


def load_ledger(path: str) -> List[Dict[str, Any]]:
    """Parse a ledger file, oldest first; [] when absent. A torn final
    line (kill mid-append) or corrupt line is skipped. Served from the
    in-process cache when this process's own appends are the only
    thing that changed the file (size-validated), so the per-step
    goodput refresh costs a list copy, not a reparse."""
    if not os.path.exists(path):
        return []
    try:
        size = os.path.getsize(path)
    except OSError:
        size = -1
    with _LOCK:
        cached = _READ_CACHE.get(path)
        if cached is not None and size >= 0 and cached[0] == size:
            return list(cached[1])
    records: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                logger.warning("ledger: skipping corrupt record line")
                continue
            if isinstance(rec, dict):
                records.append(rec)
    if size >= 0:
        with _LOCK:
            # Only when the file still matches what we parsed — a
            # concurrent append invalidates rather than caches a
            # half-view.
            try:
                if os.path.getsize(path) == size:
                    _READ_CACHE[path] = (size, list(records))
            except OSError:
                pass
    return records


def describe(records: List[Dict[str, Any]]) -> List[str]:
    """Human-readable summary lines for ``fsck --stats``: event counts,
    run/segment structure with spans, and interrupted (unclosed)
    segments — a run whose segment was followed by another run-start,
    or whose trail ends at a preemption notice, never settled cleanly."""
    if not records:
        return ["empty ledger"]
    counts: Dict[str, int] = {}
    for r in records:
        counts[str(r.get("event", "?"))] = (
            counts.get(str(r.get("event", "?")), 0) + 1
        )
    lines = [
        f"{len(records)} event(s): "
        + ", ".join(f"{k}={counts[k]}" for k in sorted(counts))
    ]
    from .goodput import analyze

    analysis = analyze(records)
    for run in analysis["runs"]:
        interrupted = [s for s in run["segments"] if s["interrupted"]]
        lines.append(
            f"run {run['run_id']}: {len(run['segments'])} segment(s), "
            f"span {run['wall_s']:.1f}s, "
            f"{run['steps_committed']} step(s) committed, "
            f"{len(interrupted)} interrupted"
        )
        for seg in interrupted:
            what = (
                f"preempted at step {seg['preemption_step']}"
                if seg.get("preemption_step") is not None
                else "ended without settling (crash or kill)"
            )
            lines.append(
                f"  segment {seg['segment']}: {what}; "
                f"{seg['lost_work_s']:.1f}s of work after the last "
                f"committed step was lost"
            )
    last = analysis["runs"][-1] if analysis["runs"] else None
    if last is not None and last["segments"]:
        tail = last["segments"][-1]
        if not tail["interrupted"]:
            lines.append(
                f"last segment open or clean (segment "
                f"{tail['segment']}, last event "
                f"{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(tail['end_ts']))})"
            )
    return lines
