"""Rolling per-manager step-telemetry history + trend regression detection.

BENCH_r05's 120s -> 71s take-time swing was only diagnosable because a
human happened to be comparing two BENCH records by hand. This module
makes the comparison structural: every committed manager step — and
every manager-served restore, so recovery time trends too — appends a
compact summary of its SnapshotReport to
``<root>/.telemetry-history.jsonl`` (rank 0, local roots; a tiered root
uses its fast tier), bounded to the newest
``TORCHSNAPSHOT_TPU_HISTORY_MAX_RECORDS`` records (default 512; <= 0
disables recording). ``doctor --trend`` / ``snapshot_stats trend``
then flag steps whose take time, per-phase time, throughput, or budget
wait sit outside a rolling median ± MAD baseline of the preceding
steps — the "this step regressed against the last N" check no longer
requires eyeballing Perfetto.

Summary schema (one JSON object per line)::

    {step, kind, path, unix_ts, take_s, phases: {...}, bytes_moved,
     blobs, mb_s, budget_wait_s, peak_staged_bytes, error}

``take_s`` is the pipeline's wall clock (the max phase-completion
offset — the legacy ``last_phase_timings`` semantics).
"""

from __future__ import annotations

import json
import logging
import os
import statistics
import threading
from typing import Any, Dict, List, Optional

from .. import knobs
from .report import SnapshotReport

logger: logging.Logger = logging.getLogger(__name__)

HISTORY_BASENAME = ".telemetry-history.jsonl"

# Serializes the read-trim-rewrite append cycle: two overlapping
# async-save commit threads appending concurrently must not lose a
# record (or tear the shared pid-suffixed tmp file).
_APPEND_LOCK = threading.Lock()

# Trend thresholds (documented in docs/observability.md): a value
# regresses when its deviation exceeds max(MAD_K * MAD, MIN_REL *
# median, the metric's absolute noise floor) — the MAD term adapts to
# noisy histories, the relative floor keeps a perfectly-flat history
# (MAD 0) from flagging, and the absolute floor keeps millisecond-scale
# checkpoints (where 3-decimal rounding alone doubles a value) from
# producing false verdicts.
TREND_WINDOW = 8
TREND_MAD_K = 4.0
TREND_MIN_REL = 0.3
# Absolute noise floors: time-like metrics below this deviation carry
# no operational signal (the phase offsets themselves round to 1 ms);
# throughput is a secondary signal (every real throughput regression
# shows up in take_s too), so its floor is set high enough that the
# garbage rates of sub-10 ms pipelines never flag.
TREND_MIN_ABS_S = 0.05
TREND_MIN_ABS_MB_S = 5.0
# Fewer prior records than this and the baseline carries no signal.
TREND_MIN_BASELINE = 2


def history_path_for(root: str) -> Optional[str]:
    """Where a manager root's history lives, or None for object-store
    roots (no local append primitive; history is a local operator aid,
    not a durability artifact)."""
    from .sink import local_fs_root

    local = local_fs_root(root)
    if local is None:
        return None
    return os.path.join(local, HISTORY_BASENAME)


def summarize_report(
    report: SnapshotReport, step: Optional[int] = None
) -> Dict[str, Any]:
    """One step's compact history record from its SnapshotReport."""
    phases = dict(report.phases)
    take_s = max(phases.values(), default=0.0)
    from . import safe_rate_mb_s

    return {
        "step": step,
        "kind": report.kind,
        "path": report.path,
        "unix_ts": round(report.unix_ts, 3),
        "take_s": round(take_s, 3),
        "phases": phases,
        "bytes_moved": report.bytes_moved,
        "blobs": report.blobs,
        "mb_s": round(safe_rate_mb_s(report.bytes_moved, take_s), 3),
        "budget_wait_s": round(report.budget_wait_s, 6),
        "peak_staged_bytes": report.peak_staged_bytes,
        # Async takes: the training-visible span — None elsewhere.
        # Rides into doctor --trend so a step whose visible time creeps
        # up (a deferral regression) flags like any other metric.
        "visible_s": (
            round(report.visible_s, 6) if report.visible_s is not None else None
        ),
        # Cross-rank coordination cost (None for single-process ops):
        # barrier waits plus max(store wire time, exchange wall) — the
        # exchange's own store round trips live inside exchange_s, so
        # summing both would double-charge them; same formula as the
        # doctor's coordination-bound rule, whose trend companion this
        # series is (a step whose coordination time creeps up — world
        # grew, store degraded — flags like any other metric).
        "coordination_s": (
            round(
                float(report.coordination.get("barrier_wait_s", 0.0))
                + max(
                    float(report.coordination.get("store_s", 0.0)),
                    float(report.coordination.get("exchange_s", 0.0)),
                ),
                6,
            )
            if report.coordination is not None
            else None
        ),
        # Wall the op spent on actual sockets (None for ops that put
        # nothing on the wire — all-zero baselines never flag): dial
        # time plus request/reply round-trip time from the report's
        # wire split. The trend companion of the wire-dial-stalled /
        # wire-hot-endpoint fleet rules — a step whose socket time
        # creeps up (backlog stall, hot owner) flags here first.
        "wire_s": (
            round(
                float(report.wire.get("dial_s", 0.0))
                + float(report.wire.get("rpc_s", 0.0)),
                6,
            )
            if report.wire is not None
            else None
        ),
        # Which write-path variant served the take's bytes (vectorized /
        # direct / fused / buffered): alongside ``tunables``, what lets
        # doctor --trend correlate a write-path knob flip with the
        # efficiency move it caused.
        "write_path": (
            dict(report.write_path) if report.write_path is not None else None
        ),
        # The effective tunable-knob values the take ran under: lets a
        # trend regression be correlated with the knob change that
        # caused it (the autotuner's decision log cross-references the
        # same keys).
        "tunables": (
            dict(report.tunables) if report.tunables is not None else None
        ),
        # Blocking-chain attribution (telemetry/critpath.py; None for
        # pre-critpath reports / overrun trace windows): the dominant
        # path segment, attribution coverage, and per-segment gated
        # seconds. Feeds one ``critpath_<segment>_s`` trend series per
        # segment plus the doctor's critical-path-shifted rule — a step
        # whose bottleneck MOVED flags even when the wall barely did.
        "critpath": (
            {
                "dominant": report.critical_path.get("dominant"),
                "coverage": report.critical_path.get("coverage"),
                "segments": {
                    k: round(float(v), 6)
                    for k, v in (
                        report.critical_path.get("segments") or {}
                    ).items()
                },
            }
            if report.critical_path
            else None
        ),
        "error": report.error,
    }


def append_summary(root: str, summary: Dict[str, Any]) -> Optional[str]:
    """Append one record, enforcing the rolling bound (atomic rewrite
    when trimming). Returns the history path, or None when disabled /
    non-local. Best-effort: history must never fail a save."""
    max_records = knobs.get_history_max_records()
    if max_records <= 0:
        return None
    path = history_path_for(root)
    if path is None:
        return None
    try:
        from .sink import atomic_write_text

        with _APPEND_LOCK:
            records = load_history(path)
            records.append(summary)
            if len(records) > max_records:
                records = records[-max_records:]
            # Atomic rewrite: the bound trims old records, and a
            # concurrent trend reader must never see a torn file.
            atomic_write_text(
                path,
                "".join(
                    json.dumps(rec, sort_keys=True) + "\n" for rec in records
                ),
            )
        return path
    except Exception as e:  # noqa: BLE001 - history must never fail a save
        logger.warning("history: could not append to %r: %r", path, e)
        return None


def load_history(path: str) -> List[Dict[str, Any]]:
    """Parse a history file, oldest first; [] when absent. Torn/corrupt
    lines are skipped (a crash mid-rewrite leaves at most one)."""
    if not os.path.exists(path):
        return []
    records: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                logger.warning("history: skipping corrupt record line")
    return records


# ---------------------------------------------------------------------------
# Trend regression detection
# ---------------------------------------------------------------------------

# metric key -> (label, direction): +1 flags increases (times, waits),
# -1 flags decreases (throughput).
_TREND_METRICS = {
    "take_s": ("take wall clock", 1),
    "budget_wait_s": ("memory-budget wait", 1),
    "mb_s": ("throughput", -1),
    # Async takes' training-visible span (None/0 for sync takes —
    # all-zero baselines never flag): a step whose visible time creeps
    # up is a deferral regression, the same defect the doctor's
    # async-visible-stall rule catches per-op.
    "visible_s": ("async visible span", 1),
    # Coordination wall (barrier + store + exchange; None/0 for
    # single-process ops — all-zero baselines never flag): the trend
    # companion of the per-op coordination-bound rule.
    "coordination_s": ("coordination time", 1),
    # Socket wall (dial + RPC round trips; None/0 for wire-less ops):
    # the trend companion of the wire-dial-stalled fleet rule.
    "wire_s": ("wire time", 1),
}


def _metric_series(records: List[Dict[str, Any]]) -> Dict[str, List[float]]:
    """Aligned per-metric value series (take/budget/throughput plus one
    series per phase seen anywhere in the history; records missing a
    phase contribute 0.0 — a phase that appears is itself signal)."""
    series: Dict[str, List[float]] = {k: [] for k in _TREND_METRICS}
    phase_names = sorted(
        {p for r in records for p in (r.get("phases") or {})}
    )
    for p in phase_names:
        series[f"phase_{p}_s"] = []
    # Critical-path segments follow the phases' dynamic pattern: one
    # series per segment seen anywhere in the history (records missing
    # it contribute 0.0 — a segment that appears is itself signal).
    seg_names = sorted(
        {
            s
            for r in records
            for s in ((r.get("critpath") or {}).get("segments") or {})
        }
    )
    for s in seg_names:
        series[f"critpath_{s}_s"] = []
    for r in records:
        for k in _TREND_METRICS:
            series[k].append(float(r.get(k) or 0.0))
        phases = r.get("phases") or {}
        for p in phase_names:
            series[f"phase_{p}_s"].append(float(phases.get(p, 0.0)))
        segments = (r.get("critpath") or {}).get("segments") or {}
        for s in seg_names:
            series[f"critpath_{s}_s"].append(float(segments.get(s, 0.0)))
    return series


def _direction(metric: str) -> int:
    if metric in _TREND_METRICS:
        return _TREND_METRICS[metric][1]
    return 1  # phase durations: increases regress


def _abs_floor(metric: str) -> float:
    return TREND_MIN_ABS_MB_S if metric == "mb_s" else TREND_MIN_ABS_S


def detect_trend_regressions(
    records: List[Dict[str, Any]],
    window: int = TREND_WINDOW,
    mad_k: float = TREND_MAD_K,
    min_rel: float = TREND_MIN_REL,
) -> List[Dict[str, Any]]:
    """Regression evidence rows over a history (oldest first): each row
    names the record (step/path/kind), the metric, its value, and the
    rolling baseline (median, MAD over the preceding ``window`` records
    *of the same kind*) it breached. Throughput regresses downward;
    times upward. Kinds are separate populations: now that restores
    append history rows too (recovery-time trends), a restore's wall
    must neither pollute the take baseline nor be judged against it."""
    out: List[Dict[str, Any]] = []
    by_kind: Dict[str, List[int]] = {}
    for i, rec in enumerate(records):
        by_kind.setdefault(str(rec.get("kind") or "take"), []).append(i)
    for kind in sorted(by_kind):
        indices = by_kind[kind]
        if len(indices) <= TREND_MIN_BASELINE:
            continue
        group = [records[i] for i in indices]
        series = _metric_series(group)
        for metric, values in series.items():
            sign = _direction(metric)
            for i in range(TREND_MIN_BASELINE, len(values)):
                baseline = values[max(0, i - window) : i]
                if len(baseline) < TREND_MIN_BASELINE:
                    continue
                med = statistics.median(baseline)
                mad = statistics.median(abs(v - med) for v in baseline)
                threshold = max(
                    mad_k * mad, min_rel * abs(med), _abs_floor(metric)
                )
                deviation = sign * (values[i] - med)
                if deviation > threshold:
                    rec = group[i]
                    out.append(
                        {
                            "index": indices[i],
                            "step": rec.get("step"),
                            "kind": kind,
                            "path": rec.get("path"),
                            "metric": metric,
                            "value": round(values[i], 3),
                            "baseline_median": round(med, 3),
                            "baseline_mad": round(mad, 3),
                            "threshold": round(threshold, 3),
                            "window": len(baseline),
                        }
                    )
    out.sort(key=lambda row: (row["index"], row["metric"]))
    return out
