"""Canonical metric names — the single registration point.

Every metric the package emits is declared here exactly once, as a
snake_case constant, and call sites reference the constant (never a
string literal). ``tools/check_metric_names.py`` enforces both halves
statically: a literal metric name at a call site, a non-snake_case
value, or a duplicate declaration fails the lane. This is what keeps
the exposition namespace stable enough for dashboards to key off.

Label conventions (labels are free-form at call sites, but keep them
small and low-cardinality):

- ``phase``:  staging | writing | loading | mirroring
- ``plugin``: fs | s3 | gcs | memory | tiered
- ``scope``:  which retry strategy instance (s3 | gcs | mirror)
- ``kind``:   take | async_take | restore | async_restore | mirror
"""

# -- pipeline (scheduler.py) -------------------------------------------------

SNAPSHOT_PHASE_SECONDS = "snapshot_phase_seconds"
MEMORY_BUDGET_WAIT_SECONDS = "memory_budget_wait_seconds"
MEMORY_BUDGET_PEAK_STAGED_BYTES = "memory_budget_peak_staged_bytes"

# -- storage plugins (storage_plugins/{fs,s3,gcs}.py) ------------------------

STORAGE_WRITE_BYTES_TOTAL = "storage_write_bytes_total"
STORAGE_WRITE_OPS_TOTAL = "storage_write_ops_total"
STORAGE_WRITE_SECONDS = "storage_write_seconds"
STORAGE_READ_BYTES_TOTAL = "storage_read_bytes_total"
STORAGE_READ_OPS_TOTAL = "storage_read_ops_total"
STORAGE_READ_SECONDS = "storage_read_seconds"
# Zero-pack / direct write-path accounting (storage_plugins/fs.py):
# bytes that went out through the vectorized pwritev kernel (each one a
# byte the slab-pack pass did NOT copy) and through O_DIRECT.
FS_VECTORIZED_WRITE_BYTES_TOTAL = "fs_vectorized_write_bytes_total"
FS_DIRECT_WRITE_BYTES_TOTAL = "fs_direct_write_bytes_total"
# batcher.py: slab bytes staged zero-pack — the pack pass they avoided.
BATCHER_PACK_BYTES_AVOIDED_TOTAL = "batcher_pack_bytes_avoided_total"

# -- retry machinery (storage_plugins/retry.py, gcs.py) ----------------------

STORAGE_RETRY_ATTEMPTS_TOTAL = "storage_retry_attempts_total"
STORAGE_RETRY_BACKOFF_SECONDS_TOTAL = "storage_retry_backoff_seconds_total"
STORAGE_RETRIES_EXHAUSTED_TOTAL = "storage_retries_exhausted_total"
GCS_RECOVER_ATTEMPTS_TOTAL = "gcs_recover_attempts_total"

# -- tiered mirror (tiered/mirror.py) ----------------------------------------

MIRROR_BLOBS_PENDING = "mirror_blobs_pending"
MIRROR_BLOBS_INFLIGHT = "mirror_blobs_inflight"
MIRROR_BLOBS_DONE_TOTAL = "mirror_blobs_done_total"
MIRROR_BYTES_TOTAL = "mirror_bytes_total"
MIRROR_SNAPSHOTS_PENDING = "mirror_snapshots_pending"
MIRROR_JOBS_DONE_TOTAL = "mirror_jobs_done_total"
MIRROR_JOBS_FAILED_TOTAL = "mirror_jobs_failed_total"
MIRROR_RESUME_TOTAL = "mirror_resume_total"
MIRROR_UPLOAD_LAG_SECONDS = "mirror_upload_lag_seconds"

# -- peer tier (tiered/peer.py) ----------------------------------------------

PEER_PUSH_BLOBS_TOTAL = "peer_push_blobs_total"
PEER_PUSH_BYTES_TOTAL = "peer_push_bytes_total"
PEER_PUSH_FAILURES_TOTAL = "peer_push_failures_total"
PEER_PULL_HITS_TOTAL = "peer_pull_hits_total"
PEER_PULL_MISSES_TOTAL = "peer_pull_misses_total"
PEER_PULL_BYTES_TOTAL = "peer_pull_bytes_total"
PEER_CACHE_BYTES = "peer_cache_bytes"
PEER_CACHE_STEPS = "peer_cache_steps"
PEER_TIER_DEGRADED_STATE = "peer_tier_degraded"

# -- content-addressed chunk store (cas/) ------------------------------------
#
# Write-side dedup accounting: chunks newly materialized into the store
# vs. writes satisfied by an existing chunk (the bytes a dense-retention
# run did NOT spend), plus the mirror's chunk-level shipping skips and
# the peer tier's inventory-by-digest dedup.

CAS_CHUNKS_WRITTEN_TOTAL = "cas_chunks_written_total"
CAS_BYTES_WRITTEN_TOTAL = "cas_bytes_written_total"
CAS_CHUNKS_DEDUPED_TOTAL = "cas_chunks_deduped_total"
CAS_BYTES_DEDUPED_TOTAL = "cas_bytes_deduped_total"
CAS_CHUNKS_RECLAIMED_TOTAL = "cas_chunks_reclaimed_total"
CAS_BYTES_RECLAIMED_TOTAL = "cas_bytes_reclaimed_total"
MIRROR_CHUNKS_SKIPPED_TOTAL = "mirror_chunks_skipped_total"
PEER_PUSH_CHUNKS_DEDUPED_TOTAL = "peer_push_chunks_deduped_total"
PEER_PUSH_BYTES_DEDUPED_TOTAL = "peer_push_bytes_deduped_total"

# -- coordination (dist_store.py, fanout.py, tiered/peer.py) -----------------
#
# What cross-rank coordination costs, attributed per structure: store
# wire round trips (requests + wall seconds, labeled by op), barrier
# arrive/depart wait time (labeled by phase and impl=tree|linear), the
# fan-out owner-table exchange, and endpoint-registry resolution. The
# per-op deltas land in SnapshotReport.coordination; the
# ``coordination-bound`` doctor rule and the scale-model harness
# (torchsnapshot_tpu/scalemodel) read them against wall time.

COORD_STORE_REQUESTS_TOTAL = "coordination_store_requests_total"
COORD_STORE_SECONDS_TOTAL = "coordination_store_seconds_total"
COORD_BARRIER_WAIT_SECONDS_TOTAL = "coordination_barrier_wait_seconds_total"
COORD_EXCHANGE_SECONDS_TOTAL = "coordination_exchange_seconds_total"
COORD_ENDPOINT_SECONDS_TOTAL = "coordination_endpoint_seconds_total"
# ShardedStore request routing, labeled shard=<index>: the skew input
# the ``store-hot-shard`` doctor rule reads (one shard absorbing a
# disproportionate request share means the crc32 route degenerated for
# this key population).
COORD_STORE_SHARD_REQUESTS_TOTAL = "coordination_store_shard_requests_total"

# -- wire observatory (telemetry/wire.py; dist_store.py, tiered/peer.py) -----
#
# The socket-level view of every byte the coordination store, peer
# tier, and CDN move (docs/observability.md "Wire observatory"). Frames
# and bytes are counted at the shared framing layer itself
# (``send_frame``/``recv_frame``), labeled ``endpoint`` (store | peer)
# and ``dir`` (send | recv); dials, per-RPC latency, pool checkouts and
# accept-queue depth at the client/server seams. The ``*_TOTAL``
# counters feed the per-op ``wire`` split in SnapshotReport; the
# histograms feed the fleet plane and the ``wire-dial-stalled`` /
# ``wire-hot-endpoint`` doctor rules.

WIRE_FRAMES_TOTAL = "wire_frames_total"
WIRE_BYTES_TOTAL = "wire_bytes_total"
WIRE_INFLIGHT_FRAMES = "wire_inflight_frames"
WIRE_DIALS_TOTAL = "wire_dials_total"
WIRE_DIAL_SECONDS_TOTAL = "wire_dial_seconds_total"
WIRE_DIAL_SECONDS = "wire_dial_seconds"
WIRE_RPCS_TOTAL = "wire_rpcs_total"
WIRE_RPC_SECONDS_TOTAL = "wire_rpc_seconds_total"
WIRE_RPC_SECONDS = "wire_rpc_seconds"
WIRE_POOL_CHECKOUTS_TOTAL = "wire_pool_checkouts_total"
WIRE_ACCEPT_QUEUE_DEPTH = "wire_accept_queue_depth"
# Frames whose propagation header failed its integrity check (chaos
# corruption, protocol skew): the transfer proceeded context-free.
WIRE_CONTEXT_DEGRADED_TOTAL = "wire_context_degraded_total"

# -- self-healing reads (scheduler.py) ---------------------------------------
#
# A restore read whose bytes failed checksum verification was re-read
# from an alternate tier (the corruption ladder, docs/chaos.md): how
# many blobs were rerouted and how many bytes the reroutes served,
# labeled by the tier that finally vouched for the bytes. The
# ``storage-corruption`` doctor rule cites these.

STORAGE_DEGRADED_READS_TOTAL = "storage_degraded_reads_total"
STORAGE_DEGRADED_READ_BYTES_TOTAL = "storage_degraded_read_bytes_total"

# -- manager (manager.py) ----------------------------------------------------

MANAGER_SAVES_TOTAL = "manager_saves_total"
MANAGER_RESTORES_TOTAL = "manager_restores_total"
MANAGER_GC_STEPS_TOTAL = "manager_gc_steps_total"
MANAGER_RETAINED_STEPS = "manager_retained_steps"

# -- reports / sinks (telemetry/sink.py) -------------------------------------

SNAPSHOT_REPORTS_TOTAL = "snapshot_reports_total"

# -- utilities (utils/rss_profiler.py) ---------------------------------------

RSS_PEAK_DELTA_BYTES = "rss_peak_delta_bytes"

# -- stall watchdog (telemetry/watchdog.py) ----------------------------------

WATCHDOG_STALLS_TOTAL = "watchdog_stalls_total"

# -- checkpoint CDN (cdn/) ---------------------------------------------------
#
# Pub/sub weight streaming from a training job to a serving fleet
# (docs/cdn.md): the publisher's announce accounting, each subscriber's
# chunk-sync byte split by serving tier (durable storage read vs.
# peer-to-peer pull vs. already-held), and the staleness/swap timings
# the ``cdn-staleness-high`` doctor rule reads.

CDN_PUBLISHES_TOTAL = "cdn_publishes_total"
CDN_ANNOUNCE_BYTES_TOTAL = "cdn_announce_bytes_total"
CDN_UPDATES_APPLIED_TOTAL = "cdn_updates_applied_total"
CDN_PULL_BYTES_TOTAL = "cdn_pull_bytes_total"
CDN_CHUNKS_HELD_TOTAL = "cdn_chunks_held_total"
CDN_STALENESS_SECONDS = "cdn_staleness_seconds"
CDN_SWAP_SECONDS = "cdn_swap_seconds"

# -- run-level goodput (telemetry/goodput.py) --------------------------------
#
# Gauges refreshed from the run ledger after every committed manager
# step (and by the ``goodput`` CLI): the run-so-far attribution of wall
# time into train vs. checkpoint-overhead buckets, plus the storage
# spend per retained step. See docs/goodput.md.

GOODPUT_OVERHEAD_FRACTION = "goodput_overhead_fraction"
GOODPUT_TRAIN_SECONDS = "goodput_train_seconds"
GOODPUT_VISIBLE_STALL_SECONDS = "goodput_visible_stall_seconds"
GOODPUT_RECOVERY_SECONDS = "goodput_recovery_seconds"
GOODPUT_LOST_WORK_SECONDS = "goodput_lost_work_seconds"
GOODPUT_LOST_STEPS = "goodput_lost_steps"
GOODPUT_STORAGE_BYTES_PER_STEP = "goodput_storage_bytes_per_step"
GOODPUT_INCREMENTAL_REUSE_RATIO = "goodput_incremental_reuse_ratio"

# -- SLO engine & incident bundles (telemetry/slo.py, telemetry/bundle.py) ---
#
# Per-objective burn-rate gauges refreshed by the rank-0 per-step SLO
# evaluation (labelled ``objective=<SLO_* id>``), the breach counter the
# edge-triggered ledger posting bumps, and the black-box capture
# counter. See docs/observability.md "SLOs & incident bundles".

OBJECTIVE_BURN_RATE = "slo_burn_rate"
OBJECTIVE_BREACHES_TOTAL = "slo_breaches_total"
BUNDLE_CAPTURES_TOTAL = "bundle_captures_total"

# ---------------------------------------------------------------------------
# Flight-recorder span/instant names (telemetry/trace.py).
#
# Same single-registration rule as the metrics above, with a colon-case
# convention (``layer:operation``) so a Perfetto timeline groups by
# layer. ``SPAN_``-prefixed constants name begin/end spans,
# ``INSTANT_``-prefixed ones point-in-time events.
# ``tools/check_span_names.py`` lints both halves: declared exactly once
# here, colon/snake-case values, no string literals at
# ``trace_annotation``/``span``/``instant`` call sites.
# ---------------------------------------------------------------------------

# snapshot.py operation envelopes
SPAN_TAKE = "snapshot:take"
SPAN_RESTORE = "snapshot:restore"
SPAN_ASYNC_TAKE_STAGE = "snapshot:async_take:stage"
SPAN_ASYNC_TAKE_COMMIT = "snapshot:async_take:commit"
SPAN_ASYNC_RESTORE_READS = "snapshot:async_restore:reads"

# scheduler.py pipeline stages
SPAN_PIPELINE_BUDGET_ACQUIRE = "pipeline:budget_acquire"
SPAN_PIPELINE_STAGE = "pipeline:stage"
SPAN_PIPELINE_WRITE_DRAIN = "pipeline:write_drain"
SPAN_PIPELINE_CONSUME = "pipeline:consume"

# io_preparer / sharded_io_preparer per-leaf executor kernels (the
# D2H+serialize and deserialize+copy inside the pipeline spans above)
SPAN_LEAF_STAGE = "stage:leaf"
SPAN_LEAF_CONSUME = "consume:leaf"
# Device-snapshot async takes: the pre-return capture pass (on-device
# clone dispatch + mutable-host-leaf copies) — the only staging-flavored
# work left inside async_take's training-visible span.
SPAN_DEVICE_CAPTURE = "stage:device_capture"

# storage plugins (fs/s3/gcs); the fs native fast path additionally
# stamps its executor-thread kernel I/O
SPAN_STORAGE_WRITE = "storage:write"
SPAN_STORAGE_READ = "storage:read"
SPAN_FS_NATIVE_WRITE = "storage:fs_native_write"
SPAN_FS_NATIVE_READ = "storage:fs_native_read"
# Zero-pack / direct write kernels: the vectorized pwritev+CRC gather
# write and the O_DIRECT aligned-body write.
SPAN_FS_NATIVE_PWRITEV = "storage:fs_native_pwritev"
SPAN_FS_NATIVE_DIRECT_WRITE = "storage:fs_native_direct_write"
INSTANT_STORAGE_RETRY = "storage:retry"
INSTANT_GCS_RECOVER = "storage:gcs_recover"

# batcher.py slab staging / spanning-read dispatch. The vectorized
# variant is a DISTINCT span: its presence (and stage_slab's absence)
# is the observable pin that the slab-pack pass did not run.
SPAN_BATCHER_STAGE_SLAB = "batcher:stage_slab"
SPAN_BATCHER_STAGE_SLAB_VECTORIZED = "batcher:stage_slab_vectorized"
SPAN_BATCHER_CONSUME_SPANNING = "batcher:consume_spanning"

# tiered mirror
SPAN_MIRROR_JOB = "mirror:job"
SPAN_MIRROR_BLOB = "mirror:blob"

# peer tier (tiered/peer.py): one push job / per-blob transfer, and a
# restore-side pull from a surviving peer's RAM
SPAN_PEER_JOB = "peer:job"
SPAN_PEER_PUSH = "peer:push"
SPAN_PEER_PULL = "peer:pull"

# dist_store.py barriers: one span per arrive/depart phase (args carry
# impl=tree|linear and the barrier prefix) — the coordination wall the
# scale-model harness attributes vs world size.
SPAN_BARRIER_ARRIVE = "barrier:arrive"
SPAN_BARRIER_DEPART = "barrier:depart"
# fanout.py: one owner-table exchange round (needs gather + window
# publication + peer consumption) under a restore round's nonce prefix.
SPAN_FANOUT_EXCHANGE = "fanout:exchange"

# cdn/ — the publish announce, one subscriber chunk-sync round (diff +
# owner fetch + peer pulls), and the staged-buffers-to-live hot swap.
SPAN_CDN_PUBLISH = "cdn:publish"
SPAN_CDN_SYNC = "cdn:sync"
SPAN_CDN_SWAP = "cdn:swap"

# telemetry/wire.py: the two sides of one framed RPC. The client span's
# args carry the propagated trace id + its own span id; the handler
# span's args carry the received trace id + parent span id (= the
# client's span id), so the trace merge CLI can stitch them into one
# causally-linked cross-process trace.
SPAN_WIRE_RPC = "wire:rpc"
SPAN_WIRE_HANDLER = "wire:handler"

# utils/rss_profiler.py: a new peak RSS delta was observed
INSTANT_RSS_PEAK = "rss:peak"

# telemetry/watchdog.py: an open span outlived the stall deadline
INSTANT_WATCHDOG_STALL = "watchdog:stall"

# ---------------------------------------------------------------------------
# Checkpoint-doctor verdict ids (telemetry/doctor.py).
#
# Same single-registration rule as the metrics and spans above, with a
# kebab-case convention (``what-is-wrong``) so verdict ids read like
# alert names. ``RULE_``-prefixed constants name diagnosis rules; the
# snaplint ``doctor-rule-ids`` rule lints both halves: declared exactly
# once here, kebab-case values, no string literals at
# ``doctor_rule``/``Verdict`` emit sites.
# ---------------------------------------------------------------------------

# The take's wall clock is the staging (D2H + serialize) phase: the
# device link, not storage, bounds the checkpoint.
RULE_D2H_BOUND = "d2h-bound"
# Requests spent a large fraction of the op blocked in
# MemoryBudget.acquire: the host-memory budget, not I/O, is the limit.
RULE_BUDGET_STARVED = "budget-starved"
# Cross-rank aggregation shows one rank far beyond the median for a
# phase: page that rank, not the storage team.
RULE_STRAGGLER_RANK = "straggler-rank"
# The write drain after staging dominates the take: the storage tier
# (or its link) is the bottleneck.
RULE_STORAGE_TIER_SLOW = "storage-tier-slow"
# The background mirror's durability lag / queue depth is growing
# faster than the take cadence drains it.
RULE_MIRROR_LAGGING = "mirror-lagging"
# One blob's write span dominates the op: a single stuck/slow write
# tail, not uniform slowness.
RULE_WRITE_TAIL_STALL = "write-tail-stall"
# A non-terminal progress heartbeat was left behind: an op died
# mid-flight (crash, preemption) without finishing.
RULE_INTERRUPTED_TAKE = "interrupted-take"
# The stall watchdog fired during this op (the trace carries the
# culprit span).
RULE_WATCHDOG_STALLED = "watchdog-stalled"
# Storage retries during the op exceeded the storm threshold.
RULE_RETRY_STORM = "retry-storm"
# An async take's training-visible span (async_take return-to-caller
# time) exceeded the visible-budget knob: staging leaked back into the
# caller's thread — the regression the device-snapshot path exists to
# prevent.
RULE_ASYNC_VISIBLE_STALL = "async-visible-stall"
# The write-path autotuner is oscillating: a tunable's decision log
# shows an A -> B -> A value cycle inside the trend window — the policy
# keeps applying and reverting the same move instead of converging
# (evidence cites the .tuner-state.json entries).
RULE_TUNER_THRASHING = "tuner-thrashing"
# A restore's storage reads exceeded what the manifest said it needed
# by the amplification threshold: whole-shard reads serving partial
# destinations, a dead fan-out (every rank fetching every shard), or
# re-reads — the report's bytes_fetched/bytes_needed fields carry the
# ratio.
RULE_RESTORE_READ_AMPLIFIED = "restore-read-amplified"
# Bench-trial rules (bench.py's former private heuristics): the take's
# achieved throughput fell below half of a *stable* bracketing probe
# pair — the slowdown happened inside the take.
RULE_IN_TAKE_STALL = "in-take-stall"
# Adjacent link probes disagreed beyond the stability factor: the
# link itself was moving; efficiency ratios are not trustworthy.
RULE_LINK_UNSTABLE = "link-unstable"
# Trend analysis: a step's metric sits beyond median + k*MAD of its
# rolling baseline.
RULE_TREND_REGRESSION = "trend-regression"
# Run-level goodput (ledger-driven): checkpointing ate more than the
# overhead-fraction threshold of this run's wall time (visible stalls +
# restores + lost work against the run ledger's measured span).
RULE_GOODPUT_DEGRADED = "goodput-degraded"
# An interruption's recovery cost (work lost since the last committed
# step plus the restore that followed) exceeded the recovery budget —
# the checkpoint interval, not the per-save latency, is what needs
# attention (evidence cites the ledger records).
RULE_RECOVERY_COST_HIGH = "recovery-cost-high"
# A restore that had an eligible peer-RAM copy was (partly) served from
# storage instead: peer transfers failed or fell through, so recovery
# paid storage latency the peer tier existed to avoid. Evidence cites
# the peer transfer failures and the per-tier byte split.
RULE_PEER_TIER_DEGRADED = "peer-tier-degraded"
# Coordination (store round-trips + barrier waits + the fan-out
# exchange), not data movement, ate a large fraction of the op's wall:
# the world size outgrew the coordination topology. Evidence cites the
# report's coordination split (barrier_wait_s / store_s / store_ops /
# exchange_s from the barrier:* spans' counters); the levers are the
# tree-barrier fanout, store shards, and batched store ops
# (docs/scaling.md).
RULE_COORDINATION_BOUND = "coordination-bound"
# The content-addressed store is on but recent committed steps reused
# ~none of their bytes even though the on-device digests say the state
# was mostly unchanged — the dedup path is broken in practice (chunks
# dir wiped/relocated, nondeterministic serialization, or an ineligible
# root silently running the legacy layout). Evidence cites the ledger's
# step-committed storage records.
RULE_DEDUP_INEFFECTIVE = "dedup-ineffective"
# Stored bytes failed digest verification: a restore rerouted reads
# around a corrupt tier copy (report ``degraded_reads``/``tier_split``
# evidence), or ``fsck --repair`` rewrote/quarantined damaged chunks
# (``repair-performed`` ledger events). The store healed — or could
# not — but the medium is rotting either way; audit the tier named by
# the evidence (docs/chaos.md).
RULE_STORAGE_CORRUPTION = "storage-corruption"
# The serving fleet is falling behind the publisher: the median
# publish-to-swap latency across the ledger's cdn-swapped records
# exceeds the knob'd staleness budget
# (TORCHSNAPSHOT_TPU_CDN_STALENESS_BUDGET_SECONDS). Cites the ledger's
# publish/swap events and the per-subscriber staleness spread.
RULE_CDN_STALENESS_HIGH = "cdn-staleness-high"
# Dial latencies are clustering at whole-second values — the kernel's
# SYN-retransmit quanta, i.e. a listen backlog overflowing under fan-in
# (the PR 15 peer-server bug class, now auto-detected from the fleet
# plane's recent-dial samples). The fix is the server's
# ``request_queue_size``, not the network.
RULE_WIRE_DIAL_STALLED = "wire-dial-stalled"
# One serving endpoint moved a disproportionate byte share of a fan-out
# round: owner election degenerated (or the fleet's chunk->owner hash
# is skewed), so a single peer's NIC is the round's critical path.
RULE_WIRE_HOT_ENDPOINT = "wire-hot-endpoint"
# One coordination-store shard absorbed a disproportionate request
# share: the crc32 key route degenerated for this key population, so
# sharding stopped spreading load (docs/scaling.md).
RULE_STORE_HOT_SHARD = "store-hot-shard"
# Critical-path analysis (telemetry/critpath.py): the dominant
# path segment of a step's critical-path attribution differs from the
# rolling window's modal dominant segment — the bottleneck MOVED (e.g.
# write drain gave way to coordination), which a magnitude-only trend
# check cannot see when the wall clock barely shifts.
RULE_CRITICAL_PATH_SHIFTED = "critical-path-shifted"
# A signal-of-record bench leg slowed beyond its declared tolerance
# (median + k*MAD over the preceding BENCH_r*.json records, with
# relative/absolute floors sized to the measured round-to-round link
# drift): the regression is in the code, not the noise. Emitted by the
# diff engine / ``tools/bench_diff.py``, never from a live op.
RULE_BENCH_REGRESSION = "bench-regression"
# A declared SLO objective is burning its error budget: the fast window
# caught a cliff or the slow window caught drift (telemetry/slo.py's
# multi-window burn-rate math over the ledger/history samples). Cites
# the per-window burn, bad-sample counts and any slo-breach ledger
# events already posted for the objective.
RULE_SLO_BURNING = "slo-burning"
# A restore's cold-start split (event-loop spin-up + plugin open +
# native-module load, recorded since PR 15) dominates the op wall
# beyond the knob'd fraction budget
# (TORCHSNAPSHOT_TPU_COLD_START_BUDGET_FRACTION): the r06 "first-trial
# restores 10-28 s vs sub-1 s warm" soft spot, ranked. Cites the
# ``{event_loop_s, plugin_open_s, native_load_s}`` breakdown.
RULE_RESTORE_COLD_START_SLOW = "restore-cold-start-slow"

# ---------------------------------------------------------------------------
# Run-ledger event ids (telemetry/ledger.py).
#
# Same single-registration rule as the families above, with the doctor
# rules' kebab-case convention. ``EVENT_``-prefixed constants name the
# typed records the manager, snapshot envelopes, tiered mirror,
# preemption saver, and GC post to ``<root>/.ledger.jsonl``; snaplint's
# ``ledger-event-ids`` rule lints both halves: declared exactly once
# here, kebab-case values, no literal event strings at
# ``post_event``/``post_event_for_snapshot`` call sites.
# ---------------------------------------------------------------------------

# A manager opened (or resumed) a run at a root: carries the stable
# run id and the 1-based segment number (one segment per process
# lifetime; a restart resumes the run id and increments the segment).
EVENT_RUN_START = "run-start"
# A step committed through the manager: the retention-visible moment,
# with the step's storage accounting (new vs. base-referenced bytes).
EVENT_STEP_COMMITTED = "step-committed"
# A take/async_take blocked training for its visible span (the whole
# wall for sync takes; return-to-caller for async ones).
EVENT_VISIBLE_STALL = "visible-stall"
# An async take's background D2H + serialize drain finished — overhead
# that OVERLAPPED training rather than stalling it.
EVENT_STAGED_DRAIN = "staged-drain"
# A tiered mirror job settled: how long the step's bytes existed only
# on the fast tier, and what replication moved.
EVENT_MIRROR_SETTLED = "mirror-settled"
# A restore/async_restore completed: recovery (or resume) time paid.
EVENT_RESTORE_SERVED = "restore-served"
# The preemption saver agreed a coordinated save target (or gave up):
# the interruption point the lost-work accounting anchors on.
EVENT_PREEMPTION = "preemption"
# Retention GC deleted a step's blobs; its step-committed storage
# records are pruned from the ledger in the same pass.
EVENT_GC_RECLAIMED = "gc-reclaimed"
# ``fsck --repair`` acted on a damaged blob/chunk: rewrote it from a
# tier whose copy verified, or quarantined it (no tier verified —
# ``chunks/.quarantine/``). The ``storage-corruption`` doctor rule
# cites these records; fields carry the location, action and tiers.
EVENT_REPAIR_PERFORMED = "repair-performed"
# The manager's post-commit CDN hook announced a step to a topic:
# carries the topic, sequence number, manifest digest and the announced
# chunk-set accounting (the publish half the ``cdn-staleness-high``
# rule correlates swaps against).
EVENT_CDN_PUBLISHED = "cdn-published"
# A subscriber hot-swapped an announced step into its serving buffers:
# carries the subscriber id, step, publish-to-swap staleness and the
# bytes-on-wire split (durable read vs. peer pull vs. already held).
EVENT_CDN_SWAPPED = "cdn-swapped"
# The rank-0 SLO evaluation saw an objective transition into breach
# (edge-triggered: one record per episode, not per evaluated step):
# carries the objective id, the target, both window burns and the
# offending last sample. The ``slo-burning`` doctor rule and the
# incident-bundle trigger both key off these records.
EVENT_SLO_BREACH = "slo-breach"

# ---------------------------------------------------------------------------
# Crash-point ids (chaos/crashpoints.py).
#
# Same single-registration rule as the families above, with the doctor
# rules' kebab-case convention. ``CRASH_``-prefixed constants name the
# kill points threaded through the take/commit/GC/mirror paths —
# ``crashpoint(names.CRASH_...)`` is a no-op in production and raises
# ``SimulatedCrash`` when the chaos engine armed that point, so the
# crash-matrix harness (chaos/harness.py) can kill an op at every
# declared point and assert the store's global invariants. snaplint's
# ``crashpoint-ids`` rule lints both halves: declared exactly once
# here, kebab-case values, no literal ids at ``crashpoint()`` sites.
# The harness enumerates this registry — adding a constant here IS
# adding the point to the matrix.
# ---------------------------------------------------------------------------

# Every rank's data writes drained durably (sync_complete returned);
# nothing control-plane exists yet.
CRASH_TAKE_WRITES_DONE = "take-writes-done"
# This rank's checksum table is durable (always before the barrier).
CRASH_CHECKSUM_TABLE_WRITTEN = "checksum-table-written"
# A CAS chunk's bytes just landed in ``chunks/`` — no map, no manifest,
# no pin references it yet (the stray-sweep + grace-window case).
CRASH_CAS_CHUNK_WRITTEN = "cas-chunk-written"
# This rank's ``cas/{rank}`` path->digest map committed.
CRASH_CAS_MAP_WRITTEN = "cas-map-written"
# Rank 0, inside the commit window: the manifest rewrite ran but the
# ``.snapshot_metadata`` marker does NOT exist yet (the step must read
# as never-happened).
CRASH_PRE_COMMIT_MARKER = "pre-commit-marker"
# The commit marker is durable; the manager index does not name the
# step yet (committed-but-unindexed).
CRASH_COMMIT_MARKER = "commit-marker"
# The tiered take handed its blob inventory to the background mirror.
CRASH_MIRROR_ENQUEUED = "mirror-enqueued"
# The post-commit peer-tier push hook ran (enqueue, not settle).
CRASH_PEER_ENQUEUED = "peer-enqueued"
# Rank 0 pinned the committing step's chunks in the refcount journal;
# the index write has not happened (pinned-but-uncommitted).
CRASH_REFCOUNT_PINNED = "refcount-pinned"
# The index backup slot is written, the primary is not (torn pair).
CRASH_INDEX_BACKUP_WRITTEN = "index-backup-written"
# Both index slots name the new step; retention deletes still pending.
CRASH_INDEX_WRITTEN = "index-written"
# Chunk GC unpinned the dropped steps; reclaim deletes still pending.
CRASH_GC_UNPINNED = "gc-unpinned"
# Step GC deleted a dropped step's commit marker; its data blobs (and
# telemetry leftovers) are still on disk.
CRASH_GC_MARKER_DELETED = "gc-marker-deleted"
# The CDN publisher wrote the announce record for a step but has NOT
# advanced the topic head yet (torn announce: subscribers must never
# observe the record).
CRASH_CDN_PUBLISH_ANNOUNCED = "cdn-publish-announced"
# A CDN subscriber finished staging an announced step's chunks into its
# shadow buffers; the hot swap has not happened (the live weights must
# still be the previous step's).
CRASH_CDN_SWAP_STAGED = "cdn-swap-staged"

# ---------------------------------------------------------------------------
# Wire RPC op ids (telemetry/wire.py; dist_store.py, tiered/peer.py).
#
# Same single-registration rule as the families above, kebab-case.
# ``RPC_``-prefixed constants name every operation that rides the shared
# socket framing (``send_frame``/``recv_frame``): the op id travels in
# the optional wire-context header, labels the per-RPC wire metrics,
# and keys the peer transport's request dispatch. snaplint's
# ``rpc-op-ids`` rule lints both halves: declared exactly once here,
# kebab-case values, no literal op strings at frame-send call sites
# (``PeerClient.request`` / ``wire.propagate``).
# ---------------------------------------------------------------------------

# Coordination-store commands (dist_store.py `_CMD_*` wire protocol).
RPC_STORE_SET = "store-set"
RPC_STORE_TRY_GET = "store-try-get"
RPC_STORE_ADD = "store-add"
RPC_STORE_DELETE = "store-delete"
RPC_STORE_MULTI_SET = "store-multi-set"
RPC_STORE_MULTI_GET = "store-multi-get"
RPC_STORE_MULTI_DELETE = "store-multi-delete"
RPC_STORE_SCAN = "store-scan"
# Peer-tier transport commands (tiered/peer.py request dispatch). The
# constants ARE the on-wire command strings: client and server both
# reference them, so the protocol and the observability namespace
# cannot drift apart.
RPC_PEER_PUSH = "peer-push"
RPC_PEER_COMMIT = "peer-commit"
RPC_PEER_PULL = "peer-pull"
RPC_PEER_REFCHUNKS = "peer-refchunks"
RPC_PEER_LIST = "peer-list"
RPC_PEER_EVICT = "peer-evict"
RPC_PEER_STATS = "peer-stats"
RPC_PEER_PING = "peer-ping"
# Composite client-side operations that open a propagation context
# spanning several frames (fanout.py's owner-table exchange, a CDN
# subscriber's chunk-sync round).
RPC_FANOUT_EXCHANGE = "fanout-exchange"
RPC_CDN_SYNC = "cdn-sync"
RPC_CDN_PUBLISH = "cdn-publish"

# ---------------------------------------------------------------------------
# SLO objective ids (telemetry/slo.py).
#
# Same single-registration rule as the families above, kebab-case
# ("what-is-promised"). ``SLO_``-prefixed constants name the declared
# service-level objectives the rank-0 per-step evaluation judges with
# multi-window burn-rate math; the id labels the ``slo_burn_rate``
# gauge, keys the per-objective target/disable knobs, and travels in
# ``slo-breach`` ledger events. snaplint's ``slo-ids`` rule lints both
# halves: declared exactly once here, kebab-case values, no literal ids
# at ``Objective(...)`` declaration sites.
# ---------------------------------------------------------------------------

# Visible training stall per take/async_take stays under the async
# visible budget (TORCHSNAPSHOT_TPU_ASYNC_VISIBLE_BUDGET_SECONDS).
SLO_TAKE_VISIBLE_STALL = "take-visible-stall"
# A restore/async_restore serves within the restore wall budget
# (TORCHSNAPSHOT_TPU_SLO_RESTORE_SECONDS).
SLO_RESTORE_WALL = "restore-wall"
# A step's bytes exist only on the fast tier no longer than the mirror
# durability-lag budget (TORCHSNAPSHOT_TPU_SLO_MIRROR_LAG_SECONDS).
SLO_MIRROR_LAG = "mirror-durability-lag"
# CDN publish-to-swap staleness per subscriber swap stays under the
# staleness budget (TORCHSNAPSHOT_TPU_CDN_STALENESS_BUDGET_SECONDS).
SLO_CDN_STALENESS = "cdn-staleness"
# Checkpoint overhead (visible stall + restore) per commit interval
# stays under the overhead fraction budget
# (TORCHSNAPSHOT_TPU_SLO_OVERHEAD_FRACTION).
SLO_GOODPUT_OVERHEAD = "goodput-overhead"
# Coordination's share of a take's wall stays under the coordination
# fraction budget (TORCHSNAPSHOT_TPU_SLO_COORDINATION_FRACTION).
SLO_COORDINATION_FRACTION = "coordination-fraction"
