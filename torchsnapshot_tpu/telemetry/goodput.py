"""Run-level goodput: attribute a training run's wall time and storage
spend from its checkpoint ledger.

The LLM checkpoint I/O literature frames goodput/ETTR — not per-save
latency — as the metric that decides checkpoint interval and tiering
policy. This module is that calculation over the run ledger
(``telemetry/ledger.py``): every run's measured wall time is split into

- **train** — the residual: time the run made forward progress;
- **visible stall** — training blocked inside takes (the whole wall
  for sync takes, return-to-caller for async ones);
- **restore / recovery** — time spent serving restores (cold resume
  and post-interruption recovery alike);
- **lost work** — for each interrupted segment, the time between the
  last committed (or restored) progress point and the segment's last
  sign of life: work a restart replays. Where a preemption event
  recorded the step, the loss is also counted in steps.

The buckets sum to the ledger-measured wall time by construction
(train is the residual, clamped at zero). Overlapped overhead — the
async takes' background D2H drain, the tiered mirror's durability lag
— is reported alongside, NOT inside the sum: it cost bandwidth, not
train-visible time. Storage spend comes from the surviving
``step-committed`` records: bytes newly written vs. base-referenced
per retained step (the incremental reuse ratio is a direct scout for a
content-addressed store), plus per-tier totals from the mirror's
settle events.

Three surfaces:

- CLI — ``python -m torchsnapshot_tpu.telemetry goodput <root>``
  (``--json`` for the machine-readable analysis);
- Prometheus — :func:`publish_gauges` refreshes the ``goodput_*``
  gauges in the process registry (the manager calls it after every
  committed step);
- doctor — the ``goodput-degraded`` / ``recovery-cost-high`` rules
  (telemetry/doctor.py) emit ranked verdicts citing ledger records.

See docs/goodput.md.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence

from . import names
from .ledger import find_ledger_for, load_ledger

logger: logging.Logger = logging.getLogger(__name__)


def _ts(record: Dict[str, Any], default: float = 0.0) -> float:
    try:
        return float(record.get("unix_ts", default))
    except (TypeError, ValueError):
        return default


def _split_segments(
    records: Sequence[Dict[str, Any]]
) -> List[Dict[str, Any]]:
    """Raw segments: one per run-start, each carrying its start record
    and the events that followed it (pre-run-start records — a ledger
    whose trim dropped history — are ignored; the trim re-anchors the
    newest run-start so the active segment never loses its start)."""
    out: List[Dict[str, Any]] = []
    cur: Optional[Dict[str, Any]] = None
    for r in records:
        if r.get("event") == names.EVENT_RUN_START:
            cur = {"start": r, "records": []}
            out.append(cur)
        elif cur is not None:
            cur["records"].append(r)
    return out


def _segment_summary(
    raw: Dict[str, Any], interrupted: bool
) -> Dict[str, Any]:
    start = raw["start"]
    recs: List[Dict[str, Any]] = raw["records"]
    start_ts = _ts(start)
    end_ts = max([start_ts] + [_ts(r, start_ts) for r in recs])
    wall = max(0.0, end_ts - start_ts)

    visible = 0.0
    restore = 0.0
    recovery_restore = 0.0
    drain = 0.0
    mirror_lags: List[float] = []
    commits: List[Dict[str, Any]] = []
    preempt: Optional[Dict[str, Any]] = None
    last_progress_ts = start_ts
    for r in recs:
        ev = r.get("event")
        if ev == names.EVENT_VISIBLE_STALL:
            visible += float(r.get("visible_s") or 0.0)
        elif ev == names.EVENT_RESTORE_SERVED:
            restore += float(r.get("restore_s") or 0.0)
            # Restores before the segment's first commit are the
            # RECOVERY restores (resuming from the previous segment's
            # checkpoint); later ones are deliberate (eval rollbacks,
            # restore_best) and must not inflate the preceding
            # interruption's recovery cost.
            if not commits:
                recovery_restore += float(r.get("restore_s") or 0.0)
            last_progress_ts = max(last_progress_ts, _ts(r, start_ts))
        elif ev == names.EVENT_STAGED_DRAIN:
            drain += float(r.get("drain_s") or 0.0)
        elif ev == names.EVENT_MIRROR_SETTLED:
            mirror_lags.append(float(r.get("lag_s") or 0.0))
        elif ev == names.EVENT_STEP_COMMITTED:
            commits.append(r)
            last_progress_ts = max(last_progress_ts, _ts(r, start_ts))
        elif ev == names.EVENT_PREEMPTION and not r.get("gave_up"):
            preempt = r

    last_commit_step = commits[-1].get("step") if commits else None
    lost_work = 0.0
    lost_steps: Optional[int] = None
    if interrupted:
        # Work after the last durable/recovered progress point died
        # with the segment — a restart replays it. In steps when the
        # preemption saver recorded where the world was.
        lost_work = max(0.0, end_ts - last_progress_ts)
        if (
            preempt is not None
            and preempt.get("step") is not None
            and last_commit_step is not None
        ):
            lost_steps = max(
                0, int(preempt["step"]) - int(last_commit_step)
            )
    train = max(0.0, wall - visible - restore - lost_work)
    return {
        "segment": start.get("segment"),
        "start_ts": round(start_ts, 6),
        "end_ts": round(end_ts, 6),
        "wall_s": round(wall, 6),
        "train_s": round(train, 6),
        "visible_stall_s": round(visible, 6),
        "restore_s": round(restore, 6),
        "recovery_restore_s": round(recovery_restore, 6),
        "lost_work_s": round(lost_work, 6),
        "lost_steps": lost_steps,
        "staged_drain_s": round(drain, 6),
        "mirror_lag_max_s": round(max(mirror_lags), 3) if mirror_lags else 0.0,
        "steps_committed": len(commits),
        "last_committed_step": last_commit_step,
        "preemption_step": (
            preempt.get("step") if preempt is not None else None
        ),
        "interrupted": interrupted,
    }


def _storage_summary(
    records: Sequence[Dict[str, Any]]
) -> Dict[str, Any]:
    committed: Dict[int, Dict[str, Any]] = {}
    reclaimed_bytes = 0
    reclaimed_steps = 0
    mirror_settles: List[Dict[str, Any]] = []
    saw_mirror = False
    for r in records:
        ev = r.get("event")
        if ev == names.EVENT_STEP_COMMITTED and r.get("step") is not None:
            committed[int(r["step"])] = r
        elif ev == names.EVENT_GC_RECLAIMED:
            reclaimed_bytes += int(r.get("bytes_reclaimed") or 0)
            reclaimed_steps += 1
        elif ev == names.EVENT_MIRROR_SETTLED:
            saw_mirror = True
            mirror_settles.append(r)
    # Per-tier parity: 'primary' counts only RETAINED steps (GC prunes
    # their step-committed records), so the durable sum must filter the
    # same way — mirror-settled events survive pruning for time
    # attribution, and summing them all would report GC'd history as
    # live durable spend.
    durable_bytes = sum(
        int(r.get("nbytes") or 0)
        for r in mirror_settles
        if not r.get("error")
        and r.get("step") is not None
        and int(r["step"]) in committed
    )
    steps = sorted(committed)
    new_total = sum(
        int(committed[s].get("bytes_new") or 0) for s in steps
    )
    reused_total = sum(
        int(committed[s].get("bytes_reused") or 0) for s in steps
    )
    grand_total = sum(
        int(committed[s].get("bytes_total") or 0) for s in steps
    )
    by_tier: Dict[str, int] = {"primary": new_total}
    if saw_mirror:
        by_tier["durable"] = durable_bytes
    return {
        "retained_steps": len(steps),
        "per_step": [
            {
                "step": s,
                "bytes_new": int(committed[s].get("bytes_new") or 0),
                "bytes_reused": int(committed[s].get("bytes_reused") or 0),
                "bytes_total": int(committed[s].get("bytes_total") or 0),
            }
            for s in steps
        ],
        "bytes_new_total": new_total,
        "bytes_reused_total": reused_total,
        "bytes_per_retained_step": (
            int(new_total / len(steps)) if steps else 0
        ),
        # How much of the retained state rides on base references
        # instead of fresh bytes — keep-last-N at ~1x storage is this
        # ratio approaching 1.0.
        "incremental_reuse_ratio": (
            round(reused_total / grand_total, 4) if grand_total else 0.0
        ),
        "reclaimed_steps": reclaimed_steps,
        "reclaimed_bytes": reclaimed_bytes,
        "by_tier": by_tier,
    }


def analyze(records: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """The full ledger analysis: per-run attribution (runs split on run
    id, segments on run-start events) plus the storage-cost summary."""
    raw_segments = _split_segments(records)
    grouped: List[Dict[str, Any]] = []
    for seg in raw_segments:
        rid = str(seg["start"].get("run_id") or "?")
        if not grouped or grouped[-1]["run_id"] != rid:
            grouped.append({"run_id": rid, "raw": []})
        grouped[-1]["raw"].append(seg)

    runs: List[Dict[str, Any]] = []
    for g in grouped:
        n = len(g["raw"])
        segments: List[Dict[str, Any]] = []
        for idx, seg in enumerate(g["raw"]):
            followed = idx < n - 1
            # The final segment is open (or ended cleanly) unless its
            # trail stops at an un-acted-on preemption notice.
            tail_preempted = (
                not followed
                and bool(seg["records"])
                and seg["records"][-1].get("event") == names.EVENT_PREEMPTION
                and not seg["records"][-1].get("gave_up")
            )
            segments.append(
                _segment_summary(seg, interrupted=followed or tail_preempted)
            )
        wall = sum(s["wall_s"] for s in segments)
        visible = sum(s["visible_stall_s"] for s in segments)
        restore = sum(s["restore_s"] for s in segments)
        lost = sum(s["lost_work_s"] for s in segments)
        train = sum(s["train_s"] for s in segments)
        downtime = sum(
            max(0.0, b["start_ts"] - a["end_ts"])
            for a, b in zip(segments, segments[1:])
        )
        known_lost_steps = [
            s["lost_steps"] for s in segments if s["lost_steps"] is not None
        ]
        interruptions: List[Dict[str, Any]] = []
        for idx, s in enumerate(segments):
            if not s["interrupted"]:
                continue
            nxt = segments[idx + 1] if idx + 1 < len(segments) else None
            restore_next = (
                nxt["recovery_restore_s"] if nxt is not None else 0.0
            )
            restart_gap = (
                max(0.0, nxt["start_ts"] - s["end_ts"])
                if nxt is not None
                else 0.0
            )
            interruptions.append(
                {
                    "segment": s["segment"],
                    "preemption_step": s["preemption_step"],
                    "last_committed_step": s["last_committed_step"],
                    "lost_steps": s["lost_steps"],
                    "lost_work_s": s["lost_work_s"],
                    "restore_s": round(restore_next, 6),
                    "restart_gap_s": round(restart_gap, 6),
                    # The checkpoint-attributable price of the
                    # interruption: replayed work + the restore that
                    # recovered it (the restart gap is scheduling, cited
                    # but not charged).
                    "recovery_cost_s": round(
                        s["lost_work_s"] + restore_next, 6
                    ),
                }
            )
        runs.append(
            {
                "run_id": g["run_id"],
                "segments": segments,
                "wall_s": round(wall, 6),
                "downtime_s": round(downtime, 6),
                "train_s": round(train, 6),
                "visible_stall_s": round(visible, 6),
                "restore_s": round(restore, 6),
                "lost_work_s": round(lost, 6),
                "lost_steps": (
                    sum(known_lost_steps) if known_lost_steps else None
                ),
                "staged_drain_s": round(
                    sum(s["staged_drain_s"] for s in segments), 6
                ),
                "mirror_lag_max_s": max(
                    (s["mirror_lag_max_s"] for s in segments), default=0.0
                ),
                "steps_committed": sum(
                    s["steps_committed"] for s in segments
                ),
                "interruptions": interruptions,
                "overhead_fraction": (
                    round((visible + restore + lost) / wall, 4)
                    if wall > 0
                    else 0.0
                ),
            }
        )
    return {
        "events": len(records),
        "runs": runs,
        "storage": _storage_summary(records),
    }


def latest_run(analysis: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    runs = analysis.get("runs") or []
    return runs[-1] if runs else None


def analyze_root(root: str) -> Optional[Dict[str, Any]]:
    """Load + analyze a manager root's (or ledger file's) ledger; None
    when no ledger exists."""
    path = find_ledger_for(root)
    if path is None:
        return None
    analysis = analyze(load_ledger(path))
    analysis["ledger_file"] = path
    return analysis


# ---------------------------------------------------------------------------
# Prometheus surface
# ---------------------------------------------------------------------------


def publish_gauges(root: str, registry: Optional[Any] = None) -> bool:
    """Refresh the ``goodput_*`` gauges from ``root``'s ledger (latest
    run), and rewrite the Prometheus textfile if one is configured —
    the manager calls this after every committed step so scrapes track
    the run, not just the last op. Best-effort; returns False when no
    ledger exists or publication failed."""
    try:
        analysis = analyze_root(root)
        if analysis is None:
            return False
        run = latest_run(analysis)
        if run is None:
            return False
        if registry is None:
            from . import metrics

            registry = metrics()
        storage = analysis["storage"]
        registry.gauge_set(
            names.GOODPUT_OVERHEAD_FRACTION, run["overhead_fraction"]
        )
        registry.gauge_set(names.GOODPUT_TRAIN_SECONDS, run["train_s"])
        registry.gauge_set(
            names.GOODPUT_VISIBLE_STALL_SECONDS, run["visible_stall_s"]
        )
        registry.gauge_set(names.GOODPUT_RECOVERY_SECONDS, run["restore_s"])
        registry.gauge_set(
            names.GOODPUT_LOST_WORK_SECONDS, run["lost_work_s"]
        )
        registry.gauge_set(
            names.GOODPUT_LOST_STEPS, run["lost_steps"] or 0
        )
        registry.gauge_set(
            names.GOODPUT_STORAGE_BYTES_PER_STEP,
            storage["bytes_per_retained_step"],
        )
        registry.gauge_set(
            names.GOODPUT_INCREMENTAL_REUSE_RATIO,
            storage["incremental_reuse_ratio"],
        )
        from .. import knobs

        prom = knobs.get_prometheus_textfile()
        if prom is not None:
            from .sink import write_prometheus_textfile

            write_prometheus_textfile(prom, registry)
        return True
    except Exception as e:  # noqa: BLE001 - telemetry must not fail the op
        logger.warning("goodput: gauge publication failed: %r", e)
        return False


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _pct(part: float, whole: float) -> str:
    return f"{100.0 * part / whole:5.1f}%" if whole > 0 else "    -"


def _mb(nbytes: float) -> float:
    return nbytes / 1024**2


def render(analysis: Dict[str, Any]) -> str:
    lines: List[str] = []
    for run in analysis["runs"]:
        wall = run["wall_s"]
        lines.append(
            f"run {run['run_id']}: {len(run['segments'])} segment(s), "
            f"wall {wall:.1f}s"
            + (
                f" (+{run['downtime_s']:.1f}s restart downtime)"
                if run["downtime_s"] > 0
                else ""
            )
            + f", {run['steps_committed']} step(s) committed, "
            f"checkpoint overhead {100.0 * run['overhead_fraction']:.1f}%"
        )
        lines.append(
            f"  train            {run['train_s']:>10.2f} s  "
            f"{_pct(run['train_s'], wall)}"
        )
        lines.append(
            f"  visible stall    {run['visible_stall_s']:>10.2f} s  "
            f"{_pct(run['visible_stall_s'], wall)}"
        )
        lines.append(
            f"  restore/recovery {run['restore_s']:>10.2f} s  "
            f"{_pct(run['restore_s'], wall)}"
        )
        lost_steps = (
            f"  ({run['lost_steps']} step(s))"
            if run["lost_steps"] is not None
            else ""
        )
        lines.append(
            f"  lost work        {run['lost_work_s']:>10.2f} s  "
            f"{_pct(run['lost_work_s'], wall)}{lost_steps}"
        )
        lines.append(
            f"  overlapped (not charged): staged drain "
            f"{run['staged_drain_s']:.2f} s, mirror lag max "
            f"{run['mirror_lag_max_s']:.2f} s"
        )
        for itr in run["interruptions"]:
            where = (
                f"preempted at step {itr['preemption_step']}"
                if itr["preemption_step"] is not None
                else "interrupted"
            )
            lines.append(
                f"  segment {itr['segment']} {where}: recovery cost "
                f"{itr['recovery_cost_s']:.2f}s "
                f"(lost work {itr['lost_work_s']:.2f}s + restore "
                f"{itr['restore_s']:.2f}s; restart gap "
                f"{itr['restart_gap_s']:.2f}s)"
            )
    storage = analysis["storage"]
    if storage["retained_steps"]:
        tier_str = ", ".join(
            f"{tier} {_mb(b):.1f} MB"
            for tier, b in sorted(storage["by_tier"].items())
        )
        lines.append(
            f"storage: {storage['retained_steps']} retained step(s), "
            f"{_mb(storage['bytes_per_retained_step']):.1f} MB/step new, "
            f"incremental reuse "
            f"{100.0 * storage['incremental_reuse_ratio']:.1f}%, "
            f"reclaimed {_mb(storage['reclaimed_bytes']):.1f} MB "
            f"across {storage['reclaimed_steps']} GC'd step(s) "
            f"[{tier_str}]"
        )
    if not lines:
        lines.append("no runs recorded")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import json as _json

    p = argparse.ArgumentParser(
        prog="torchsnapshot_tpu.telemetry goodput",
        description=(
            "Attribute a training run's wall time (train vs. checkpoint "
            "overhead vs. recovery vs. lost work) and storage spend "
            "from its run ledger (<root>/.ledger.jsonl)."
        ),
    )
    p.add_argument(
        "root",
        help="manager root (or a .ledger.jsonl file) to analyze",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="machine-readable analysis instead of the text report",
    )
    args = p.parse_args(list(argv) if argv is not None else None)

    analysis = analyze_root(args.root)
    if analysis is None:
        print(
            f"goodput: no run ledger found for {args.root!r} (ledgers "
            f"record at <root>/.ledger.jsonl; enable with "
            f"TORCHSNAPSHOT_TPU_LEDGER=1)"
        )
        return 1
    if args.json:
        print(_json.dumps(analysis, indent=1, sort_keys=True))
        return 0
    print(f"goodput: {analysis['ledger_file']} ({analysis['events']} event(s))")
    print(render(analysis))
    return 0
