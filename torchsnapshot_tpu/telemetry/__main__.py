"""``python -m torchsnapshot_tpu.telemetry <events.jsonl>``."""

import sys

from .stats import main

if __name__ == "__main__":
    sys.exit(main())
