"""Incident black-box bundles: the evidence, frozen at the moment it
mattered.

Every diagnostic artifact the stack records lives next to a live
snapshot root — which preemption, retention GC, or a cleanup job may
destroy before anyone investigates. ``capture_bundle`` assembles a
bounded, self-contained incident directory the moment something goes
wrong (an SLO breach, a watchdog stall episode, a failed op) so the
post-mortem reads the run as it was, not as whatever survived.

A bundle deliberately MIMICS a snapshot directory's on-disk layout —
the ledger tail as ``.ledger.jsonl``, the step-history tail, the
triggering snapshot's SnapshotReports as ``.telemetry.jsonl``, its
Chrome traces and heartbeat files under their original basenames, the
tuner decision state — plus a ``manifest.json`` carrying the trigger,
an env fingerprint, the effective knob/tunable vector, and the
capture-time doctor verdicts. Because the layout IS a snapshot dir,
the entire offline analysis stack works against a bundle unchanged:
``doctor --bundle <path>``, ``telemetry slo <path>``, ``telemetry
trace <path>``, ``telemetry goodput <path>`` and ``diff <bundleA>
<bundleB>`` all reproduce the live run's answers from a relocated copy
with the original root gone (pinned by test).

Captures are edge-triggered by their callers (one per breach episode /
stall episode), rate-limited per bundle dir, and size-capped: artifact
copies stop once the byte budget is spent, with JSONL tails truncated
newest-last so the budget buys the most recent evidence. A
non-positive ``TORCHSNAPSHOT_TPU_BUNDLE_MAX_BYTES`` disables capture
entirely (the test conftest pins it so). Best-effort throughout: a
failed capture logs and returns None, never fails the op that
triggered it.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import platform
import shutil
import socket
import sys
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .. import knobs

logger = logging.getLogger(__name__)

BUNDLE_DIR_BASENAME = ".bundles"
MANIFEST_BASENAME = "manifest.json"
BUNDLE_VERSION = 1

# Rate-limit state per bundle dir (monotonic stamp of the last capture
# attempt that passed the gate). Process-local, lock-guarded: the
# watchdog thread and async-save commit threads both trigger.
_LOCK = threading.Lock()
_LAST_CAPTURE: Dict[str, float] = {}


def reset_bundle_state() -> None:
    """Drop the rate-limit stamps (tests)."""
    with _LOCK:
        _LAST_CAPTURE.clear()


def bundle_root_for(root: str) -> Optional[str]:
    """Where a root's bundles land: the knob'd dir, else ``.bundles``
    on the root's local tier (a tiered root's fast tier — the bundle
    must survive remote-tier cleanup)."""
    configured = knobs.get_bundle_dir()
    if configured:
        return configured
    from .sink import local_fs_root

    local = local_fs_root(root)
    if local is None and "://" not in root:
        local = root
    if local is None:
        return None
    return os.path.join(local, BUNDLE_DIR_BASENAME)


def is_bundle(path: str) -> bool:
    """True when ``path`` is a captured bundle dir (has a manifest)."""
    return os.path.isfile(os.path.join(path, MANIFEST_BASENAME))


def load_manifest(path: str) -> Optional[Dict[str, Any]]:
    """The bundle's manifest, or None when unreadable/absent."""
    try:
        with open(os.path.join(path, MANIFEST_BASENAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def list_bundles(root: str) -> List[Dict[str, Any]]:
    """Captured bundles for a root (or of a bundle dir's parent),
    oldest first: ``{path, trigger, reason, unix_ts, bytes, files}``
    per bundle, from each manifest."""
    candidates: List[str] = []
    if is_bundle(root):
        candidates = [root]
    else:
        broot = root if os.path.basename(root) == BUNDLE_DIR_BASENAME else None
        if broot is None:
            broot = bundle_root_for(root)
        if broot is not None and os.path.isdir(broot):
            candidates = sorted(
                os.path.join(broot, name) for name in os.listdir(broot)
            )
    out: List[Dict[str, Any]] = []
    for path in candidates:
        manifest = load_manifest(path)
        if manifest is None:
            continue
        out.append(
            {
                "path": path,
                "trigger": manifest.get("trigger"),
                "reason": manifest.get("reason"),
                "unix_ts": manifest.get("unix_ts"),
                "bytes": manifest.get("bytes"),
                "files": len(manifest.get("files", [])),
            }
        )
    out.sort(key=lambda b: b.get("unix_ts") or 0)
    return out


def default_capture_root() -> Optional[str]:
    """The root a rootless trigger (the stall watchdog) captures for:
    the first manager root this process opened a run ledger at — owning
    the ledger is what makes this process that root's rank 0."""
    from . import ledger

    owned = ledger.owned_roots()
    if not owned:
        return None
    return os.path.dirname(owned[0])


def _latest_snapshot_path(root: str) -> Optional[str]:
    """The newest snapshot dir under a manager root that recorded
    reports — the op the incident evidence should center on."""
    from .sink import SNAPSHOT_EVENTS_BASENAME, local_fs_root

    local = local_fs_root(root)
    if local is None and "://" not in root:
        local = root
    if local is None or not os.path.isdir(local):
        return None
    best: Optional[Tuple[float, str]] = None
    for name in os.listdir(local):
        events = os.path.join(local, name, SNAPSHOT_EVENTS_BASENAME)
        try:
            mtime = os.path.getmtime(events)
        except OSError:
            continue
        if best is None or mtime > best[0]:
            best = (mtime, os.path.join(local, name))
    return best[1] if best else None


def _copy_file(
    src: str, dest_dir: str, name: str, remaining: int
) -> Optional[Dict[str, Any]]:
    """Whole-file copy within budget; skipped (None) when it would not
    fit. Atomic enough for a bundle: the bundle dir itself is built
    under a ``.tmp`` name and renamed once complete."""
    try:
        size = os.path.getsize(src)
        if size > remaining:
            return None
        shutil.copyfile(src, os.path.join(dest_dir, name))
        return {"name": name, "bytes": size, "truncated": False}
    except OSError as e:
        logger.warning("bundle: could not copy %r: %r", src, e)
        return None


def _copy_jsonl_tail(
    src: str, dest_dir: str, name: str, remaining: int
) -> Optional[Dict[str, Any]]:
    """JSONL copy that truncates to the newest lines fitting the
    budget — the tail is where the incident is."""
    try:
        size = os.path.getsize(src)
        if size <= remaining:
            return _copy_file(src, dest_dir, name, remaining)
        if remaining <= 0:
            return None
        with open(src, errors="replace") as f:
            lines = f.readlines()
        kept: List[str] = []
        budget = remaining
        for line in reversed(lines):
            nbytes = len(line.encode("utf-8"))
            if nbytes > budget:
                break
            kept.append(line)
            budget -= nbytes
        if not kept:
            return None
        kept.reverse()
        dest = os.path.join(dest_dir, name)
        with open(dest, "w") as f:
            f.writelines(kept)
        return {
            "name": name,
            "bytes": os.path.getsize(dest),
            "truncated": True,
        }
    except OSError as e:
        logger.warning("bundle: could not tail-copy %r: %r", src, e)
        return None


def _env_fingerprint() -> Dict[str, Any]:
    return {
        "platform": platform.platform(),
        "python": sys.version.split()[0],
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
    }


def _knob_env() -> Dict[str, str]:
    """The operator-set knob surface verbatim — what made THIS run
    behave the way the evidence shows."""
    return {
        k: v
        for k, v in sorted(os.environ.items())
        if k.startswith("TORCHSNAPSHOT_TPU_")
    }


def capture_bundle(
    root: str,
    trigger: str,
    reason: str = "",
    step: Optional[int] = None,
    snapshot_path: Optional[str] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> Optional[str]:
    """Freeze the root's diagnostic evidence into one bounded bundle
    dir; returns its path, or None when capture is disabled, gated by
    the rate limit, or nothing could be assembled. Never raises."""
    try:
        return _capture(root, trigger, reason, step, snapshot_path, extra)
    except Exception as e:  # noqa: BLE001 - must never fail the trigger
        logger.warning("bundle: capture for %r failed: %r", root, e)
        return None


def _capture(
    root: str,
    trigger: str,
    reason: str,
    step: Optional[int],
    snapshot_path: Optional[str],
    extra: Optional[Dict[str, Any]],
) -> Optional[str]:
    max_bytes = knobs.get_bundle_max_bytes()
    if max_bytes <= 0:
        return None
    from .ledger import step_from_path

    # A step dir handed in as the root (the failed-op trigger passes
    # the op's own path): capture at its manager root — the bundle
    # must survive the step's retention GC.
    if step_from_path(root) is not None:
        if snapshot_path is None:
            snapshot_path = root
        root = os.path.dirname(root.rstrip("/")) or root
    bundle_root = bundle_root_for(root)
    if bundle_root is None:
        return None
    min_interval = knobs.get_bundle_min_interval_seconds()
    now = time.monotonic()
    with _LOCK:
        last = _LAST_CAPTURE.get(bundle_root)
        if (
            min_interval > 0
            and last is not None
            and now - last < min_interval
        ):
            return None
        # Stamp before the (slow) assembly so a concurrent trigger
        # does not start a second capture of the same incident.
        _LAST_CAPTURE[bundle_root] = now

    from .history import HISTORY_BASENAME, history_path_for
    from .ledger import LEDGER_BASENAME, find_ledger_for
    from .progress import find_progress_files
    from .sink import SNAPSHOT_EVENTS_BASENAME, local_fs_root
    from .stats import find_events_for
    from .trace import find_trace_files
    from .wire import FLEET_ENDPOINT_BASENAME

    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
    name = f"bundle-{trigger}-{stamp}-{os.getpid()}"
    dest = os.path.join(bundle_root, name)
    if os.path.exists(dest):  # same trigger, same second, same pid
        dest = f"{dest}-{int(time.time() * 1000) % 1000:03d}"
    tmp = dest + ".tmp"
    os.makedirs(tmp, exist_ok=True)

    files: List[Dict[str, Any]] = []
    remaining = max_bytes

    def add(entry: Optional[Dict[str, Any]]) -> None:
        nonlocal remaining
        if entry is not None:
            files.append(entry)
            remaining -= entry["bytes"]

    # Priority order: the budget buys the run-level story first (the
    # ledger and history tails), then the triggering op's own records.
    ledger_file = find_ledger_for(root)
    if ledger_file is not None:
        add(_copy_jsonl_tail(ledger_file, tmp, LEDGER_BASENAME, remaining))
    hist_path = history_path_for(root)
    if hist_path is not None and os.path.exists(hist_path):
        add(_copy_jsonl_tail(hist_path, tmp, HISTORY_BASENAME, remaining))

    if snapshot_path is None:
        snapshot_path = _latest_snapshot_path(root)
    if snapshot_path is not None:
        reports = find_events_for(snapshot_path)
        if reports and remaining > 0:
            lines = [json.dumps(r, sort_keys=True) + "\n" for r in reports]
            kept: List[str] = []
            budget = remaining
            for line in reversed(lines):
                nbytes = len(line.encode("utf-8"))
                if nbytes > budget:
                    break
                kept.append(line)
                budget -= nbytes
            if kept:
                kept.reverse()
                dest_path = os.path.join(tmp, SNAPSHOT_EVENTS_BASENAME)
                with open(dest_path, "w") as f:
                    f.writelines(kept)
                add(
                    {
                        "name": SNAPSHOT_EVENTS_BASENAME,
                        "bytes": os.path.getsize(dest_path),
                        "truncated": len(kept) < len(lines),
                    }
                )
        for trace_path in find_trace_files(snapshot_path):
            base = os.path.basename(trace_path)
            if not base.startswith("."):
                base = f".trace-{base}"
            add(_copy_file(trace_path, tmp, base, remaining))
        for progress_path in find_progress_files(snapshot_path):
            add(
                _copy_file(
                    progress_path,
                    tmp,
                    os.path.basename(progress_path),
                    remaining,
                )
            )

    local = local_fs_root(root)
    if local is None and "://" not in root:
        local = root
    if local is not None:
        from ..tuner.state import TUNER_STATE_BASENAME

        for aux in (TUNER_STATE_BASENAME, FLEET_ENDPOINT_BASENAME):
            aux_path = os.path.join(local, aux)
            if os.path.exists(aux_path):
                add(_copy_file(aux_path, tmp, aux, remaining))

    # Capture-time doctor verdicts: what the live rules said with every
    # signal still on disk — the baseline an offline re-diagnosis of
    # this bundle is compared against.
    verdicts: List[Dict[str, Any]] = []
    try:
        from .doctor import diagnose_snapshot

        target = snapshot_path if snapshot_path is not None else root
        verdicts = [v.to_dict() for v in diagnose_snapshot(target)]
    except Exception as e:  # noqa: BLE001
        logger.warning("bundle: capture-time diagnosis failed: %r", e)

    mirror_state: Optional[Dict[str, Any]] = None
    try:
        from ..tiered.mirror import mirror_state_for_path

        mirror_state = mirror_state_for_path(snapshot_path or root)
    except Exception:  # noqa: BLE001
        mirror_state = None

    manifest = {
        "version": BUNDLE_VERSION,
        "trigger": trigger,
        "reason": reason,
        "step": step,
        "root": root,
        "snapshot_path": snapshot_path,
        "unix_ts": round(time.time(), 6),
        "max_bytes": max_bytes,
        "bytes": max_bytes - remaining,
        "env": _env_fingerprint(),
        "knobs": _knob_env(),
        "tunables": knobs.tunable_snapshot(),
        "files": files,
        "verdicts": verdicts,
        "mirror_state": mirror_state,
        "extra": extra or {},
    }
    from .sink import atomic_write_text

    atomic_write_text(
        os.path.join(tmp, MANIFEST_BASENAME),
        json.dumps(manifest, indent=2, sort_keys=True),
    )
    os.rename(tmp, dest)

    from . import metrics
    from . import names

    metrics().counter_inc(names.BUNDLE_CAPTURES_TOTAL, trigger=trigger)
    logger.warning(
        "bundle: captured %s (%s%s, %d files, %d bytes)",
        dest,
        trigger,
        f": {reason}" if reason else "",
        len(files),
        max_bytes - remaining,
    )
    return dest


def render(bundles: List[Dict[str, Any]]) -> str:
    if not bundles:
        return "no bundles captured"
    lines = [
        f"{'captured':<20} {'trigger':<14} {'files':>5} {'bytes':>10} path"
    ]
    for b in bundles:
        ts = b.get("unix_ts")
        when = (
            time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime(ts))
            if isinstance(ts, (int, float))
            else "-"
        )
        lines.append(
            f"{when:<20} {str(b.get('trigger')):<14} "
            f"{b.get('files', 0):>5} {b.get('bytes', 0):>10} {b['path']}"
        )
    return "\n".join(lines)


def main(argv: Optional[Iterable[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="torchsnapshot_tpu.telemetry bundle",
        description=(
            "List a root's captured incident bundles, or capture one "
            "now. Analyze a bundle with `telemetry doctor --bundle`, "
            "`telemetry slo`, or `telemetry diff`."
        ),
    )
    parser.add_argument(
        "root", help="manager root, bundle parent dir, or bundle dir"
    )
    parser.add_argument(
        "--capture",
        action="store_true",
        help="capture a bundle for the root now",
    )
    parser.add_argument(
        "--trigger", default="manual", help="trigger label for --capture"
    )
    parser.add_argument(
        "--reason", default="", help="reason line for --capture"
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    args = parser.parse_args(list(argv) if argv is not None else None)
    if args.capture:
        path = capture_bundle(
            args.root, trigger=args.trigger, reason=args.reason
        )
        if path is None:
            print(
                "bundle capture disabled or rate-limited "
                "(TORCHSNAPSHOT_TPU_BUNDLE_MAX_BYTES <= 0 disables it)"
            )
            return 1
        print(path)
        return 0
    bundles = list_bundles(args.root)
    if args.json:
        print(json.dumps(bundles, indent=2, sort_keys=True))
    else:
        print(render(bundles))
    return 0
