"""Process-coordination store: KV primitives, object collectives, barriers.

Reference parity: torchsnapshot/dist_store.py (TCPStore bootstrap +
``LinearBarrier``). The TPU-native stack has no torch ``c10d`` store, so this
module provides:

- :class:`Store` — the primitive interface (set/get/add/delete) plus object
  collectives built on it. All snapshot coordination traffic is metadata
  (manifests, plans, error reports) — array bytes never travel here.
- :class:`TCPStore` — a self-contained socket KV server hosted by rank 0,
  used by tests and by multi-process CPU/TPU runs without a JAX coordinator.
- :class:`JaxCoordinationStore` — adapter over the JAX distributed runtime's
  coordination-service KV (``jax.distributed``), for real pods.
- :class:`LinearBarrier` — two-phase (arrive/depart) barrier with error
  propagation, safe to use off the main thread; the async-commit primitive
  (reference dist_store.py:91-196, used at snapshot.py:948-969 because the
  background commit thread must not issue collectives).
- :class:`TreeBarrier` — the default production barrier (same contract,
  built by :func:`make_barrier`): arrive/depart aggregate through a
  fanout-``k`` rank tree, so no single key ever has more than ``k``
  writers or readers and the critical path is O(log_k world) instead of
  every rank rendezvousing on the leader's counter.
- :class:`ShardedStore` — N member stores behind deterministic
  key->shard hashing, so a thousand-rank world's key traffic spreads
  over N server sockets instead of serializing through one hub.

Scaling disciplines (docs/scaling.md; measured by
``benchmarks/coordination_scaling.py`` over the scalemodel harness):
every wait loop backs off exponentially (``_PollPacer``, cap ~100 ms)
so an idle 1000-rank barrier doesn't hammer the store at O(world/5ms)
QPS, and multi-key traffic rides the batched ``multi_set`` /
``multi_get`` / ``multi_delete`` primitives — one wire round trip per
*batch*, not per key. Store requests and barrier waits feed the
coordination telemetry (``coordination_*`` counters, ``barrier:*``
spans) that the ``coordination-bound`` doctor rule reads.

Collective keys are transient: the last participant to finish an operation
deletes its keys, so long-lived stores don't leak.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
import pickle
import socket
import socketserver
import struct
import threading
import time
import zlib
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from . import knobs

_DEFAULT_TIMEOUT_S = 300.0
_POLL_INTERVAL_S = 0.005
_POLL_CAP_S = 0.1
_CONNECT_TIMEOUT_S = 30.0

# Process-wide (initial, cap) the pacer binds per instance. Not an
# operator knob: the one consumer is the scale-model harness's legacy
# baseline (initial == cap reproduces the pre-backoff fixed-interval
# polling so its O(world) QPS wall stays measurable after the fix).
_POLL_PROFILE: Tuple[float, float] = (_POLL_INTERVAL_S, _POLL_CAP_S)


def _set_poll_profile(initial: float, cap: float) -> Tuple[float, float]:
    """Swap the process-wide poll profile; returns the previous one.
    Scale-model harness use only — production always runs the backoff
    defaults. Affects pacers constructed AFTER the call."""
    global _POLL_PROFILE
    prev = _POLL_PROFILE
    _POLL_PROFILE = (float(initial), float(cap))
    return prev


# Aggregate idle-poll budget a wait loop sizes its backoff cap against:
# cap ≈ world / _POLL_QPS_BUDGET, clamped to [initial, _POLL_CAP_S]. A
# 2-proc barrier keeps ~5 ms detection latency (the cap would only cost
# it latency — two pollers cannot hammer anything), a 256-rank one backs
# off to ~50 ms, a 1000-rank one to the 100 ms ceiling (~10k QPS fleet-
# wide either way). World-aware call sites (barriers, fan-out rounds)
# pass the scaled cap; plain key waits keep the defaults.
_POLL_QPS_BUDGET = 5000.0


def scaled_poll_cap(world_size: int) -> float:
    profile_initial, profile_cap = _POLL_PROFILE
    return min(
        profile_cap,
        max(profile_initial, world_size / _POLL_QPS_BUDGET),
    )


class _PollPacer:
    """Deadline-aware exponential poll backoff for store wait loops.

    Fixed-interval polling is an O(world) QPS multiplier: a 1000-rank
    barrier polling one key every 5 ms lands 200k requests/s on the
    store while *nothing changes*. Backoff doubles the interval per
    miss up to ~100 ms (late enough that a long wait costs each rank
    ~10 QPS, early enough that release latency stays bounded by the
    cap), never sleeping past the caller's deadline, and resets on
    observation so a busy exchange keeps its low first-poll latency."""

    def __init__(
        self,
        initial: Optional[float] = None,
        cap: Optional[float] = None,
    ) -> None:
        self._initial = _POLL_PROFILE[0] if initial is None else initial
        self._cap = _POLL_PROFILE[1] if cap is None else cap
        self._delay = self._initial

    def reset(self) -> None:
        self._delay = self._initial

    def sleep(self, deadline: Optional[float] = None) -> None:
        delay = self._delay
        if deadline is not None:
            delay = min(delay, max(0.0, deadline - time.monotonic()))
        if delay > 0:
            time.sleep(delay)
        self._delay = min(self._delay * 2.0, self._cap)


# ---------------------------------------------------------------------------
# Coordination telemetry (best-effort; never fails a collective)
# ---------------------------------------------------------------------------

_TELE_MODULES = None


def _tele_modules():
    """(telemetry pkg, names, trace) lazily resolved: dist_store sits
    below the telemetry package in the import graph, so the binding
    happens on first use, never at import time."""
    global _TELE_MODULES
    if _TELE_MODULES is None:
        from . import telemetry as _telemetry
        from .telemetry import names as _names
        from .telemetry import trace as _trace

        _TELE_MODULES = (_telemetry, _names, _trace)
    return _TELE_MODULES


def _observe_store_requests(op: str, seconds: float, requests: int = 1) -> None:
    """One store round trip's worth of coordination accounting. The
    per-op deltas land in SnapshotReport.coordination (report.py), which
    is what the scale-model harness and the ``coordination-bound``
    doctor rule attribute against wall time."""
    try:
        telemetry, n, _ = _tele_modules()
        reg = telemetry.metrics()
        reg.counter_inc(n.COORD_STORE_REQUESTS_TOTAL, float(requests), op=op)
        reg.counter_inc(n.COORD_STORE_SECONDS_TOTAL, seconds, op=op)
    except Exception:  # noqa: BLE001 - telemetry must never break the store
        pass


_WIRE_MODULE = None


def _wire():
    """telemetry.wire lazily resolved (same discipline as
    :func:`_tele_modules`): the wire observatory instruments this
    module's framing layer, but dist_store must stay importable below
    the telemetry package."""
    global _WIRE_MODULE
    if _WIRE_MODULE is None:
        from .telemetry import wire as _wire_mod

        _WIRE_MODULE = _wire_mod
    return _WIRE_MODULE


@dataclass
class ProcessGroup:
    """What :class:`~torchsnapshot_tpu.pg_wrapper.PGWrapper` consumes: a
    store plus this process's coordinates."""

    store: "Store"
    rank: int
    world_size: int


class StoreTimeoutError(TimeoutError):
    pass


class BarrierError(RuntimeError):
    """A peer reported an error into the barrier (reference
    dist_store.py:177-193)."""


_READ_GRACE_S = 5.0


class _TransientReads:
    """Tolerance tracker for deadline-bounded poll loops.

    ``try_get`` raises on transport/service failures (None strictly means
    "key definitively absent"). A poll loop should read a *brief* failure
    as "not yet" — the deadline machinery exists to ride out hiccups —
    but a store failing continuously must re-raise rather than be polled
    until the full deadline: on a TCPStore, a dead socket means the
    leader is gone, and 300 s of retries would mask a peer death."""

    def __init__(self, grace: float = _READ_GRACE_S) -> None:
        self._grace = grace
        self._first_failure: Optional[float] = None

    def read(self, fn):
        """Run ``fn`` (a store read); None if it failed within grace."""
        try:
            out = fn()
        except Exception:
            now = time.monotonic()
            if self._first_failure is None:
                self._first_failure = now
            if now - self._first_failure > self._grace:
                raise
            return None
        self._first_failure = None
        return out


class Store(abc.ABC):
    """KV primitives + derived object collectives."""

    # -- primitives -------------------------------------------------------

    @abc.abstractmethod
    def set(self, key: str, value: bytes) -> None: ...

    @abc.abstractmethod
    def try_get(self, key: str) -> Optional[bytes]:
        """The value, or None when the key is *definitively absent*.
        Raises on transport/service failures — callers distinguishing
        "peer did not signal" from "could not observe" depend on it."""

    @abc.abstractmethod
    def add(self, key: str, amount: int) -> int:
        """Atomically add to an integer key (created at 0); returns the new
        value."""

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    # -- batched primitives ----------------------------------------------
    #
    # Default implementations degrade to per-key loops so every Store
    # (including the JAX coordination-service adapter) supports them;
    # stores with a wire protocol (TCPStore, and ShardedStore per
    # member) override with ONE round trip per batch — the difference
    # between a fan-out round's setup costing O(world) sequential
    # requests and O(1).

    def multi_set(self, items: Dict[str, bytes]) -> None:
        for key, value in items.items():
            self.set(key, value)

    def multi_get(self, keys: Sequence[str]) -> Dict[str, Optional[bytes]]:
        """Value per key (None where definitively absent), same failure
        semantics as :meth:`try_get`."""
        return {key: self.try_get(key) for key in keys}

    def multi_delete(self, keys: Iterable[str]) -> None:
        for key in keys:
            self.delete(key)

    def scan(self, prefix: str) -> List[str]:
        """All present keys starting with ``prefix`` (sorted). Registry
        consumers only (the fleet plane enumerating ``__obs/``) — not
        every backing store can enumerate, so the default refuses
        rather than silently returning nothing."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support prefix scans"
        )

    # -- blocking helpers -------------------------------------------------

    def get(
        self,
        key: str,
        timeout: float = _DEFAULT_TIMEOUT_S,
        poll_cap: Optional[float] = None,
    ) -> bytes:
        """Blocking read with exponential poll backoff. ``poll_cap``
        bounds the backoff (callers that know the world size pass
        :func:`scaled_poll_cap` so a 2-proc collective keeps ~5 ms
        detection latency; the default cap is the 100 ms ceiling)."""
        deadline = time.monotonic() + timeout
        reads = _TransientReads()
        pacer = _PollPacer(cap=poll_cap)
        while True:
            val = reads.read(lambda: self.try_get(key))
            if val is not None:
                return val
            if time.monotonic() > deadline:
                raise StoreTimeoutError(f"Timed out waiting for store key {key!r}")
            pacer.sleep(deadline)

    def wait_any(
        self, keys: Sequence[str], timeout: float = _DEFAULT_TIMEOUT_S
    ) -> Dict[str, bytes]:
        """Block until at least one of ``keys`` exists; returns all present.
        Polls the whole key set in one batched round trip per tick."""
        deadline = time.monotonic() + timeout
        reads = _TransientReads()
        pacer = _PollPacer()
        while True:
            got = reads.read(lambda: self.multi_get(list(keys)))
            present = {
                k: v for k, v in (got or {}).items() if v is not None
            }
            if present:
                return present
            if time.monotonic() > deadline:
                raise StoreTimeoutError(f"Timed out waiting for any of {keys!r}")
            pacer.sleep(deadline)

    # -- object collectives ----------------------------------------------

    def _cleanup(self, prefix: str, world_size: int, keys: List[str]) -> None:
        if self.add(f"{prefix}/__done", 1) == world_size:
            self.multi_delete(keys + [f"{prefix}/__done"])

    def exchange(
        self,
        prefix: str,
        rank: int,
        world_size: int,
        obj: Any,
        timeout: float = _DEFAULT_TIMEOUT_S,
    ) -> List[Any]:
        """All-gather of picklable objects.

        Rank 0 aggregates the per-rank blobs into ONE combined value that
        everyone else fetches with a single get: O(1) store round-trips
        per non-leader rank instead of O(world), so a v4-32-pod manifest
        gather doesn't issue world² sequential requests through the
        leader's socket (the bytes are inherently O(world²) for an
        all-gather; the round-trips need not be).
        """
        cap = scaled_poll_cap(world_size)
        self.set(f"{prefix}/{rank}", pickle.dumps(obj))
        if rank == 0:
            blobs = [
                self.get(f"{prefix}/{i}", timeout, poll_cap=cap)
                for i in range(world_size)
            ]
            out = [pickle.loads(b) for b in blobs]
            self.set(f"{prefix}/__all", pickle.dumps(blobs))
        else:
            out = [
                pickle.loads(b)
                for b in pickle.loads(
                    self.get(f"{prefix}/__all", timeout, poll_cap=cap)
                )
            ]
        self._cleanup(
            prefix,
            world_size,
            [f"{prefix}/{i}" for i in range(world_size)] + [f"{prefix}/__all"],
        )
        return out

    def gather(
        self,
        prefix: str,
        rank: int,
        world_size: int,
        obj: Any,
        dst: int = 0,
        timeout: float = _DEFAULT_TIMEOUT_S,
    ) -> Optional[List[Any]]:
        """Gather picklable objects to ``dst`` (rank order); None elsewhere.

        Unlike :meth:`exchange`, non-destination ranks publish their own
        blob and do NOT fetch the combined value: per non-dst rank the
        store traffic is O(own blob) + one counter bump, not
        O(world x blob) — the difference between a manifest gather that
        funnels world² bytes through the leader's socket and one that
        moves each manifest once (reference analog: the c10d gather the
        reference's snapshot.py:879-901 all_gather spreads peer-to-peer;
        here non-leaders don't need the global manifest at all — rank 0
        alone writes metadata, and restore reads it from storage).
        """
        blob = pickle.dumps(obj)
        out = None
        if rank == dst:
            # The destination's own blob never touches the store (nobody
            # else reads it); the loads() keeps all-gather's copy
            # semantics for the local entry.
            cap = scaled_poll_cap(world_size)
            out = [
                pickle.loads(blob)
                if i == rank
                else pickle.loads(
                    self.get(f"{prefix}/{i}", timeout, poll_cap=cap)
                )
                for i in range(world_size)
            ]
        else:
            self.set(f"{prefix}/{rank}", blob)
        # Keys survive until every rank (dst included, which increments
        # only after reading all blobs) has passed through _cleanup;
        # deleting dst's never-set key is a no-op.
        self._cleanup(
            prefix, world_size, [f"{prefix}/{i}" for i in range(world_size)]
        )
        return out

    def broadcast(
        self,
        prefix: str,
        rank: int,
        world_size: int,
        obj: Any,
        src: int = 0,
        timeout: float = _DEFAULT_TIMEOUT_S,
    ) -> Any:
        if rank == src:
            self.set(f"{prefix}/obj", pickle.dumps(obj))
            out = obj
        else:
            out = pickle.loads(
                self.get(
                    f"{prefix}/obj",
                    timeout,
                    poll_cap=scaled_poll_cap(world_size),
                )
            )
        self._cleanup(prefix, world_size, [f"{prefix}/obj"])
        return out

    def scatter(
        self,
        prefix: str,
        rank: int,
        world_size: int,
        objs: Optional[Sequence[Any]],
        src: int = 0,
        timeout: float = _DEFAULT_TIMEOUT_S,
    ) -> Any:
        if rank == src:
            assert objs is not None and len(objs) == world_size
            for i, o in enumerate(objs):
                self.set(f"{prefix}/{i}", pickle.dumps(o))
        out = pickle.loads(
            self.get(
                f"{prefix}/{rank}", timeout, poll_cap=scaled_poll_cap(world_size)
            )
        )
        self._cleanup(prefix, world_size, [f"{prefix}/{i}" for i in range(world_size)])
        return out

    def barrier(
        self,
        prefix: str,
        rank: int,
        world_size: int,
        timeout: float = _DEFAULT_TIMEOUT_S,
    ) -> None:
        if self.add(f"{prefix}/arrive", 1) == world_size:
            self.set(f"{prefix}/go", b"1")
        else:
            self.get(
                f"{prefix}/go", timeout, poll_cap=scaled_poll_cap(world_size)
            )
        if self.add(f"{prefix}/depart", 1) == world_size:
            for k in (f"{prefix}/arrive", f"{prefix}/go", f"{prefix}/depart"):
                self.delete(k)


# ---------------------------------------------------------------------------
# TCP store
# ---------------------------------------------------------------------------

_CMD_SET, _CMD_TRY_GET, _CMD_ADD, _CMD_DELETE = 0, 1, 2, 3
# Batched commands: one frame each way per BATCH. arg carries the
# key->value dict (multi_set) or key list (multi_get / multi_delete);
# the scalar ``key`` slot of the request tuple is unused ("").
_CMD_MULTI_SET, _CMD_MULTI_GET, _CMD_MULTI_DELETE = 4, 5, 6
# Prefix scan (key enumeration): the fleet metrics plane's reader
# (telemetry/wire.py collect_fleet) discovers `__obs/` publishers with
# it. ``key`` carries the prefix; arg is unused.
_CMD_SCAN = 7

_CMD_OP_NAMES = {
    _CMD_SET: "set",
    _CMD_TRY_GET: "try_get",
    _CMD_ADD: "add",
    _CMD_DELETE: "delete",
    _CMD_MULTI_SET: "multi_set",
    _CMD_MULTI_GET: "multi_get",
    _CMD_MULTI_DELETE: "multi_delete",
    _CMD_SCAN: "scan",
}


def _store_rpc_ids():
    """cmd int -> declared RPC op id (names.RPC_STORE_*), resolved
    lazily so the registry stays the single source of op-id strings."""
    _, n, _ = _tele_modules()
    return {
        _CMD_SET: n.RPC_STORE_SET,
        _CMD_TRY_GET: n.RPC_STORE_TRY_GET,
        _CMD_ADD: n.RPC_STORE_ADD,
        _CMD_DELETE: n.RPC_STORE_DELETE,
        _CMD_MULTI_SET: n.RPC_STORE_MULTI_SET,
        _CMD_MULTI_GET: n.RPC_STORE_MULTI_GET,
        _CMD_MULTI_DELETE: n.RPC_STORE_MULTI_DELETE,
        _CMD_SCAN: n.RPC_STORE_SCAN,
    }


# Chaos-engineering seam (chaos/engine.py install_wire_chaos): when
# set, every frame in BOTH directions passes through the hook — fail /
# delay / corrupt injection over the one framing the TCP store and the
# peer transport share. None in production; reads cost one global load.
_WIRE_CHAOS = None


def send_frame(
    sock: socket.socket, payload: bytes, endpoint: str = "store"
) -> None:
    """Length-prefixed frame write — the one wire framing shared by the
    TCP store and the peer-tier transport (tiered/peer.py), so the two
    socket protocols cannot drift in how they delimit messages.

    Wire observatory (telemetry/wire.py): when the sending thread has
    an active :func:`~torchsnapshot_tpu.telemetry.wire.propagate`
    context, the payload is prefixed with the compact trace header
    BEFORE the chaos hook sees it — chaos corrupts the header exactly
    like real wire damage would, and the receiver degrades it to a
    context-free frame. Frame/byte counts land per ``endpoint``."""
    try:
        w = _wire()
        ctx = w.current_context()
        if ctx is not None:
            payload = w.encode_frame(ctx, payload)
    except Exception:  # noqa: BLE001 - observability never breaks the wire
        pass
    hook = _WIRE_CHAOS
    if hook is not None:
        payload = hook("wire-send", payload)
        if payload is None:
            return  # dropped frame: the receiver waits it out
    try:
        _wire().observe_frame(endpoint, "send", len(payload) + 4)
    except Exception:  # noqa: BLE001 - observability never breaks the wire
        pass
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def recv_frame(sock: socket.socket, endpoint: str = "store") -> bytes:
    header = _recv_exact(sock, 4)
    (length,) = struct.unpack("<I", header)
    payload = _recv_exact(sock, length)
    hook = _WIRE_CHAOS
    if hook is not None:
        payload = hook("wire-recv", payload)
    try:
        w = _wire()
        w.observe_frame(endpoint, "recv", len(payload) + 4)
        ctx, payload = w.decode_frame(payload)
        # Stash (or clear) the inbound context so the handler that
        # processes this frame can link its span to the sender's.
        w.set_received_context(ctx)
    except Exception:  # noqa: BLE001 - observability never breaks the wire
        pass
    return payload


# Internal aliases kept for the store's own call sites.
_send_msg = send_frame
_recv_msg = recv_frame


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(n)
        if not chunk:
            raise ConnectionError("store connection closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class _StoreServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True
    # socketserver's default listen backlog is 5: a thousand-rank world
    # connecting at once overflows the SYN queue and rides kernel
    # connect retries for seconds. Size the backlog for the fleet.
    request_queue_size = 1024

    def __init__(self, addr) -> None:
        super().__init__(addr, _StoreRequestHandler)
        self.kv: Dict[str, bytes] = {}
        self.kv_lock = threading.Lock()
        # Concurrent-handler count: the wire observatory's userspace
        # proxy for accept pressure (the kernel accept queue itself is
        # not portably readable).
        self.active_handlers = 0
        self.active_lock = threading.Lock()


class _StoreRequestHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: _StoreServer = self.server  # type: ignore[assignment]
        with server.active_lock:
            server.active_handlers += 1
            depth = server.active_handlers
        try:
            _wire().observe_accept_depth("store", depth)
        except Exception:  # noqa: BLE001 - observability is best-effort
            pass
        try:
            while True:
                msg = pickle.loads(_recv_msg(self.request))
                cmd, key, arg = msg
                with server.kv_lock:
                    if cmd == _CMD_SET:
                        server.kv[key] = arg
                        reply = None
                    elif cmd == _CMD_TRY_GET:
                        reply = server.kv.get(key)
                    elif cmd == _CMD_ADD:
                        new = int(server.kv.get(key, b"0")) + arg
                        server.kv[key] = str(new).encode()
                        reply = new
                    elif cmd == _CMD_DELETE:
                        server.kv.pop(key, None)
                        reply = None
                    elif cmd == _CMD_MULTI_SET:
                        server.kv.update(arg)
                        reply = None
                    elif cmd == _CMD_MULTI_GET:
                        reply = {k: server.kv.get(k) for k in arg}
                    elif cmd == _CMD_MULTI_DELETE:
                        for k in arg:
                            server.kv.pop(k, None)
                        reply = None
                    elif cmd == _CMD_SCAN:
                        reply = sorted(
                            k for k in server.kv if k.startswith(key)
                        )
                    else:  # pragma: no cover
                        raise ValueError(f"bad store command {cmd}")
                _send_msg(self.request, pickle.dumps(reply))
        except (ConnectionError, EOFError):
            return
        finally:
            with server.active_lock:
                server.active_handlers -= 1


class TCPStore(Store):
    """Socket KV store; rank 0 hosts the server in a daemon thread
    (reference analog: ``get_or_create_store`` bootstrapping a c10d
    TCPStore, dist_store.py:22-88)."""

    def __init__(
        self,
        host: str,
        port: int,
        is_server: bool,
        connect_timeout: float = _CONNECT_TIMEOUT_S,
    ) -> None:
        self._server: Optional[_StoreServer] = None
        self._connect_timeout = connect_timeout
        if is_server:
            self._server = _StoreServer((host, port))
            self.port = self._server.server_address[1]
            self._server_thread = threading.Thread(
                target=self._server.serve_forever, daemon=True
            )
            self._server_thread.start()
        else:
            self.port = port
        self.host = host
        self._sock: Optional[socket.socket] = None
        self._sock_lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            deadline = time.monotonic() + self._connect_timeout
            while True:
                # Per-attempt timeout bounded by the remaining deadline:
                # without it, an unreachable host (firewall DROP, dead
                # VM) sits in the kernel's SYN-retry cycle for minutes
                # and the deadline below never gets a chance to fire.
                remaining = deadline - time.monotonic()
                try:
                    t_dial = time.monotonic()
                    sock = socket.create_connection(
                        (self.host, self.port),
                        timeout=max(0.05, min(5.0, remaining)),
                    )
                    try:
                        # Dial latency per successful attempt: a full
                        # listen backlog shows up here as whole-second
                        # SYN-retransmit quanta (wire-dial-stalled).
                        _wire().observe_dial(
                            "store", time.monotonic() - t_dial
                        )
                    except Exception:  # noqa: BLE001 - best-effort
                        pass
                    # Back to blocking mode: the per-attempt timeout
                    # must not leak into request/response recv calls.
                    sock.settimeout(None)
                    sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                    self._sock = sock
                    break
                except socket.gaierror:
                    # Name resolution failing is a misconfiguration
                    # (typo'd host), not a leader that hasn't bound
                    # yet: fail fast instead of burning the deadline.
                    raise
                except OSError as e:
                    try:
                        _wire().observe_dial("store", 0.0, ok=False)
                    except Exception:  # noqa: BLE001 - best-effort
                        pass
                    # Deadline-bounded with a clear timeout error: a
                    # leader that never comes up must read as "store
                    # unreachable", not as a raw ECONNREFUSED (or a
                    # minutes-late EHOSTUNREACH) from deep inside a
                    # collective.
                    if time.monotonic() > deadline:
                        raise StoreTimeoutError(
                            f"Timed out connecting to store at "
                            f"{self.host}:{self.port} after "
                            f"{self._connect_timeout:.1f}s (is the rank-0 "
                            f"store server up?)"
                        ) from e
                    time.sleep(0.05)
        return self._sock

    def _request(self, cmd: int, key: str, arg: Any = None) -> Any:
        t0 = time.monotonic()
        with self._sock_lock:
            sock = self._connect()
            _send_msg(sock, pickle.dumps((cmd, key, arg)))
            reply = pickle.loads(_recv_msg(sock))
        elapsed = time.monotonic() - t0
        _observe_store_requests(_CMD_OP_NAMES.get(cmd, "other"), elapsed)
        try:
            w = _wire()
            w.observe_rpc("store", _store_rpc_ids()[cmd], elapsed)
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass
        return reply

    def set(self, key: str, value: bytes) -> None:
        self._request(_CMD_SET, key, value)

    def try_get(self, key: str) -> Optional[bytes]:
        return self._request(_CMD_TRY_GET, key)

    def add(self, key: str, amount: int) -> int:
        return self._request(_CMD_ADD, key, amount)

    def delete(self, key: str) -> None:
        self._request(_CMD_DELETE, key)

    def multi_set(self, items: Dict[str, bytes]) -> None:
        self._request(_CMD_MULTI_SET, "", dict(items))

    def multi_get(self, keys: Sequence[str]) -> Dict[str, Optional[bytes]]:
        return self._request(_CMD_MULTI_GET, "", list(keys))

    def multi_delete(self, keys: Iterable[str]) -> None:
        self._request(_CMD_MULTI_DELETE, "", list(keys))

    def scan(self, prefix: str) -> List[str]:
        return self._request(_CMD_SCAN, prefix)

    def close(self) -> None:
        with self._sock_lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class InProcessStore(Store):
    """Thread-shared store for single-process/multi-thread tests."""

    def __init__(self) -> None:
        self._kv: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def set(self, key: str, value: bytes) -> None:
        with self._lock:
            self._kv[key] = value

    def try_get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._kv.get(key)

    def add(self, key: str, amount: int) -> int:
        with self._lock:
            new = int(self._kv.get(key, b"0")) + amount
            self._kv[key] = str(new).encode()
            return new

    def delete(self, key: str) -> None:
        with self._lock:
            self._kv.pop(key, None)

    def multi_set(self, items: Dict[str, bytes]) -> None:
        with self._lock:
            self._kv.update(items)

    def multi_get(self, keys: Sequence[str]) -> Dict[str, Optional[bytes]]:
        with self._lock:
            return {k: self._kv.get(k) for k in keys}

    def multi_delete(self, keys: Iterable[str]) -> None:
        with self._lock:
            for k in keys:
                self._kv.pop(k, None)

    def scan(self, prefix: str) -> List[str]:
        with self._lock:
            return sorted(k for k in self._kv if k.startswith(prefix))


# ---------------------------------------------------------------------------
# Sharded store
# ---------------------------------------------------------------------------


def shard_for_key(key: str, num_shards: int) -> int:
    """Deterministic key->shard routing (crc32, like the fan-out owner
    table — ``hash()`` is process-randomized and MUST NOT be used here:
    every rank has to route a key to the same shard)."""
    return zlib.crc32(key.encode("utf-8", "surrogatepass")) % num_shards


class ShardedStore(Store):
    """N member stores behind deterministic key->shard hashing.

    A single TCPStore hub serializes world x keys traffic through one
    socket's accept/handler path; sharding spreads the key space over N
    independent servers so coordination throughput scales with N. Every
    primitive routes by :func:`shard_for_key`; per-key atomicity (``add``,
    the collectives' cleanup counters) holds because a key always lands
    on the same member. Batched ops are grouped per shard — one round
    trip per *touched shard*, not per key. Collectives/barriers from the
    base class work unchanged: they are built on the primitives.
    """

    def __init__(self, stores: Sequence[Store]) -> None:
        if not stores:
            raise ValueError("ShardedStore needs at least one member store")
        self._stores: List[Store] = list(stores)

    @property
    def num_shards(self) -> int:
        return len(self._stores)

    def _count_shard(self, shard: int, requests: int = 1) -> None:
        """Per-shard request accounting: the skew evidence behind the
        ``store-hot-shard`` doctor rule and the fleet snapshot's
        ``store_shards`` split."""
        try:
            telemetry, n, _ = _tele_modules()
            telemetry.metrics().counter_inc(
                n.COORD_STORE_SHARD_REQUESTS_TOTAL,
                float(requests),
                shard=str(shard),
            )
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass

    def _member(self, key: str) -> Store:
        shard = shard_for_key(key, len(self._stores))
        self._count_shard(shard)
        return self._stores[shard]

    def _group(self, keys: Iterable[str]) -> Dict[int, List[str]]:
        grouped: Dict[int, List[str]] = {}
        for key in keys:
            grouped.setdefault(
                shard_for_key(key, len(self._stores)), []
            ).append(key)
        for shard in grouped:
            self._count_shard(shard)
        return grouped

    def set(self, key: str, value: bytes) -> None:
        self._member(key).set(key, value)

    def try_get(self, key: str) -> Optional[bytes]:
        return self._member(key).try_get(key)

    def add(self, key: str, amount: int) -> int:
        return self._member(key).add(key, amount)

    def delete(self, key: str) -> None:
        self._member(key).delete(key)

    def multi_set(self, items: Dict[str, bytes]) -> None:
        for shard, keys in self._group(items).items():
            self._stores[shard].multi_set({k: items[k] for k in keys})

    def multi_get(self, keys: Sequence[str]) -> Dict[str, Optional[bytes]]:
        out: Dict[str, Optional[bytes]] = {}
        for shard, shard_keys in self._group(keys).items():
            out.update(self._stores[shard].multi_get(shard_keys))
        return out

    def multi_delete(self, keys: Iterable[str]) -> None:
        for shard, shard_keys in self._group(keys).items():
            self._stores[shard].multi_delete(shard_keys)

    def scan(self, prefix: str) -> List[str]:
        out: List[str] = []
        for member in self._stores:
            out.extend(member.scan(prefix))
        return sorted(set(out))

    def close(self) -> None:
        for member in self._stores:
            close = getattr(member, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 - best-effort teardown
                    pass


def bootstrap_sharded_store(
    base: Store,
    rank: int,
    world_size: int,
    num_shards: Optional[int] = None,
    prefix: str = "__ts/shard_store",
    timeout: float = _DEFAULT_TIMEOUT_S,
) -> Store:
    """Stand up a :class:`ShardedStore` of TCPStore members over an
    existing coordination store (which only needs ``set``/``get``).

    Rank 0's knob reading decides the shard count for the whole job —
    published through ``base`` exactly like the TCPStore-bootstrap
    address (the same agreement-by-broadcast discipline as the fan-out
    nonce): env skew across ranks can never split the key space two
    ways. Shard ``i`` is hosted by rank ``i % world_size``, so on a
    multi-host pod the server sockets spread across hosts instead of
    stacking on the leader. ``num_shards <= 1`` returns ``base``
    unchanged (the packaged default)."""
    if rank == 0:
        if num_shards is None:
            num_shards = knobs.get_store_shards()
        num_shards = max(1, min(int(num_shards), world_size * 8))
        base.set(f"{prefix}/n", str(num_shards).encode())
    else:
        num_shards = int(base.get(f"{prefix}/n", timeout))
    if num_shards <= 1:
        return base
    members: List[Optional[Store]] = [None] * num_shards
    for i in range(num_shards):
        if i % world_size != rank:
            continue
        # THIS rank's own interface, not _routable_host(): its first
        # choice is the coordinator (rank 0's) address, which is the
        # wrong advert for a shard server bound on any other host.
        host = _local_advertise_host()
        tcp = TCPStore(host="0.0.0.0", port=0, is_server=True)
        tcp.host = host
        base.set(f"{prefix}/{i}", f"{host}:{tcp.port}".encode())
        members[i] = tcp
    for i in range(num_shards):
        if members[i] is not None:
            continue
        host, port = base.get(f"{prefix}/{i}", timeout).decode().rsplit(":", 1)
        members[i] = TCPStore(host=host, port=int(port), is_server=False)
    return ShardedStore([m for m in members if m is not None])


class JaxCoordinationStore(Store):
    """KV store over the JAX distributed coordination service.

    Usable once ``jax.distributed.initialize`` has run; rides DCN like the
    rest of JAX's control plane. Atomic counters require the coordination
    client's ``key_value_increment`` (present in current jaxlib); on an
    older jaxlib without it, ``add`` raises and snapshot coordination
    should use :class:`TCPStore` instead.
    """

    def __init__(self) -> None:
        import uuid

        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "jax.distributed is not initialized; "
                "JaxCoordinationStore requires a coordinator"
            )
        self._client = client
        # Self-check the absent-key classification NOW: try_get maps the
        # coordination service's NOT_FOUND status to None by matching the
        # status token in the raised exception. A jaxlib that words the
        # absent-key status differently would otherwise turn EVERY
        # absent-key poll into a raise — after the _TransientReads grace,
        # all barriers and preemption polls on real pods would fail, a
        # silent total-breakage mode whose cause (message wording) sits
        # far from its symptom. Probing a key that provably was never set
        # makes the mismatch loud at construction instead.
        probe = f"__ts_absent_probe/{uuid.uuid4().hex}"
        try:
            val = self.try_get(probe)
        except Exception as e:
            raise RuntimeError(
                "JaxCoordinationStore: absent-key probe failed — either "
                "this jaxlib reports an absent key in a way try_get does "
                "not classify as NOT_FOUND, or the coordination service "
                "is unreachable. Use TCPStore coordination instead "
                f"(probe raised {e!r})."
            ) from e
        if val is not None:
            raise RuntimeError(
                "JaxCoordinationStore: absent-key probe returned a value "
                f"({val!r}) for a key that was never set; refusing to use "
                "a store with broken get semantics"
            )

    def set(self, key: str, value: bytes) -> None:
        self._client.key_value_set_bytes(key, value)

    def try_get(self, key: str) -> Optional[bytes]:
        try:
            return bytes(self._client.key_value_try_get_bytes(key))
        except Exception as e:
            # Only "key absent" maps to None (the coordination service
            # reports it as a NOT_FOUND status; match the status token or
            # a NotFound exception type so a jaxlib that re-words the
            # message still classifies correctly). A transport/service
            # failure must raise: callers read None as "peer did not
            # signal", and conflating the two turns an unhealthy
            # coordinator into a false all-clear exactly where the signal
            # matters (e.g. the preemption grace check before a lone save).
            msg = str(e).lower()
            if (
                "not_found" in msg
                or "not found" in msg
                or "notfound" in type(e).__name__.lower()
            ):
                return None
            raise

    def supports_add(self) -> bool:
        """Whether this jaxlib's coordination client has atomic increment.
        ``add`` is load-bearing for every collective's cleanup and for
        ``Store.barrier``, so a runtime without it must be detected at
        :func:`jax_process_group` time (which then bootstraps a TCPStore
        through the KV service — set/get are always available), not
        mid-collective."""
        return getattr(self._client, "key_value_increment", None) is not None

    def add(self, key: str, amount: int) -> int:
        inc = getattr(self._client, "key_value_increment", None)
        if inc is not None:
            return int(inc(key, amount))
        raise NotImplementedError(
            "This jaxlib's coordination client lacks atomic increment; "
            "use TCPStore for snapshot coordination instead"
        )

    def delete(self, key: str) -> None:
        try:
            self._client.key_value_delete(key)
        except Exception:
            pass


def jax_process_group():
    """The process group for a ``jax.distributed``-initialized job: rank
    and world from the JAX runtime, coordination over its KV service —
    no address side-channel to plumb. This is how multi-host TPU pods
    hand ``pg=`` to ``Snapshot.take``/``CheckpointManager``::

        jax.distributed.initialize()
        pg = jax_process_group()
        ts.Snapshot.take(path, app_state, pg=pg)

    (Reference analog: get_or_create_store reusing the c10d default
    TCPStore, dist_store.py:22-88.)

    On a jaxlib whose coordination client lacks atomic increment, a
    TCPStore is bootstrapped through the KV service transparently (rank 0
    hosts, publishes its address via set; everyone else gets it) — the
    failure mode otherwise would be a ``NotImplementedError`` surfacing
    mid-collective, far from its cause.

    The result is cached per process: repeated calls return the SAME
    ProcessGroup (hence the same store object). This keeps the ``__pg/*``
    op-seq namespace shared across call sites, and — on the TCPStore
    fallback path — prevents a second call from bootstrapping a second
    server under the same address key and splitting ranks between the two.
    """
    global _JAX_PG
    with _JAX_PG_LOCK:
        if _JAX_PG is not None:
            return _JAX_PG
        import jax

        rank = jax.process_index()
        world = jax.process_count()
        kv = JaxCoordinationStore()
        store: Store = kv
        if not kv.supports_add():
            store = _bootstrap_tcp_store(kv, rank)
        # Store sharding (docs/scaling.md): rank 0's knob decides the
        # shard count for the whole job; the members bootstrap through
        # the KV service like the TCPStore fallback. Default 1 = no-op.
        if world > 1:
            store = bootstrap_sharded_store(store, rank, world)
        _JAX_PG = ProcessGroup(
            store=store,
            rank=rank,
            world_size=world,
        )
        return _JAX_PG


_JAX_PG: Optional[ProcessGroup] = None
_JAX_PG_LOCK = threading.Lock()


def _routable_host() -> str:
    """An address peers on other hosts can dial for RANK 0's machine.
    The jax coordinator address is best (rank 0 of jax.distributed
    hosts the coordinator, and every process demonstrably reached it);
    else this machine's own interface. Only correct on the rank that
    hosts the coordinator — any-rank servers advertise via
    :func:`_local_advertise_host` instead."""
    try:
        from jax._src import distributed

        addr = getattr(distributed.global_state, "coordinator_address", None)
        if addr:
            return addr.rsplit(":", 1)[0]
    except Exception:
        pass
    return _local_advertise_host()


def _local_advertise_host() -> str:
    """An address peers on other hosts can dial for THIS machine —
    correct on any rank. Unlike :func:`_routable_host` (whose first
    choice is the jax coordinator address — right only for the rank
    that HOSTS the coordinator, i.e. rank 0's TCP-store bootstrap), a
    per-rank server (shard store member, peer-tier cache) must
    advertise its own interface: outbound-interface IP first (the UDP
    connect sends no traffic), hostname last."""
    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            probe.connect(("8.8.8.8", 80))
            return probe.getsockname()[0]
        finally:
            probe.close()
    except Exception:
        return socket.gethostname()


def _bootstrap_tcp_store(
    kv: Store, rank: int, timeout: float = _DEFAULT_TIMEOUT_S
) -> "TCPStore":
    """Bootstrap a TCPStore using only ``set``/``get`` of ``kv`` (the two
    primitives every coordination KV has): rank 0 binds a free port and
    publishes ``host:port``; the rest fetch and connect."""
    addr_key = "__ts/tcp_store_addr"
    if rank == 0:
        host = _routable_host()
        tcp = TCPStore(host="0.0.0.0", port=0, is_server=True)
        tcp.host = host  # clients (and rank 0's own socket) dial this addr
        kv.set(addr_key, f"{host}:{tcp.port}".encode())
        return tcp
    host, port = kv.get(addr_key, timeout).decode().rsplit(":", 1)
    return TCPStore(host=host, port=int(port), is_server=False)


# ---------------------------------------------------------------------------
# Endpoint registry (peer-tier transport bootstrap)
# ---------------------------------------------------------------------------

_ENDPOINT_PREFIX = "__endpoint"


def publish_endpoint(
    store: Store, service: str, rank: int, host: str, port: int
) -> None:
    """Advertise a per-rank network endpoint through the coordination
    store. Unlike collective keys, endpoint keys are a *registry*: they
    are overwritten on re-publish (a replacement rank re-announces
    itself after a preemption under the same rank id) and never
    cleaned up by a counter — a surviving peer must stay discoverable
    for the whole run. Nonce-free by design: the rank id IS the
    identity the ring placement keys on."""
    store.set(f"{_ENDPOINT_PREFIX}/{service}/{rank}", f"{host}:{port}".encode())


def lookup_endpoint(
    store: Store, service: str, rank: int
) -> Optional[Tuple[str, int]]:
    """The advertised ``(host, port)`` for ``rank``, or None when the
    rank never published (or the store read failed — an unreachable
    registry must read as "no endpoint", never raise into a restore
    that can correctly proceed without peers)."""
    try:
        raw = store.try_get(f"{_ENDPOINT_PREFIX}/{service}/{rank}")
    except Exception:
        return None
    if raw is None:
        return None
    return _parse_endpoint(raw)


def _parse_endpoint(raw: bytes) -> Optional[Tuple[str, int]]:
    try:
        host, port = raw.decode().rsplit(":", 1)
        return host, int(port)
    except (ValueError, UnicodeDecodeError):
        return None


def lookup_endpoints(
    store: Store, service: str, ranks: Iterable[int]
) -> Dict[int, Tuple[str, int]]:
    """Batched registry resolve: every advertised ``(host, port)`` for
    ``ranks``, in ONE ``multi_get`` round trip — restore setup resolving
    a thousand surviving peers costs one store request, not a thousand
    sequential lookups. Ranks that never published (or whose entries are
    garbage) are simply absent from the result; a failed store read
    returns ``{}`` (same "no endpoint, never raise" contract as
    :func:`lookup_endpoint`). The resolve wall time feeds the
    ``coordination_endpoint_seconds_total`` counter."""
    rank_list = list(ranks)
    keys = [f"{_ENDPOINT_PREFIX}/{service}/{r}" for r in rank_list]
    t0 = time.monotonic()
    try:
        got = store.multi_get(keys)
    except Exception:
        return {}
    finally:
        try:
            telemetry, n, _ = _tele_modules()
            telemetry.metrics().counter_inc(
                n.COORD_ENDPOINT_SECONDS_TOTAL,
                time.monotonic() - t0,
                service=service,
            )
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass
    out: Dict[int, Tuple[str, int]] = {}
    for rank, key in zip(rank_list, keys):
        raw = got.get(key)
        if raw is None:
            continue
        parsed = _parse_endpoint(raw)
        if parsed is not None:
            out[rank] = parsed
    return out


# ---------------------------------------------------------------------------
# Barriers
# ---------------------------------------------------------------------------


class StoreBarrier:
    """Shared two-phase (arrive/depart) barrier machinery with error
    propagation. Subclasses implement ``_phase`` (the rendezvous
    topology) and ``_cleanup`` (post-depart key removal); the contract —
    usable off the main thread, ``report_error`` poisons every peer's
    pending/future wait with :class:`BarrierError`, ``depart`` before
    ``arrive`` raises — is identical across topologies, so call sites
    (snapshot.py's ``_nonce_barrier``, fanout rounds) swap
    transparently via :func:`make_barrier`. Every phase is traced
    (``barrier:arrive``/``barrier:depart`` spans) and its wall time
    feeds ``coordination_barrier_wait_seconds_total`` — the evidence the
    ``coordination-bound`` doctor rule cites.
    """

    _IMPL = "base"

    def __init__(
        self, prefix: str, store: Store, rank: int, world_size: int
    ) -> None:
        self.prefix = prefix
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self._arrived = False

    def _key(self, name: str) -> str:
        return f"{self.prefix}/{name}"

    def _check_error(self, reads: Optional[_TransientReads] = None) -> None:
        # One-shot call sites (no shared tracker) still get single-hiccup
        # tolerance from a fresh tracker: the first failed read returns
        # None ("no error seen"), matching the pre-strict-try_get
        # semantics; only a shared tracker accumulating failures past the
        # grace re-raises.
        if reads is None:
            reads = _TransientReads()
        err = reads.read(lambda: self.store.try_get(self._key("error")))
        if err is not None:
            exc = pickle.loads(err)
            raise BarrierError(
                f"Rank {self.rank}: a peer reported an error into barrier "
                f"{self.prefix!r}"
            ) from exc

    def _wait_for(self, key: str, timeout: float) -> None:
        """Deadline-aware wait with exponential poll backoff (see
        ``_PollPacer``): a 1000-rank barrier parked here must idle at
        ~10 QPS per rank, not 200/s."""
        deadline = time.monotonic() + timeout
        reads = _TransientReads()
        pacer = _PollPacer(cap=scaled_poll_cap(self.world_size))
        while True:
            got = reads.read(
                lambda: self.store.multi_get([self._key("error"), key])
            )
            if got is not None:
                err = got.get(self._key("error"))
                if err is not None:
                    self._raise_peer_error(err)
                if got.get(key) is not None:
                    return
            if time.monotonic() > deadline:
                raise StoreTimeoutError(
                    f"Rank {self.rank} timed out in barrier {self.prefix!r} "
                    f"waiting for {key!r}"
                )
            pacer.sleep(deadline)

    def _raise_peer_error(self, payload: bytes) -> None:
        exc = pickle.loads(payload)
        raise BarrierError(
            f"Rank {self.rank}: a peer reported an error into barrier "
            f"{self.prefix!r}"
        ) from exc

    def _wait_count(self, key: str, target: int, timeout: float) -> None:
        """Poll ONE counter key until it reaches ``target``: the waiter's
        cost is O(1) store requests per poll regardless of world size
        (a per-rank-key scan would be world−1 sequential requests per
        tick — minutes of pure polling on a large pod). Error key and
        counter ride one batched round trip."""
        if target <= 0:
            self._check_error()
            return
        deadline = time.monotonic() + timeout
        reads = _TransientReads()
        pacer = _PollPacer(cap=scaled_poll_cap(self.world_size))
        while True:
            got = reads.read(
                lambda: self.store.multi_get([self._key("error"), key])
            )
            if got is not None:
                err = got.get(self._key("error"))
                if err is not None:
                    self._raise_peer_error(err)
                val = got.get(key)
                if val is not None and int(val) >= target:
                    return
            if time.monotonic() > deadline:
                raise StoreTimeoutError(
                    f"Rank {self.rank} timed out in barrier {self.prefix!r} "
                    f"waiting for {key!r} to reach {target}"
                )
            pacer.sleep(deadline)

    def _phase(self, phase: str, timeout: float) -> None:
        raise NotImplementedError

    def _cleanup(self, timeout: float) -> None:
        raise NotImplementedError

    def _observed_phase(self, phase: str, timeout: float) -> None:
        t0 = time.monotonic()
        token = None
        tele = n = trace = None
        try:
            tele, n, trace = _tele_modules()
            token = trace.get_recorder().begin(
                n.SPAN_BARRIER_ARRIVE
                if phase == "arrive"
                else n.SPAN_BARRIER_DEPART,
                prefix=self.prefix,
                rank=self.rank,
                world=self.world_size,
                impl=self._IMPL,
            )
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            token = None
        try:
            self._phase(phase, timeout)
        finally:
            try:
                if token is not None:
                    trace.get_recorder().end(token)
                if tele is not None:
                    tele.metrics().counter_inc(
                        n.COORD_BARRIER_WAIT_SECONDS_TOTAL,
                        time.monotonic() - t0,
                        phase=phase,
                        impl=self._IMPL,
                    )
            except Exception:  # noqa: BLE001 - telemetry is best-effort
                pass

    def arrive(self, timeout: float = _DEFAULT_TIMEOUT_S) -> None:
        self._observed_phase("arrive", timeout)
        self._arrived = True

    def depart(self, timeout: float = _DEFAULT_TIMEOUT_S) -> None:
        if not self._arrived:
            raise RuntimeError("depart() called before arrive()")
        self._observed_phase("depart", timeout)
        self._cleanup(timeout)

    def report_error(self, exc: BaseException) -> None:
        try:
            payload = pickle.dumps(exc)
        except Exception:
            payload = pickle.dumps(RuntimeError(repr(exc)))
        self.store.set(self._key("error"), payload)


class LinearBarrier(StoreBarrier):
    """Two-phase leader-centric barrier with error propagation.

    Reference parity: dist_store.py:91-196. Phase one (``arrive``):
    followers deposit into one counter, the leader observes all deposits
    then releases one ``go`` key. Phase two (``depart``): mirrored. Kept
    behind the ``TORCHSNAPSHOT_TPU_TREE_BARRIER=0`` kill switch (see
    :func:`make_barrier`): per-rank round trips are O(1), but every rank
    rendezvouses on the leader's two keys, so at large world sizes the
    hub store serializes world waiters per phase — the wall the
    scale-model bench convicts (docs/scaling.md).
    """

    _IMPL = "linear"

    def _phase(self, phase: str, timeout: float) -> None:
        if self.rank == 0:
            self._wait_count(
                self._key(f"{phase}/count"), self.world_size - 1, timeout
            )
            self.store.set(self._key(f"{phase}/go"), b"1")
        else:
            self._check_error()
            self.store.add(self._key(f"{phase}/count"), 1)
            self._wait_for(self._key(f"{phase}/go"), timeout)

    def _cleanup(self, timeout: float) -> None:
        """Best-effort removal of this barrier's keys after a successful
        depart so a long-lived store doesn't accumulate them. Followers ack
        that they are past the depart release before the leader deletes."""
        try:
            if self.rank != 0:
                self.store.add(self._key("done/count"), 1)
                return
            self._wait_count(
                self._key("done/count"), self.world_size - 1, timeout
            )
            self.store.multi_delete(
                [
                    self._key(f"{phase}/{part}")
                    for phase in ("arrive", "depart", "done")
                    for part in ("count", "go")
                ]
                + [self._key("error")]
            )
        except Exception:  # pragma: no cover - cleanup must never fail a commit
            pass


class TreeBarrier(StoreBarrier):
    """Tree-structured two-phase barrier: O(log_k world) critical path,
    no key with more than ``fanout`` writers or readers.

    Ranks form an implicit ``fanout``-ary tree (children of ``r`` are
    ``r*k+1 .. r*k+k``). Per phase, a rank (1) waits for its own counter
    to reach its child count — each child increments it only after its
    whole subtree arrived — (2) increments its parent's counter, (3)
    waits for its release key, then (4) releases its children with one
    batched ``multi_set``. The aggregate store load stays O(world) per
    phase (it must — every rank signals once), but it spreads over
    world/k distinct keys (shardable via :class:`ShardedStore`) instead
    of rendezvousing on the leader's one counter, and the release wave
    is a k-way broadcast tree instead of world ranks polling one key.

    Same contract as :class:`LinearBarrier` (``report_error`` poisons
    every pending wait via the shared ``{prefix}/error`` key, which is
    also the error channel fan-out rounds poll).
    """

    _IMPL = "tree"

    def __init__(
        self,
        prefix: str,
        store: Store,
        rank: int,
        world_size: int,
        fanout: Optional[int] = None,
    ) -> None:
        super().__init__(prefix, store, rank, world_size)
        if fanout is None:
            fanout = knobs.get_barrier_fanout()
        self.fanout = max(2, int(fanout))

    def _children(self) -> List[int]:
        base = self.rank * self.fanout
        return [
            child
            for child in range(base + 1, base + self.fanout + 1)
            if child < self.world_size
        ]

    def _phase(self, phase: str, timeout: float) -> None:
        children = self._children()
        if children:
            self._wait_count(
                self._key(f"{phase}/c/{self.rank}"), len(children), timeout
            )
        if self.rank != 0:
            self._check_error()
            parent = (self.rank - 1) // self.fanout
            self.store.add(self._key(f"{phase}/c/{parent}"), 1)
            self._wait_for(self._key(f"{phase}/go/{self.rank}"), timeout)
        if children:
            self.store.multi_set(
                {self._key(f"{phase}/go/{child}"): b"1" for child in children}
            )

    def _cleanup(self, timeout: float) -> None:
        """Each rank deletes ITS OWN keys — no done-counter rendezvous
        needed: a rank's counter was last written before it observed the
        target (children increment before waiting for release), and its
        release key was last written before it returned from the wait,
        so after this rank's depart nobody touches them again."""
        try:
            keys = [
                self._key(f"{phase}/{part}/{self.rank}")
                for phase in ("arrive", "depart")
                for part in ("c", "go")
            ]
            if self.rank == 0:
                keys.append(self._key("error"))
            self.store.multi_delete(keys)
        except Exception:  # pragma: no cover - cleanup must never fail a commit
            pass


def make_barrier(
    prefix: str, store: Store, rank: int, world_size: int
) -> StoreBarrier:
    """The blessed barrier constructor for every coordination phase:
    :class:`TreeBarrier` (default; fanout from
    ``TORCHSNAPSHOT_TPU_BARRIER_FANOUT``) unless the
    ``TORCHSNAPSHOT_TPU_TREE_BARRIER=0`` kill switch selects the
    leader-centric :class:`LinearBarrier`. Rank-uniform inputs only —
    both knobs are tunables the autotuner moves through the broadcast
    vector, so geometries can't mix mid-run."""
    if knobs.is_tree_barrier_enabled():
        return TreeBarrier(prefix, store, rank, world_size)
    return LinearBarrier(prefix, store, rank, world_size)
