"""Process-coordination store: KV primitives, object collectives, barriers.

Reference parity: torchsnapshot/dist_store.py (TCPStore bootstrap +
``LinearBarrier``). The TPU-native stack has no torch ``c10d`` store, so this
module provides:

- :class:`Store` — the primitive interface (set/get/add/delete) plus object
  collectives built on it. All snapshot coordination traffic is metadata
  (manifests, plans, error reports) — array bytes never travel here.
- :class:`TCPStore` — a self-contained socket KV server hosted by rank 0,
  used by tests and by multi-process CPU/TPU runs without a JAX coordinator.
- :class:`JaxCoordinationStore` — adapter over the JAX distributed runtime's
  coordination-service KV (``jax.distributed``), for real pods.
- :class:`LinearBarrier` — two-phase (arrive/depart) barrier with error
  propagation, safe to use off the main thread; the async-commit primitive
  (reference dist_store.py:91-196, used at snapshot.py:948-969 because the
  background commit thread must not issue collectives).

Collective keys are transient: the last participant to finish an operation
deletes its keys, so long-lived stores don't leak.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

_DEFAULT_TIMEOUT_S = 300.0
_POLL_INTERVAL_S = 0.005
_CONNECT_TIMEOUT_S = 30.0


@dataclass
class ProcessGroup:
    """What :class:`~torchsnapshot_tpu.pg_wrapper.PGWrapper` consumes: a
    store plus this process's coordinates."""

    store: "Store"
    rank: int
    world_size: int


class StoreTimeoutError(TimeoutError):
    pass


class BarrierError(RuntimeError):
    """A peer reported an error into the barrier (reference
    dist_store.py:177-193)."""


_READ_GRACE_S = 5.0


class _TransientReads:
    """Tolerance tracker for deadline-bounded poll loops.

    ``try_get`` raises on transport/service failures (None strictly means
    "key definitively absent"). A poll loop should read a *brief* failure
    as "not yet" — the deadline machinery exists to ride out hiccups —
    but a store failing continuously must re-raise rather than be polled
    until the full deadline: on a TCPStore, a dead socket means the
    leader is gone, and 300 s of retries would mask a peer death."""

    def __init__(self, grace: float = _READ_GRACE_S) -> None:
        self._grace = grace
        self._first_failure: Optional[float] = None

    def read(self, fn):
        """Run ``fn`` (a store read); None if it failed within grace."""
        try:
            out = fn()
        except Exception:
            now = time.monotonic()
            if self._first_failure is None:
                self._first_failure = now
            if now - self._first_failure > self._grace:
                raise
            return None
        self._first_failure = None
        return out


class Store(abc.ABC):
    """KV primitives + derived object collectives."""

    # -- primitives -------------------------------------------------------

    @abc.abstractmethod
    def set(self, key: str, value: bytes) -> None: ...

    @abc.abstractmethod
    def try_get(self, key: str) -> Optional[bytes]:
        """The value, or None when the key is *definitively absent*.
        Raises on transport/service failures — callers distinguishing
        "peer did not signal" from "could not observe" depend on it."""

    @abc.abstractmethod
    def add(self, key: str, amount: int) -> int:
        """Atomically add to an integer key (created at 0); returns the new
        value."""

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    # -- blocking helpers -------------------------------------------------

    def get(self, key: str, timeout: float = _DEFAULT_TIMEOUT_S) -> bytes:
        deadline = time.monotonic() + timeout
        reads = _TransientReads()
        while True:
            val = reads.read(lambda: self.try_get(key))
            if val is not None:
                return val
            if time.monotonic() > deadline:
                raise StoreTimeoutError(f"Timed out waiting for store key {key!r}")
            time.sleep(_POLL_INTERVAL_S)

    def wait_any(
        self, keys: Sequence[str], timeout: float = _DEFAULT_TIMEOUT_S
    ) -> Dict[str, bytes]:
        """Block until at least one of ``keys`` exists; returns all present."""
        deadline = time.monotonic() + timeout
        reads = _TransientReads()
        while True:
            present = {}
            for k in keys:
                v = reads.read(lambda k=k: self.try_get(k))
                if v is not None:
                    present[k] = v
            if present:
                return present
            if time.monotonic() > deadline:
                raise StoreTimeoutError(f"Timed out waiting for any of {keys!r}")
            time.sleep(_POLL_INTERVAL_S)

    # -- object collectives ----------------------------------------------

    def _cleanup(self, prefix: str, world_size: int, keys: List[str]) -> None:
        if self.add(f"{prefix}/__done", 1) == world_size:
            for k in keys + [f"{prefix}/__done"]:
                self.delete(k)

    def exchange(
        self,
        prefix: str,
        rank: int,
        world_size: int,
        obj: Any,
        timeout: float = _DEFAULT_TIMEOUT_S,
    ) -> List[Any]:
        """All-gather of picklable objects.

        Rank 0 aggregates the per-rank blobs into ONE combined value that
        everyone else fetches with a single get: O(1) store round-trips
        per non-leader rank instead of O(world), so a v4-32-pod manifest
        gather doesn't issue world² sequential requests through the
        leader's socket (the bytes are inherently O(world²) for an
        all-gather; the round-trips need not be).
        """
        self.set(f"{prefix}/{rank}", pickle.dumps(obj))
        if rank == 0:
            blobs = [
                self.get(f"{prefix}/{i}", timeout) for i in range(world_size)
            ]
            out = [pickle.loads(b) for b in blobs]
            self.set(f"{prefix}/__all", pickle.dumps(blobs))
        else:
            out = [
                pickle.loads(b)
                for b in pickle.loads(self.get(f"{prefix}/__all", timeout))
            ]
        self._cleanup(
            prefix,
            world_size,
            [f"{prefix}/{i}" for i in range(world_size)] + [f"{prefix}/__all"],
        )
        return out

    def gather(
        self,
        prefix: str,
        rank: int,
        world_size: int,
        obj: Any,
        dst: int = 0,
        timeout: float = _DEFAULT_TIMEOUT_S,
    ) -> Optional[List[Any]]:
        """Gather picklable objects to ``dst`` (rank order); None elsewhere.

        Unlike :meth:`exchange`, non-destination ranks publish their own
        blob and do NOT fetch the combined value: per non-dst rank the
        store traffic is O(own blob) + one counter bump, not
        O(world x blob) — the difference between a manifest gather that
        funnels world² bytes through the leader's socket and one that
        moves each manifest once (reference analog: the c10d gather the
        reference's snapshot.py:879-901 all_gather spreads peer-to-peer;
        here non-leaders don't need the global manifest at all — rank 0
        alone writes metadata, and restore reads it from storage).
        """
        blob = pickle.dumps(obj)
        out = None
        if rank == dst:
            # The destination's own blob never touches the store (nobody
            # else reads it); the loads() keeps all-gather's copy
            # semantics for the local entry.
            out = [
                pickle.loads(blob)
                if i == rank
                else pickle.loads(self.get(f"{prefix}/{i}", timeout))
                for i in range(world_size)
            ]
        else:
            self.set(f"{prefix}/{rank}", blob)
        # Keys survive until every rank (dst included, which increments
        # only after reading all blobs) has passed through _cleanup;
        # deleting dst's never-set key is a no-op.
        self._cleanup(
            prefix, world_size, [f"{prefix}/{i}" for i in range(world_size)]
        )
        return out

    def broadcast(
        self,
        prefix: str,
        rank: int,
        world_size: int,
        obj: Any,
        src: int = 0,
        timeout: float = _DEFAULT_TIMEOUT_S,
    ) -> Any:
        if rank == src:
            self.set(f"{prefix}/obj", pickle.dumps(obj))
            out = obj
        else:
            out = pickle.loads(self.get(f"{prefix}/obj", timeout))
        self._cleanup(prefix, world_size, [f"{prefix}/obj"])
        return out

    def scatter(
        self,
        prefix: str,
        rank: int,
        world_size: int,
        objs: Optional[Sequence[Any]],
        src: int = 0,
        timeout: float = _DEFAULT_TIMEOUT_S,
    ) -> Any:
        if rank == src:
            assert objs is not None and len(objs) == world_size
            for i, o in enumerate(objs):
                self.set(f"{prefix}/{i}", pickle.dumps(o))
        out = pickle.loads(self.get(f"{prefix}/{rank}", timeout))
        self._cleanup(prefix, world_size, [f"{prefix}/{i}" for i in range(world_size)])
        return out

    def barrier(
        self,
        prefix: str,
        rank: int,
        world_size: int,
        timeout: float = _DEFAULT_TIMEOUT_S,
    ) -> None:
        if self.add(f"{prefix}/arrive", 1) == world_size:
            self.set(f"{prefix}/go", b"1")
        else:
            self.get(f"{prefix}/go", timeout)
        if self.add(f"{prefix}/depart", 1) == world_size:
            for k in (f"{prefix}/arrive", f"{prefix}/go", f"{prefix}/depart"):
                self.delete(k)


# ---------------------------------------------------------------------------
# TCP store
# ---------------------------------------------------------------------------

_CMD_SET, _CMD_TRY_GET, _CMD_ADD, _CMD_DELETE = 0, 1, 2, 3


def send_frame(sock: socket.socket, payload: bytes) -> None:
    """Length-prefixed frame write — the one wire framing shared by the
    TCP store and the peer-tier transport (tiered/peer.py), so the two
    socket protocols cannot drift in how they delimit messages."""
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def recv_frame(sock: socket.socket) -> bytes:
    header = _recv_exact(sock, 4)
    (length,) = struct.unpack("<I", header)
    return _recv_exact(sock, length)


# Internal aliases kept for the store's own call sites.
_send_msg = send_frame
_recv_msg = recv_frame


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(n)
        if not chunk:
            raise ConnectionError("store connection closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class _StoreServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr) -> None:
        super().__init__(addr, _StoreRequestHandler)
        self.kv: Dict[str, bytes] = {}
        self.kv_lock = threading.Lock()


class _StoreRequestHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: _StoreServer = self.server  # type: ignore[assignment]
        try:
            while True:
                msg = pickle.loads(_recv_msg(self.request))
                cmd, key, arg = msg
                with server.kv_lock:
                    if cmd == _CMD_SET:
                        server.kv[key] = arg
                        reply = None
                    elif cmd == _CMD_TRY_GET:
                        reply = server.kv.get(key)
                    elif cmd == _CMD_ADD:
                        new = int(server.kv.get(key, b"0")) + arg
                        server.kv[key] = str(new).encode()
                        reply = new
                    elif cmd == _CMD_DELETE:
                        server.kv.pop(key, None)
                        reply = None
                    else:  # pragma: no cover
                        raise ValueError(f"bad store command {cmd}")
                _send_msg(self.request, pickle.dumps(reply))
        except (ConnectionError, EOFError):
            return


class TCPStore(Store):
    """Socket KV store; rank 0 hosts the server in a daemon thread
    (reference analog: ``get_or_create_store`` bootstrapping a c10d
    TCPStore, dist_store.py:22-88)."""

    def __init__(
        self,
        host: str,
        port: int,
        is_server: bool,
        connect_timeout: float = _CONNECT_TIMEOUT_S,
    ) -> None:
        self._server: Optional[_StoreServer] = None
        self._connect_timeout = connect_timeout
        if is_server:
            self._server = _StoreServer((host, port))
            self.port = self._server.server_address[1]
            self._server_thread = threading.Thread(
                target=self._server.serve_forever, daemon=True
            )
            self._server_thread.start()
        else:
            self.port = port
        self.host = host
        self._sock: Optional[socket.socket] = None
        self._sock_lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            deadline = time.monotonic() + self._connect_timeout
            while True:
                # Per-attempt timeout bounded by the remaining deadline:
                # without it, an unreachable host (firewall DROP, dead
                # VM) sits in the kernel's SYN-retry cycle for minutes
                # and the deadline below never gets a chance to fire.
                remaining = deadline - time.monotonic()
                try:
                    sock = socket.create_connection(
                        (self.host, self.port),
                        timeout=max(0.05, min(5.0, remaining)),
                    )
                    # Back to blocking mode: the per-attempt timeout
                    # must not leak into request/response recv calls.
                    sock.settimeout(None)
                    sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                    self._sock = sock
                    break
                except socket.gaierror:
                    # Name resolution failing is a misconfiguration
                    # (typo'd host), not a leader that hasn't bound
                    # yet: fail fast instead of burning the deadline.
                    raise
                except OSError as e:
                    # Deadline-bounded with a clear timeout error: a
                    # leader that never comes up must read as "store
                    # unreachable", not as a raw ECONNREFUSED (or a
                    # minutes-late EHOSTUNREACH) from deep inside a
                    # collective.
                    if time.monotonic() > deadline:
                        raise StoreTimeoutError(
                            f"Timed out connecting to store at "
                            f"{self.host}:{self.port} after "
                            f"{self._connect_timeout:.1f}s (is the rank-0 "
                            f"store server up?)"
                        ) from e
                    time.sleep(0.05)
        return self._sock

    def _request(self, cmd: int, key: str, arg: Any = None) -> Any:
        with self._sock_lock:
            sock = self._connect()
            _send_msg(sock, pickle.dumps((cmd, key, arg)))
            return pickle.loads(_recv_msg(sock))

    def set(self, key: str, value: bytes) -> None:
        self._request(_CMD_SET, key, value)

    def try_get(self, key: str) -> Optional[bytes]:
        return self._request(_CMD_TRY_GET, key)

    def add(self, key: str, amount: int) -> int:
        return self._request(_CMD_ADD, key, amount)

    def delete(self, key: str) -> None:
        self._request(_CMD_DELETE, key)

    def close(self) -> None:
        with self._sock_lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class InProcessStore(Store):
    """Thread-shared store for single-process/multi-thread tests."""

    def __init__(self) -> None:
        self._kv: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def set(self, key: str, value: bytes) -> None:
        with self._lock:
            self._kv[key] = value

    def try_get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._kv.get(key)

    def add(self, key: str, amount: int) -> int:
        with self._lock:
            new = int(self._kv.get(key, b"0")) + amount
            self._kv[key] = str(new).encode()
            return new

    def delete(self, key: str) -> None:
        with self._lock:
            self._kv.pop(key, None)


class JaxCoordinationStore(Store):
    """KV store over the JAX distributed coordination service.

    Usable once ``jax.distributed.initialize`` has run; rides DCN like the
    rest of JAX's control plane. Atomic counters require the coordination
    client's ``key_value_increment`` (present in current jaxlib); on an
    older jaxlib without it, ``add`` raises and snapshot coordination
    should use :class:`TCPStore` instead.
    """

    def __init__(self) -> None:
        import uuid

        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "jax.distributed is not initialized; "
                "JaxCoordinationStore requires a coordinator"
            )
        self._client = client
        # Self-check the absent-key classification NOW: try_get maps the
        # coordination service's NOT_FOUND status to None by matching the
        # status token in the raised exception. A jaxlib that words the
        # absent-key status differently would otherwise turn EVERY
        # absent-key poll into a raise — after the _TransientReads grace,
        # all barriers and preemption polls on real pods would fail, a
        # silent total-breakage mode whose cause (message wording) sits
        # far from its symptom. Probing a key that provably was never set
        # makes the mismatch loud at construction instead.
        probe = f"__ts_absent_probe/{uuid.uuid4().hex}"
        try:
            val = self.try_get(probe)
        except Exception as e:
            raise RuntimeError(
                "JaxCoordinationStore: absent-key probe failed — either "
                "this jaxlib reports an absent key in a way try_get does "
                "not classify as NOT_FOUND, or the coordination service "
                "is unreachable. Use TCPStore coordination instead "
                f"(probe raised {e!r})."
            ) from e
        if val is not None:
            raise RuntimeError(
                "JaxCoordinationStore: absent-key probe returned a value "
                f"({val!r}) for a key that was never set; refusing to use "
                "a store with broken get semantics"
            )

    def set(self, key: str, value: bytes) -> None:
        self._client.key_value_set_bytes(key, value)

    def try_get(self, key: str) -> Optional[bytes]:
        try:
            return bytes(self._client.key_value_try_get_bytes(key))
        except Exception as e:
            # Only "key absent" maps to None (the coordination service
            # reports it as a NOT_FOUND status; match the status token or
            # a NotFound exception type so a jaxlib that re-words the
            # message still classifies correctly). A transport/service
            # failure must raise: callers read None as "peer did not
            # signal", and conflating the two turns an unhealthy
            # coordinator into a false all-clear exactly where the signal
            # matters (e.g. the preemption grace check before a lone save).
            msg = str(e).lower()
            if (
                "not_found" in msg
                or "not found" in msg
                or "notfound" in type(e).__name__.lower()
            ):
                return None
            raise

    def supports_add(self) -> bool:
        """Whether this jaxlib's coordination client has atomic increment.
        ``add`` is load-bearing for every collective's cleanup and for
        ``Store.barrier``, so a runtime without it must be detected at
        :func:`jax_process_group` time (which then bootstraps a TCPStore
        through the KV service — set/get are always available), not
        mid-collective."""
        return getattr(self._client, "key_value_increment", None) is not None

    def add(self, key: str, amount: int) -> int:
        inc = getattr(self._client, "key_value_increment", None)
        if inc is not None:
            return int(inc(key, amount))
        raise NotImplementedError(
            "This jaxlib's coordination client lacks atomic increment; "
            "use TCPStore for snapshot coordination instead"
        )

    def delete(self, key: str) -> None:
        try:
            self._client.key_value_delete(key)
        except Exception:
            pass


def jax_process_group():
    """The process group for a ``jax.distributed``-initialized job: rank
    and world from the JAX runtime, coordination over its KV service —
    no address side-channel to plumb. This is how multi-host TPU pods
    hand ``pg=`` to ``Snapshot.take``/``CheckpointManager``::

        jax.distributed.initialize()
        pg = jax_process_group()
        ts.Snapshot.take(path, app_state, pg=pg)

    (Reference analog: get_or_create_store reusing the c10d default
    TCPStore, dist_store.py:22-88.)

    On a jaxlib whose coordination client lacks atomic increment, a
    TCPStore is bootstrapped through the KV service transparently (rank 0
    hosts, publishes its address via set; everyone else gets it) — the
    failure mode otherwise would be a ``NotImplementedError`` surfacing
    mid-collective, far from its cause.

    The result is cached per process: repeated calls return the SAME
    ProcessGroup (hence the same store object). This keeps the ``__pg/*``
    op-seq namespace shared across call sites, and — on the TCPStore
    fallback path — prevents a second call from bootstrapping a second
    server under the same address key and splitting ranks between the two.
    """
    global _JAX_PG
    with _JAX_PG_LOCK:
        if _JAX_PG is not None:
            return _JAX_PG
        import jax

        rank = jax.process_index()
        kv = JaxCoordinationStore()
        store: Store = kv
        if not kv.supports_add():
            store = _bootstrap_tcp_store(kv, rank)
        _JAX_PG = ProcessGroup(
            store=store,
            rank=rank,
            world_size=jax.process_count(),
        )
        return _JAX_PG


_JAX_PG: Optional[ProcessGroup] = None
_JAX_PG_LOCK = threading.Lock()


def _routable_host() -> str:
    """An address peers on other hosts can dial for this machine. The jax
    coordinator address is best (rank 0 of jax.distributed hosts the
    coordinator, and every process demonstrably reached it); else the
    outbound-interface IP (UDP connect sends no traffic); hostname last."""
    try:
        from jax._src import distributed

        addr = getattr(distributed.global_state, "coordinator_address", None)
        if addr:
            return addr.rsplit(":", 1)[0]
    except Exception:
        pass
    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            probe.connect(("8.8.8.8", 80))
            return probe.getsockname()[0]
        finally:
            probe.close()
    except Exception:
        return socket.gethostname()


def _bootstrap_tcp_store(
    kv: Store, rank: int, timeout: float = _DEFAULT_TIMEOUT_S
) -> "TCPStore":
    """Bootstrap a TCPStore using only ``set``/``get`` of ``kv`` (the two
    primitives every coordination KV has): rank 0 binds a free port and
    publishes ``host:port``; the rest fetch and connect."""
    addr_key = "__ts/tcp_store_addr"
    if rank == 0:
        host = _routable_host()
        tcp = TCPStore(host="0.0.0.0", port=0, is_server=True)
        tcp.host = host  # clients (and rank 0's own socket) dial this addr
        kv.set(addr_key, f"{host}:{tcp.port}".encode())
        return tcp
    host, port = kv.get(addr_key, timeout).decode().rsplit(":", 1)
    return TCPStore(host=host, port=int(port), is_server=False)


# ---------------------------------------------------------------------------
# Endpoint registry (peer-tier transport bootstrap)
# ---------------------------------------------------------------------------

_ENDPOINT_PREFIX = "__endpoint"


def publish_endpoint(
    store: Store, service: str, rank: int, host: str, port: int
) -> None:
    """Advertise a per-rank network endpoint through the coordination
    store. Unlike collective keys, endpoint keys are a *registry*: they
    are overwritten on re-publish (a replacement rank re-announces
    itself after a preemption under the same rank id) and never
    cleaned up by a counter — a surviving peer must stay discoverable
    for the whole run. Nonce-free by design: the rank id IS the
    identity the ring placement keys on."""
    store.set(f"{_ENDPOINT_PREFIX}/{service}/{rank}", f"{host}:{port}".encode())


def lookup_endpoint(
    store: Store, service: str, rank: int
) -> Optional[Tuple[str, int]]:
    """The advertised ``(host, port)`` for ``rank``, or None when the
    rank never published (or the store read failed — an unreachable
    registry must read as "no endpoint", never raise into a restore
    that can correctly proceed without peers)."""
    try:
        raw = store.try_get(f"{_ENDPOINT_PREFIX}/{service}/{rank}")
    except Exception:
        return None
    if raw is None:
        return None
    try:
        host, port = raw.decode().rsplit(":", 1)
        return host, int(port)
    except (ValueError, UnicodeDecodeError):
        return None


# ---------------------------------------------------------------------------
# LinearBarrier
# ---------------------------------------------------------------------------


class LinearBarrier:
    """Two-phase leader-centric barrier with error propagation.

    Reference parity: dist_store.py:91-196. Usable off the main thread (the
    async-snapshot commit thread must not run collectives). Phase one
    (``arrive``): followers deposit, the leader collects all deposits then
    releases. Phase two (``depart``): mirrored. ``report_error`` poisons the
    barrier: every peer's pending/future wait raises :class:`BarrierError`
    so no rank commits.
    """

    def __init__(
        self, prefix: str, store: Store, rank: int, world_size: int
    ) -> None:
        self.prefix = prefix
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self._arrived = False

    def _key(self, name: str) -> str:
        return f"{self.prefix}/{name}"

    def _check_error(self, reads: Optional[_TransientReads] = None) -> None:
        # One-shot call sites (no shared tracker) still get single-hiccup
        # tolerance from a fresh tracker: the first failed read returns
        # None ("no error seen"), matching the pre-strict-try_get
        # semantics; only a shared tracker accumulating failures past the
        # grace re-raises.
        if reads is None:
            reads = _TransientReads()
        err = reads.read(lambda: self.store.try_get(self._key("error")))
        if err is not None:
            exc = pickle.loads(err)
            raise BarrierError(
                f"Rank {self.rank}: a peer reported an error into barrier "
                f"{self.prefix!r}"
            ) from exc

    def _wait_for(self, key: str, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        reads = _TransientReads()
        while True:
            self._check_error(reads)
            if reads.read(lambda: self.store.try_get(key)) is not None:
                return
            if time.monotonic() > deadline:
                raise StoreTimeoutError(
                    f"Rank {self.rank} timed out in barrier {self.prefix!r} "
                    f"waiting for {key!r}"
                )
            time.sleep(_POLL_INTERVAL_S)

    def _wait_count(self, key: str, target: int, timeout: float) -> None:
        """Poll ONE counter key until it reaches ``target``: the leader's
        wait is O(1) store requests per poll regardless of world size
        (a per-rank-key scan would be world−1 sequential requests per
        5 ms tick — minutes of pure polling on a large pod)."""
        if target <= 0:
            self._check_error()
            return
        deadline = time.monotonic() + timeout
        reads = _TransientReads()
        while True:
            self._check_error(reads)
            val = reads.read(lambda: self.store.try_get(key))
            if val is not None and int(val) >= target:
                return
            if time.monotonic() > deadline:
                raise StoreTimeoutError(
                    f"Rank {self.rank} timed out in barrier {self.prefix!r} "
                    f"waiting for {key!r} to reach {target}"
                )
            time.sleep(_POLL_INTERVAL_S)

    def _phase(self, phase: str, timeout: float) -> None:
        if self.rank == 0:
            self._wait_count(
                self._key(f"{phase}/count"), self.world_size - 1, timeout
            )
            self.store.set(self._key(f"{phase}/go"), b"1")
        else:
            self._check_error()
            self.store.add(self._key(f"{phase}/count"), 1)
            self._wait_for(self._key(f"{phase}/go"), timeout)

    def arrive(self, timeout: float = _DEFAULT_TIMEOUT_S) -> None:
        self._phase("arrive", timeout)
        self._arrived = True

    def depart(self, timeout: float = _DEFAULT_TIMEOUT_S) -> None:
        if not self._arrived:
            raise RuntimeError("depart() called before arrive()")
        self._phase("depart", timeout)
        self._cleanup(timeout)

    def _cleanup(self, timeout: float) -> None:
        """Best-effort removal of this barrier's keys after a successful
        depart so a long-lived store doesn't accumulate them. Followers ack
        that they are past the depart release before the leader deletes."""
        try:
            if self.rank != 0:
                self.store.add(self._key("done/count"), 1)
                return
            self._wait_count(
                self._key("done/count"), self.world_size - 1, timeout
            )
            for phase in ("arrive", "depart", "done"):
                self.store.delete(self._key(f"{phase}/count"))
                self.store.delete(self._key(f"{phase}/go"))
            self.store.delete(self._key("error"))
        except Exception:  # pragma: no cover - cleanup must never fail a commit
            pass

    def report_error(self, exc: BaseException) -> None:
        try:
            payload = pickle.dumps(exc)
        except Exception:
            payload = pickle.dumps(RuntimeError(repr(exc)))
        self.store.set(self._key("error"), payload)
