"""CheckpointManager: step-numbered snapshots with retention and
latest-step resume.

The reference exposes only the single-snapshot primitives
(snapshot.py:175-243) and its examples hand-roll the loop around them
(examples/simple_example.py:59-76: restore-if-exists, then periodic
takes). This module packages that loop the way TPU training jobs use it:

    mgr = CheckpointManager(root, keep_last_n=3)
    start = mgr.restore_latest(app_state)          # None on a fresh run
    for step in range(start or 0, total_steps):
        ...
        if step % save_every == 0:
            mgr.save(step, app_state)              # or async_save

Storage-agnostic: steps live at ``{root}/step_{step:010d}`` and the
committed-step list is a rank-0-maintained ``.manager_index`` JSON blob
(storage plugins have no directory listing, so the index is the source
of truth; a step whose take crashed before commit never enters it and is
invisible to restore). Retention deletes every blob named by the dropped
step's manifest — the commit marker first, so a half-deleted step can
never be mistaken for a valid one.

Incremental mode (``incremental=True``, or per-save): each save records
on-device digests and references the previous committed step's unchanged
chunks instead of rewriting them (incremental.py). The index additionally
tracks which origin steps each step's manifest references; retention
*pins* a dropped step whose blobs are still referenced by a retained step
(blobs stay, step leaves the visible list) and deletes it as soon as no
retained step references it — so incremental chains never dangle and
storage is reclaimed exactly when safe.
"""

from __future__ import annotations

import asyncio
import json
import logging
import re
import threading
from typing import Any, Dict, List, Optional, Set

from . import knobs, telemetry
from .chaos import crashpoint as _crashpoint
from .event_loop import run_in_fresh_event_loop
from .telemetry import names as metric_names
from .io_types import ReadIO, StoragePlugin, WriteIO
from .manifest import (
    ChunkedArrayEntry,
    Entry,
    Manifest,
    ShardedArrayEntry,
    SnapshotMetadata,
    entry_locations,
)
from .pg_wrapper import PGWrapper
from .snapshot import SNAPSHOT_METADATA_FNAME, PendingSnapshot, Snapshot
from .stateful import AppState
from .storage_plugin import join_path, split_tiered_url, url_to_storage_plugin

logger: logging.Logger = logging.getLogger(__name__)

INDEX_BLOB = ".manager_index"
INDEX_BACKUP_BLOB = ".manager_index.backup"


def _step_dirname(step: int) -> str:
    return f"step_{step:010d}"


_REF_LOCATION_RE = re.compile(r"^\.\./step_(\d+)/")


def referenced_steps(manifest: Manifest) -> Set[int]:
    """Origin steps an (incremental) snapshot's manifest references.
    Chained refs collapse at take time (incremental.py), so locations
    always name the originating step directly."""
    out: Set[int] = set()
    for entry in manifest.values():
        for location in _entry_locations(entry):
            m = _REF_LOCATION_RE.match(location)
            if m:
                out.add(int(m.group(1)))
    return out


# Re-exported for existing importers; the implementation moved to
# manifest.entry_locations (the CAS refcount derivation needs it below
# the manager layer).
_entry_locations = entry_locations


def _manifest_chunk_refs(manifest: Manifest) -> Dict[str, int]:
    """The content-addressed chunks a manifest references (digest key ->
    nbytes); empty for legacy-layout snapshots."""
    from .cas import chunk_refs

    return chunk_refs(manifest)


def _manifest_digest_map(manifest: Manifest) -> Dict[Any, Any]:
    """Every on-device digest a manifest records, keyed by structural
    position ``(manifest path, offsets, sizes)`` with the covered byte
    count — comparing two consecutive steps' maps measures how much of
    the state the digests say was unchanged (ledger evidence for the
    dedup-ineffective doctor rule). Empty for digest-less takes."""
    from .serialization import array_size_bytes

    out: Dict[Any, Any] = {}
    for path, entry in manifest.items():
        if isinstance(entry, (ShardedArrayEntry, ChunkedArrayEntry)):
            pieces = (
                entry.shards
                if isinstance(entry, ShardedArrayEntry)
                else entry.chunks
            )
            for piece in pieces:
                if piece.array.digest:
                    out[(path, tuple(piece.offsets), tuple(piece.sizes))] = (
                        piece.array.digest,
                        array_size_bytes(
                            piece.array.shape, piece.array.dtype
                        ),
                    )
        else:
            digest = getattr(entry, "digest", None)
            if digest:
                out[(path, (), ())] = (
                    digest,
                    array_size_bytes(entry.shape, entry.dtype),
                )
    return out


async def read_index_full_async(storage: StoragePlugin) -> Dict[str, Any]:
    """Primary slot, falling back to the backup slot: the index is
    rewritten on every save (backup slot first), so a crash mid-write
    must not brick the manager — whichever slot survives is valid,
    at worst one save stale. Returns ``{"steps": [...], "refs":
    {step: [origin steps]}, "pinned": [...]}``; the latter two default
    empty for pre-incremental indexes. Module-level so read-only
    consumers (``fsck --cas``) share the exact recovery semantics."""
    io_failed: List[str] = []
    corrupt: List[str] = []
    absent: List[str] = []
    for slot in (INDEX_BLOB, INDEX_BACKUP_BLOB):
        read_io = ReadIO(path=slot)
        try:
            await storage.read(read_io)
        except FileNotFoundError:
            absent.append(slot)
            continue
        except Exception as e:  # noqa: BLE001
            logger.warning("Could not read index slot %s: %r", slot, e)
            io_failed.append(slot)
            continue
        if read_io.buf is None:
            absent.append(slot)
            continue
        try:
            raw = json.loads(bytes(read_io.buf))
            return {
                "steps": sorted(int(s) for s in raw["steps"]),
                "refs": {
                    str(int(k)): sorted(int(v) for v in vs)
                    for k, vs in raw.get("refs", {}).items()
                },
                "pinned": sorted(int(p) for p in raw.get("pinned", [])),
                "metrics": {
                    str(int(k)): float(v)
                    for k, v in raw.get("metrics", {}).items()
                },
                "evicted": sorted(
                    int(s) for s in raw.get("evicted", [])
                ),
                # Pre-marker indexes with committed steps may predate
                # incremental-ref recording entirely: a missing refs
                # entry there means "unknown" and GC must verify before
                # deleting. A fresh (empty) index is trivially complete.
                "refs_complete": bool(
                    raw.get("refs_complete", not raw["steps"])
                ),
            }
        except (ValueError, KeyError, TypeError) as e:
            logger.warning(
                "Index slot %s is corrupt (%r); trying %s",
                slot,
                e,
                INDEX_BACKUP_BLOB,
            )
            corrupt.append(slot)
    # "Slots absent" (fresh directory) yields []. One corrupt slot with
    # the OTHER slot absent is the same thing: writes go backup-then-
    # primary (_write_index_async), so that state can only be a torn
    # FIRST-ever index write — no step list was ever readable; self-
    # recover.  Everything else ("slots unreadable": transient I/O
    # errors, or BOTH slots corrupt) must NOT be treated as empty — a
    # subsequent index rewrite would silently orphan every previously
    # committed step.  Fail the operation loudly instead; a transient
    # storage error heals on retry.
    if io_failed or len(corrupt) > 1:
        raise RuntimeError(
            "checkpoint index unreadable "
            f"(io_failed={io_failed!r}, corrupt={corrupt!r}); "
            "refusing to treat the step list as empty"
        )
    return {
        "steps": [], "refs": {}, "pinned": [], "metrics": {},
        "evicted": [], "refs_complete": True,
    }


class _PendingManagedSnapshot:
    """Wraps a PendingSnapshot so index update + retention run once the
    background commit succeeds."""

    def __init__(
        self,
        manager: "CheckpointManager",
        step: int,
        pending: PendingSnapshot,
        metric: Optional[float] = None,
    ):
        self._manager = manager
        self._step = step
        self._pending = pending
        self._metric = metric
        self._committed = False
        self._commit_lock = threading.Lock()

    def wait(self, phase: str = "committed") -> Optional[Snapshot]:
        """Passes ``phase`` through to :meth:`PendingSnapshot.wait`.
        Index update + retention run only on the ``"committed"`` wait —
        a ``"staged"`` wait observes D2H completion without making the
        step visible to ``restore_latest`` (the drain paths that flush
        checkpoints before teardown must wait for ``"committed"``, and
        this wrapper's default does)."""
        if phase not in ("staged", "committed"):
            # Same contract as PendingSnapshot.wait: a typo'd phase must
            # not silently become a committed wait with index/retention
            # side effects.
            raise ValueError(
                f'phase must be "staged" or "committed", got {phase!r}'
            )
        if phase == "staged":
            self._pending.wait(phase="staged")
            return None
        snapshot = self._pending.wait()  # raises on failed take: no index entry
        # Idempotent join, lock-guarded: wait() may be called from more
        # than one place (progress loop + shutdown path, possibly on
        # different threads) and must commit + record history exactly
        # once — a duplicate history record widens the trend baseline.
        with self._commit_lock:
            if self._committed:
                return snapshot
            self._manager._commit_step(
                self._step,
                refs=lambda: referenced_steps(snapshot.metadata.manifest),
                metric=self._metric,
                chunk_refs=lambda: _manifest_chunk_refs(
                    snapshot.metadata.manifest
                ),
            )
            telemetry.metrics().counter_inc(metric_names.MANAGER_SAVES_TOTAL)
            self._manager._record_step_history(self._step)
            self._manager._post_step_ledger(self._step, snapshot)
            self._manager._evaluate_slos(self._step)
            self._manager._publish_cdn_step(self._step, snapshot)
            self._manager._autotune_step(self._step)
            self._committed = True
        return snapshot

    def done(self) -> bool:
        return self._pending.done()

    def staged(self) -> bool:
        return self._pending.staged()


class _ManagedPendingRestore:
    """Wraps a PendingRestore so the restore's telemetry summary lands
    in the manager's step history once the apply succeeds — the
    async-restore report is only emitted at ``wait()`` time (the apply
    runs on the calling thread), so the recording must ride the same
    call. Delegates everything else to the wrapped handle."""

    def __init__(self, manager: "CheckpointManager", step: int, pending: Any):
        self._manager = manager
        self._step = step
        self._pending = pending
        self._recorded = False

    def wait(self) -> None:
        out = self._pending.wait()
        if not self._recorded:
            self._recorded = True
            self._manager._record_restore_history(self._step)
        return out

    def done(self) -> bool:
        return self._pending.done()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._pending, name)


class CheckpointManager:
    def __init__(
        self,
        root: str,
        keep_last_n: Optional[int] = None,
        pg: Optional[Any] = None,
        incremental: bool = False,
        keep_best_n: Optional[int] = None,
        best_mode: str = "min",
        keep_fast_last_n: Optional[int] = None,
        keep_peer_last_n: Optional[int] = None,
        cdn_topic: Optional[str] = None,
        cdn_store: Optional[Any] = None,
    ) -> None:
        if keep_last_n is not None and keep_last_n < 1:
            raise ValueError(f"keep_last_n must be >= 1, got {keep_last_n}")
        if keep_best_n is not None and keep_best_n < 1:
            raise ValueError(f"keep_best_n must be >= 1, got {keep_best_n}")
        if keep_peer_last_n is not None and keep_peer_last_n < 1:
            raise ValueError(
                f"keep_peer_last_n must be >= 1, got {keep_peer_last_n}"
            )
        if keep_fast_last_n is not None and keep_fast_last_n < 1:
            raise ValueError(
                f"keep_fast_last_n must be >= 1, got {keep_fast_last_n}"
            )
        if keep_fast_last_n is not None and split_tiered_url(root) is None:
            raise ValueError(
                "keep_fast_last_n requires a tiered:// root (fast-tier "
                "eviction needs a durable tier to fall back to)"
            )
        if best_mode not in ("min", "max"):
            raise ValueError(f"best_mode must be 'min' or 'max', got {best_mode}")
        self.root = root
        self.keep_last_n = keep_last_n
        # Metric-driven retention: steps saved with a ``metric=`` keep the
        # best ``keep_best_n`` scores (``best_mode``: lower- or
        # higher-is-better) IN ADDITION to the newest ``keep_last_n`` —
        # the "checkpoint the best eval loss" loop without hand-rolled GC.
        # When only keep_best_n is set, unscored steps are never GC'd
        # (see _retained).
        self.keep_best_n = keep_best_n
        self.best_mode = best_mode
        # Tier-aware retention (tiered:// roots only): retained steps
        # older than the newest ``keep_fast_last_n`` are dropped from the
        # FAST tier once durable-complete — they stay committed and
        # restorable through the per-blob durable fallback. A step is
        # never evicted before its durable commit marker exists, and the
        # durable tier is only ever touched by the normal retention GC
        # (which the index's pin logic already guards for incremental
        # refs).
        self.keep_fast_last_n = keep_fast_last_n
        # Peer-RAM retention (docs/peer.md): each rank's neighbor keeps
        # the newest N committed steps' shards in its host-RAM cache.
        # Default None = no count bound — the cache's byte budget (LRU
        # with the newest committed step pinned) is then the only
        # limit; set N=1 to keep exactly the step restore_latest would
        # pick and nothing older.
        self.keep_peer_last_n = keep_peer_last_n
        # Default for save()/async_save(): digest-enabled takes that
        # reference the previous committed step's unchanged chunks.
        self.incremental = incremental
        # One wrapper for the manager's own collectives; Snapshot calls get
        # the raw pg and build their own wrappers — safe because the op
        # sequence is shared across wrappers of the same pg (pg_wrapper).
        self._pg_arg = pg
        self._pg = PGWrapper(pg)
        # Peer-tier bring-up (tiered/peer.py): start this process's
        # cache server and advertise its endpoint through the
        # coordination store. Inert for single-process jobs, under the
        # TORCHSNAPSHOT_TPU_PEER_TIER=0 kill switch, or when pg carries
        # no store; failures degrade (the tier is recovery insurance,
        # never a reason a manager cannot construct).
        try:
            from .tiered import peer as peer_tier

            peer_tier.maybe_configure(
                self._pg, keep_last_n=keep_peer_last_n
            )
        except Exception as e:  # noqa: BLE001 - peer tier is best-effort
            logger.warning("peer tier: configure failed: %r", e)
        # Content-addressed chunk store (docs/cas.md): lazily-resolved
        # rank-0 handle over the root's ``chunks/`` refcount journal.
        # False = unresolved; None = root has no local tier (no CAS).
        # Resolution is evidence-driven, not knob-driven: a root holding
        # CAS steps from an earlier run keeps refcounted GC even with
        # the knob now off.
        self._cas_store: Any = False
        # Checkpoint CDN publish side (docs/cdn.md): with the CDN knob
        # on and a topic named, rank 0 announces every committed step's
        # chunk set to the coordination store so a serving fleet can
        # track the run. ``cdn_store`` overrides the pg's store (tests,
        # cross-job stores). Publisher is built lazily on first commit
        # — constructing a manager must not touch the store.
        self.cdn_topic = cdn_topic
        self._cdn_store_arg = cdn_store
        self._cdn_publisher: Any = None
        # Exact per-step storage accounting computed at commit time
        # (chunks newly materialized vs. reused), read back by
        # _post_step_ledger; and the previous committed manifest's
        # digest map, for the ledger's bytes_digest_unchanged signal.
        self._last_cas_accounting: Optional[Dict[str, Any]] = None
        self._prev_digest_map: Dict[str, Any] = {}
        if self._pg.get_rank() == 0:
            try:
                self._reconcile_cas()
            except Exception as e:  # noqa: BLE001 - healing is best-effort
                logger.warning("CAS refcount reconcile failed: %r", e)
        # Lazily-constructed write-path autotuner (tuner/autotuner.py);
        # stays None while TORCHSNAPSHOT_TPU_AUTOTUNE=0 — the kill
        # switch means no tuner object, no state file, no broadcast.
        self._autotuner: Optional[Any] = None
        # Run-level goodput ledger (telemetry/ledger.py): rank 0 opens
        # (or, after a restart/preemption, resumes) the run — the
        # run-start event anchors every segment's wall-time attribution
        # and registers this process as the root's only ledger writer.
        # None while TORCHSNAPSHOT_TPU_LEDGER=0 (no file appears).
        self._ledger_run_id: Optional[str] = None
        if knobs.is_ledger_enabled() and self._pg.get_rank() == 0:
            try:
                from .telemetry import ledger as run_ledger

                self._ledger_run_id = run_ledger.open_run(
                    self.root, world_size=self._pg.get_world_size()
                )
            except Exception as e:  # noqa: BLE001 - ledger is best-effort
                logger.warning("could not open the run ledger: %r", e)

    # ------------------------------------------------------------------
    # saving
    # ------------------------------------------------------------------

    def step_path(self, step: int) -> str:
        # join_path is tiered-aware: with a tiered:// root, the step
        # segment lands on BOTH tiers' roots.
        return join_path(self.root, _step_dirname(step))

    def _incremental_take_kwargs(
        self, incremental: Optional[bool], take_kwargs: Dict[str, Any]
    ) -> Dict[str, Any]:
        """Resolve the per-save incremental setting and, when on, point the
        take at the latest committed step. Rank 0 resolves the base and
        everyone follows — ranks must never diff against different bases."""
        if incremental is None:
            incremental = self.incremental
        if not incremental:
            return take_kwargs
        if "incremental_base" in take_kwargs:
            return {**take_kwargs, "record_digests": True}
        base_step = (
            self.latest_step() if self._pg.get_rank() == 0 else None
        )
        base_step = self._pg.broadcast_object(base_step)
        out = {**take_kwargs, "record_digests": True}
        if base_step is not None:
            out["incremental_base"] = self.step_path(base_step)
        return out

    def save(
        self,
        step: int,
        app_state: AppState,
        incremental: Optional[bool] = None,
        metric: Optional[float] = None,
        **take_kwargs: Any,
    ) -> Snapshot:
        """Synchronous checkpoint of ``step``; updates the index and
        applies retention after the commit. ``incremental`` overrides the
        manager-level default for this save; ``metric`` records this
        step's score for ``keep_best_n`` retention and ``best_step()``
        (rank 0's value is authoritative)."""
        self._validate_metric(metric)
        take_kwargs = self._incremental_take_kwargs(incremental, take_kwargs)
        snapshot = Snapshot.take(
            self.step_path(step), app_state, pg=self._pg_arg, **take_kwargs
        )
        self._commit_step(
            step,
            refs=lambda: referenced_steps(snapshot.metadata.manifest),
            metric=metric,
            chunk_refs=lambda: _manifest_chunk_refs(
                snapshot.metadata.manifest
            ),
        )
        telemetry.metrics().counter_inc(metric_names.MANAGER_SAVES_TOTAL)
        self._record_step_history(step)
        self._post_step_ledger(step, snapshot)
        self._evaluate_slos(step)
        self._publish_cdn_step(step, snapshot)
        self._autotune_step(step)
        return snapshot

    @staticmethod
    def _validate_metric(metric: Optional[float]) -> None:
        """NaN/inf poison min()/sort comparisons, silently selecting a
        diverged checkpoint as 'best' — reject them at the API boundary."""
        if metric is None:
            return
        import math

        if not math.isfinite(float(metric)):
            raise ValueError(
                f"metric must be finite, got {metric!r} (a diverged "
                f"eval score must not enter best-checkpoint retention)"
            )

    def async_save(
        self,
        step: int,
        app_state: AppState,
        incremental: Optional[bool] = None,
        metric: Optional[float] = None,
        **take_kwargs: Any,
    ) -> _PendingManagedSnapshot:
        """Pipelined checkpoint; the index entry and retention pass happen
        in ``wait()`` after the background commit succeeds."""
        self._validate_metric(metric)
        take_kwargs = self._incremental_take_kwargs(incremental, take_kwargs)
        pending = Snapshot.async_take(
            self.step_path(step), app_state, pg=self._pg_arg, **take_kwargs
        )
        return _PendingManagedSnapshot(self, step, pending, metric=metric)

    def _record_step_history(self, step: int) -> None:
        """Append the just-committed step's telemetry summary to the
        manager root's rolling history (``.telemetry-history.jsonl``),
        the input ``doctor --trend`` baselines against. Rank 0 only;
        best-effort (history must never fail a save); knob-bounded
        (TORCHSNAPSHOT_TPU_HISTORY_MAX_RECORDS, <= 0 disables)."""
        if self._pg.get_rank() != 0:
            return
        try:
            from .telemetry import history, last_report

            # Path-keyed lookup: overlapping async saves each find their
            # own step's report, never whichever commit thread emitted
            # last.
            report = last_report(
                "take", "async_take", path=self.step_path(step)
            )
            if report is None:
                return
            history.append_summary(
                self.root, history.summarize_report(report, step=step)
            )
        except Exception as e:  # noqa: BLE001 - history is best-effort
            logger.warning(
                "could not record step %d telemetry history: %r", step, e
            )

    def _post_step_ledger(self, step: int, snapshot: Snapshot) -> None:
        """Post the just-committed step to the run ledger: the
        retention-visible moment, with the step's storage accounting —
        bytes newly written vs. referenced from an incremental base
        (the reuse ratio the goodput engine's storage-cost curve
        reports) — then refresh the run-so-far ``goodput_*`` gauges.
        Rank 0 only; best-effort (the ledger must never fail a save)."""
        if self._pg.get_rank() != 0 or not knobs.is_ledger_enabled():
            return
        try:
            from .fsck import blob_requirements
            from .telemetry import last_report
            from .telemetry import ledger as run_ledger
            from .telemetry import names as event_names
            from .telemetry.goodput import publish_gauges

            need = blob_requirements(snapshot.metadata.manifest)
            bytes_new = sum(
                n for loc, n in need.items() if not loc.startswith("../")
            )
            bytes_reused = sum(
                n for loc, n in need.items() if loc.startswith("../")
            )
            fields: Dict[str, Any] = {
                "step": step,
                "bytes_new": int(bytes_new),
                "bytes_reused": int(bytes_reused),
                "bytes_total": int(bytes_new + bytes_reused),
                "blobs": len(need),
            }
            # CAS steps: every data location is a ``../chunks/`` ref, so
            # the prefix split above cannot see new vs. reused — replace
            # it with the EXACT per-chunk accounting the commit's
            # refcount pin computed (chunks already pinned = reused).
            acct = self._last_cas_accounting
            if acct is not None and acct.get("step") == step:
                fields.update(
                    cas=True,
                    bytes_new=acct["bytes_new"],
                    bytes_reused=acct["bytes_reused"],
                    bytes_total=acct["bytes_total"],
                    chunks_new=acct["chunks_new"],
                    chunks_reused=acct["chunks_reused"],
                )
            # How much of the state the on-device digests say was
            # UNCHANGED since the previous committed step — the
            # ``dedup-ineffective`` doctor rule compares this against
            # the realized reuse ratio (unchanged bytes that were
            # nevertheless re-stored mean the dedup path is broken).
            cur_digests = _manifest_digest_map(snapshot.metadata.manifest)
            if cur_digests:
                prev = self._prev_digest_map
                unchanged = sum(
                    n
                    for k, (d, n) in cur_digests.items()
                    if prev.get(k, (None, 0))[0] == d
                )
                fields["bytes_digest_unchanged"] = int(unchanged)
                fields["bytes_digest_covered"] = int(
                    sum(n for _, n in cur_digests.values())
                )
            self._prev_digest_map = cur_digests
            report = last_report(
                "take", "async_take", path=self.step_path(step)
            )
            if report is not None:
                fields["kind"] = report.kind
                fields["take_s"] = round(
                    max(report.phases.values(), default=0.0), 6
                )
            run_ledger.post_event(
                self.root, event_names.EVENT_STEP_COMMITTED, **fields
            )
            publish_gauges(self.root)
        except Exception as e:  # noqa: BLE001 - ledger is best-effort
            logger.warning(
                "could not post step %d to the run ledger: %r", step, e
            )

    def _evaluate_slos(self, step: int) -> None:
        """Re-judge the declared SLOs against the run's recorded
        evidence at the retention-visible moment (telemetry/slo.py):
        refreshes the burn-rate gauges, posts an edge-triggered
        ``slo-breach`` ledger event per objective episode, and captures
        one incident bundle per evaluation that saw a fresh breach.
        Rank 0 only — the evidence it judges is rank-0-recorded;
        best-effort (a judgment must never fail a save)."""
        if (
            self._pg.get_rank() != 0
            or not knobs.is_slo_enabled()
            or not knobs.is_ledger_enabled()
        ):
            return
        try:
            from .telemetry import slo

            slo.evaluate_step(self.root, step)
        except Exception as e:  # noqa: BLE001 - the SLO engine is best-effort
            logger.warning(
                "could not evaluate SLOs at step %d: %r", step, e
            )

    def _publish_cdn_step(self, step: int, snapshot: Snapshot) -> None:
        """Announce the just-committed step's chunk set on the CDN
        topic (docs/cdn.md). Rank 0 only, post-commit only — the
        announce's chunks are already durable by construction. Steps
        without content-addressed chunks (CAS off) have nothing a
        fleet can dedup-pull, so they are skipped, not half-announced.
        Best-effort: a publish failure degrades serving freshness,
        never the save."""
        if (
            self.cdn_topic is None
            or self._pg.get_rank() != 0
            or not knobs.is_cdn_enabled()
        ):
            return
        try:
            chunks = _manifest_chunk_refs(snapshot.metadata.manifest)
            if not chunks:
                logger.debug(
                    "cdn: step %d carries no CAS chunks; not published",
                    step,
                )
                return
            if self._cdn_publisher is None:
                store = (
                    self._cdn_store_arg
                    if self._cdn_store_arg is not None
                    else self._pg.store
                )
                if store is None:
                    logger.warning(
                        "cdn: topic %r configured but no coordination "
                        "store is reachable; steps will not be published",
                        self.cdn_topic,
                    )
                    self.cdn_topic = None
                    return
                from .cdn import CdnPublisher

                self._cdn_publisher = CdnPublisher(
                    store,
                    self.cdn_topic,
                    publisher_id=f"rank0@{self.root}",
                    root=self.root,
                )
            self._cdn_publisher.publish(step, chunks)
        except Exception as e:  # noqa: BLE001 - publishing is best-effort
            logger.warning("cdn: could not publish step %d: %r", step, e)

    def _autotune_step(self, step: int) -> None:
        """One closed-loop tuning pass after ``step`` committed: rank 0
        reads the step's report, decides the next knob vector, and
        every rank applies the broadcast decision (tuner/autotuner.py).
        The TORCHSNAPSHOT_TPU_AUTOTUNE=0 kill switch must be set
        uniformly across ranks (like every geometry-affecting knob) —
        with it, this is a pure no-op. Best-effort: tuning must never
        fail a save."""
        if not knobs.is_autotune_enabled():
            return
        try:
            if self._autotuner is None:
                from .tuner import Autotuner

                self._autotuner = Autotuner(self.root)
            report = None
            if self._pg.get_rank() == 0:
                from .telemetry import last_report

                report = last_report(
                    "take", "async_take", path=self.step_path(step)
                )
            self._autotuner.tune_after_step(step, report, self._pg)
        except Exception as e:  # noqa: BLE001 - tuning is best-effort
            logger.warning(
                "autotuner: skipped tuning after step %d: %r", step, e
            )

    # ------------------------------------------------------------------
    # resuming
    # ------------------------------------------------------------------

    def all_steps(self) -> List[int]:
        """Committed steps, ascending. Every rank may call this; the index
        blob is tiny."""
        return self._read_index()

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def best_step(self) -> Optional[int]:
        """The committed step with the best recorded metric (``best_mode``
        ordering; newest wins ties), or None when no step has one."""
        index = self._with_root_storage(self._read_index_full_async)
        scored = [s for s in index["steps"] if str(s) in index["metrics"]]
        if not scored:
            return None
        return min(
            scored, key=lambda s: self._metric_sort_key(s, index["metrics"])
        )

    def restore_best(self, app_state: AppState) -> Optional[int]:
        """Restore the best-metric committed step; returns it, or None if
        no step carries a metric. Rank 0 resolves, everyone follows."""
        step = self.best_step() if self._pg.get_rank() == 0 else None
        step = self._pg.broadcast_object(step)
        if step is None:
            return None
        self.restore(step, app_state)
        return step

    def restore(self, step: int, app_state: AppState) -> None:
        Snapshot(self.step_path(step), pg=self._pg_arg).restore(app_state)
        telemetry.metrics().counter_inc(metric_names.MANAGER_RESTORES_TOTAL)
        self._record_restore_history(step)

    def _record_restore_history(self, step: int) -> None:
        """Append the just-served restore's telemetry summary to the
        same rolling history takes feed — recovery time is a trend
        metric too (``doctor --trend`` baselines per kind, so restore
        rows never pollute take baselines). Rank 0 only; best-effort."""
        if self._pg.get_rank() != 0:
            return
        try:
            from .telemetry import history, last_report

            report = last_report(
                "restore", "async_restore", path=self.step_path(step)
            )
            if report is None:
                return
            history.append_summary(
                self.root, history.summarize_report(report, step=step)
            )
        except Exception as e:  # noqa: BLE001 - history is best-effort
            logger.warning(
                "could not record step %d restore history: %r", step, e
            )

    def restore_latest(self, app_state: AppState) -> Optional[int]:
        """Restore the newest committed step into ``app_state``; returns
        its step number, or None when no checkpoint exists (fresh run).
        Rank 0 resolves the step and everyone follows — ranks must never
        resume from different steps."""
        step = self.latest_step() if self._pg.get_rank() == 0 else None
        step = self._pg.broadcast_object(step)
        if step is None:
            return None
        self.restore(step, app_state)
        return step

    def async_restore(self, step: int, app_state: AppState):
        """Pipelined restore of ``step`` (Snapshot.async_restore): reads
        run in the background; call ``.wait()`` to apply."""
        pending = Snapshot(self.step_path(step), pg=self._pg_arg).async_restore(
            app_state
        )
        # Counted at initiation (the wait handle is Snapshot-level):
        # async resumes must move the same counter sync ones do.
        telemetry.metrics().counter_inc(metric_names.MANAGER_RESTORES_TOTAL)
        return _ManagedPendingRestore(self, step, pending)

    def async_restore_latest(self, app_state: AppState):
        """Kick off a pipelined restore of the newest committed step;
        returns ``(step, PendingRestore)`` or ``None`` on a fresh run.
        Overlap jit compilation with the reads, then ``wait()``."""
        step = self.latest_step() if self._pg.get_rank() == 0 else None
        step = self._pg.broadcast_object(step)
        if step is None:
            return None
        return step, self.async_restore(step, app_state)

    # ------------------------------------------------------------------
    # index + retention (rank 0 only; peers observe via the index blob)
    # ------------------------------------------------------------------

    def _with_root_storage(self, coro_fn):
        """Run ``coro_fn(storage)`` against the manager root in a fresh
        event loop, closing the plugin on every path."""

        async def body():
            storage = url_to_storage_plugin(self.root)
            try:
                return await coro_fn(storage)
            finally:
                await storage.close()

        return run_in_fresh_event_loop(body())

    def _commit_step(
        self,
        step: int,
        refs: Optional[Any] = None,
        metric: Optional[float] = None,
        chunk_refs: Optional[Any] = None,
    ) -> None:
        """``refs``/``chunk_refs`` may be values or zero-arg callables.
        Pass callables when computing them requires the snapshot
        manifest: they are evaluated only on rank 0, after the early
        return — non-leader ranks hold no in-memory metadata and must
        not pull the global manifest from storage just to drop it."""
        if self._pg.get_rank() != 0:
            return
        if callable(refs):
            refs = refs()
        if callable(chunk_refs):
            chunk_refs = chunk_refs()
        self._with_root_storage(
            lambda storage: self._commit_step_async(
                step, storage, refs or set(), metric, chunk_refs or {}
            )
        )

    def _retained(
        self, steps: List[int], just_saved: int, metrics: Dict[str, float]
    ) -> List[int]:
        """Retention policy: newest ``keep_last_n`` ∪ best ``keep_best_n``
        (by recorded metric) ∪ the just-saved step (never GC'd in its own
        commit — a rollback may produce a numerically-old step).

        With ``keep_best_n`` alone (``keep_last_n=None``), only *scored*
        steps compete for deletion: unscored steps are all retained, so
        enabling metric retention never silently GCs checkpoints that
        were saved without a metric."""
        if self.keep_last_n is None and self.keep_best_n is None:
            return list(steps)
        keep: Set[int] = set()
        if self.keep_last_n is not None:
            keep.update(steps[-self.keep_last_n :])
        if self.keep_best_n is not None:
            scored = [s for s in steps if str(s) in metrics]
            scored.sort(key=lambda s: self._metric_sort_key(s, metrics))
            keep.update(scored[: self.keep_best_n])
            if self.keep_last_n is None:
                keep.update(s for s in steps if str(s) not in metrics)
        if just_saved not in keep:
            # A step-counter reset/rollback produced a numerically-old (or
            # metric-poor) step: keep it anyway, loudly — operators need
            # the signal that the index now mixes numbering epochs.
            logger.warning(
                "Just-saved step %d falls outside the retention policy "
                "(retained: %s); keeping it anyway — the just-saved "
                "checkpoint is never deleted",
                just_saved,
                sorted(keep),
            )
            keep.add(just_saved)
        return [s for s in steps if s in keep]

    def _metric_sort_key(self, step: int, metrics: Dict[str, float]):
        """One ordering for retention AND best_step()/restore_best(), so
        they can never disagree about which step is 'best': best metric
        first (mode-signed), newest step wins ties."""
        sign = 1.0 if self.best_mode == "min" else -1.0
        return (sign * metrics[str(step)], -step)

    async def _commit_step_async(
        self,
        step: int,
        storage: StoragePlugin,
        refs: Set[int],
        metric: Optional[float] = None,
        chunk_refs: Optional[Dict[str, int]] = None,
    ) -> None:
        index = await self._read_index_full_async(storage)
        steps = [s for s in index["steps"] if s != step]
        steps.append(step)
        steps.sort()
        refs_map: Dict[str, List[int]] = dict(index["refs"])
        if refs:
            refs_map[str(step)] = sorted(refs)
        else:
            refs_map.pop(str(step), None)
        # CAS refcounts: pin the step's chunks BEFORE the index write —
        # a crash between the two leaves a pinned-but-uncommitted step
        # (garbage retained until reconcile), never an indexed step
        # whose chunks a racing GC could reclaim. The pin also yields
        # the step's exact storage accounting (chunks already live =
        # reused bytes) for the run ledger.
        self._last_cas_accounting = self._cas_pin_step(
            step, chunk_refs or {}
        )
        metrics: Dict[str, float] = dict(index["metrics"])
        if metric is not None:
            metrics[str(step)] = float(metric)
        else:
            metrics.pop(str(step), None)
        pinned: Set[int] = set(index["pinned"])
        evicted: Set[int] = set(index["evicted"])
        evicted.discard(step)  # a re-saved step is fast-resident again

        retained = self._retained(steps, step, metrics)
        dropped = [s for s in steps if s not in retained]
        steps = retained

        # Explicit retention check (the orphaned-base guard): in an
        # index NOT marked ``refs_complete`` — written before
        # incremental refs existed — a retained step's missing refs
        # entry means "unknown", not "none": presuming it ref-free
        # while GC deletes bases would leave its ``../step_*``
        # locations dangling, with fsck the only thing that would ever
        # notice. Re-derive refs from each such step's own manifest,
        # exactly once: every index this version writes carries the
        # marker, under which absence soundly means verified-empty.
        if not index["refs_complete"]:
            for s in steps:
                if str(s) not in refs_map:
                    derived = await self._derive_refs_async(storage, s)
                    if derived:
                        refs_map[str(s)] = sorted(derived)

        # Pin-or-delete: a dropped (or previously pinned) step whose blobs
        # a *retained* step's manifest still references must keep its
        # blobs. Refs name origin steps directly (chained refs collapse at
        # take time), so one pass over retained steps' ref lists is the
        # full liveness set — pins don't propagate.
        needed: Set[int] = set()
        for s in steps:
            needed.update(refs_map.get(str(s), ()))
        to_delete: List[int] = []
        for old in dropped:
            if old in needed:
                pinned.add(old)
            else:
                to_delete.append(old)
        for p in sorted(pinned):
            if p not in needed:
                pinned.discard(p)
                to_delete.append(p)
        for gone in to_delete:
            refs_map.pop(str(gone), None)
            metrics.pop(str(gone), None)
            evicted.discard(gone)

        # Fast-tier eviction pass (tiered roots with keep_fast_last_n):
        # surviving steps beyond the newest N — pinned incremental origins
        # included — lose their fast-tier copies once durable-complete.
        # Eviction is attempted before the index write so the recorded
        # evicted set never claims a step this pass failed to evict.
        if self.keep_fast_last_n is not None:
            hot = set(steps[-self.keep_fast_last_n :])
            hot.add(step)
            candidates = [
                s
                for s in sorted(set(steps) | pinned)
                if s not in hot and s not in evicted
            ]
            for old in candidates:
                try:
                    if await self._evict_fast_async(old):
                        evicted.add(old)
                except Exception as e:  # noqa: BLE001 - must not fail a save
                    logger.warning(
                        "Failed to evict step %d from the fast tier: %r",
                        old,
                        e,
                    )

        await self._write_index_async(
            steps, storage, refs=refs_map, pinned=sorted(pinned),
            metrics=metrics, evicted=sorted(evicted),
        )
        registry = telemetry.metrics()
        registry.gauge_set(metric_names.MANAGER_RETAINED_STEPS, len(steps))
        if to_delete:
            registry.counter_inc(
                metric_names.MANAGER_GC_STEPS_TOTAL, len(to_delete)
            )
        for old in to_delete:
            try:
                await self._delete_step_async(old)
            except Exception as e:  # noqa: BLE001 - GC must not fail a save
                logger.warning("Failed to GC step %d: %r", old, e)
        # Chunk-store GC: unpin the deleted steps and reclaim chunks no
        # pinned step references (grace-window + orphan deferral inside).
        # Runs AFTER the step deletes so an interrupted pass errs toward
        # retaining chunks, never toward dangling refs. Runs on EVERY
        # commit, not only ones that dropped steps — grace-deferred
        # orphans and crashed takes' strays must age out even in runs
        # whose retention never deletes anything (keep-everything, or
        # still inside the first keep_last_n saves).
        try:
            await self._cas_collect_async(storage, step, to_delete)
        except Exception as e:  # noqa: BLE001 - GC must not fail a save
            logger.warning("CAS chunk GC failed: %r", e)

    async def _derive_refs_async(
        self, storage: StoragePlugin, step: int
    ) -> Set[int]:
        """Re-derive a step's origin-step refs from its committed
        manifest (the explicit retention check for refs-less index
        entries). An unreadable manifest conservatively pins nothing
        AND nothing referencing it is deleted this pass — the read
        error propagates to the caller's warning path."""
        read_io = ReadIO(
            path=f"{_step_dirname(step)}/{SNAPSHOT_METADATA_FNAME}"
        )
        try:
            await storage.read(read_io)
        except FileNotFoundError:
            return set()
        metadata = SnapshotMetadata.from_yaml(bytes(read_io.buf).decode())
        return referenced_steps(metadata.manifest)

    # ------------------------------------------------------------------
    # content-addressed chunk store (docs/cas.md; rank 0 only)
    # ------------------------------------------------------------------

    def _get_cas_store(self):
        """The root's chunk store handle, or None for roots without a
        local filesystem tier. Resolved once; cheap for legacy roots
        (the journal load of a nonexistent file is one failed open)."""
        if self._cas_store is not False:
            return self._cas_store
        from .cas import CASStore, local_chunks_dir

        if local_chunks_dir(self.root) is None:
            self._cas_store = None
        else:
            self._cas_store = CASStore(self.root)
        return self._cas_store

    def _cas_pin_step(
        self, step: int, chunk_refs: Dict[str, int]
    ) -> Optional[Dict[str, Any]]:
        """Pin a committing step's chunks in the refcount journal and
        return its exact storage accounting (bytes newly materialized
        vs. reused from already-pinned chunks). None for legacy steps
        (no chunk refs) — the journal is never created for them."""
        store = self._get_cas_store()
        if store is None or not chunk_refs:
            return None
        pins, orphans = store.load()
        pinned_before: Set[str] = set()
        for s, chunks in pins.items():
            if s != step:
                pinned_before.update(chunks)
        reused = {
            k: n for k, n in chunk_refs.items() if k in pinned_before
        }
        new = {
            k: n for k, n in chunk_refs.items() if k not in pinned_before
        }
        store.pin(step, chunk_refs)
        # Kill point: pinned-but-unindexed (the index write is still
        # pending) — construction-time reconcile must unpin on reload.
        _crashpoint(metric_names.CRASH_REFCOUNT_PINNED)
        # Chunks resurrected from the orphan (grace-deferred) list are
        # live again: drop them from it so GC stops considering them.
        revived = set(chunk_refs) & set(orphans)
        if revived:
            store.clear_orphans(revived)
        return {
            "step": step,
            "chunks_new": len(new),
            "chunks_reused": len(reused),
            "bytes_new": int(sum(new.values())),
            "bytes_reused": int(sum(reused.values())),
            "bytes_total": int(sum(chunk_refs.values())),
        }

    async def _cas_collect_async(
        self,
        storage: StoragePlugin,
        trigger_step: int,
        deleted_steps: List[int],
    ) -> None:
        """Unpin GC'd steps and reclaim refcount-dead chunks. A dead
        chunk younger than the grace window is deferred as a journaled
        orphan (a concurrent not-yet-pinned take may have just deduped
        against it — its touch keeps the mtime fresh) and retried on a
        later pass. Reclaimed bytes are posted to the run ledger so the
        goodput storage curve tracks what retention actually keeps."""
        from .cas import CHUNKS_DIRNAME

        store = self._get_cas_store()
        if store is None:
            return
        pins, orphans, leases = store.load_full()
        candidates: Dict[str, int] = dict(orphans)
        unpinned = False
        for old in deleted_steps:
            chunks = pins.pop(old, None)
            if chunks is not None:
                store.unpin(old)
                unpinned = True
                candidates.update(chunks)
        if unpinned:
            # Kill point: steps unpinned, reclaim deletes still pending
            # (dead chunks must age out via grace/stray sweeps, never
            # dangle).
            _crashpoint(metric_names.CRASH_GC_UNPINNED)
        # Leases (CDN subscriber pins) count as live: a serving fleet's
        # durable copy source must survive step retention until the
        # fleet re-leases without it.
        live = store.live_chunks(pins, leases)
        # Stray sweep: on-disk chunks in NO pin and NO orphan record —
        # a take that crashed before its commit pinned them, or pins
        # reconcile dropped. Without this they would never become GC
        # candidates (candidates are otherwise journal-derived only)
        # and leak forever. Folding them into this pass is safe for a
        # concurrent in-flight take: its fresh chunks defer through the
        # grace window below, and its commit's pin revives them from
        # the orphan list.
        for key, nbytes in store.list_chunks().items():
            if key not in live and key not in candidates:
                candidates[key] = nbytes
        if not candidates:
            if unpinned:
                store.maybe_compact()
            return
        grace = knobs.get_cas_gc_grace_seconds()
        reclaimed: Dict[str, int] = {}
        cleared: Set[str] = set()
        deferred: Dict[str, int] = {}
        for key, nbytes in candidates.items():
            if key in live:
                cleared.add(key)  # re-pinned since it was orphaned
                continue
            age = store.chunk_age_seconds(key)
            if age is None:
                cleared.add(key)  # already gone (fsck/manual cleanup)
                continue
            if grace > 0 and age < grace:
                deferred[key] = nbytes
                continue
            try:
                await storage.delete(f"{CHUNKS_DIRNAME}/{key}")
            except FileNotFoundError:
                pass
            reclaimed[key] = nbytes
        store.clear_orphans((cleared | set(reclaimed)) & set(orphans))
        store.record_orphans(
            {k: n for k, n in deferred.items() if k not in orphans}
        )
        store.maybe_compact()
        if reclaimed:
            registry = telemetry.metrics()
            registry.counter_inc(
                metric_names.CAS_CHUNKS_RECLAIMED_TOTAL, len(reclaimed)
            )
            registry.counter_inc(
                metric_names.CAS_BYTES_RECLAIMED_TOTAL,
                sum(reclaimed.values()),
            )
            if knobs.is_ledger_enabled():
                try:
                    from .telemetry import ledger as run_ledger
                    from .telemetry import names as event_names

                    run_ledger.post_event(
                        self.root,
                        event_names.EVENT_GC_RECLAIMED,
                        step=trigger_step,
                        bytes_reclaimed=int(sum(reclaimed.values())),
                        blobs=len(reclaimed),
                        chunks=True,
                    )
                except Exception as e:  # noqa: BLE001 - best-effort
                    logger.warning(
                        "could not post chunk GC to the run ledger: %r", e
                    )
        if deferred:
            logger.info(
                "CAS GC deferred %d dead-but-fresh chunk(s) inside the "
                "%.0fs grace window (a concurrent take may hold them); "
                "a later pass reclaims them",
                len(deferred),
                grace,
            )

    def _reconcile_cas(self) -> None:
        """Construction-time healing (rank 0): bring the refcount
        journal in line with the index + manifests. Covers a crash that
        lost or tore the journal after steps committed (chunks written,
        refcount append missing — wholesale OR one step's pin lost
        while other pins survived), and stale pins of steps that left
        the index. No-op — zero manifest reads — when the root has no
        chunk store, the store is empty, or every indexed step's pin
        state already matches the journal."""
        import os as _os

        store = self._get_cas_store()
        if store is None or not _os.path.isdir(store.local_dir):
            return
        from .cas import chunk_refs as _chunk_refs

        pins, _ = store.load()
        if not pins and not store.list_chunks():
            return  # empty store: nothing pinned, nothing on disk
        index = self._with_root_storage(self._read_index_full_async)
        expected = set(index["steps"]) | set(index["pinned"])
        stale_pins = set(pins) - expected
        # Indexed steps with NO pin record: a legacy-layout step (no
        # chunk refs — absence from the journal IS canonical) or a
        # committed CAS step whose pin append was lost or torn while
        # OTHER pins survived (partial journal damage). Only the
        # manifest can tell them apart, and guessing wrong would let
        # the stray sweep reclaim a committed step's chunks — so read
        # exactly these manifests and re-derive. Steps whose pin record
        # survived are trusted as-is (the pin was derived from the same
        # manifest at commit time).
        missing_pins = expected - set(pins)
        if not stale_pins and not missing_pins:
            return

        async def _refs_of_missing(storage: StoragePlugin):
            mapping: Dict[int, Dict[str, int]] = {}
            for s in sorted(missing_pins):
                read_io = ReadIO(
                    path=f"{_step_dirname(s)}/{SNAPSHOT_METADATA_FNAME}"
                )
                try:
                    await storage.read(read_io)
                except FileNotFoundError:
                    mapping[s] = {}
                    continue
                metadata = SnapshotMetadata.from_yaml(
                    bytes(read_io.buf).decode()
                )
                mapping[s] = _chunk_refs(metadata.manifest)
            return mapping

        mapping = self._with_root_storage(_refs_of_missing)
        for s in expected & set(pins):
            mapping[s] = pins[s]
        if store.reconcile(mapping):
            logger.info(
                "CAS refcount journal reconciled against the index "
                "(%d committed/pinned steps)",
                len(expected),
            )

    async def _read_index_async(self, storage: StoragePlugin) -> List[int]:
        return (await self._read_index_full_async(storage))["steps"]

    async def _read_index_full_async(
        self, storage: StoragePlugin
    ) -> Dict[str, Any]:
        return await read_index_full_async(storage)

    async def _write_index_async(
        self,
        steps: List[int],
        storage: StoragePlugin,
        refs: Optional[Dict[str, List[int]]] = None,
        pinned: Optional[List[int]] = None,
        metrics: Optional[Dict[str, float]] = None,
        evicted: Optional[List[int]] = None,
    ) -> None:
        payload_obj: Dict[str, Any] = {"steps": steps}
        if steps:
            # Under this marker, a step's ABSENT refs entry soundly
            # means verified-empty — the GC retention check re-derives
            # refs from manifests only for unmarked (older) indexes.
            payload_obj["refs_complete"] = True
        if refs:
            payload_obj["refs"] = refs
        if pinned:
            payload_obj["pinned"] = pinned
        if metrics:
            payload_obj["metrics"] = metrics
        if evicted:
            payload_obj["evicted"] = evicted
        payload = json.dumps(payload_obj).encode()
        # Backup FIRST, primary second. With this order a torn *primary*
        # write always leaves a valid new backup behind it, and a torn
        # backup write leaves the previous (valid, one-save-stale) primary
        # — consistent with the caller's view, since the save never
        # returned. It also makes "corrupt primary + absent backup"
        # impossible except for a torn first-ever index write, which is
        # what _read_index_async's recovery rule assumes.
        await storage.write(WriteIO(path=INDEX_BACKUP_BLOB, buf=payload))
        # Kill point: the torn pair — a valid NEW backup behind a stale
        # primary, the exact state the read-side recovery rule assumes.
        _crashpoint(metric_names.CRASH_INDEX_BACKUP_WRITTEN)
        await storage.write(WriteIO(path=INDEX_BLOB, buf=payload))
        _crashpoint(metric_names.CRASH_INDEX_WRITTEN)

    def _read_index(self) -> List[int]:
        return self._with_root_storage(self._read_index_async)

    async def _evict_fast_async(self, step: int) -> bool:
        """Drop one step's FAST-tier copy (tiered roots only); the step
        stays committed and restorable via the per-blob durable fallback.
        Returns True when evicted, False when the step is not yet safe to
        evict (durable commit marker absent — the mirror is still
        working, or failed and will resume)."""
        from .integrity import table_path
        from .tiered.journal import MirrorJournal
        from .tiered.mirror import is_durable_async
        from .tiered.plugin import TieredStoragePlugin

        path = self.step_path(step)
        if not await is_durable_async(path):
            return False
        storage = url_to_storage_plugin(path)
        try:
            if not isinstance(storage, TieredStoragePlugin):
                return False
            # The durable manifest is authoritative for what to remove
            # (the fast copy may already be partial).
            read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
            await storage.durable.read(read_io)
            metadata = SnapshotMetadata.from_yaml(bytes(read_io.buf).decode())
            locations: Set[str] = set()
            for entry in metadata.manifest.values():
                locations.update(_entry_locations(entry))
            locations = {l for l in locations if not l.startswith("../")}
            from .cas import chunk_map_path

            for rank in range(metadata.world_size):
                locations.add(table_path(rank))
                locations.add(chunk_map_path(rank))

            async def _drop(location: str) -> None:
                try:
                    await storage.fast.delete(location)
                except FileNotFoundError:
                    pass

            # Commit marker first (deletion discipline shared with
            # _delete_step_async), then data, then the journal. The
            # telemetry event log and progress heartbeats are not
            # manifest-named; drop them explicitly or every evicted
            # step leaks files.
            from .telemetry.progress import SNAPSHOT_PROGRESS_PREFIX
            from .telemetry.sink import SNAPSHOT_EVENTS_BASENAME

            from .tiered.peer import placement_doc_path

            await _drop(SNAPSHOT_METADATA_FNAME)
            await _drop(SNAPSHOT_EVENTS_BASENAME)
            for rank in range(metadata.world_size):
                await _drop(f"{SNAPSHOT_PROGRESS_PREFIX}{rank}.json")
                await _drop(placement_doc_path(rank))
            slots = asyncio.Semaphore(knobs.get_per_rank_io_concurrency())

            async def _drop_slotted(location: str) -> None:
                async with slots:
                    await _drop(location)

            results = await asyncio.gather(
                *(_drop_slotted(l) for l in sorted(locations)),
                return_exceptions=True,
            )
            for r in results:
                if isinstance(r, BaseException):
                    raise r
            await MirrorJournal(blobs={}).delete(storage.fast)
        finally:
            await storage.close()
        logger.info("Evicted step %d from the fast tier", step)
        return True

    def wait_durable(
        self, step: int, timeout: Optional[float] = None
    ) -> None:
        """Durability barrier: block until ``step`` is fully mirrored to
        the durable tier AND the durable tier's index names it — i.e.
        until the durable tier alone could serve ``restore_latest``.
        Immediate no-op for non-tiered roots (their commit was the
        durable write). Raises ``TimeoutError`` on deadline, and
        re-raises a failed mirror's error (the fast tier remains
        restorable; the journal resumes the upload).

        ``timeout=None`` (the default) is NOT unbounded: it resolves to
        the ``TORCHSNAPSHOT_TPU_WAIT_DURABLE_TIMEOUT_SECONDS`` knob
        (default 30 min) so a wedged durable tier surfaces as a clear
        ``TimeoutError`` instead of a silent poll loop the stall
        watchdog is the only escape from. A non-positive knob value
        restores the unbounded wait, explicitly."""
        import time as _time

        from .tiered.mirror import wait_durable as _wait_durable

        if timeout is None:
            default_timeout = knobs.get_wait_durable_timeout_seconds()
            timeout = default_timeout if default_timeout > 0 else None
        tiers = split_tiered_url(self.root)
        deadline = (
            _time.monotonic() + timeout if timeout is not None else None
        )
        _wait_durable(self.step_path(step), timeout=timeout)
        if tiers is None:
            return
        fast_root, durable_root = tiers
        from .tiered.mirror import get_mirror

        mirror = get_mirror()
        resumed_root = False
        while True:

            async def _read_durable_index(_url=durable_root):
                storage = url_to_storage_plugin(_url)
                try:
                    return await self._read_index_full_async(storage)
                finally:
                    await storage.close()

            try:
                index = run_in_fresh_event_loop(_read_durable_index())
                if step in index["steps"]:
                    return
            except (FileNotFoundError, RuntimeError):
                pass  # index not mirrored yet
            # The index trails through the ROOT's own mirror jobs: if the
            # newest one failed and nothing is in flight, polling would
            # never progress — resume it once, then surface its error.
            root_jobs = mirror.jobs_for(fast_root)
            if root_jobs and all(j.done_evt.is_set() for j in root_jobs):
                if root_jobs[-1].error is not None:
                    if not resumed_root:
                        resumed_root = True
                        mirror.resume(self.root)
                    else:
                        raise RuntimeError(
                            f"step {step} is durable, but mirroring the "
                            f"manager index keeps failing; the fast tier "
                            f"remains authoritative and resume_mirrors() "
                            f"retries the upload"
                        ) from root_jobs[-1].error
            if deadline is not None and _time.monotonic() >= deadline:
                raise TimeoutError(
                    f"step {step} durable, but the durable index does not "
                    f"name it within {timeout}s"
                )
            _time.sleep(0.05)

    def resume_mirrors(self) -> List[int]:
        """Re-enqueue interrupted durable-tier mirrors after a restart:
        every committed step whose durable commit marker is absent
        resumes from its journal (completed blobs are skipped) or, when
        no journal survived, from its fast-tier manifest. Returns the
        resumed steps. Rank 0 only (peers no-op); no-op for non-tiered
        roots."""
        if split_tiered_url(self.root) is None or self._pg.get_rank() != 0:
            return []
        from .tiered.mirror import get_mirror, is_durable

        mirror = get_mirror()
        resumed: List[int] = []
        for step in self.all_steps():
            path = self.step_path(step)
            if not is_durable(path) and mirror.resume(path) is not None:
                resumed.append(step)
        # The root's own control blobs (index slots) may also have an
        # interrupted mirror journaled.
        mirror.resume(self.root)
        return resumed

    async def _delete_step_async(self, step: int) -> None:
        """Delete a step's blobs, manifest-driven (plugins cannot list).
        The commit marker goes first: once it is gone the step is simply
        uncommitted, so a crash mid-deletion leaves garbage bytes but
        never a corrupt-looking valid snapshot."""
        from .integrity import table_path

        storage = url_to_storage_plugin(self.step_path(step))
        try:
            from .tiered.plugin import TieredStoragePlugin

            if isinstance(storage, TieredStoragePlugin) and storage.fast_url:
                # The step is leaving BOTH tiers: stop any in-flight
                # mirror first (its fast-tier source blobs are about to
                # vanish; letting it run would only fail noisily).
                from .tiered.mirror import get_mirror

                get_mirror().cancel_path(storage.fast_url)
            read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
            try:
                await storage.read(read_io)
            except FileNotFoundError:
                return  # never committed; nothing authoritative to walk
            metadata = SnapshotMetadata.from_yaml(bytes(read_io.buf).decode())
            await storage.delete(SNAPSHOT_METADATA_FNAME)
            # Kill point: the dropped step is uncommitted but its data
            # blobs remain — garbage, never a valid-looking snapshot.
            _crashpoint(metric_names.CRASH_GC_MARKER_DELETED)
            if isinstance(storage, TieredStoragePlugin):
                from .tiered.journal import MirrorJournal

                await MirrorJournal(blobs={}).delete(storage.fast)
            # The snapshot-adjacent telemetry log and any progress
            # heartbeats (a crashed take leaves one behind) are not
            # named by the manifest; remove them with the step or GC
            # leaks files per dropped step. Shared-dir heartbeats have
            # no other reaper at all.
            from .telemetry.progress import (
                SNAPSHOT_PROGRESS_PREFIX,
                remove_dir_heartbeats,
            )
            from .telemetry.sink import SNAPSHOT_EVENTS_BASENAME

            remove_dir_heartbeats(self.step_path(step))

            try:
                await storage.delete(SNAPSHOT_EVENTS_BASENAME)
            except FileNotFoundError:
                pass  # sink was never enabled for this step
            from .tiered.peer import placement_doc_path

            for rank in range(metadata.world_size):
                try:
                    await storage.delete(
                        f"{SNAPSHOT_PROGRESS_PREFIX}{rank}.json"
                    )
                except FileNotFoundError:
                    pass  # no heartbeat recorded / already settled
                try:
                    await storage.delete(placement_doc_path(rank))
                except FileNotFoundError:
                    pass  # no peer push ever recorded placement

            locations: Set[str] = set()
            manifest: Manifest = metadata.manifest
            for entry in manifest.values():
                locations.update(_entry_locations(entry))
            # Parent-relative locations are another step's blobs (this
            # step was incremental): never delete outside the step dir.
            locations = {l for l in locations if not l.startswith("../")}
            from .cas import chunk_map_path

            for rank in range(metadata.world_size):
                locations.add(table_path(rank))
                # CAS chunk maps are step blobs too (absent for legacy
                # steps; _delete_one tolerates the miss).
                locations.add(chunk_map_path(rank))
            # Bounded-concurrent deletes: a dropped step of a large sharded
            # model has thousands of blobs, and serial object-store
            # round-trips would stall rank 0's save() for minutes.
            slots = asyncio.Semaphore(knobs.get_per_rank_io_concurrency())

            async def _delete_one(location: str) -> None:
                async with slots:
                    try:
                        await storage.delete(location)
                    except FileNotFoundError:
                        pass  # checksum tables are optional; slabs dedupe

            # return_exceptions: let every delete settle before the plugin
            # closes (a bare gather would abandon in-flight siblings to die
            # against a closing plugin), then surface the first failure.
            results = await asyncio.gather(
                *(_delete_one(l) for l in sorted(locations)),
                return_exceptions=True,
            )
            for r in results:
                if isinstance(r, BaseException):
                    raise r
        finally:
            await storage.close()
        # Peer-RAM copies of the dropped step: best-effort eviction
        # from every advertised peer cache (they self-bound via budget
        # LRU + keep_peer_last_n regardless; this reclaims promptly).
        try:
            from .tiered.peer import maybe_evict_step

            maybe_evict_step(self.step_path(step))
        except Exception as e:  # noqa: BLE001 - GC must not fail a save
            logger.warning(
                "peer tier: evicting step %d peer copies failed: %r",
                step,
                e,
            )
        self._post_gc_ledger(step, metadata.manifest)
        logger.info("Retention dropped step %d", step)

    def _post_gc_ledger(self, step: int, manifest: Manifest) -> None:
        """Record the GC'd step in the run ledger (bytes reclaimed —
        base-referenced locations belong to other steps and are not
        counted) and prune its ``step-committed`` storage records so
        the goodput storage curve tracks what retention actually
        keeps. Runs on rank 0 only (GC is rank-0 work); best-effort."""
        if not knobs.is_ledger_enabled():
            return
        try:
            from .fsck import blob_requirements
            from .telemetry import ledger as run_ledger
            from .telemetry import names as event_names

            need = blob_requirements(manifest)
            own = {
                loc: n
                for loc, n in need.items()
                if not loc.startswith("../")
            }
            run_ledger.post_event(
                self.root,
                event_names.EVENT_GC_RECLAIMED,
                step=step,
                bytes_reclaimed=int(sum(own.values())),
                blobs=len(own),
            )
            run_ledger.prune_steps(self.root, {step})
        except Exception as e:  # noqa: BLE001 - GC must not fail a save
            logger.warning(
                "could not record GC of step %d in the run ledger: %r",
                step,
                e,
            )
