// Native I/O runtime for torchsnapshot_tpu.
//
// The reference library has no native code (SURVEY.md §2.9) — it leans on
// aiofiles' thread pool and torch internals. Here the file-I/O and
// slab-packing hot paths are C++: plain C-ABI functions loaded via ctypes
// (ctypes releases the GIL for the duration of every call, so N executor
// threads drive N concurrent pwrite/pread streams at full bandwidth).
//
// Design rules:
//  - C ABI only (no pybind11 in this image); every function is
//    exception-free and returns 0 / -errno.
//  - No allocation of caller-visible memory: callers own all buffers, so
//    the Python side keeps zero-copy memoryview semantics.
//  - Threaded gather-memcpy for slab packing: memory bandwidth on a many-
//    core host is only reachable with multiple streams.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// Saturate transfer sizes to 1 GiB per syscall (Linux caps rw syscalls at
// 0x7ffff000 bytes anyway; looping also gives EINTR handling a boundary).
constexpr uint64_t kMaxIoChunk = 1ull << 30;

int write_all(int fd, const char* buf, uint64_t len, uint64_t offset) {
  while (len > 0) {
    uint64_t n = len < kMaxIoChunk ? len : kMaxIoChunk;
    ssize_t w = ::pwrite(fd, buf, n, static_cast<off_t>(offset));
    if (w < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    buf += w;
    offset += static_cast<uint64_t>(w);
    len -= static_cast<uint64_t>(w);
  }
  return 0;
}

int read_all(int fd, char* buf, uint64_t len, uint64_t offset) {
  while (len > 0) {
    uint64_t n = len < kMaxIoChunk ? len : kMaxIoChunk;
    ssize_t r = ::pread(fd, buf, n, static_cast<off_t>(offset));
    if (r < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (r == 0) return -EIO;  // short file: caller asked past EOF
    buf += r;
    offset += static_cast<uint64_t>(r);
    len -= static_cast<uint64_t>(r);
  }
  return 0;
}

}  // namespace

extern "C" {

// Write `len` bytes to a fresh file at `path` (O_TRUNC). `do_fsync`:
// 0 = none (commit protocol tolerates torn data files; metadata is the
// barrier), 1 = fdatasync before close.
int ts_write_file(const char* path, const void* buf, uint64_t len,
                  int do_fsync) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC | O_CLOEXEC, 0644);
  if (fd < 0) return -errno;
  int rc = write_all(fd, static_cast<const char*>(buf), len, 0);
  if (rc == 0 && do_fsync) {
    if (::fdatasync(fd) != 0) rc = -errno;
  }
  if (::close(fd) != 0 && rc == 0) rc = -errno;
  return rc;
}

// Read exactly `len` bytes at `offset` from `path` into caller's buffer.
int ts_pread_range(const char* path, void* buf, uint64_t len,
                   uint64_t offset) {
  int fd = ::open(path, O_RDONLY | O_CLOEXEC);
  if (fd < 0) return -errno;
  int rc = read_all(fd, static_cast<char*>(buf), len, offset);
  if (::close(fd) != 0 && rc == 0) rc = -errno;
  return rc;
}

int64_t ts_file_size(const char* path) {
  struct stat st;
  if (::stat(path, &st) != 0) return -static_cast<int64_t>(errno);
  return static_cast<int64_t>(st.st_size);
}

// Scatter `n` source buffers into `dst` at `dst_offsets`, using up to
// `n_threads` threads. Work is split by bytes, and a single large source
// region is itself split across threads, so one 1 GiB tensor doesn't
// serialize the pack.
void ts_gather_memcpy(void* dst, const void** srcs, const uint64_t* sizes,
                      const uint64_t* dst_offsets, uint64_t n,
                      int n_threads) {
  uint64_t total = 0;
  for (uint64_t i = 0; i < n; ++i) total += sizes[i];
  if (total == 0) return;
  if (n_threads < 1) n_threads = 1;
  uint64_t per_thread = (total + n_threads - 1) / n_threads;

  auto worker = [&](uint64_t begin, uint64_t end) {
    // [begin, end) in concatenated-byte space.
    uint64_t pos = 0;
    for (uint64_t i = 0; i < n && pos < end; ++i) {
      uint64_t lo = pos, hi = pos + sizes[i];
      pos = hi;
      if (hi <= begin) continue;
      uint64_t s = begin > lo ? begin - lo : 0;
      uint64_t e = (end < hi ? end : hi) - lo;
      if (e <= s) continue;
      std::memcpy(static_cast<char*>(dst) + dst_offsets[i] + s,
                  static_cast<const char*>(srcs[i]) + s, e - s);
    }
  };

  std::vector<std::thread> threads;
  for (int t = 1; t < n_threads; ++t) {
    uint64_t begin = per_thread * t;
    if (begin >= total) break;
    uint64_t end = begin + per_thread < total ? begin + per_thread : total;
    threads.emplace_back(worker, begin, end);
  }
  worker(0, per_thread < total ? per_thread : total);
  for (auto& th : threads) th.join();
}

}  // extern "C"

// CRC32-C (Castagnoli) for storage integrity records. The integrity
// pass runs once over every byte a take writes and a restore reads, so
// on slow cores a byte-at-a-time table CRC rivals the I/O it protects:
// use the SSE4.2 crc32 instruction when the CPU has it (runtime
// detected), else slicing-by-8 tables.

namespace {

struct Crc32cTables {
  uint32_t t[8][256];
  Crc32cTables() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c >> 1) ^ (0x82F63B78u & (0u - (c & 1)));
      t[0][i] = c;
    }
    for (int s = 1; s < 8; ++s)
      for (uint32_t i = 0; i < 256; ++i)
        t[s][i] = (t[s - 1][i] >> 8) ^ t[0][t[s - 1][i] & 0xFF];
  }
};

// Slicing-by-8 (little-endian): 8 bytes per step through 8 tables.
uint32_t crc32c_sw(const unsigned char* p, uint64_t len, uint32_t crc) {
  static const Crc32cTables tables;  // magic static: thread-safe init
  const auto& t = tables.t;
  while (len >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    v ^= crc;
    crc = t[7][v & 0xFF] ^ t[6][(v >> 8) & 0xFF] ^ t[5][(v >> 16) & 0xFF] ^
          t[4][(v >> 24) & 0xFF] ^ t[3][(v >> 32) & 0xFF] ^
          t[2][(v >> 40) & 0xFF] ^ t[1][(v >> 48) & 0xFF] ^
          t[0][(v >> 56) & 0xFF];
    p += 8;
    len -= 8;
  }
  while (len--) crc = t[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return crc;
}

#if defined(__x86_64__)
__attribute__((target("sse4.2"))) uint32_t crc32c_hw(const unsigned char* p,
                                                     uint64_t len,
                                                     uint32_t crc) {
  uint64_t c = crc;
  while (len >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    c = __builtin_ia32_crc32di(c, v);
    p += 8;
    len -= 8;
  }
  crc = static_cast<uint32_t>(c);
  while (len--) crc = __builtin_ia32_crc32qi(crc, *p++);
  return crc;
}

bool crc32c_hw_available() {
  static const bool v = __builtin_cpu_supports("sse4.2");
  return v;
}
#else
uint32_t crc32c_hw(const unsigned char*, uint64_t, uint32_t) { return 0; }
bool crc32c_hw_available() { return false; }
#endif

}  // namespace

extern "C" {

uint32_t ts_crc32c(const void* buf, uint64_t len, uint32_t seed) {
  uint32_t crc = ~seed;
  const unsigned char* p = static_cast<const unsigned char*>(buf);
  crc = crc32c_hw_available() ? crc32c_hw(p, len, crc)
                              : crc32c_sw(p, len, crc);
  return ~crc;
}

// Fused write + integrity pass: write `len` bytes to a fresh file while
// computing the CRC32-C of every `page_size` page (seed 0 each, the
// integrity table's page format) in the same loop — each page is CRC'd
// while its bytes are still cache-hot from the write, and the blob
// makes one pass through memory instead of two. `out_page_crcs` must
// hold ceil(len / page_size) entries (0 pages for an empty blob).
int ts_write_file_crc(const char* path, const void* buf, uint64_t len,
                      uint64_t page_size, uint32_t* out_page_crcs,
                      int do_fsync) {
  if (page_size == 0) return -EINVAL;
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC | O_CLOEXEC, 0644);
  if (fd < 0) return -errno;
  const bool hw = crc32c_hw_available();
  const char* p = static_cast<const char*>(buf);
  uint64_t off = 0;
  int rc = 0;
  uint64_t page = 0;
  while (off < len) {
    uint64_t n = len - off < page_size ? len - off : page_size;
    rc = write_all(fd, p + off, n, off);
    if (rc != 0) break;
    const unsigned char* q = reinterpret_cast<const unsigned char*>(p + off);
    uint32_t crc = 0xFFFFFFFFu;
    crc = hw ? crc32c_hw(q, n, crc) : crc32c_sw(q, n, crc);
    out_page_crcs[page++] = ~crc;
    off += n;
  }
  if (rc == 0 && do_fsync) {
    if (::fdatasync(fd) != 0) rc = -errno;
  }
  if (::close(fd) != 0 && rc == 0) rc = -errno;
  return rc;
}

// Fused read + integrity pass, the mirror of ts_write_file_crc: read
// `len` bytes at `offset` while computing each `page_size` page's
// CRC32-C (seed 0, the integrity table's page format) cache-hot.
int ts_pread_crc(const char* path, void* buf, uint64_t len, uint64_t offset,
                 uint64_t page_size, uint32_t* out_page_crcs) {
  if (page_size == 0) return -EINVAL;
  int fd = ::open(path, O_RDONLY | O_CLOEXEC);
  if (fd < 0) return -errno;
  const bool hw = crc32c_hw_available();
  char* p = static_cast<char*>(buf);
  uint64_t done = 0;
  int rc = 0;
  uint64_t page = 0;
  while (done < len) {
    uint64_t n = len - done < page_size ? len - done : page_size;
    rc = read_all(fd, p + done, n, offset + done);
    if (rc != 0) break;
    const unsigned char* q = reinterpret_cast<const unsigned char*>(p + done);
    uint32_t crc = 0xFFFFFFFFu;
    crc = hw ? crc32c_hw(q, n, crc) : crc32c_sw(q, n, crc);
    out_page_crcs[page++] = ~crc;
    done += n;
  }
  if (::close(fd) != 0 && rc == 0) rc = -errno;
  return rc;
}

}  // extern "C"
