// Native I/O runtime for torchsnapshot_tpu.
//
// The reference library has no native code (SURVEY.md §2.9) — it leans on
// aiofiles' thread pool and torch internals. Here the file-I/O and
// slab-packing hot paths are C++: plain C-ABI functions loaded via ctypes
// (ctypes releases the GIL for the duration of every call, so N executor
// threads drive N concurrent pwrite/pread streams at full bandwidth).
//
// Design rules:
//  - C ABI only (no pybind11 in this image); every function is
//    exception-free and returns 0 / -errno.
//  - No allocation of caller-visible memory: callers own all buffers, so
//    the Python side keeps zero-copy memoryview semantics.
//  - Threaded gather-memcpy for slab packing: memory bandwidth on a many-
//    core host is only reachable with multiple streams.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <limits.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <thread>
#include <unistd.h>
#include <vector>

#ifndef O_DIRECT
// Non-Linux libc: no O_DIRECT flag. ts_write_file_crc_direct then opens
// with a zero flag and behaves like the buffered fused write — callers
// treat the path as "supported but not actually direct", which is the
// correct degradation (bytes and CRCs are identical either way).
#define O_DIRECT 0
#endif
#ifndef IOV_MAX
#define IOV_MAX 1024
#endif

namespace {

// Saturate transfer sizes to 1 GiB per syscall (Linux caps rw syscalls at
// 0x7ffff000 bytes anyway; looping also gives EINTR handling a boundary).
constexpr uint64_t kMaxIoChunk = 1ull << 30;

int write_all(int fd, const char* buf, uint64_t len, uint64_t offset) {
  while (len > 0) {
    uint64_t n = len < kMaxIoChunk ? len : kMaxIoChunk;
    ssize_t w = ::pwrite(fd, buf, n, static_cast<off_t>(offset));
    if (w < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    buf += w;
    offset += static_cast<uint64_t>(w);
    len -= static_cast<uint64_t>(w);
  }
  return 0;
}

int read_all(int fd, char* buf, uint64_t len, uint64_t offset) {
  while (len > 0) {
    uint64_t n = len < kMaxIoChunk ? len : kMaxIoChunk;
    ssize_t r = ::pread(fd, buf, n, static_cast<off_t>(offset));
    if (r < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (r == 0) return -EIO;  // short file: caller asked past EOF
    buf += r;
    offset += static_cast<uint64_t>(r);
    len -= static_cast<uint64_t>(r);
  }
  return 0;
}

}  // namespace

extern "C" {

// Write `len` bytes to a fresh file at `path` (O_TRUNC). `do_fsync`:
// 0 = none (commit protocol tolerates torn data files; metadata is the
// barrier), 1 = fdatasync before close.
int ts_write_file(const char* path, const void* buf, uint64_t len,
                  int do_fsync) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC | O_CLOEXEC, 0644);
  if (fd < 0) return -errno;
  int rc = write_all(fd, static_cast<const char*>(buf), len, 0);
  if (rc == 0 && do_fsync) {
    if (::fdatasync(fd) != 0) rc = -errno;
  }
  if (::close(fd) != 0 && rc == 0) rc = -errno;
  return rc;
}

// Read exactly `len` bytes at `offset` from `path` into caller's buffer.
int ts_pread_range(const char* path, void* buf, uint64_t len,
                   uint64_t offset) {
  int fd = ::open(path, O_RDONLY | O_CLOEXEC);
  if (fd < 0) return -errno;
  int rc = read_all(fd, static_cast<char*>(buf), len, offset);
  if (::close(fd) != 0 && rc == 0) rc = -errno;
  return rc;
}

int64_t ts_file_size(const char* path) {
  struct stat st;
  if (::stat(path, &st) != 0) return -static_cast<int64_t>(errno);
  return static_cast<int64_t>(st.st_size);
}

// Scatter `n` source buffers into `dst` at `dst_offsets`, using up to
// `n_threads` threads. Work is split by bytes, and a single large source
// region is itself split across threads, so one 1 GiB tensor doesn't
// serialize the pack.
void ts_gather_memcpy(void* dst, const void** srcs, const uint64_t* sizes,
                      const uint64_t* dst_offsets, uint64_t n,
                      int n_threads) {
  uint64_t total = 0;
  for (uint64_t i = 0; i < n; ++i) total += sizes[i];
  if (total == 0) return;
  if (n_threads < 1) n_threads = 1;
  uint64_t per_thread = (total + n_threads - 1) / n_threads;

  auto worker = [&](uint64_t begin, uint64_t end) {
    // [begin, end) in concatenated-byte space.
    uint64_t pos = 0;
    for (uint64_t i = 0; i < n && pos < end; ++i) {
      uint64_t lo = pos, hi = pos + sizes[i];
      pos = hi;
      if (hi <= begin) continue;
      uint64_t s = begin > lo ? begin - lo : 0;
      uint64_t e = (end < hi ? end : hi) - lo;
      if (e <= s) continue;
      std::memcpy(static_cast<char*>(dst) + dst_offsets[i] + s,
                  static_cast<const char*>(srcs[i]) + s, e - s);
    }
  };

  std::vector<std::thread> threads;
  for (int t = 1; t < n_threads; ++t) {
    uint64_t begin = per_thread * t;
    if (begin >= total) break;
    uint64_t end = begin + per_thread < total ? begin + per_thread : total;
    threads.emplace_back(worker, begin, end);
  }
  worker(0, per_thread < total ? per_thread : total);
  for (auto& th : threads) th.join();
}

}  // extern "C"

// CRC32-C (Castagnoli) for storage integrity records. The integrity
// pass runs once over every byte a take writes and a restore reads, so
// on slow cores a byte-at-a-time table CRC rivals the I/O it protects:
// use the SSE4.2 crc32 instruction when the CPU has it (runtime
// detected), else slicing-by-8 tables.

namespace {

struct Crc32cTables {
  uint32_t t[8][256];
  Crc32cTables() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c >> 1) ^ (0x82F63B78u & (0u - (c & 1)));
      t[0][i] = c;
    }
    for (int s = 1; s < 8; ++s)
      for (uint32_t i = 0; i < 256; ++i)
        t[s][i] = (t[s - 1][i] >> 8) ^ t[0][t[s - 1][i] & 0xFF];
  }
};

// Slicing-by-8 (little-endian): 8 bytes per step through 8 tables.
uint32_t crc32c_sw(const unsigned char* p, uint64_t len, uint32_t crc) {
  static const Crc32cTables tables;  // magic static: thread-safe init
  const auto& t = tables.t;
  while (len >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    v ^= crc;
    crc = t[7][v & 0xFF] ^ t[6][(v >> 8) & 0xFF] ^ t[5][(v >> 16) & 0xFF] ^
          t[4][(v >> 24) & 0xFF] ^ t[3][(v >> 32) & 0xFF] ^
          t[2][(v >> 40) & 0xFF] ^ t[1][(v >> 48) & 0xFF] ^
          t[0][(v >> 56) & 0xFF];
    p += 8;
    len -= 8;
  }
  while (len--) crc = t[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return crc;
}

#if defined(__x86_64__)
__attribute__((target("sse4.2"))) uint32_t crc32c_hw(const unsigned char* p,
                                                     uint64_t len,
                                                     uint32_t crc) {
  uint64_t c = crc;
  while (len >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    c = __builtin_ia32_crc32di(c, v);
    p += 8;
    len -= 8;
  }
  crc = static_cast<uint32_t>(c);
  while (len--) crc = __builtin_ia32_crc32qi(crc, *p++);
  return crc;
}

bool crc32c_hw_available() {
  static const bool v = __builtin_cpu_supports("sse4.2");
  return v;
}
#else
uint32_t crc32c_hw(const unsigned char*, uint64_t, uint32_t) { return 0; }
bool crc32c_hw_available() { return false; }
#endif

}  // namespace

extern "C" {

uint32_t ts_crc32c(const void* buf, uint64_t len, uint32_t seed) {
  uint32_t crc = ~seed;
  const unsigned char* p = static_cast<const unsigned char*>(buf);
  crc = crc32c_hw_available() ? crc32c_hw(p, len, crc)
                              : crc32c_sw(p, len, crc);
  return ~crc;
}

// Fused write + integrity pass: write `len` bytes to a fresh file while
// computing the CRC32-C of every `page_size` page (seed 0 each, the
// integrity table's page format) in the same loop — each page is CRC'd
// while its bytes are still cache-hot from the write, and the blob
// makes one pass through memory instead of two. `out_page_crcs` must
// hold ceil(len / page_size) entries (0 pages for an empty blob).
int ts_write_file_crc(const char* path, const void* buf, uint64_t len,
                      uint64_t page_size, uint32_t* out_page_crcs,
                      int do_fsync) {
  if (page_size == 0) return -EINVAL;
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC | O_CLOEXEC, 0644);
  if (fd < 0) return -errno;
  const bool hw = crc32c_hw_available();
  const char* p = static_cast<const char*>(buf);
  uint64_t off = 0;
  int rc = 0;
  uint64_t page = 0;
  while (off < len) {
    uint64_t n = len - off < page_size ? len - off : page_size;
    rc = write_all(fd, p + off, n, off);
    if (rc != 0) break;
    const unsigned char* q = reinterpret_cast<const unsigned char*>(p + off);
    uint32_t crc = 0xFFFFFFFFu;
    crc = hw ? crc32c_hw(q, n, crc) : crc32c_sw(q, n, crc);
    out_page_crcs[page++] = ~crc;
    off += n;
  }
  if (rc == 0 && do_fsync) {
    if (::fdatasync(fd) != 0) rc = -errno;
  }
  if (::close(fd) != 0 && rc == 0) rc = -errno;
  return rc;
}

// Zero-pack vectorized write: gather `n` caller-owned buffers straight
// into a fresh file with pwritev — no staging-buffer pack pass — while
// (optionally) computing the CRC32-C of every `page_size` page of the
// CONCATENATED byte stream, pages crossing iovec boundaries freely.
// `out_page_crcs` may be NULL (plain vectorized write, no integrity
// pass); otherwise it must hold ceil(sum(lens) / page_size) entries.
// Writes go out in cache-sized batches (<= IOV_MAX iovecs each) and
// each batch is CRC'd immediately after its pwritev returns, while the
// bytes are still cache-hot — the same one-memory-pass property as
// ts_write_file_crc, without the pack that used to precede it.
int ts_pwritev_file_crc(const char* path, const void** bufs,
                        const uint64_t* lens, uint64_t n,
                        uint64_t page_size, uint32_t* out_page_crcs,
                        int do_fsync) {
  if (out_page_crcs != nullptr && page_size == 0) return -EINVAL;
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC | O_CLOEXEC, 0644);
  if (fd < 0) return -errno;
  const bool hw = crc32c_hw_available();
  // Batch bound: small enough that the post-write CRC pass still finds
  // the bytes in cache, large enough to amortize the syscall.
  constexpr uint64_t kBatchBytes = 4ull << 20;
  int rc = 0;
  uint64_t off = 0;          // file offset (== bytes fully written)
  uint64_t i = 0;            // current source buffer
  uint64_t part_off = 0;     // progress within bufs[i]
  // Rolling page-CRC state across batches/iovecs.
  uint64_t page = 0;
  uint64_t page_fill = 0;
  uint32_t crc = 0xFFFFFFFFu;
  struct iovec iov[IOV_MAX];
  while (i < n) {
    // Assemble the next batch of iovecs.
    uint64_t bi = i, bpart = part_off, batch_bytes = 0;
    int cnt = 0;
    while (bi < n && cnt < IOV_MAX && batch_bytes < kBatchBytes) {
      uint64_t avail = lens[bi] - bpart;
      if (avail == 0) { ++bi; bpart = 0; continue; }
      uint64_t take = avail < kBatchBytes - batch_bytes
                          ? avail
                          : kBatchBytes - batch_bytes;
      iov[cnt].iov_base =
          const_cast<char*>(static_cast<const char*>(bufs[bi])) + bpart;
      iov[cnt].iov_len = static_cast<size_t>(take);
      ++cnt;
      batch_bytes += take;
      bpart += take;
      if (bpart == lens[bi]) { ++bi; bpart = 0; }
    }
    if (cnt == 0) { i = bi; part_off = bpart; continue; }
    // Write the batch, handling short writes by advancing the iovecs.
    uint64_t written = 0;
    int k = 0;
    while (written < batch_bytes) {
      ssize_t w = ::pwritev(fd, iov + k, cnt - k,
                            static_cast<off_t>(off + written));
      if (w < 0) {
        if (errno == EINTR) continue;
        rc = -errno;
        break;
      }
      written += static_cast<uint64_t>(w);
      uint64_t adv = static_cast<uint64_t>(w);
      while (adv > 0 && k < cnt) {
        if (adv >= iov[k].iov_len) {
          adv -= iov[k].iov_len;
          ++k;
        } else {
          iov[k].iov_base = static_cast<char*>(iov[k].iov_base) + adv;
          iov[k].iov_len -= static_cast<size_t>(adv);
          adv = 0;
        }
      }
    }
    if (rc != 0) break;
    // CRC the batch's bytes (cache-hot), chaining pages across
    // iovec/batch boundaries.
    if (out_page_crcs != nullptr) {
      uint64_t ci = i, cpart = part_off, left = batch_bytes;
      while (left > 0) {
        uint64_t avail = lens[ci] - cpart;
        if (avail == 0) { ++ci; cpart = 0; continue; }
        uint64_t take = avail < left ? avail : left;
        const unsigned char* q =
            static_cast<const unsigned char*>(bufs[ci]) + cpart;
        while (take > 0) {
          uint64_t room = page_size - page_fill;
          uint64_t span = take < room ? take : room;
          crc = hw ? crc32c_hw(q, span, crc) : crc32c_sw(q, span, crc);
          page_fill += span;
          q += span;
          take -= span;
          left -= span;
          cpart += span;
          if (page_fill == page_size) {
            out_page_crcs[page++] = ~crc;
            crc = 0xFFFFFFFFu;
            page_fill = 0;
          }
        }
        if (cpart == lens[ci]) { ++ci; cpart = 0; }
      }
    }
    off += batch_bytes;
    i = bi;
    part_off = bpart;
  }
  if (rc == 0 && out_page_crcs != nullptr && page_fill > 0) {
    out_page_crcs[page++] = ~crc;
  }
  if (rc == 0 && do_fsync) {
    if (::fdatasync(fd) != 0) rc = -errno;
  }
  if (::close(fd) != 0 && rc == 0) rc = -errno;
  return rc;
}

// Page-cache-bypassing fused write for large ALIGNED buffers: open
// O_DIRECT, write the 4096-aligned body straight to the device (the
// trainer never re-reads checkpoint bytes — caching them only evicts
// pages it will), write the unaligned tail through a second buffered
// fd, and compute each `page_size` page's CRC32-C in the same loop.
// `out_page_crcs` may be NULL (no integrity consumer — the plain-write
// path): the CRC pass is skipped entirely, and `page_size` 0 then
// defaults to an internal chunking unit. The caller guarantees `buf`
// is kDirectAlign-aligned; filesystems without O_DIRECT (tmpfs) fail
// the open with EINVAL, which the Python side treats as a sticky
// per-plugin decline back to the buffered path.
constexpr uint64_t kDirectAlign = 4096;

int ts_write_file_crc_direct(const char* path, const void* buf,
                             uint64_t len, uint64_t page_size,
                             uint32_t* out_page_crcs, int do_fsync) {
  if (out_page_crcs != nullptr &&
      (page_size == 0 || page_size % kDirectAlign != 0)) {
    return -EINVAL;
  }
  if (page_size == 0) page_size = 4ull << 20;
  if (page_size % kDirectAlign != 0) return -EINVAL;
  if (reinterpret_cast<uintptr_t>(buf) % kDirectAlign != 0) return -EINVAL;
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC | O_CLOEXEC | O_DIRECT,
                  0644);
  if (fd < 0) return -errno;
  const bool hw = crc32c_hw_available();
  const char* p = static_cast<const char*>(buf);
  const uint64_t body = len / kDirectAlign * kDirectAlign;
  uint64_t off = 0;
  uint64_t page = 0;
  int rc = 0;
  int tail_fd = -1;
  while (off < len) {
    uint64_t n = len - off < page_size ? len - off : page_size;
    uint64_t direct_n = off + n <= body ? n : (body > off ? body - off : 0);
    if (direct_n > 0) {
      rc = write_all(fd, p + off, direct_n, off);
      if (rc != 0) break;
    }
    if (direct_n < n) {
      // Unaligned tail (final page only): buffered fd, same file.
      if (tail_fd < 0) {
        tail_fd = ::open(path, O_WRONLY | O_CLOEXEC);
        if (tail_fd < 0) { rc = -errno; break; }
      }
      rc = write_all(tail_fd, p + off + direct_n, n - direct_n,
                     off + direct_n);
      if (rc != 0) break;
    }
    if (out_page_crcs != nullptr) {
      const unsigned char* q =
          reinterpret_cast<const unsigned char*>(p + off);
      uint32_t crc = 0xFFFFFFFFu;
      crc = hw ? crc32c_hw(q, n, crc) : crc32c_sw(q, n, crc);
      out_page_crcs[page++] = ~crc;
    }
    off += n;
  }
  if (rc == 0 && do_fsync) {
    if (::fdatasync(fd) != 0) rc = -errno;
    if (rc == 0 && tail_fd >= 0 && ::fdatasync(tail_fd) != 0) rc = -errno;
  }
  if (tail_fd >= 0 && ::close(tail_fd) != 0 && rc == 0) rc = -errno;
  if (::close(fd) != 0 && rc == 0) rc = -errno;
  return rc;
}

// Fused read + integrity pass, the mirror of ts_write_file_crc: read
// `len` bytes at `offset` while computing each `page_size` page's
// CRC32-C (seed 0, the integrity table's page format) cache-hot.
int ts_pread_crc(const char* path, void* buf, uint64_t len, uint64_t offset,
                 uint64_t page_size, uint32_t* out_page_crcs) {
  if (page_size == 0) return -EINVAL;
  int fd = ::open(path, O_RDONLY | O_CLOEXEC);
  if (fd < 0) return -errno;
  const bool hw = crc32c_hw_available();
  char* p = static_cast<char*>(buf);
  uint64_t done = 0;
  int rc = 0;
  uint64_t page = 0;
  while (done < len) {
    uint64_t n = len - done < page_size ? len - done : page_size;
    rc = read_all(fd, p + done, n, offset + done);
    if (rc != 0) break;
    const unsigned char* q = reinterpret_cast<const unsigned char*>(p + done);
    uint32_t crc = 0xFFFFFFFFu;
    crc = hw ? crc32c_hw(q, n, crc) : crc32c_sw(q, n, crc);
    out_page_crcs[page++] = ~crc;
    done += n;
  }
  if (::close(fd) != 0 && rc == 0) rc = -errno;
  return rc;
}

}  // extern "C"
