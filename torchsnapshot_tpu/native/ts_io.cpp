// Native I/O runtime for torchsnapshot_tpu.
//
// The reference library has no native code (SURVEY.md §2.9) — it leans on
// aiofiles' thread pool and torch internals. Here the file-I/O and
// slab-packing hot paths are C++: plain C-ABI functions loaded via ctypes
// (ctypes releases the GIL for the duration of every call, so N executor
// threads drive N concurrent pwrite/pread streams at full bandwidth).
//
// Design rules:
//  - C ABI only (no pybind11 in this image); every function is
//    exception-free and returns 0 / -errno.
//  - No allocation of caller-visible memory: callers own all buffers, so
//    the Python side keeps zero-copy memoryview semantics.
//  - Threaded gather-memcpy for slab packing: memory bandwidth on a many-
//    core host is only reachable with multiple streams.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// Saturate transfer sizes to 1 GiB per syscall (Linux caps rw syscalls at
// 0x7ffff000 bytes anyway; looping also gives EINTR handling a boundary).
constexpr uint64_t kMaxIoChunk = 1ull << 30;

int write_all(int fd, const char* buf, uint64_t len, uint64_t offset) {
  while (len > 0) {
    uint64_t n = len < kMaxIoChunk ? len : kMaxIoChunk;
    ssize_t w = ::pwrite(fd, buf, n, static_cast<off_t>(offset));
    if (w < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    buf += w;
    offset += static_cast<uint64_t>(w);
    len -= static_cast<uint64_t>(w);
  }
  return 0;
}

int read_all(int fd, char* buf, uint64_t len, uint64_t offset) {
  while (len > 0) {
    uint64_t n = len < kMaxIoChunk ? len : kMaxIoChunk;
    ssize_t r = ::pread(fd, buf, n, static_cast<off_t>(offset));
    if (r < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (r == 0) return -EIO;  // short file: caller asked past EOF
    buf += r;
    offset += static_cast<uint64_t>(r);
    len -= static_cast<uint64_t>(r);
  }
  return 0;
}

}  // namespace

extern "C" {

// Write `len` bytes to a fresh file at `path` (O_TRUNC). `do_fsync`:
// 0 = none (commit protocol tolerates torn data files; metadata is the
// barrier), 1 = fdatasync before close.
int ts_write_file(const char* path, const void* buf, uint64_t len,
                  int do_fsync) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC | O_CLOEXEC, 0644);
  if (fd < 0) return -errno;
  int rc = write_all(fd, static_cast<const char*>(buf), len, 0);
  if (rc == 0 && do_fsync) {
    if (::fdatasync(fd) != 0) rc = -errno;
  }
  if (::close(fd) != 0 && rc == 0) rc = -errno;
  return rc;
}

// Read exactly `len` bytes at `offset` from `path` into caller's buffer.
int ts_pread_range(const char* path, void* buf, uint64_t len,
                   uint64_t offset) {
  int fd = ::open(path, O_RDONLY | O_CLOEXEC);
  if (fd < 0) return -errno;
  int rc = read_all(fd, static_cast<char*>(buf), len, offset);
  if (::close(fd) != 0 && rc == 0) rc = -errno;
  return rc;
}

int64_t ts_file_size(const char* path) {
  struct stat st;
  if (::stat(path, &st) != 0) return -static_cast<int64_t>(errno);
  return static_cast<int64_t>(st.st_size);
}

// Scatter `n` source buffers into `dst` at `dst_offsets`, using up to
// `n_threads` threads. Work is split by bytes, and a single large source
// region is itself split across threads, so one 1 GiB tensor doesn't
// serialize the pack.
void ts_gather_memcpy(void* dst, const void** srcs, const uint64_t* sizes,
                      const uint64_t* dst_offsets, uint64_t n,
                      int n_threads) {
  uint64_t total = 0;
  for (uint64_t i = 0; i < n; ++i) total += sizes[i];
  if (total == 0) return;
  if (n_threads < 1) n_threads = 1;
  uint64_t per_thread = (total + n_threads - 1) / n_threads;

  auto worker = [&](uint64_t begin, uint64_t end) {
    // [begin, end) in concatenated-byte space.
    uint64_t pos = 0;
    for (uint64_t i = 0; i < n && pos < end; ++i) {
      uint64_t lo = pos, hi = pos + sizes[i];
      pos = hi;
      if (hi <= begin) continue;
      uint64_t s = begin > lo ? begin - lo : 0;
      uint64_t e = (end < hi ? end : hi) - lo;
      if (e <= s) continue;
      std::memcpy(static_cast<char*>(dst) + dst_offsets[i] + s,
                  static_cast<const char*>(srcs[i]) + s, e - s);
    }
  };

  std::vector<std::thread> threads;
  for (int t = 1; t < n_threads; ++t) {
    uint64_t begin = per_thread * t;
    if (begin >= total) break;
    uint64_t end = begin + per_thread < total ? begin + per_thread : total;
    threads.emplace_back(worker, begin, end);
  }
  worker(0, per_thread < total ? per_thread : total);
  for (auto& th : threads) th.join();
}

// CRC32-C (Castagnoli), table-driven; for storage integrity records.
uint32_t ts_crc32c(const void* buf, uint64_t len, uint32_t seed) {
  struct Table {
    uint32_t v[256];
    Table() {
      for (uint32_t i = 0; i < 256; ++i) {
        uint32_t c = i;
        for (int k = 0; k < 8; ++k)
          c = (c >> 1) ^ (0x82F63B78u & (0u - (c & 1)));
        v[i] = c;
      }
    }
  };
  static const Table table_holder;  // magic static: thread-safe init
  const uint32_t* table = table_holder.v;
  uint32_t crc = ~seed;
  const unsigned char* p = static_cast<const unsigned char*>(buf);
  for (uint64_t i = 0; i < len; ++i)
    crc = table[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

}  // extern "C"
