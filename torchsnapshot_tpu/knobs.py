"""Environment-variable knobs with test-friendly override context managers.

Reference parity: torchsnapshot/knobs.py:21-98. Same knob surface (max chunk
size, max shard size, slab threshold, batching toggle, per-rank memory budget
override, partitioner kill-switch), re-homed under the ``TORCHSNAPSHOT_TPU_``
prefix. Values are read lazily on every call so tests and subprocesses can
flip them at any time.

Throughput-relevant knobs (the *tunable* set: staging threads, per-rank
I/O concurrency, staging-pool geometry, memory-budget fraction,
chunk/shard/slab-threshold sizes) additionally honor a **programmatic
override layer** — the write surface of the closed-loop autotuner
(``torchsnapshot_tpu/tuner``). Precedence is fixed: an env var (operator
intent) always wins; a tuner override applies only where no env var is
set; the documented default closes the chain. Everything below the env
var is process-local state — nothing the tuner does leaks into
subprocesses or survives a restart (the tuner's own decision log does,
``.tuner-state.json``). See docs/tuning.md.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, Generator, Optional, Union

_MAX_CHUNK_SIZE_BYTES_ENV = "TORCHSNAPSHOT_TPU_MAX_CHUNK_SIZE_BYTES"
_MAX_SHARD_SIZE_BYTES_ENV = "TORCHSNAPSHOT_TPU_MAX_SHARD_SIZE_BYTES"
_SLAB_SIZE_THRESHOLD_BYTES_ENV = "TORCHSNAPSHOT_TPU_SLAB_SIZE_THRESHOLD_BYTES"
_ENABLE_BATCHING_ENV = "TORCHSNAPSHOT_TPU_ENABLE_BATCHING"
_PER_RANK_MEMORY_BUDGET_BYTES_ENV = "TORCHSNAPSHOT_TPU_PER_RANK_MEMORY_BUDGET_BYTES"
_DISABLE_PARTITIONER_ENV = "TORCHSNAPSHOT_TPU_DISABLE_PARTITIONER"
_PER_RANK_IO_CONCURRENCY_ENV = "TORCHSNAPSHOT_TPU_PER_RANK_IO_CONCURRENCY"
_STAGING_THREADS_ENV = "TORCHSNAPSHOT_TPU_STAGING_THREADS"
_DISABLE_CHECKSUMS_ENV = "TORCHSNAPSHOT_TPU_DISABLE_CHECKSUMS"
_S3_ENDPOINT_URL_ENV = "TORCHSNAPSHOT_TPU_S3_ENDPOINT"
_INCREMENTAL_CHUNK_SIZE_BYTES_ENV = "TORCHSNAPSHOT_TPU_INCREMENTAL_CHUNK_BYTES"
_DEVICE_PACK_ENV = "TORCHSNAPSHOT_TPU_DEVICE_PACK"
_RESTORE_FLUSH_BYTES_ENV = "TORCHSNAPSHOT_TPU_RESTORE_PLACEMENT_FLUSH_BYTES"
_MIRROR_IO_CONCURRENCY_ENV = "TORCHSNAPSHOT_TPU_MIRROR_IO_CONCURRENCY"
_MIRROR_PROGRESS_WINDOW_ENV = (
    "TORCHSNAPSHOT_TPU_MIRROR_PROGRESS_WINDOW_SECONDS"
)
_TELEMETRY_ENV = "TORCHSNAPSHOT_TPU_TELEMETRY"
_TELEMETRY_DIR_ENV = "TORCHSNAPSHOT_TPU_TELEMETRY_DIR"
_PROM_FILE_ENV = "TORCHSNAPSHOT_TPU_PROM_FILE"
_TRACE_ENV = "TORCHSNAPSHOT_TPU_TRACE"
_TRACE_DIR_ENV = "TORCHSNAPSHOT_TPU_TRACE_DIR"
_TRACE_BUFFER_EVENTS_ENV = "TORCHSNAPSHOT_TPU_TRACE_BUFFER_EVENTS"
_WATCHDOG_SECONDS_ENV = "TORCHSNAPSHOT_TPU_WATCHDOG_SECONDS"
_DISABLE_NATIVE_ENV = "TORCHSNAPSHOT_TPU_DISABLE_NATIVE"
_WAIT_DURABLE_TIMEOUT_ENV = "TORCHSNAPSHOT_TPU_WAIT_DURABLE_TIMEOUT_SECONDS"
_PROGRESS_SECONDS_ENV = "TORCHSNAPSHOT_TPU_PROGRESS_SECONDS"
_PROGRESS_DIR_ENV = "TORCHSNAPSHOT_TPU_PROGRESS_DIR"
_HISTORY_MAX_RECORDS_ENV = "TORCHSNAPSHOT_TPU_HISTORY_MAX_RECORDS"
_ASYNC_DEVICE_SNAPSHOT_ENV = "TORCHSNAPSHOT_TPU_ASYNC_DEVICE_SNAPSHOT"
_STAGING_POOL_SLAB_BYTES_ENV = "TORCHSNAPSHOT_TPU_STAGING_POOL_SLAB_BYTES"
_STAGING_POOL_SLABS_ENV = "TORCHSNAPSHOT_TPU_STAGING_POOL_SLABS"
_ASYNC_VISIBLE_BUDGET_ENV = "TORCHSNAPSHOT_TPU_ASYNC_VISIBLE_BUDGET_SECONDS"
_AUTOTUNE_ENV = "TORCHSNAPSHOT_TPU_AUTOTUNE"
_MEMORY_BUDGET_FRACTION_ENV = "TORCHSNAPSHOT_TPU_MEMORY_BUDGET_FRACTION"
_FANOUT_RESTORE_ENV = "TORCHSNAPSHOT_TPU_FANOUT_RESTORE"
_LEDGER_ENV = "TORCHSNAPSHOT_TPU_LEDGER"
_LEDGER_MAX_RECORDS_ENV = "TORCHSNAPSHOT_TPU_LEDGER_MAX_RECORDS"
_PEER_TIER_ENV = "TORCHSNAPSHOT_TPU_PEER_TIER"
_PEER_RING_OFFSET_ENV = "TORCHSNAPSHOT_TPU_PEER_RING_OFFSET"
_PEER_CACHE_BUDGET_BYTES_ENV = "TORCHSNAPSHOT_TPU_PEER_CACHE_BUDGET_BYTES"
_PEER_TRANSFER_TIMEOUT_ENV = (
    "TORCHSNAPSHOT_TPU_PEER_TRANSFER_TIMEOUT_SECONDS"
)
_WRITE_VECTORIZED_ENV = "TORCHSNAPSHOT_TPU_WRITE_VECTORIZED"
_FS_DIRECT_IO_ENV = "TORCHSNAPSHOT_TPU_FS_DIRECT_IO"
_CAS_ENV = "TORCHSNAPSHOT_TPU_CAS"
_CAS_GC_GRACE_ENV = "TORCHSNAPSHOT_TPU_CAS_GC_GRACE_SECONDS"
_CDN_ENV = "TORCHSNAPSHOT_TPU_CDN"
_CDN_STALENESS_BUDGET_ENV = (
    "TORCHSNAPSHOT_TPU_CDN_STALENESS_BUDGET_SECONDS"
)
_CDN_PULL_TIMEOUT_ENV = "TORCHSNAPSHOT_TPU_CDN_PULL_TIMEOUT_SECONDS"
_TREE_BARRIER_ENV = "TORCHSNAPSHOT_TPU_TREE_BARRIER"
_BARRIER_FANOUT_ENV = "TORCHSNAPSHOT_TPU_BARRIER_FANOUT"
_STORE_SHARDS_ENV = "TORCHSNAPSHOT_TPU_STORE_SHARDS"
_FLEET_OBS_ENV = "TORCHSNAPSHOT_TPU_FLEET_OBS"
_SLO_ENV = "TORCHSNAPSHOT_TPU_SLO"
_SLO_FAST_WINDOW_ENV = "TORCHSNAPSHOT_TPU_SLO_FAST_WINDOW"
_SLO_SLOW_WINDOW_ENV = "TORCHSNAPSHOT_TPU_SLO_SLOW_WINDOW"
_SLO_FAST_BURN_ENV = "TORCHSNAPSHOT_TPU_SLO_FAST_BURN_THRESHOLD"
_SLO_SLOW_BURN_ENV = "TORCHSNAPSHOT_TPU_SLO_SLOW_BURN_THRESHOLD"
_SLO_ERROR_BUDGET_ENV = "TORCHSNAPSHOT_TPU_SLO_ERROR_BUDGET_FRACTION"
_SLO_RESTORE_BUDGET_ENV = "TORCHSNAPSHOT_TPU_SLO_RESTORE_SECONDS"
_SLO_MIRROR_LAG_BUDGET_ENV = "TORCHSNAPSHOT_TPU_SLO_MIRROR_LAG_SECONDS"
_SLO_OVERHEAD_BUDGET_ENV = "TORCHSNAPSHOT_TPU_SLO_OVERHEAD_FRACTION"
_SLO_COORD_BUDGET_ENV = "TORCHSNAPSHOT_TPU_SLO_COORDINATION_FRACTION"
_BUNDLE_DIR_ENV = "TORCHSNAPSHOT_TPU_BUNDLE_DIR"
_BUNDLE_MAX_BYTES_ENV = "TORCHSNAPSHOT_TPU_BUNDLE_MAX_BYTES"
_BUNDLE_MIN_INTERVAL_ENV = (
    "TORCHSNAPSHOT_TPU_BUNDLE_MIN_INTERVAL_SECONDS"
)
_COLD_START_BUDGET_FRACTION_ENV = (
    "TORCHSNAPSHOT_TPU_COLD_START_BUDGET_FRACTION"
)

_DEFAULT_TRACE_BUFFER_EVENTS: int = 16384
_DEFAULT_WATCHDOG_SECONDS: float = 60.0
_DEFAULT_WAIT_DURABLE_TIMEOUT_SECONDS: float = 1800.0
_DEFAULT_PROGRESS_SECONDS: float = 1.0
_DEFAULT_HISTORY_MAX_RECORDS: int = 512
_DEFAULT_LEDGER_MAX_RECORDS: int = 4096

# Fanout 16 measured best at world 256 over TCP in the scale-model
# sweep (depth 2 up to 4096 ranks; 8 pays an extra level's release
# latency, 32 re-concentrates arrivals) — see docs/scaling.md.
_DEFAULT_BARRIER_FANOUT: int = 16
_DEFAULT_STORE_SHARDS: int = 1

_DEFAULT_PEER_RING_OFFSET: int = 1
_DEFAULT_PEER_CACHE_BUDGET_BYTES: int = 1024 * 1024 * 1024
_DEFAULT_PEER_TRANSFER_TIMEOUT_SECONDS: float = 30.0

_DEFAULT_STAGING_POOL_SLAB_BYTES: int = 128 * 1024 * 1024
_DEFAULT_STAGING_POOL_SLABS: int = 2
_DEFAULT_ASYNC_VISIBLE_BUDGET_SECONDS: float = 5.0

_DEFAULT_MAX_CHUNK_SIZE_BYTES: int = 512 * 1024 * 1024
_DEFAULT_MAX_SHARD_SIZE_BYTES: int = 512 * 1024 * 1024
_DEFAULT_SLAB_SIZE_THRESHOLD_BYTES: int = 128 * 1024 * 1024
_DEFAULT_INCREMENTAL_CHUNK_SIZE_BYTES: int = 16 * 1024 * 1024
_DEFAULT_RESTORE_FLUSH_BYTES: int = 128 * 1024 * 1024
_DEFAULT_MEMORY_BUDGET_FRACTION: float = 0.6

_DEFAULT_SLO_FAST_WINDOW: int = 8
_DEFAULT_SLO_SLOW_WINDOW: int = 64
_DEFAULT_SLO_FAST_BURN_THRESHOLD: float = 2.0
_DEFAULT_SLO_SLOW_BURN_THRESHOLD: float = 1.0
_DEFAULT_SLO_ERROR_BUDGET_FRACTION: float = 0.1
_DEFAULT_SLO_RESTORE_SECONDS: float = 60.0
_DEFAULT_SLO_MIRROR_LAG_SECONDS: float = 120.0
_DEFAULT_SLO_OVERHEAD_FRACTION: float = 0.1
_DEFAULT_SLO_COORDINATION_FRACTION: float = 0.3
_DEFAULT_BUNDLE_MAX_BYTES: int = 64 * 1024 * 1024
_DEFAULT_BUNDLE_MIN_INTERVAL_SECONDS: float = 300.0
_DEFAULT_COLD_START_BUDGET_FRACTION: float = 0.5


def _get_int_env(name: str, default: int) -> int:
    val = os.environ.get(name)
    if val is None:
        return default
    return int(val)


# ---------------------------------------------------------------------------
# Programmatic tunable overrides (the autotuner's write surface).
#
# Keyed by env-var name so a tuner decision and the operator escape hatch
# name the same thing. Guarded by a lock: the autotuner applies vectors
# from async-save commit threads while pipelines read concurrently.
# ---------------------------------------------------------------------------

_TUNER_OVERRIDES: Dict[str, Union[int, float]] = {}
_TUNER_OVERRIDES_LOCK = threading.Lock()


def set_tuner_override(env_name: str, value: Union[int, float]) -> None:
    """Install one tunable's programmatic value. Applies only while no
    env var of the same name is set — env always wins (the operator's
    hand-set value is the one thing the tuner must never fight)."""
    with _TUNER_OVERRIDES_LOCK:
        _TUNER_OVERRIDES[env_name] = value


def clear_tuner_override(env_name: str) -> None:
    with _TUNER_OVERRIDES_LOCK:
        _TUNER_OVERRIDES.pop(env_name, None)


def clear_tuner_overrides() -> None:
    """Drop every programmatic override (kill switch / test teardown)."""
    with _TUNER_OVERRIDES_LOCK:
        _TUNER_OVERRIDES.clear()


def get_tuner_overrides() -> Dict[str, Union[int, float]]:
    """Snapshot of the active programmatic overrides (copy)."""
    with _TUNER_OVERRIDES_LOCK:
        return dict(_TUNER_OVERRIDES)


def _get_tunable_int(name: str, default: int) -> int:
    """Override-aware read for tunable knobs: env var > tuner override >
    default. The accessor every tunable getter routes through (snaplint's
    knob-env-literal rule keeps direct env reads of tunable names out of
    the rest of the package, so the precedence chain cannot fork)."""
    val = os.environ.get(name)
    if val is not None:
        return int(val)
    with _TUNER_OVERRIDES_LOCK:
        ov = _TUNER_OVERRIDES.get(name)
    if ov is not None:
        return int(ov)
    return default


def _get_tunable_float(name: str, default: float) -> float:
    val = os.environ.get(name)
    if val is not None:
        return float(val)
    with _TUNER_OVERRIDES_LOCK:
        ov = _TUNER_OVERRIDES.get(name)
    if ov is not None:
        return float(ov)
    return default


def get_max_chunk_size_bytes() -> int:
    """Arrays larger than this are split into chunks written independently."""
    return _get_tunable_int(
        _MAX_CHUNK_SIZE_BYTES_ENV, _DEFAULT_MAX_CHUNK_SIZE_BYTES
    )


def get_max_shard_size_bytes() -> int:
    """Device shards larger than this are subdivided before writing."""
    return _get_tunable_int(
        _MAX_SHARD_SIZE_BYTES_ENV, _DEFAULT_MAX_SHARD_SIZE_BYTES
    )


def get_slab_size_threshold_bytes() -> int:
    """Write requests smaller than this are eligible for slab batching."""
    return _get_tunable_int(
        _SLAB_SIZE_THRESHOLD_BYTES_ENV, _DEFAULT_SLAB_SIZE_THRESHOLD_BYTES
    )


def is_batching_enabled() -> bool:
    """Batching is opt-in; presence of the env var turns it on
    (reference: knobs.py:53-57)."""
    return _ENABLE_BATCHING_ENV in os.environ


def get_per_rank_memory_budget_bytes_override() -> Optional[int]:
    val = os.environ.get(_PER_RANK_MEMORY_BUDGET_BYTES_ENV)
    return int(val) if val is not None else None


def is_partitioner_disabled() -> bool:
    return _DISABLE_PARTITIONER_ENV in os.environ


def get_per_rank_io_concurrency() -> int:
    """Max concurrent storage I/O ops per process (reference: scheduler.py:30)."""
    return _get_tunable_int(_PER_RANK_IO_CONCURRENCY_ENV, 16)


def get_s3_endpoint_url() -> Optional[str]:
    """Non-AWS S3-compatible endpoint (MinIO CI lanes, private object
    stores); unset = real S3."""
    return os.environ.get(_S3_ENDPOINT_URL_ENV) or None


def get_staging_threads() -> int:
    """Threads for device->host staging / (de)serialization
    (reference: scheduler.py:29)."""
    return _get_tunable_int(_STAGING_THREADS_ENV, 4)


def is_checksums_disabled() -> bool:
    """Blob CRC recording (take) and verification (restore) are on by
    default; presence of the env var disables both."""
    return _DISABLE_CHECKSUMS_ENV in os.environ


def is_device_pack_enabled() -> bool:
    """Opt-in: slab members resident on device are packed into one uint8
    buffer by a fused XLA program and leave via a single D2H transfer
    (the reference's GPU-slab analog). Pays when per-transfer overhead
    dominates (very many tiny leaves, high per-call-latency hosts);
    measured slower than prefetched per-member transfers on links that
    pipeline small async copies well — hence off by default, like
    batching itself."""
    return _DEVICE_PACK_ENV in os.environ


def get_incremental_chunk_size_bytes() -> int:
    """Chunk/shard-piece granularity for digest-enabled takes: the skip
    unit of incremental checkpointing. Tighter than the plain chunk knob
    (a sparse update dirties only the chunks its rows land in); applied
    as ``min`` with the chunk/shard knobs whenever digests are recorded,
    so boundaries stay stable across the base/incremental chain."""
    return _get_int_env(
        _INCREMENTAL_CHUNK_SIZE_BYTES_ENV, _DEFAULT_INCREMENTAL_CHUNK_SIZE_BYTES
    )


def get_mirror_io_concurrency() -> int:
    """Max concurrent blob uploads inside the tiered-storage background
    mirror. Defaults to the per-rank I/O concurrency: the mirror contends
    with the next take's fast-tier writes, not with the take's durable
    writes (those no longer exist), so the same bound applies."""
    val = os.environ.get(_MIRROR_IO_CONCURRENCY_ENV)
    if val is not None:
        return int(val)
    return get_per_rank_io_concurrency()


def get_mirror_progress_window_seconds() -> float:
    """Collective-progress retry window for the tiered mirror's durable
    uploads (storage_plugins/retry.py semantics: any completed upload
    refreshes the shared deadline)."""
    val = os.environ.get(_MIRROR_PROGRESS_WINDOW_ENV)
    if val is not None:
        return float(val)
    from .storage_plugins.retry import DEFAULT_PROGRESS_WINDOW_SECONDS

    return DEFAULT_PROGRESS_WINDOW_SECONDS


def get_telemetry_dir() -> Optional[str]:
    """Local directory for the telemetry JSONL event log
    (``<dir>/events.jsonl``). Takes precedence over the
    snapshot-adjacent sink; unset = no directory sink."""
    return os.environ.get(_TELEMETRY_DIR_ENV) or None


def is_telemetry_sink_enabled() -> bool:
    """Snapshot-adjacent JSONL sink toggle: with the env var present,
    every take/restore/mirror against a *local* snapshot path appends
    its SnapshotReport to ``<snapshot>/.telemetry.jsonl``. A telemetry
    dir (above) also counts as enablement — reports then go there
    instead. The registry itself always records; these knobs only
    control whether anything is written out."""
    return _TELEMETRY_ENV in os.environ or get_telemetry_dir() is not None


def get_trace_dir() -> Optional[str]:
    """Local directory for flight-recorder Chrome-trace exports
    (``<dir>/trace-<kind>-rank<r>.json``). Takes precedence over the
    snapshot-adjacent trace files; unset = no directory sink."""
    return os.environ.get(_TRACE_DIR_ENV) or None


def is_trace_sink_enabled() -> bool:
    """Trace-export toggle: with the env var present, every
    take/restore/mirror against a *local* snapshot path writes its span
    timeline to ``<snapshot>/.trace-<kind>-rank<r>.json``. A trace dir
    (above) also counts as enablement. The flight recorder itself
    always records into its bounded ring; these knobs only control
    whether timelines are written out."""
    return _TRACE_ENV in os.environ or get_trace_dir() is not None


def get_trace_buffer_events() -> int:
    """Flight-recorder ring capacity, in completed events. Oldest
    events evict first; the recorder counts what it dropped."""
    return _get_int_env(_TRACE_BUFFER_EVENTS_ENV, _DEFAULT_TRACE_BUFFER_EVENTS)


def get_watchdog_deadline_seconds() -> float:
    """Open-span age past which the stall watchdog fires (emits a
    ``watchdog:stall`` instant event, logs the open-span tree + thread
    stacks, bumps ``watchdog_stalls_total``). <= 0 disables the
    watchdog; the test suite's conftest sets 0 so only opted-in tests
    exercise it. Re-read on every watchdog scan, so overrides apply to
    a live watchdog thread."""
    val = os.environ.get(_WATCHDOG_SECONDS_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_WATCHDOG_SECONDS


def is_native_disabled() -> bool:
    """Kill-switch for the ctypes native I/O runtime (``_native.py``):
    presence of the env var keeps ``lib()`` returning None so every
    caller stays on its pure-Python path. Behavior is identical either
    way, only slower — the switch exists for bisecting suspected
    native-path issues and for machines where building the .so is
    undesirable."""
    return _DISABLE_NATIVE_ENV in os.environ


def get_wait_durable_timeout_seconds() -> float:
    """Default deadline for durability barriers (``wait_durable`` on the
    manager and the tiered mirror) when the caller passes no explicit
    timeout. A mirror wedged on a browning-out durable tier must
    surface as a ``TimeoutError`` naming the step, not as an unbounded
    poll loop only the stall watchdog can see into. <= 0 restores the
    old unbounded wait (explicitly opted into, never the default)."""
    val = os.environ.get(_WAIT_DURABLE_TIMEOUT_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_WAIT_DURABLE_TIMEOUT_SECONDS


def get_progress_interval_seconds() -> float:
    """Minimum interval between live-progress heartbeat rewrites
    (``<snapshot>/.progress-rank<r>.json``, telemetry/progress.py).
    <= 0 disables the file heartbeat entirely; the in-memory
    ``telemetry.current_progress()`` view is always on regardless. The
    test conftest sets 0 so the fast suite's snapshot dirs stay
    deterministic."""
    val = os.environ.get(_PROGRESS_SECONDS_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_PROGRESS_SECONDS


def get_progress_dir() -> Optional[str]:
    """Local directory for live-progress heartbeat files
    (``<dir>/progress-rank<r>.json``). Takes precedence over the
    snapshot-adjacent heartbeat — the object-store escape hatch, like
    the telemetry/trace dir knobs; unset = snapshot-adjacent when the
    snapshot path is local."""
    return os.environ.get(_PROGRESS_DIR_ENV) or None


def get_history_max_records() -> int:
    """Bound on the per-manager rolling step-telemetry history
    (``<root>/.telemetry-history.jsonl``, telemetry/history.py): the
    newest N summaries are kept, older ones rewritten away. <= 0
    disables history recording entirely; the test conftest sets 0 so
    tier-1 manager tests stay deterministic."""
    val = os.environ.get(_HISTORY_MAX_RECORDS_ENV)
    if val is not None:
        return int(val)
    return _DEFAULT_HISTORY_MAX_RECORDS


def is_ledger_enabled() -> bool:
    """The run-level goodput ledger (``<root>/.ledger.jsonl``,
    telemetry/ledger.py): on by default — the manager, snapshot
    envelopes, tiered mirror, preemption saver, and GC post typed
    events rank-0-only, and the goodput engine attributes the run's
    wall time from them (docs/goodput.md). Set to ``"0"`` to disable
    every ledger read/write (no file appears in the root; the test
    conftest pins 0 so tier-1 manager dirs stay deterministic). A
    non-positive max-records bound (below) also disables recording."""
    return (
        os.environ.get(_LEDGER_ENV, "1") != "0"
        and get_ledger_max_records() > 0
    )


def get_ledger_max_records() -> int:
    """Bound on the run ledger: the newest N records are kept, older
    ones trimmed away (the newest run-start is always retained so the
    active run's attribution never loses its anchor). <= 0 disables
    ledger recording entirely."""
    val = os.environ.get(_LEDGER_MAX_RECORDS_ENV)
    if val is not None:
        return int(val)
    return _DEFAULT_LEDGER_MAX_RECORDS


def is_async_device_snapshot_enabled() -> bool:
    """Default-on device-snapshot async takes: ``async_take`` pins a
    consistent snapshot before returning (on-device clones for jax
    leaves — dispatched, not awaited; host copies for mutable numpy
    leaves; eager pickles for objects) and defers the whole D2H +
    serialize + write pipeline to the background commit thread, so the
    training-visible span is independent of checkpoint size. Costs a
    transient ~1x copy of the saved device state in HBM. Set to ``"0"``
    to restore the pre-deferral behavior (staging completes before
    ``async_take`` returns; no device clone, no extra HBM)."""
    return os.environ.get(_ASYNC_DEVICE_SNAPSHOT_ENV, "1") != "0"


def get_staging_pool_slab_bytes() -> int:
    """Slab size of the background drain's host staging pool
    (scheduler.StagingPool). Together with the slab count this bounds
    the deferred async take's host staging footprint; the pool never
    exceeds the process memory budget it is accounted against."""
    return _get_tunable_int(
        _STAGING_POOL_SLAB_BYTES_ENV, _DEFAULT_STAGING_POOL_SLAB_BYTES
    )


def get_staging_pool_slabs() -> int:
    """Slab count of the background drain's host staging pool. The
    default of 2 is classic double buffering: one slab's worth of
    requests stages (D2H + serialize) while the previous slab's worth
    drains to storage."""
    return _get_tunable_int(_STAGING_POOL_SLABS_ENV, _DEFAULT_STAGING_POOL_SLABS)


def get_async_visible_budget_seconds() -> float:
    """Threshold for the checkpoint doctor's ``async-visible-stall``
    rule: an async take whose training-visible span (``async_take``
    return-to-caller time, recorded as ``visible_s`` in its
    SnapshotReport) exceeds this budget is flagged — with device
    snapshotting on, the visible span should be plan + capture dispatch,
    never the D2H drain. <= 0 disables the rule."""
    val = os.environ.get(_ASYNC_VISIBLE_BUDGET_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_ASYNC_VISIBLE_BUDGET_SECONDS


def is_autotune_enabled() -> bool:
    """The write-path autotuner's kill switch: set to ``"0"`` and the
    tuner never runs — no ``.tuner-state.json`` reads/writes, no knob
    overrides, no cross-rank decision broadcast; behavior is identical
    to a build without the tuner (pinned by test). Default on: recurring
    manager saves are the tuner's training signal and the whole point is
    working without per-environment hand-tuning. Hand-set env knobs are
    individually respected either way (env always wins per knob)."""
    return os.environ.get(_AUTOTUNE_ENV, "1") != "0"


def is_fanout_restore_enabled() -> bool:
    """Single-reader fan-out restore (docs/restore.md): in a multi-rank
    restore, each unique saved shard blob is fetched from the storage
    plugin by exactly one owner rank and distributed to the peers that
    need it over the coordination store's object collectives — a fleet
    of N restoring processes pays ~1x storage reads instead of Nx. Set
    to ``"0"`` to fall back to every-rank-reads (each process pulls its
    own bytes straight from storage — the pre-fan-out behavior, and the
    right choice when storage bandwidth dwarfs the coordinator link).
    Rank 0's value decides for the whole job (broadcast-agreed at
    restore start), so env skew across ranks can never diverge the
    collective schedule. Single-process restores never fan out."""
    return os.environ.get(_FANOUT_RESTORE_ENV, "1") != "0"


def is_peer_tier_enabled() -> bool:
    """Peer-redundant hot checkpoints (docs/peer.md): every rank pushes
    its committed shards into a neighbor rank's host-RAM cache (ring
    placement), and restores resolve a peer RAM -> local fast tier ->
    durable ladder per shard — so recovery after a single-host
    preemption is bounded by host-RAM copy speed, not storage. On by
    default, but inert until a process group with a coordination store
    is configured (``CheckpointManager(pg=...)`` or an explicit
    ``tiered.peer.maybe_configure``) — single-process jobs never start
    a server. Set to ``"0"`` to kill the tier entirely: no server, no
    pushes, no pulls; restores read exactly the pre-peer path. Every
    peer failure mode degrades to a correct-if-slower restore either
    way; the switch exists for bisecting and for fleets whose
    interconnect should not carry checkpoint bytes."""
    return os.environ.get(_PEER_TIER_ENV, "1") != "0"


def get_peer_ring_offset() -> int:
    """Ring placement distance: rank ``r`` pushes its shards to rank
    ``(r + offset) % world``. The default of +1 survives any single-rank
    preemption; widen it (e.g. to the hosts-per-failure-domain count)
    when co-scheduled neighbors tend to be preempted together."""
    return _get_int_env(_PEER_RING_OFFSET_ENV, _DEFAULT_PEER_RING_OFFSET)


def get_peer_cache_budget_bytes() -> int:
    """Host-RAM bound on one process's peer cache (the shards pushed TO
    this rank). LRU by step with the newest committed step pinned; a
    push that cannot fit even after eviction is refused — the pusher
    degrades to storage-only durability for that blob, never the cache
    over its budget."""
    return _get_int_env(
        _PEER_CACHE_BUDGET_BYTES_ENV, _DEFAULT_PEER_CACHE_BUDGET_BYTES
    )


def get_peer_transfer_timeout_seconds() -> float:
    """Per-transfer deadline (connect + one blob push or pull) on the
    peer transport, and the no-progress retry window for pushes. A dead
    peer costs a pusher at most a few of these before the job degrades
    (WARN + ``peer_tier_degraded``); a puller falls through to the next
    tier after one."""
    val = os.environ.get(_PEER_TRANSFER_TIMEOUT_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_PEER_TRANSFER_TIMEOUT_SECONDS


_DEFAULT_CAS_GC_GRACE_SECONDS = 900.0


def is_cas_enabled() -> bool:
    """Content-addressed chunk store (docs/cas.md), default OFF: with
    ``"1"``, new takes write their data blobs once into a root-level
    ``chunks/`` store keyed by content digest, manifests reference the
    chunks (``../chunks/<key>`` parent refs), and the manager refcounts
    them — dense retention costs ~one full step plus deltas, and the
    mirror/peer tiers ship only chunks their destination doesn't hold.
    Requires a root with a local filesystem tier (fs, or tiered with an
    fs fast tier); ineligible roots warn once and take the legacy
    layout. Restores resolve either layout regardless of this knob."""
    return os.environ.get(_CAS_ENV, "0") not in ("", "0")


def get_cas_gc_grace_seconds() -> float:
    """Minimum age (mtime) before the manager's chunk GC may delete a
    refcount-dead chunk. The grace window is the concurrent-take guard:
    a take that dedups against an existing chunk touches its mtime
    before relying on it, so an in-flight (not-yet-pinned) step's
    chunks always look fresh to a racing GC pass and are deferred as
    journaled orphans instead of reclaimed. Non-positive = reclaim
    immediately (tests)."""
    val = os.environ.get(_CAS_GC_GRACE_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_CAS_GC_GRACE_SECONDS


_DEFAULT_CDN_STALENESS_BUDGET_SECONDS = 5.0


def is_cdn_enabled() -> bool:
    """Checkpoint CDN (docs/cdn.md), default OFF: with ``"1"``, a
    manager constructed with a ``cdn_topic`` publishes every committed
    step — manifest digest plus CAS chunk keys — to a subscription
    topic riding the coordination store, and serving-side
    ``CdnSubscriber`` processes stream the chunk deltas peer-to-peer
    and hot-swap them in. Off = the manager never announces and never
    touches the topic keys; subscribers constructed explicitly still
    work (the knob gates the *training-job* side, where an accidental
    publish would add coordination traffic to every commit)."""
    return os.environ.get(_CDN_ENV, "0") not in ("", "0")


def is_fleet_obs_enabled() -> bool:
    """Fleet metrics plane (telemetry/wire.py, docs/observability.md),
    default OFF: with ``"1"``, storm ranks, CDN publishers, and CDN
    subscribers periodically publish compact crc-guarded wire/progress
    snapshots under ``__obs/`` on the coordination store (world-scaled
    pacing, reaped on clean shutdown), which ``python -m
    torchsnapshot_tpu.telemetry fleet <target>`` renders as a live
    per-member table. Off = no ``__obs/`` keys are ever written (the
    test conftest pins 0 so tier-1 store traffic stays deterministic);
    the fleet CLI still reads whatever another process published."""
    return os.environ.get(_FLEET_OBS_ENV, "0") not in ("", "0")


def get_cdn_staleness_budget_seconds() -> float:
    """The publish-to-swap latency budget the ``cdn-staleness-high``
    doctor rule holds the fleet to: when the median staleness across
    the run ledger's cdn-swapped records exceeds this, the rule fires.
    Also the subscriber storm's pass/fail line in the cdn_streaming
    bench leg."""
    val = os.environ.get(_CDN_STALENESS_BUDGET_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_CDN_STALENESS_BUDGET_SECONDS


def get_cdn_pull_timeout_seconds() -> float:
    """Per-chunk deadline for a subscriber's peer-to-peer pull (connect
    + one digest-verified transfer) AND the wait for the chunk's elected
    owner to materialize it. On expiry the subscriber falls back to the
    durable store read — correctness never rides a peer, only the ~1x
    storage-read economics do. Defaults to the peer transfer timeout."""
    val = os.environ.get(_CDN_PULL_TIMEOUT_ENV)
    if val is not None:
        return float(val)
    return get_peer_transfer_timeout_seconds()


def is_slo_enabled() -> bool:
    """The rank-0 per-step SLO evaluation (telemetry/slo.py): on by
    default — each committed manager step re-judges the declared
    objectives with multi-window burn-rate math over the run ledger and
    step history, exports ``slo_burn_rate{objective}`` gauges, and
    posts an edge-triggered ``slo-breach`` ledger event when an
    objective starts burning. Set to ``"0"`` to disable the whole
    evaluation (the test conftest pins 0 so tier-1 manager runs stay
    deterministic); needs the ledger on to have samples to judge."""
    return os.environ.get(_SLO_ENV, "1") != "0"


def get_slo_fast_window() -> int:
    """Sample count of the fast burn window: the last-N-samples look
    that catches cliffs (a plugin suddenly slow, a tier gone). <= 0
    disables the fast window (breaches then need the slow window)."""
    val = os.environ.get(_SLO_FAST_WINDOW_ENV)
    if val is not None:
        return int(val)
    return _DEFAULT_SLO_FAST_WINDOW


def get_slo_slow_window() -> int:
    """Sample count of the slow burn window: the long look that
    catches drift a fast window averages away. <= 0 disables it."""
    val = os.environ.get(_SLO_SLOW_WINDOW_ENV)
    if val is not None:
        return int(val)
    return _DEFAULT_SLO_SLOW_WINDOW


def get_slo_fast_burn_threshold() -> float:
    """Burn-rate threshold for the fast window (burn 1.0 = spending
    error budget exactly at the sustainable rate; the higher fast
    threshold demands a real cliff, not one unlucky sample)."""
    val = os.environ.get(_SLO_FAST_BURN_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_SLO_FAST_BURN_THRESHOLD


def get_slo_slow_burn_threshold() -> float:
    """Burn-rate threshold for the slow window (1.0 = any sustained
    overspend of the error budget fires)."""
    val = os.environ.get(_SLO_SLOW_BURN_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_SLO_SLOW_BURN_THRESHOLD


def get_slo_error_budget_fraction() -> float:
    """Allowed bad-sample fraction per objective (the error budget):
    burn rate = observed bad fraction / this. The 0.1 default tolerates
    one slow op in ten before an objective burns at rate 1.0."""
    val = os.environ.get(_SLO_ERROR_BUDGET_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_SLO_ERROR_BUDGET_FRACTION


def get_slo_restore_seconds() -> float:
    """Target of the ``restore-wall`` objective: a restore serving
    slower than this is a bad sample. <= 0 disables the objective."""
    val = os.environ.get(_SLO_RESTORE_BUDGET_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_SLO_RESTORE_SECONDS


def get_slo_mirror_lag_seconds() -> float:
    """Target of the ``mirror-durability-lag`` objective: a step whose
    bytes existed only on the fast tier longer than this is a bad
    sample. <= 0 disables the objective."""
    val = os.environ.get(_SLO_MIRROR_LAG_BUDGET_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_SLO_MIRROR_LAG_SECONDS


def get_slo_overhead_fraction() -> float:
    """Target of the ``goodput-overhead`` objective: a commit interval
    whose checkpoint overhead (visible stall + restore) exceeds this
    fraction of the interval's wall is a bad sample. <= 0 disables."""
    val = os.environ.get(_SLO_OVERHEAD_BUDGET_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_SLO_OVERHEAD_FRACTION


def get_slo_coordination_fraction() -> float:
    """Target of the ``coordination-fraction`` objective: a take whose
    coordination share of the op wall exceeds this fraction is a bad
    sample. <= 0 disables the objective."""
    val = os.environ.get(_SLO_COORD_BUDGET_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_SLO_COORDINATION_FRACTION


def get_bundle_dir() -> Optional[str]:
    """Where incident bundles land. Unset = ``<root>/.bundles`` next to
    the snapshot root that triggered the capture (kept on the local
    tier for tiered roots so a bundle survives remote-tier cleanup)."""
    return os.environ.get(_BUNDLE_DIR_ENV) or None


def get_bundle_max_bytes() -> int:
    """Size cap per incident bundle: artifact copies stop (JSONL tails
    are truncated to fit) once the bundle reaches this many bytes. <= 0
    disables bundle capture entirely (the test conftest pins 0 so no
    trigger in tier-1 ever writes a ``.bundles/`` dir)."""
    return _get_int_env(_BUNDLE_MAX_BYTES_ENV, _DEFAULT_BUNDLE_MAX_BYTES)


def get_bundle_min_interval_seconds() -> float:
    """Rate limit between bundle captures per bundle dir: a breach
    storm produces one black box, not one per step."""
    val = os.environ.get(_BUNDLE_MIN_INTERVAL_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_BUNDLE_MIN_INTERVAL_SECONDS


def get_cold_start_budget_fraction() -> float:
    """Threshold for the doctor's ``restore-cold-start-slow`` rule: a
    restore whose recorded ``cold_start_s`` (event-loop spin-up +
    plugin open + native-module load) exceeds this fraction of the op
    wall is flagged with its split. <= 0 disables the rule."""
    val = os.environ.get(_COLD_START_BUDGET_FRACTION_ENV)
    if val is not None:
        return float(val)
    return _DEFAULT_COLD_START_BUDGET_FRACTION


def is_write_vectorized_enabled() -> bool:
    """Zero-pack vectorized slab writes (default ON): the batcher's slab
    stage hands its members' staged buffers straight to the storage
    plugin as a multi-buffer payload, written with one vectorized
    ``pwritev`` + fused per-page CRC kernel — the ``gather_memcpy``
    slab-pack pass (one full memory pass over every staged byte)
    disappears. Set to ``"0"`` to restore the packed path (stage into a
    contiguous slab buffer first). Plugins without multi-buffer support
    are consolidated for transparently either way; blob bytes and
    integrity tables are bit-identical on both paths. Tunable: the
    autotuner may flip it (env always wins)."""
    return _get_tunable_int(_WRITE_VECTORIZED_ENV, 1) != 0


def is_fs_direct_io_enabled() -> bool:
    """O_DIRECT fs writes for large 4096-aligned buffers (default OFF —
    filesystems vary; the autotuner can turn it on where the doctor says
    the storage tier is the wall): the aligned body of a qualifying blob
    bypasses the page cache (checkpoint bytes the trainer never re-reads
    would only evict pages it will), the unaligned tail is written
    buffered, and per-page CRCs ride the same pass. Unsupported
    filesystems (tmpfs: EINVAL) decline sticky-per-plugin back to the
    buffered path — correctness is identical everywhere."""
    return _get_tunable_int(_FS_DIRECT_IO_ENV, 0) != 0


def is_tree_barrier_enabled() -> bool:
    """Tree-structured coordination barriers (docs/scaling.md), default
    ON: every store barrier (``dist_store.make_barrier`` — the take
    commit, restore key, and async plan/apply rendezvous) aggregates
    arrive/depart through a fanout-``k`` rank tree, so no single store
    key serializes more than ``k`` ranks and the critical path is
    O(log_k world). Set to ``"0"`` to fall back to the leader-centric
    :class:`~torchsnapshot_tpu.dist_store.LinearBarrier` (the
    pre-scale-model behavior — the bisecting kill switch). Rank 0's
    tunable broadcast keeps the choice job-uniform when the autotuner
    is on; the error-propagation contract is identical either way."""
    return os.environ.get(_TREE_BARRIER_ENV, "1") != "0"


def get_barrier_fanout() -> int:
    """Tree-barrier branching factor ``k``: per phase a rank waits on at
    most ``k`` children and releases at most ``k`` — latency is
    O(k·log_k world) store waits deep. Small k = deeper tree, less
    per-key contention; large k degrades toward the linear barrier.
    Tunable: the autotuner may move it (env always wins)."""
    return max(2, _get_tunable_int(_BARRIER_FANOUT_ENV, _DEFAULT_BARRIER_FANOUT))


def get_store_shards() -> int:
    """Coordination-store shard count (docs/scaling.md): >1 bootstraps
    that many TCPStore servers (spread across ranks) behind
    deterministic key->shard hashing, so the hub socket stops
    serializing world x keys traffic. Rank 0's reading decides for the
    whole job (published through the base store at bootstrap, like the
    fan-out nonce). Default 1 = the single-hub behavior. Tunable: the
    autotuner may move it — it takes effect at the next store
    bootstrap, not mid-run."""
    return max(1, _get_tunable_int(_STORE_SHARDS_ENV, _DEFAULT_STORE_SHARDS))


def get_memory_budget_fraction() -> float:
    """Fraction of *available* host memory the per-process staging
    budget may claim (scheduler.get_process_memory_budget_bytes; the
    historical hard-coded 0.6). Tunable: the autotuner raises it on
    ``budget-starved`` verdicts and backs off on regression. An explicit
    TORCHSNAPSHOT_TPU_PER_RANK_MEMORY_BUDGET_BYTES override bypasses
    the fraction entirely, as before."""
    return _get_tunable_float(
        _MEMORY_BUDGET_FRACTION_ENV, _DEFAULT_MEMORY_BUDGET_FRACTION
    )


def tunable_snapshot() -> Dict[str, Union[int, float]]:
    """Effective value of every tunable knob right now (env > tuner
    override > default) — the ``tunables`` field each SnapshotReport
    records so a history row / ``doctor --trend`` regression can be
    correlated with the knob change that caused it. Keys are the short
    tunable names the tuner's decision log uses (docs/tuning.md)."""
    return {
        "staging_threads": get_staging_threads(),
        "io_concurrency": get_per_rank_io_concurrency(),
        "staging_pool_slab_bytes": get_staging_pool_slab_bytes(),
        "staging_pool_slabs": get_staging_pool_slabs(),
        "memory_budget_fraction": get_memory_budget_fraction(),
        "max_chunk_size_bytes": get_max_chunk_size_bytes(),
        "max_shard_size_bytes": get_max_shard_size_bytes(),
        "slab_size_threshold_bytes": get_slab_size_threshold_bytes(),
        "write_vectorized": int(is_write_vectorized_enabled()),
        "fs_direct_io": int(is_fs_direct_io_enabled()),
        "barrier_fanout": get_barrier_fanout(),
        "store_shards": get_store_shards(),
    }


def get_prometheus_textfile() -> Optional[str]:
    """Prometheus text-exposition file, rewritten (atomically) after
    every report emission — the node-exporter textfile-collector
    convention. Unset = disabled."""
    return os.environ.get(_PROM_FILE_ENV) or None


def get_restore_placement_flush_bytes() -> int:
    """Streaming-restore flush granularity: once this many bytes of leaves
    have completed their reads, their device placements flush as one
    batched ``jax.device_put`` while remaining reads continue. Smaller =
    more read/H2D overlap but more dispatches (per-dispatch latency is
    what the batching amortizes); 0 = place everything in one batch after
    all reads (the pre-streaming behavior)."""
    return _get_int_env(_RESTORE_FLUSH_BYTES_ENV, _DEFAULT_RESTORE_FLUSH_BYTES)


@contextlib.contextmanager
def _override_env(name: str, value: Optional[str]) -> Generator[None, None, None]:
    prev = os.environ.get(name)
    try:
        if value is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = value
        yield
    finally:
        if prev is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = prev


@contextlib.contextmanager
def override_max_chunk_size_bytes(nbytes: int) -> Generator[None, None, None]:
    with _override_env(_MAX_CHUNK_SIZE_BYTES_ENV, str(nbytes)):
        yield


@contextlib.contextmanager
def override_max_shard_size_bytes(nbytes: int) -> Generator[None, None, None]:
    with _override_env(_MAX_SHARD_SIZE_BYTES_ENV, str(nbytes)):
        yield


@contextlib.contextmanager
def override_slab_size_threshold_bytes(nbytes: int) -> Generator[None, None, None]:
    with _override_env(_SLAB_SIZE_THRESHOLD_BYTES_ENV, str(nbytes)):
        yield


@contextlib.contextmanager
def enable_batching() -> Generator[None, None, None]:
    with _override_env(_ENABLE_BATCHING_ENV, "1"):
        yield


@contextlib.contextmanager
def override_per_rank_memory_budget_bytes(nbytes: int) -> Generator[None, None, None]:
    with _override_env(_PER_RANK_MEMORY_BUDGET_BYTES_ENV, str(nbytes)):
        yield


@contextlib.contextmanager
def disable_checksums() -> Generator[None, None, None]:
    with _override_env(_DISABLE_CHECKSUMS_ENV, "1"):
        yield


@contextlib.contextmanager
def override_incremental_chunk_size_bytes(
    nbytes: int,
) -> Generator[None, None, None]:
    with _override_env(_INCREMENTAL_CHUNK_SIZE_BYTES_ENV, str(nbytes)):
        yield


@contextlib.contextmanager
def enable_device_pack() -> Generator[None, None, None]:
    with _override_env(_DEVICE_PACK_ENV, "1"):
        yield


@contextlib.contextmanager
def override_restore_placement_flush_bytes(
    nbytes: int,
) -> Generator[None, None, None]:
    with _override_env(_RESTORE_FLUSH_BYTES_ENV, str(nbytes)):
        yield


@contextlib.contextmanager
def enable_telemetry() -> Generator[None, None, None]:
    with _override_env(_TELEMETRY_ENV, "1"):
        yield


@contextlib.contextmanager
def override_telemetry_dir(path: str) -> Generator[None, None, None]:
    with _override_env(_TELEMETRY_DIR_ENV, path):
        yield


@contextlib.contextmanager
def override_prometheus_textfile(path: str) -> Generator[None, None, None]:
    with _override_env(_PROM_FILE_ENV, path):
        yield


@contextlib.contextmanager
def enable_trace() -> Generator[None, None, None]:
    with _override_env(_TRACE_ENV, "1"):
        yield


@contextlib.contextmanager
def override_trace_dir(path: str) -> Generator[None, None, None]:
    with _override_env(_TRACE_DIR_ENV, path):
        yield


@contextlib.contextmanager
def override_trace_buffer_events(n: int) -> Generator[None, None, None]:
    with _override_env(_TRACE_BUFFER_EVENTS_ENV, str(n)):
        yield


@contextlib.contextmanager
def override_watchdog_deadline_seconds(
    seconds: float,
) -> Generator[None, None, None]:
    with _override_env(_WATCHDOG_SECONDS_ENV, str(seconds)):
        yield


@contextlib.contextmanager
def disable_native() -> Generator[None, None, None]:
    with _override_env(_DISABLE_NATIVE_ENV, "1"):
        yield


@contextlib.contextmanager
def override_wait_durable_timeout_seconds(
    seconds: float,
) -> Generator[None, None, None]:
    with _override_env(_WAIT_DURABLE_TIMEOUT_ENV, str(seconds)):
        yield


@contextlib.contextmanager
def override_progress_interval_seconds(
    seconds: float,
) -> Generator[None, None, None]:
    with _override_env(_PROGRESS_SECONDS_ENV, str(seconds)):
        yield


@contextlib.contextmanager
def override_progress_dir(path: str) -> Generator[None, None, None]:
    with _override_env(_PROGRESS_DIR_ENV, path):
        yield


@contextlib.contextmanager
def override_history_max_records(n: int) -> Generator[None, None, None]:
    with _override_env(_HISTORY_MAX_RECORDS_ENV, str(n)):
        yield


@contextlib.contextmanager
def enable_ledger() -> Generator[None, None, None]:
    """Force the run ledger ON for the block (the suite's conftest pins
    it off so tier-1 manager dirs hold exactly the files the code under
    test wrote; ledger/goodput tests opt back in here)."""
    with _override_env(_LEDGER_ENV, "1"):
        yield


@contextlib.contextmanager
def disable_ledger() -> Generator[None, None, None]:
    with _override_env(_LEDGER_ENV, "0"):
        yield


@contextlib.contextmanager
def override_ledger_max_records(n: int) -> Generator[None, None, None]:
    with _override_env(_LEDGER_MAX_RECORDS_ENV, str(n)):
        yield


@contextlib.contextmanager
def disable_async_device_snapshot() -> Generator[None, None, None]:
    with _override_env(_ASYNC_DEVICE_SNAPSHOT_ENV, "0"):
        yield


@contextlib.contextmanager
def override_staging_pool_slab_bytes(nbytes: int) -> Generator[None, None, None]:
    with _override_env(_STAGING_POOL_SLAB_BYTES_ENV, str(nbytes)):
        yield


@contextlib.contextmanager
def override_staging_pool_slabs(n: int) -> Generator[None, None, None]:
    with _override_env(_STAGING_POOL_SLABS_ENV, str(n)):
        yield


@contextlib.contextmanager
def override_async_visible_budget_seconds(
    seconds: float,
) -> Generator[None, None, None]:
    with _override_env(_ASYNC_VISIBLE_BUDGET_ENV, str(seconds)):
        yield


@contextlib.contextmanager
def enable_autotune() -> Generator[None, None, None]:
    """Force the autotuner ON for the block (the suite's conftest turns
    it off process-wide); programmatic overrides installed inside the
    block are cleared on exit so no tuned geometry leaks into the next
    test."""
    with _override_env(_AUTOTUNE_ENV, "1"):
        try:
            yield
        finally:
            clear_tuner_overrides()


@contextlib.contextmanager
def disable_autotune() -> Generator[None, None, None]:
    with _override_env(_AUTOTUNE_ENV, "0"):
        yield


@contextlib.contextmanager
def enable_fanout_restore() -> Generator[None, None, None]:
    """Force fan-out restore ON for the block (the test suite's conftest
    pins it off so tier-1 restores exercise the exact pre-fan-out read
    path they assert about; fan-out tests opt back in here)."""
    with _override_env(_FANOUT_RESTORE_ENV, "1"):
        yield


@contextlib.contextmanager
def disable_fanout_restore() -> Generator[None, None, None]:
    with _override_env(_FANOUT_RESTORE_ENV, "0"):
        yield


@contextlib.contextmanager
def override_memory_budget_fraction(
    fraction: float,
) -> Generator[None, None, None]:
    with _override_env(_MEMORY_BUDGET_FRACTION_ENV, str(fraction)):
        yield


@contextlib.contextmanager
def override_staging_threads(n: int) -> Generator[None, None, None]:
    with _override_env(_STAGING_THREADS_ENV, str(n)):
        yield


@contextlib.contextmanager
def override_per_rank_io_concurrency(n: int) -> Generator[None, None, None]:
    with _override_env(_PER_RANK_IO_CONCURRENCY_ENV, str(n)):
        yield


@contextlib.contextmanager
def enable_peer_tier() -> Generator[None, None, None]:
    """Force the peer tier ON for the block (the test suite's conftest
    pins it off so tier-1 saves/restores exercise the exact pre-peer
    read/write paths they assert about; peer-tier tests opt back in
    here or via an env override in their workers)."""
    with _override_env(_PEER_TIER_ENV, "1"):
        yield


@contextlib.contextmanager
def disable_peer_tier() -> Generator[None, None, None]:
    with _override_env(_PEER_TIER_ENV, "0"):
        yield


@contextlib.contextmanager
def override_peer_ring_offset(offset: int) -> Generator[None, None, None]:
    with _override_env(_PEER_RING_OFFSET_ENV, str(offset)):
        yield


@contextlib.contextmanager
def override_peer_cache_budget_bytes(
    nbytes: int,
) -> Generator[None, None, None]:
    with _override_env(_PEER_CACHE_BUDGET_BYTES_ENV, str(nbytes)):
        yield


@contextlib.contextmanager
def override_peer_transfer_timeout_seconds(
    seconds: float,
) -> Generator[None, None, None]:
    with _override_env(_PEER_TRANSFER_TIMEOUT_ENV, str(seconds)):
        yield


@contextlib.contextmanager
def disable_write_vectorized() -> Generator[None, None, None]:
    """Force the packed slab path for the block (byte-identity tests
    compare it against the default zero-pack path)."""
    with _override_env(_WRITE_VECTORIZED_ENV, "0"):
        yield


@contextlib.contextmanager
def enable_write_vectorized() -> Generator[None, None, None]:
    with _override_env(_WRITE_VECTORIZED_ENV, "1"):
        yield


@contextlib.contextmanager
def enable_cas() -> Generator[None, None, None]:
    """Force the content-addressed chunk store ON for the block (the
    suite's conftest pins it off so tier-1 snapshot/manager dirs hold
    exactly the legacy file set; CAS tests opt back in here)."""
    with _override_env(_CAS_ENV, "1"):
        yield


@contextlib.contextmanager
def disable_cas() -> Generator[None, None, None]:
    with _override_env(_CAS_ENV, "0"):
        yield


@contextlib.contextmanager
def enable_cdn() -> Generator[None, None, None]:
    """Force the checkpoint-CDN publish hook ON for the block (the
    suite's conftest pins it off so tier-1 manager tests see no
    announce traffic; CDN tests opt back in here)."""
    with _override_env(_CDN_ENV, "1"):
        yield


@contextlib.contextmanager
def enable_fleet_obs() -> Generator[None, None, None]:
    """Force the fleet metrics plane ON for the block (the suite's
    conftest pins it off so tier-1 store traffic holds exactly the keys
    the code under test wrote; fleet-plane tests opt back in here)."""
    with _override_env(_FLEET_OBS_ENV, "1"):
        yield


@contextlib.contextmanager
def override_cdn_pull_timeout_seconds(
    seconds: float,
) -> Generator[None, None, None]:
    """Pin the CDN peer-pull deadline for the block (subscribers read
    it per pull, so the storm harness tightens it fleet-wide without
    threading a parameter through every subscriber)."""
    with _override_env(_CDN_PULL_TIMEOUT_ENV, str(seconds)):
        yield


@contextlib.contextmanager
def override_cas_gc_grace_seconds(
    seconds: float,
) -> Generator[None, None, None]:
    with _override_env(_CAS_GC_GRACE_ENV, str(seconds)):
        yield


@contextlib.contextmanager
def enable_fs_direct_io() -> Generator[None, None, None]:
    """Force O_DIRECT eligibility ON for the block (the suite's conftest
    pins it off — CI filesystems vary; direct-I/O tests opt back in and
    assert the decline ladder where the fs refuses)."""
    with _override_env(_FS_DIRECT_IO_ENV, "1"):
        yield


@contextlib.contextmanager
def disable_fs_direct_io() -> Generator[None, None, None]:
    with _override_env(_FS_DIRECT_IO_ENV, "0"):
        yield


@contextlib.contextmanager
def enable_tree_barrier() -> Generator[None, None, None]:
    with _override_env(_TREE_BARRIER_ENV, "1"):
        yield


@contextlib.contextmanager
def disable_tree_barrier() -> Generator[None, None, None]:
    """Force the leader-centric LinearBarrier for the block (the
    kill-switch path; scale-model baselines and bisects use it)."""
    with _override_env(_TREE_BARRIER_ENV, "0"):
        yield


@contextlib.contextmanager
def override_barrier_fanout(fanout: int) -> Generator[None, None, None]:
    with _override_env(_BARRIER_FANOUT_ENV, str(fanout)):
        yield


@contextlib.contextmanager
def override_store_shards(n: int) -> Generator[None, None, None]:
    with _override_env(_STORE_SHARDS_ENV, str(n)):
        yield


@contextlib.contextmanager
def override_mirror_io_concurrency(n: int) -> Generator[None, None, None]:
    with _override_env(_MIRROR_IO_CONCURRENCY_ENV, str(n)):
        yield


@contextlib.contextmanager
def override_mirror_progress_window_seconds(
    seconds: float,
) -> Generator[None, None, None]:
    with _override_env(_MIRROR_PROGRESS_WINDOW_ENV, str(seconds)):
        yield


@contextlib.contextmanager
def enable_slo() -> Generator[None, None, None]:
    """Force the per-step SLO evaluation ON for the block (the suite's
    conftest pins it off so tier-1 manager runs post no slo-breach
    events; SLO tests opt back in here)."""
    with _override_env(_SLO_ENV, "1"):
        yield


@contextlib.contextmanager
def disable_slo() -> Generator[None, None, None]:
    with _override_env(_SLO_ENV, "0"):
        yield


@contextlib.contextmanager
def override_slo_windows(
    fast: int, slow: int
) -> Generator[None, None, None]:
    """Pin both burn windows for the block (unit pins drive exact
    sample counts through them)."""
    with _override_env(_SLO_FAST_WINDOW_ENV, str(fast)):
        with _override_env(_SLO_SLOW_WINDOW_ENV, str(slow)):
            yield


@contextlib.contextmanager
def override_slo_restore_seconds(
    seconds: float,
) -> Generator[None, None, None]:
    with _override_env(_SLO_RESTORE_BUDGET_ENV, str(seconds)):
        yield


@contextlib.contextmanager
def override_slo_mirror_lag_seconds(
    seconds: float,
) -> Generator[None, None, None]:
    with _override_env(_SLO_MIRROR_LAG_BUDGET_ENV, str(seconds)):
        yield


@contextlib.contextmanager
def override_slo_overhead_fraction(
    fraction: float,
) -> Generator[None, None, None]:
    with _override_env(_SLO_OVERHEAD_BUDGET_ENV, str(fraction)):
        yield


@contextlib.contextmanager
def override_slo_coordination_fraction(
    fraction: float,
) -> Generator[None, None, None]:
    with _override_env(_SLO_COORD_BUDGET_ENV, str(fraction)):
        yield


@contextlib.contextmanager
def override_bundle_dir(path: str) -> Generator[None, None, None]:
    with _override_env(_BUNDLE_DIR_ENV, path):
        yield


@contextlib.contextmanager
def override_bundle_max_bytes(nbytes: int) -> Generator[None, None, None]:
    """Re-enable (and bound) bundle capture for the block (the suite's
    conftest pins the cap to 0 = capture disabled; bundle tests opt
    back in here)."""
    with _override_env(_BUNDLE_MAX_BYTES_ENV, str(nbytes)):
        yield


@contextlib.contextmanager
def override_bundle_min_interval_seconds(
    seconds: float,
) -> Generator[None, None, None]:
    with _override_env(_BUNDLE_MIN_INTERVAL_ENV, str(seconds)):
        yield


@contextlib.contextmanager
def override_cold_start_budget_fraction(
    fraction: float,
) -> Generator[None, None, None]:
    with _override_env(_COLD_START_BUDGET_FRACTION_ENV, str(fraction)):
        yield
