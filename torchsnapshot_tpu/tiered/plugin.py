"""TieredStoragePlugin: write through the fast tier, read fast-first
with per-blob durable fallback, hand written blobs to the background
mirror at close.

The plugin is deliberately thin: it composes two ordinary plugins and
keeps a record of what was written through it. ``Snapshot.take`` /
``async_take`` need no changes — every data write, checksum table and
the commit marker land on the fast tier at fast-tier bandwidth, the take
commits there, and when the take closes its plugin the accumulated blob
inventory is enqueued to the process-wide :class:`Mirror` (commit marker
ordered last). A take that failed before commit enqueues only data
blobs — harmless on the durable tier (no commit marker ever follows),
and the step's eventual GC removes them from both tiers.

Reads try the fast tier and fall back per blob on ``FileNotFoundError``:
an evicted, partially-evicted or never-local (restarted host) fast tier
is transparent to restore, ``fsck`` and checksum-table loading alike.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..io_types import ReadIO, StoragePlugin, WriteIO, payload_nbytes
from ..storage_plugin import url_to_storage_plugin

_METADATA_FNAME = ".snapshot_metadata"  # == snapshot.SNAPSHOT_METADATA_FNAME


class TieredStoragePlugin(StoragePlugin):
    def __init__(
        self,
        fast_url: Optional[str] = None,
        durable_url: Optional[str] = None,
        fast: Optional[StoragePlugin] = None,
        durable: Optional[StoragePlugin] = None,
        mirror=None,
    ) -> None:
        """Compose ``fast`` and ``durable`` tiers, each given as a URL
        (constructed via the registry) or as a ready plugin instance.
        Mirroring requires URLs (the background worker builds its own
        plugin instances); instance-composed plugins are read/write
        valid but never enqueue — the explicit-composition escape hatch
        for tests and custom topologies."""
        if fast is None:
            if fast_url is None:
                raise ValueError("either fast or fast_url is required")
            fast = url_to_storage_plugin(fast_url)
        if durable is None:
            if durable_url is None:
                raise ValueError("either durable or durable_url is required")
            durable = url_to_storage_plugin(durable_url)
        self.fast = fast
        self.durable = durable
        self.fast_url = fast_url
        self.durable_url = durable_url
        self._mirror = mirror
        # path -> staged byte count, in write order; drained into a
        # mirror job at close().
        self._written: Dict[str, int] = {}

    # -- writes: fast tier only ------------------------------------------

    @property
    def supports_multibuffer(self) -> bool:  # type: ignore[override]
        # Writes land on the fast tier, so its capability decides whether
        # the scheduler may hand us a zero-pack BufferList payload.
        return getattr(self.fast, "supports_multibuffer", False)

    async def write(self, write_io: WriteIO) -> None:
        await self.fast.write(write_io)
        self._written[write_io.path] = payload_nbytes(write_io.buf)

    def note_written(self, path: str, nbytes: int) -> None:
        """Record a blob for mirror enqueue without writing it — the CAS
        wrapper's dedup hits land here: the bytes already live on the
        fast tier, but this step's durability claim still covers them.
        If the chunk's original writer crashed before its mirror ran,
        nothing else would ever ship it; enqueueing it lets the mirror's
        durable-side existence probe decide (a held chunk costs one
        ranged byte, not a copy)."""
        self._written[path] = int(nbytes)

    async def write_with_checksum(self, write_io: WriteIO):
        entry = await self.fast.write_with_checksum(write_io)
        if entry is not None:
            self._written[write_io.path] = payload_nbytes(write_io.buf)
        return entry

    # -- reads: fast first, durable per-blob fallback --------------------

    async def read(self, read_io: ReadIO) -> None:
        try:
            await self.fast.read(read_io)
            read_io.served_by = "fast"
        except FileNotFoundError:
            await self.durable.read(read_io)
            read_io.served_by = "durable"

    async def read_degraded(self, read_io: ReadIO) -> bool:
        """Corruption fallthrough (docs/chaos.md): the tier that served
        ``read_io`` produced bytes that failed digest verification —
        re-read from the tier(s) not yet tried. The caller re-verifies;
        a mismatch there comes back here until both tiers are exhausted."""
        tried = getattr(read_io, "_tiers_tried", None)
        if tried is None:
            tried = {read_io.served_by} if read_io.served_by else set()
            read_io._tiers_tried = tried
        for tier, plugin in (
            ("durable", self.durable),
            ("fast", self.fast),
        ):
            if tier in tried:
                continue
            tried.add(tier)
            try:
                await plugin.read(read_io)
            except (FileNotFoundError, OSError):
                continue  # absent/torn here: keep walking the ladder
            read_io.served_by = tier
            return True
        return False

    async def read_with_checksum(self, read_io: ReadIO):
        try:
            return await self.fast.read_with_checksum(read_io)
        except FileNotFoundError:
            # Decline having read nothing: the scheduler falls back to
            # read(), whose durable fallback serves the blob.
            return None

    # -- delete: both tiers (step GC removes the step entirely) ----------

    async def delete(self, path: str) -> None:
        found = False
        try:
            await self.fast.delete(path)
            found = True
        except FileNotFoundError:
            pass
        try:
            await self.durable.delete(path)
            found = True
        except FileNotFoundError:
            pass
        if not found:
            raise FileNotFoundError(path)

    # -- close: hand the write record to the mirror ----------------------

    async def close(self) -> None:
        if self._written and self.fast_url and self.durable_url:
            mirror = self._mirror
            if mirror is None:
                from .mirror import get_mirror

                mirror = get_mirror()
            metadata_path = (
                _METADATA_FNAME if _METADATA_FNAME in self._written else None
            )
            mirror.enqueue(
                self.fast_url,
                self.durable_url,
                dict(self._written),
                metadata_path=metadata_path,
            )
            self._written.clear()
            from ..chaos import crashpoint
            from ..telemetry import names as _names

            crashpoint(_names.CRASH_MIRROR_ENQUEUED)
        await self.fast.close()
        await self.durable.close()
