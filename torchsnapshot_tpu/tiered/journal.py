"""Crash-consistent mirror-progress journal, stored in the FAST tier.

One journal per snapshot (or manager-root) directory, rewritten as JSON
on every progress point with the manager-index double-slot discipline
(backup slot first, primary second — manager.py's torn-write rationale):
whichever slot survives a crash is valid, at worst one blob stale, and a
stale journal only costs a re-upload of blobs whose completion was not
yet recorded — never correctness, because the durable commit marker is
mirrored strictly last and a blob upload is idempotent.

Schema (version 1)::

    {
      "version": 1,
      "blobs": {"<path>": <nbytes>},   # full inventory to mirror
      "done": ["<path>", ...],          # fully uploaded to the durable tier
      "metadata": "<path>" | null,      # commit marker; mirrored LAST
      "durable_committed": bool         # metadata landed on the durable tier
    }

The journal is both the resume state (a restarted Mirror uploads only
``blobs - done``) and the discovery state (fsck reports a
partially-mirrored durable step from it). A snapshot with NO journal and
no durable commit marker resumes via its fast-tier manifest instead —
the journal is an optimization, never a correctness dependency.
"""

from __future__ import annotations

import json
import logging
from typing import Dict, Optional, Set

from ..io_types import ReadIO, StoragePlugin, WriteIO

logger: logging.Logger = logging.getLogger(__name__)

JOURNAL_BLOB = ".mirror_journal"
JOURNAL_BACKUP_BLOB = ".mirror_journal.backup"


class MirrorJournal:
    """Per-directory mirror progress: blob inventory + done set."""

    def __init__(
        self,
        blobs: Optional[Dict[str, int]] = None,
        done: Optional[Set[str]] = None,
        metadata: Optional[str] = None,
        durable_committed: bool = False,
    ) -> None:
        self.blobs: Dict[str, int] = dict(blobs or {})
        self.done: Set[str] = set(done or ())
        self.metadata = metadata
        self.durable_committed = durable_committed

    # ------------------------------------------------------------------

    def register(
        self,
        blobs: Dict[str, int],
        metadata: Optional[str] = None,
        fresh: bool = True,
    ) -> None:
        """Merge a mirror job's inventory. With ``fresh`` (newly-written
        blobs handed over at plugin close) re-registered paths lose their
        done flag — their durable copy is stale; the manager index is
        rewritten on every save and must re-mirror each time. A RESUMED
        job (``fresh=False``) merges the inventory but keeps done flags:
        skipping completed uploads is the journal's whole point."""
        for path, nbytes in blobs.items():
            self.blobs[path] = nbytes
            if fresh:
                self.done.discard(path)
        if metadata is not None:
            self.metadata = metadata
            if fresh:
                self.durable_committed = False

    def pending(self) -> list:
        """Data blobs still to upload, commit marker excluded (it goes
        last, via :attr:`metadata`)."""
        return sorted(
            p for p in self.blobs if p not in self.done and p != self.metadata
        )

    @property
    def complete(self) -> bool:
        data_done = all(
            p in self.done for p in self.blobs if p != self.metadata
        )
        if self.metadata is None:
            return data_done
        return data_done and self.durable_committed

    # ------------------------------------------------------------------

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "version": 1,
                "blobs": self.blobs,
                "done": sorted(self.done),
                "metadata": self.metadata,
                "durable_committed": self.durable_committed,
            }
        ).encode("utf-8")

    @classmethod
    def from_json(cls, raw: bytes) -> "MirrorJournal":
        doc = json.loads(raw)
        return cls(
            blobs={str(k): int(v) for k, v in doc["blobs"].items()},
            done={str(p) for p in doc.get("done", [])},
            metadata=doc.get("metadata"),
            durable_committed=bool(doc.get("durable_committed", False)),
        )

    # ------------------------------------------------------------------

    @classmethod
    async def load(cls, fast: StoragePlugin) -> Optional["MirrorJournal"]:
        """Primary slot, falling back to backup (manager-index recovery
        rule). Both slots unreadable -> None: the caller falls back to a
        full re-mirror, which is always safe."""
        for slot in (JOURNAL_BLOB, JOURNAL_BACKUP_BLOB):
            read_io = ReadIO(path=slot)
            try:
                await fast.read(read_io)
            except FileNotFoundError:
                continue
            except Exception as e:  # noqa: BLE001 - degrade to re-mirror
                logger.warning("mirror journal slot %s unreadable: %r", slot, e)
                continue
            if read_io.buf is None:
                continue
            try:
                return cls.from_json(bytes(read_io.buf))
            except (ValueError, KeyError, TypeError) as e:
                logger.warning(
                    "mirror journal slot %s is corrupt (%r); trying backup",
                    slot,
                    e,
                )
        return None

    async def save(self, fast: StoragePlugin) -> None:
        payload = self.to_json()
        await fast.write(WriteIO(path=JOURNAL_BACKUP_BLOB, buf=payload))
        await fast.write(WriteIO(path=JOURNAL_BLOB, buf=payload))

    async def delete(self, fast: StoragePlugin) -> None:
        """Drop both slots (step GC / post-eviction cleanup)."""
        for slot in (JOURNAL_BLOB, JOURNAL_BACKUP_BLOB):
            try:
                await fast.delete(slot)
            except FileNotFoundError:
                pass
