"""Peer-RAM checkpoint tier: survive preemption at host-RAM speed.

The tiered subsystem's third tier (docs/peer.md). The mirror (mirror.py)
buys *durability* off the take's critical path; this module buys cheap
*recovery*: every rank pushes the shards it committed into a neighbor
rank's host-RAM cache (ring placement, ``(rank + offset) % world``), so
after a single-host preemption the replacement rank pulls its shards
from the surviving peer's RAM instead of paying a durable-storage
restore. The in-memory redundant checkpointing pattern the LLM
checkpoint I/O study (arXiv:2512.24511) and ByteCheckpoint
(arXiv:2407.20143) identify as the gap between checkpoint *interval*
and checkpoint *cost*.

Topology and transport:

- Each participating process runs one peer cache server (daemon
  threads, length-prefixed frames shared with the TCP store —
  ``dist_store.send_frame``) over a :class:`PeerCache` bounded by a
  :class:`~torchsnapshot_tpu.scheduler.PeerCacheBudget` (LRU by step,
  the newest committed step pinned).
- Endpoints ride the coordination store's endpoint registry
  (``dist_store.publish_endpoint`` — overwritten on re-publish, so a
  replacement rank re-announces itself under the same rank id).
- Pushes run on a background worker (mirror-shaped job queue) with a
  per-transfer timeout and the shared collective-progress retry
  strategy; a dead peer costs the pusher a bounded number of timeouts
  and then *degrades* — WARN + ``peer_tier_degraded`` gauge — never a
  wedged push. Each push job records a placement journal entry
  (``.peer_placement-rank<r>.json``) next to the snapshot (fast tier
  for tiered paths) so ``fsck --tier peer`` can audit coverage offline.

Restore ladder (per shard): **peer RAM → local fast tier → durable**
in *availability* order — with one optimization: a blob already resident
on the LOCAL fast tier is read from local disk directly (free) instead
of shipped over the interconnect; only bytes this host actually lost
pull from peers. :func:`build_restore_context` assembles a fanout-style
owner table over the *surviving* peers (one inventory RPC per endpoint,
issued concurrently; dead peers are skipped with a WARN), and
:meth:`PeerRestoreContext.wrap` hands the read pipeline a plugin view
that pulls table-resident blobs from peer RAM — every pulled byte
digest-verified through the integrity layer before it is trusted, and
ranged reads of paged blobs sliced server-side so only the window
crosses the socket — and falls through per blob on ANY failure
(dead peer, stale step, checksum mismatch, budget-refused partial
push). Every peer failure mode resolves to a correct-if-slower
restore, never a wrong or hung one.

Kill switch: ``TORCHSNAPSHOT_TPU_PEER_TIER=0`` (no server, no pushes,
no pulls). Knobs: ring offset, cache budget bytes, transfer timeout
(knobs.py).
"""

from __future__ import annotations

import asyncio
import json
import logging
import pickle
import queue
import socket
import socketserver
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import knobs, telemetry
from ..dist_store import (
    Store,
    lookup_endpoint,
    lookup_endpoints,
    publish_endpoint,
    recv_frame,
    send_frame,
)
from ..event_loop import run_in_fresh_event_loop
from ..integrity import ChecksumError, verify_checksum
from ..io_types import ReadIO, StoragePlugin, WriteIO
from ..scheduler import PeerCacheBudget
from ..storage_plugin import split_tiered_url, url_to_storage_plugin
from ..storage_plugins.retry import (
    CollectiveProgressRetryStrategy,
    RetriesExhausted,
)
from ..telemetry import names as metric_names
from ..telemetry import wire
from ..telemetry.trace import get_recorder as _trace_recorder

logger: logging.Logger = logging.getLogger(__name__)

# Endpoint-registry service name (dist_store.publish_endpoint).
PEER_SERVICE = "peer-tier"

# Placement-journal basename prefix: one doc per pushing rank per step
# dir, written to the local/fast tier after each push job settles.
PEER_PLACEMENT_PREFIX = ".peer_placement-rank"

# A pulling endpoint is declared dead for the rest of one restore after
# this many consecutive transport failures (checksum mismatches do NOT
# count — the transport is fine, the bytes are not).
_PULL_DEAD_AFTER_FAILURES = 2


def peer_step_key(path_url: str) -> str:
    """The cache key for one snapshot path: the fast-tier URL for
    tiered paths (identical string on every rank), the path itself
    otherwise. Pushers and pullers must derive the same key from the
    same manager step path."""
    tiers = split_tiered_url(path_url)
    base = tiers[0] if tiers is not None else path_url
    return base.rstrip("/")


def placement_doc_path(rank: int) -> str:
    return f"{PEER_PLACEMENT_PREFIX}{rank}.json"


class PeerTransferError(RuntimeError):
    """A peer transport operation failed (connect/timeout/protocol)."""


# ---------------------------------------------------------------------------
# The cache (the receiving side's host RAM)
# ---------------------------------------------------------------------------


class _StepSlot:
    __slots__ = ("blobs", "committed", "step", "chunk_refs")

    def __init__(self, step: Optional[int]) -> None:
        # path -> (checksum-table entry, bytes)
        self.blobs: Dict[str, Tuple[tuple, bytes]] = {}
        # Content-addressed blobs this step references in the cache's
        # shared chunk pool (bytes stored once across steps).
        self.chunk_refs: set = set()
        self.committed = False
        self.step = step

    @property
    def holds_bytes(self) -> bool:
        return bool(self.blobs or self.chunk_refs)


class PeerCache:
    """Host-RAM store of peer-pushed checkpoint blobs.

    Steps evict LRU (arrival/commit order) under the byte budget, with
    the newest *committed* step pinned — the one copy that must survive
    arbitrary pressure, because it is the one a replacement rank will
    ask for. A push that cannot fit even after evicting every unpinned
    step is refused (``("refused", "budget")``) — the pusher records
    the degradation; restores simply miss and fall through."""

    def __init__(
        self,
        budget: Optional[PeerCacheBudget] = None,
        keep_last_n: Optional[int] = None,
    ) -> None:
        self._budget = (
            budget
            if budget is not None
            else PeerCacheBudget(knobs.get_peer_cache_budget_bytes())
        )
        self.keep_last_n = keep_last_n
        self._lock = threading.Lock()
        # Insertion/commit order doubles as LRU order: Python dicts
        # preserve it and `move_to_end`-style refreshes re-insert.
        self._steps: Dict[str, _StepSlot] = {}
        self._pinned: Optional[str] = None
        # Shared chunk pool (docs/cas.md): content-addressed blobs are
        # stored ONCE across steps — path -> (entry, bytes) plus a
        # per-chunk refcount of the step slots referencing it. Budget
        # bytes are reserved at first insert and released when the last
        # referencing step drops.
        self._chunks: Dict[str, Tuple[tuple, bytes]] = {}
        self._chunk_rc: Dict[str, int] = {}

    # -- mutation (server handler threads) ------------------------------

    def _is_chunk(self, path: str) -> bool:
        from ..cas import is_chunk_location

        return is_chunk_location(path)

    def _ref_chunk_locked(self, slot: _StepSlot, path: str) -> None:
        if path not in slot.chunk_refs:
            slot.chunk_refs.add(path)
            self._chunk_rc[path] = self._chunk_rc.get(path, 0) + 1

    def reference_chunks(
        self, step_key: str, step: Optional[int], paths: List[str]
    ) -> List[str]:
        """Inventory-by-digest dedup: of ``paths`` (chunk locations),
        reference the ones already pooled under ``step_key`` and return
        them — the pusher then ships bytes only for the misses."""
        with self._lock:
            hits = [p for p in paths if p in self._chunks]
            if hits:
                slot = self._steps.get(step_key)
                if slot is None:
                    slot = _StepSlot(step)
                    self._steps[step_key] = slot
                for p in hits:
                    self._ref_chunk_locked(slot, p)
            self._publish_gauges_locked()
            return hits

    def put(
        self,
        step_key: str,
        step: Optional[int],
        path: str,
        entry: tuple,
        data: bytes,
    ) -> Tuple[bool, str]:
        nbytes = len(data)
        with self._lock:
            if nbytes > self._budget.total_bytes:
                # Doomed from the start: a blob larger than the whole
                # budget must be refused WITHOUT collateral eviction —
                # destroying older steps' copies cannot make it fit.
                self._publish_gauges_locked()
                return False, "budget"
            slot = self._steps.get(step_key)
            if slot is None:
                slot = _StepSlot(step)
                self._steps[step_key] = slot
            if self._is_chunk(path):
                # Content-addressed: the path IS the content, so a
                # pooled copy serves every step — reference it (no new
                # bytes) or insert it once.
                if path in self._chunks:
                    self._ref_chunk_locked(slot, path)
                    self._publish_gauges_locked()
                    return True, "ok"
                while not self._budget.try_reserve(nbytes):
                    if not self._evict_one_locked(exclude=step_key):
                        self._publish_gauges_locked()
                        return False, "budget"
                self._chunks[path] = (tuple(entry), data)
                self._ref_chunk_locked(slot, path)
                self._publish_gauges_locked()
                return True, "ok"
            prior = slot.blobs.pop(path, None)
            if prior is not None:
                self._budget.release(len(prior[1]))
            while not self._budget.try_reserve(nbytes):
                if not self._evict_one_locked(exclude=step_key):
                    self._publish_gauges_locked()
                    return False, "budget"
            slot.blobs[path] = (tuple(entry), data)
            self._publish_gauges_locked()
            return True, "ok"

    def commit(self, step_key: str, step: Optional[int]) -> None:
        with self._lock:
            slot = self._steps.pop(step_key, None)
            if slot is None:
                slot = _StepSlot(step)
            slot.committed = True
            if step is not None:
                slot.step = step
            self._steps[step_key] = slot  # LRU refresh: newest position
            if slot.holds_bytes:
                self._pinned = step_key
            # An EMPTY committed step (every push refused/raced away)
            # must not steal the pin: the previous pinned step is still
            # the newest copy a replacement rank could actually use.
            if self.keep_last_n is not None:
                # Only steps that actually HOLD bytes compete for the
                # retention window: an empty committed slot must not
                # push a usable copy out of it.
                committed = [
                    k
                    for k, s in self._steps.items()
                    if s.committed and s.holds_bytes
                ]
                for old in committed[: -max(1, self.keep_last_n)]:
                    self._drop_locked(old)
            self._publish_gauges_locked()

    def evict_step(self, step_key: str) -> bool:
        with self._lock:
            if step_key not in self._steps:
                return False
            self._drop_locked(step_key)
            self._publish_gauges_locked()
            return True

    def _drop_locked(self, step_key: str) -> None:
        slot = self._steps.pop(step_key, None)
        if slot is None:
            return
        for _, data in slot.blobs.values():
            self._budget.release(len(data))
        for path in slot.chunk_refs:
            rc = self._chunk_rc.get(path, 0) - 1
            if rc <= 0:
                self._chunk_rc.pop(path, None)
                pooled = self._chunks.pop(path, None)
                if pooled is not None:
                    self._budget.release(len(pooled[1]))
            else:
                self._chunk_rc[path] = rc
        if self._pinned == step_key:
            self._pinned = None

    def _evict_one_locked(self, exclude: str) -> bool:
        for key in self._steps:
            if key == exclude or key == self._pinned:
                continue
            self._drop_locked(key)
            return True
        return False

    # -- reads ----------------------------------------------------------

    def get(self, step_key: str, path: str) -> Optional[Tuple[tuple, bytes]]:
        with self._lock:
            if self._is_chunk(path):
                # Content-addressed: a pooled chunk serves ANY step —
                # the path names the bytes, not their provenance.
                pooled = self._chunks.get(path)
                if pooled is not None:
                    return pooled
            slot = self._steps.get(step_key)
            if slot is None:
                return None
            return slot.blobs.get(path)

    def inventory(self, step_key: str) -> Dict[str, tuple]:
        with self._lock:
            slot = self._steps.get(step_key)
            if slot is None:
                return {}
            out = {p: e for p, (e, _) in slot.blobs.items()}
            for p in slot.chunk_refs:
                pooled = self._chunks.get(p)
                if pooled is not None:
                    out[p] = pooled[0]
            return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "steps": len(self._steps),
                "blobs": sum(len(s.blobs) for s in self._steps.values()),
                "chunks": len(self._chunks),
                "chunk_bytes": sum(
                    len(d) for _, d in self._chunks.values()
                ),
                "bytes": self._budget.reserved_bytes(),
                "budget_bytes": self._budget.total_bytes,
                "pinned": self._pinned,
                "committed_steps": sorted(
                    k for k, s in self._steps.items() if s.committed
                ),
            }

    def _publish_gauges_locked(self) -> None:
        try:
            registry = telemetry.metrics()
            registry.gauge_set(
                metric_names.PEER_CACHE_BYTES,
                self._budget.reserved_bytes(),
            )
            registry.gauge_set(
                metric_names.PEER_CACHE_STEPS, len(self._steps)
            )
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass


# ---------------------------------------------------------------------------
# Transport: server + client (length-prefixed frames, pickled tuples)
# ---------------------------------------------------------------------------


class _PeerServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True
    # The stock backlog of 5 drops connects when a fleet syncs at once
    # (every non-owner dials the owner within the same announce poll
    # interval); the kernel's SYN retransmit then stalls the dropped
    # dialers for whole seconds. Queue a fleet's worth instead.
    request_queue_size = 128

    def __init__(self, addr, cache: PeerCache) -> None:
        super().__init__(addr, _PeerRequestHandler)
        self.cache = cache
        # Concurrent-handler count: the wire observatory's userspace
        # proxy for accept pressure on this cache server.
        self.active_handlers = 0
        self.active_lock = threading.Lock()


class _PeerRequestHandler(socketserver.BaseRequestHandler):
    def _dispatch(self, cmd: str, args: tuple, cache: PeerCache) -> Any:
        registry = telemetry.metrics()
        if cmd == metric_names.RPC_PEER_PUSH:
            step_key, step, path, entry, data = args
            return cache.put(step_key, step, path, entry, data)
        if cmd == metric_names.RPC_PEER_COMMIT:
            step_key, step = args
            cache.commit(step_key, step)
            return (True, "ok")
        if cmd == metric_names.RPC_PEER_PULL:
            if len(args) == 3:
                step_key, path, rng = args
            else:
                step_key, path = args
                rng = None
            found = cache.get(step_key, path)
            if found is not None and rng is not None:
                # Server-side slice: a ranged read of a cached
                # blob ships only the requested window, not the
                # whole blob, over the socket.
                entry, data = found
                found = (
                    entry,
                    data[int(rng[0]) : int(rng[1])],
                )
            if found is not None:
                registry.counter_inc(metric_names.PEER_PULL_HITS_TOTAL)
                registry.counter_inc(
                    metric_names.PEER_PULL_BYTES_TOTAL,
                    len(found[1]),
                )
            else:
                registry.counter_inc(metric_names.PEER_PULL_MISSES_TOTAL)
            return found
        if cmd == metric_names.RPC_PEER_REFCHUNKS:
            step_key, step, paths = args
            return cache.reference_chunks(step_key, step, list(paths))
        if cmd == metric_names.RPC_PEER_LIST:
            (step_key,) = args
            return cache.inventory(step_key)
        if cmd == metric_names.RPC_PEER_EVICT:
            (step_key,) = args
            return cache.evict_step(step_key)
        if cmd == metric_names.RPC_PEER_STATS:
            return cache.stats()
        if cmd == metric_names.RPC_PEER_PING:
            return "pong"
        return None

    def handle(self) -> None:
        server: _PeerServer = self.server  # type: ignore[assignment]
        cache = server.cache
        with server.active_lock:
            server.active_handlers += 1
            depth = server.active_handlers
        try:
            wire.observe_accept_depth("peer", depth)
        except Exception:  # noqa: BLE001 - observability is best-effort
            pass
        try:
            while True:
                cmd, args = pickle.loads(
                    recv_frame(self.request, endpoint="peer")
                )
                # Stitch the sender's context into this side's trace:
                # the handler span carries the CLIENT's span id as
                # parent, so the merged cross-rank timeline links the
                # subscriber's pull to the serving peer's work.
                ctx = wire.last_received_context()
                if ctx is not None:
                    with _trace_recorder().span(
                        metric_names.SPAN_WIRE_HANDLER,
                        op=ctx.op,
                        trace_id=ctx.trace_id,
                        parent_span_id=ctx.span_id,
                    ):
                        reply = self._dispatch(cmd, args, cache)
                else:
                    reply = self._dispatch(cmd, args, cache)
                send_frame(self.request, pickle.dumps(reply), endpoint="peer")
        except (ConnectionError, EOFError, OSError):
            return
        finally:
            with server.active_lock:
                server.active_handlers -= 1


class PeerClient:
    """One connection to a peer's cache server; every operation is
    bounded by the transfer-timeout knob (connect and per-frame socket
    ops alike) and any failure raises :class:`PeerTransferError` with
    the connection torn down — the next call redials."""

    def __init__(
        self, host: str, port: int, timeout: Optional[float] = None
    ) -> None:
        self.host = host
        self.port = port
        self.timeout = (
            timeout
            if timeout is not None
            else knobs.get_peer_transfer_timeout_seconds()
        )
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            t_dial = time.monotonic()
            try:
                sock = socket.create_connection(
                    (self.host, self.port), timeout=self.timeout
                )
            except OSError:
                try:
                    wire.observe_dial("peer", 0.0, ok=False)
                except Exception:  # noqa: BLE001 - best-effort
                    pass
                raise
            try:
                # Dial latency per successful connect: a full listen
                # backlog on the serving peer shows up here as whole-
                # second SYN-retransmit quanta (wire-dial-stalled).
                wire.observe_dial("peer", time.monotonic() - t_dial)
            except Exception:  # noqa: BLE001 - best-effort
                pass
            sock.settimeout(self.timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = sock
        return self._sock

    def request(self, cmd: str, *args: Any) -> Any:
        t0 = time.monotonic()
        with self._lock:
            try:
                # Propagate (or extend) this thread's wire context so
                # the request frame carries trace/span/op — the serving
                # peer's handler span links back to it in the merged
                # trace. ``cmd`` IS the declared RPC id (names.RPC_*).
                with wire.propagate(cmd) as ctx, _trace_recorder().span(
                    metric_names.SPAN_WIRE_RPC,
                    op=cmd,
                    trace_id=ctx.trace_id,
                    span_id=ctx.span_id,
                ):
                    sock = self._connect()
                    send_frame(sock, pickle.dumps((cmd, args)), endpoint="peer")
                    reply = pickle.loads(recv_frame(sock, endpoint="peer"))
            except (OSError, EOFError, pickle.PickleError) as e:
                self._teardown_locked()
                raise PeerTransferError(
                    f"peer {self.host}:{self.port} {cmd} failed: {e!r}"
                ) from e
        try:
            wire.observe_rpc("peer", cmd, time.monotonic() - t0)
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass
        return reply

    def _teardown_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._teardown_locked()

    # Typed convenience wrappers. The op ids are the declared RPC
    # registry constants (names.RPC_PEER_*) — snaplint's rpc-op-ids
    # rule keeps literal op strings out of request call sites.

    def push(
        self,
        step_key: str,
        step: Optional[int],
        path: str,
        entry: tuple,
        data: bytes,
    ) -> Tuple[bool, str]:
        return tuple(
            self.request(
                metric_names.RPC_PEER_PUSH, step_key, step, path, entry, data
            )
        )

    def commit(self, step_key: str, step: Optional[int]) -> None:
        self.request(metric_names.RPC_PEER_COMMIT, step_key, step)

    def reference_chunks(
        self, step_key: str, step: Optional[int], paths: List[str]
    ) -> List[str]:
        """Dedup probe: which of these content-addressed chunk paths the
        peer already pools (now referenced under ``step_key``). The
        pusher ships bytes only for the rest."""
        return list(
            self.request(
                metric_names.RPC_PEER_REFCHUNKS, step_key, step, list(paths)
            )
        )

    def pull(
        self,
        step_key: str,
        path: str,
        byte_range: Optional[Tuple[int, int]] = None,
    ) -> Optional[Tuple[tuple, bytes]]:
        return self.request(
            metric_names.RPC_PEER_PULL, step_key, path, byte_range
        )

    def list_step(self, step_key: str) -> Dict[str, tuple]:
        return dict(self.request(metric_names.RPC_PEER_LIST, step_key))

    def evict(self, step_key: str) -> bool:
        return bool(self.request(metric_names.RPC_PEER_EVICT, step_key))

    def stats(self) -> Dict[str, Any]:
        return dict(self.request(metric_names.RPC_PEER_STATS))

    def ping(self) -> bool:
        """Liveness probe: a full request/response round trip through
        the peer's dispatch loop (not just a TCP connect), so a hung
        server reads as dead. True iff the peer answered."""
        try:
            return self.request(metric_names.RPC_PEER_PING) == "pong"
        except (OSError, RuntimeError):
            return False


# ---------------------------------------------------------------------------
# The replicator (the pushing side's background worker)
# ---------------------------------------------------------------------------


class PeerPushJob:
    """One step's push work: blob inventory + completion handle."""

    def __init__(
        self,
        path_url: str,
        step_key: str,
        step: Optional[int],
        blobs: Dict[str, Optional[tuple]],
        committed: bool,
    ) -> None:
        self.path_url = path_url
        self.step_key = step_key
        self.step = step
        self.blobs = dict(blobs)
        self.committed = committed
        self.done_evt = threading.Event()
        self.error: Optional[BaseException] = None
        self.blobs_pushed = 0
        self.bytes_pushed = 0
        self.pushed: List[str] = []
        self.blobs_refused = 0
        self.blobs_skipped = 0
        self.blobs_failed = 0
        # Content-addressed chunks the peer already held (inventory-by-
        # digest dedup): placed without crossing the wire.
        self.blobs_deduped = 0
        self.bytes_deduped = 0
        self.target_rank: Optional[int] = None
        self.endpoint: Optional[Tuple[str, int]] = None

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done_evt.wait(timeout)


class PeerReplicator:
    """Process-wide peer-tier runtime: the local cache server plus the
    background push worker. Inert until :meth:`configure` runs (which
    needs a coordination store and rank/world coordinates); every
    public method is a no-op-shaped fallback before then."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._configured = False
        self._store: Optional[Store] = None
        self._rank = 0
        self._world = 1
        self._server: Optional[_PeerServer] = None
        self._server_thread: Optional[threading.Thread] = None
        self.cache = PeerCache()
        self.host: Optional[str] = None
        self.port: Optional[int] = None
        self._queue: "queue.Queue[Optional[PeerPushJob]]" = queue.Queue()
        self._jobs: List[PeerPushJob] = []
        self._worker: Optional[threading.Thread] = None
        self._stopped = False
        self.degraded = False
        self._failures = 0

    # -- setup -----------------------------------------------------------

    def configure(
        self,
        store: Store,
        rank: int,
        world_size: int,
        keep_last_n: Optional[int] = None,
    ) -> bool:
        """Start the cache server (once) and advertise its endpoint.
        Idempotent; re-configuring refreshes ``keep_last_n`` and
        re-publishes the endpoint (the replacement-rank re-announce)."""
        with self._lock:
            if self._stopped:
                return False
            self._store = store
            self._rank = int(rank)
            self._world = int(world_size)
            if keep_last_n is not None:
                self.cache.keep_last_n = keep_last_n
            if self._server is None:
                server = _PeerServer(("0.0.0.0", 0), self.cache)
                self._server = server
                self.port = server.server_address[1]
                self.host = _advertise_host()
                self._server_thread = threading.Thread(
                    target=server.serve_forever,
                    name="peer-tier-server",
                    daemon=True,
                )
                self._server_thread.start()
            self._configured = True
        try:
            publish_endpoint(
                store, PEER_SERVICE, self._rank, self.host, self.port
            )
        except Exception as e:  # noqa: BLE001 - degraded, not fatal
            logger.warning("peer tier: endpoint publish failed: %r", e)
            self._note_degraded()
        return True

    @property
    def configured(self) -> bool:
        return self._configured

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world

    def endpoint_for(self, rank: int) -> Optional[Tuple[str, int]]:
        if self._store is None:
            return None
        return lookup_endpoint(self._store, PEER_SERVICE, rank)

    def resolve_endpoints(self, ranks) -> Dict[int, Tuple[str, int]]:
        """Every advertised endpoint for ``ranks`` in ONE batched store
        round trip (``dist_store.lookup_endpoints``); {} before
        configure or on a failed registry read."""
        if self._store is None:
            return {}
        return lookup_endpoints(self._store, PEER_SERVICE, ranks)

    def target_rank(self) -> int:
        return (self._rank + knobs.get_peer_ring_offset()) % max(
            1, self._world
        )

    # -- pushing ---------------------------------------------------------

    def enqueue_push(
        self,
        path_url: str,
        blobs: Dict[str, Optional[tuple]],
        committed: bool = True,
        step: Optional[int] = None,
    ) -> Optional[PeerPushJob]:
        """Queue one step's blobs for replication to the ring neighbor;
        returns a handle, or None when the tier cannot run (not
        configured, single-process world, or a ring offset that maps
        the rank onto itself)."""
        with self._lock:
            if (
                not self._configured
                or self._stopped
                or self._world <= 1
                or not blobs
            ):
                return None
            if self.target_rank() == self._rank:
                return None
            job = PeerPushJob(
                path_url, peer_step_key(path_url), step, blobs, committed
            )
            # Settled jobs carry no state restores need (the cache is
            # the truth): keep EVERY unsettled job (drain() — the
            # preemption-grace flush — must wait on all of them) plus
            # the newest few failures for state().
            unsettled = [
                j for j in self._jobs if not j.done_evt.is_set()
            ]
            failed = [
                j
                for j in self._jobs
                if j.done_evt.is_set() and j.error is not None
            ][-8:]
            self._jobs = failed + unsettled
            self._jobs.append(job)
            self._ensure_worker_locked()
        self._queue.put(job)
        return job

    def _ensure_worker_locked(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._worker_main, name="peer-tier-push", daemon=True
            )
            self._worker.start()

    def _worker_main(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            recorder = _trace_recorder()
            job_span = recorder.begin(
                metric_names.SPAN_PEER_JOB,
                step=job.step_key,
                blobs=len(job.blobs),
            )
            try:
                run_in_fresh_event_loop(self._run_job(job))
                if job.blobs_failed == 0 and job.blobs_refused == 0:
                    self._clear_degraded()
            except BaseException as e:  # noqa: BLE001 - degrade, never raise
                job.error = e
                self._note_degraded()
                logger.warning(
                    "peer tier: push of %s to rank %s degraded (%r); the "
                    "restore ladder falls through to storage",
                    job.step_key,
                    job.target_rank,
                    e,
                )
            finally:
                recorder.end(job_span)
                self._settle_telemetry(job)
                job.done_evt.set()
                self._queue.task_done()

    async def _run_job(self, job: PeerPushJob) -> None:
        job.target_rank = self.target_rank()
        endpoint = self.endpoint_for(job.target_rank)
        job.endpoint = endpoint
        if endpoint is None:
            raise PeerTransferError(
                f"rank {job.target_rank} published no peer endpoint"
            )
        timeout = knobs.get_peer_transfer_timeout_seconds()
        storage = url_to_storage_plugin(job.path_url)
        client = PeerClient(endpoint[0], endpoint[1], timeout=timeout)
        retry = CollectiveProgressRetryStrategy(
            progress_window_seconds=timeout, scope="peer"
        )
        loop = asyncio.get_running_loop()
        try:
            # Inventory-by-digest dedup, one RPC: content-addressed
            # chunk paths the neighbor already pools are *referenced*
            # under this step (no bytes cross the wire) — a dense-
            # retention run pushes one full step plus deltas.
            from ..cas import is_chunk_location

            deduped: set = set()
            chunk_paths = sorted(
                p for p in job.blobs if is_chunk_location(p)
            )
            if chunk_paths:

                async def _ref_once():
                    return await loop.run_in_executor(
                        None,
                        client.reference_chunks,
                        job.step_key,
                        job.step,
                        chunk_paths,
                    )

                hits = await retry.run(
                    _ref_once, retriable_exceptions=(PeerTransferError,)
                )
                for p in hits:
                    deduped.add(p)
                    job.blobs_deduped += 1
                    entry = job.blobs.get(p)
                    if entry is not None and len(entry) >= 3:
                        job.bytes_deduped += int(entry[2])
                    job.pushed.append(p)
            for path in sorted(job.blobs):
                if path in deduped:
                    continue
                entry = job.blobs[path]
                read_io = ReadIO(path=path)
                try:
                    await storage.read(read_io)
                except FileNotFoundError:
                    # Eviction/GC raced the push: the blob is gone
                    # locally, so there is nothing to replicate.
                    job.blobs_skipped += 1
                    continue
                data = bytes(read_io.buf)
                if entry is None:
                    from ..integrity import compute_checksum_entry

                    entry = compute_checksum_entry(data)

                def _push_sync(
                    p: str = path, e: tuple = entry, d: bytes = data
                ):
                    return client.push(job.step_key, job.step, p, e, d)

                async def _push_once():
                    return await loop.run_in_executor(None, _push_sync)

                with _trace_recorder().span(
                    metric_names.SPAN_PEER_PUSH, blob=path
                ):
                    accepted, reason = await retry.run(
                        _push_once,
                        retriable_exceptions=(PeerTransferError,),
                    )
                if accepted:
                    job.blobs_pushed += 1
                    job.bytes_pushed += len(data)
                    job.pushed.append(path)
                else:
                    # The peer's budget refused the blob: permanent for
                    # this step (the cache is full of pinned bytes) —
                    # count it and move on, the ladder falls through.
                    job.blobs_refused += 1
            if job.committed:
                async def _commit_once():
                    return await loop.run_in_executor(
                        None, client.commit, job.step_key, job.step
                    )

                await retry.run(
                    _commit_once, retriable_exceptions=(PeerTransferError,)
                )
            await self._write_placement(storage, job)
        except (PeerTransferError, RetriesExhausted) as e:
            # Only blobs neither pushed, budget-refused, nor GC-skipped
            # actually FAILED on the transport — refusals/skips are
            # already counted and must not be double-reported to the
            # doctor/fsck evidence.
            job.blobs_failed = max(
                0,
                len(job.blobs)
                - job.blobs_pushed
                - job.blobs_refused
                - job.blobs_skipped
                - job.blobs_deduped,
            )
            try:
                await self._write_placement(storage, job, error=repr(e))
            except Exception:  # noqa: BLE001 - already degrading
                pass
            raise
        finally:
            client.close()
            await storage.close()

    async def _write_placement(
        self,
        storage: StoragePlugin,
        job: PeerPushJob,
        error: Optional[str] = None,
    ) -> None:
        """Placement journal entry for this push (fast/local tier): the
        offline record of which blobs have peer copies where —
        ``fsck --tier peer``'s evidence."""
        from .plugin import TieredStoragePlugin

        doc = {
            "step_key": job.step_key,
            "step": job.step,
            "pusher_rank": self._rank,
            "target_rank": job.target_rank,
            "endpoint": (
                f"{job.endpoint[0]}:{job.endpoint[1]}"
                if job.endpoint
                else None
            ),
            "committed": job.committed,
            "blobs_pushed": job.blobs_pushed,
            "blobs_refused": job.blobs_refused,
            "blobs_skipped": job.blobs_skipped,
            "blobs_failed": job.blobs_failed,
            "blobs_deduped": job.blobs_deduped,
            "bytes_pushed": job.bytes_pushed,
            "bytes_deduped": job.bytes_deduped,
            # Only the blobs that actually LANDED in the peer's RAM —
            # the placement claim fsck audits against requirements.
            "blobs": sorted(job.pushed),
            "blobs_total": len(job.blobs),
            "error": error,
            "unix_ts": round(time.time(), 3),
        }
        payload = json.dumps(doc, sort_keys=True).encode()
        target = (
            storage.fast
            if isinstance(storage, TieredStoragePlugin)
            else storage
        )
        await target.write(
            WriteIO(path=placement_doc_path(self._rank), buf=payload)
        )

    def _settle_telemetry(self, job: PeerPushJob) -> None:
        try:
            registry = telemetry.metrics()
            registry.counter_inc(
                metric_names.PEER_PUSH_BLOBS_TOTAL, job.blobs_pushed
            )
            registry.counter_inc(
                metric_names.PEER_PUSH_BYTES_TOTAL, job.bytes_pushed
            )
            if job.blobs_deduped:
                registry.counter_inc(
                    metric_names.PEER_PUSH_CHUNKS_DEDUPED_TOTAL,
                    job.blobs_deduped,
                )
                registry.counter_inc(
                    metric_names.PEER_PUSH_BYTES_DEDUPED_TOTAL,
                    job.bytes_deduped,
                )
            failures = job.blobs_failed + job.blobs_refused
            if failures or job.error is not None:
                registry.counter_inc(
                    metric_names.PEER_PUSH_FAILURES_TOTAL, max(1, failures)
                )
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass

    def _note_degraded(self) -> None:
        self.degraded = True
        self._failures += 1
        try:
            telemetry.metrics().gauge_set(
                metric_names.PEER_TIER_DEGRADED_STATE, 1
            )
        except Exception:  # noqa: BLE001
            pass

    def _clear_degraded(self) -> None:
        if not self.degraded:
            return
        self.degraded = False
        try:
            telemetry.metrics().gauge_set(
                metric_names.PEER_TIER_DEGRADED_STATE, 0
            )
        except Exception:  # noqa: BLE001
            pass

    # -- completion / lifecycle -----------------------------------------

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every queued push settles (True) or the timeout
        lapses (False). The preemption drain hook: inside the eviction
        grace window this ships the last committed step's delta into
        the surviving peer's RAM — host-RAM bandwidth, not a durable
        commit — so the replacement's restore has a hot copy."""
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        with self._lock:
            jobs = list(self._jobs)
        for job in jobs:
            remaining = None
            if deadline is not None:
                remaining = max(0.0, deadline - time.monotonic())
            if not job.wait(remaining):
                return False
        return True

    def state(self) -> Dict[str, Any]:
        with self._lock:
            pending = [j for j in self._jobs if not j.done_evt.is_set()]
            return {
                "configured": self._configured,
                "rank": self._rank,
                "world_size": self._world,
                "endpoint": (
                    f"{self.host}:{self.port}" if self.port else None
                ),
                "degraded": self.degraded,
                "failures": self._failures,
                "jobs_pending": len(pending),
                "cache": self.cache.stats(),
            }

    def stop(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            self._configured = False
            worker = self._worker
            server = self._server
            server_thread = self._server_thread
        self._queue.put(None)
        if worker is not None:
            worker.join(timeout=10)
        if server is not None:
            server.shutdown()
            server.server_close()
        if server_thread is not None:
            server_thread.join(timeout=10)


def _advertise_host() -> str:
    """The address peers dial for THIS process's cache server. Must be
    rank-local: ``_routable_host``'s first choice is the jax
    coordinator (rank 0's) address, which every non-rank-0 host would
    wrongly advertise for a server bound on its own machine."""
    from ..dist_store import _local_advertise_host

    try:
        return _local_advertise_host()
    except Exception:  # noqa: BLE001 - last resort
        return socket.gethostname()


# ---------------------------------------------------------------------------
# Process-wide replicator + integration hooks
# ---------------------------------------------------------------------------

_replicator: Optional[PeerReplicator] = None
_replicator_lock = threading.Lock()
# One-shot warning latch: peer tier enabled but inert (checksums off).
_WARNED_NO_CHECKSUMS = False


def get_replicator() -> PeerReplicator:
    global _replicator
    with _replicator_lock:
        if _replicator is None:
            _replicator = PeerReplicator()
        return _replicator


def reset_peer_tier() -> None:
    """Stop and discard the process replicator (tests simulating a
    restarted — or preempted — process)."""
    global _replicator
    with _replicator_lock:
        rep, _replicator = _replicator, None
    if rep is not None:
        rep.stop()


def maybe_configure(pg: Any, keep_last_n: Optional[int] = None) -> bool:
    """Configure the peer tier for this process if the knob is on and a
    multi-rank coordination store exists; False otherwise. Safe to call
    repeatedly (manager construction, replacement-rank restart)."""
    if not knobs.is_peer_tier_enabled():
        return False
    from ..pg_wrapper import PGWrapper

    wrapper = pg if isinstance(pg, PGWrapper) else PGWrapper(pg)
    store = wrapper.store
    if store is None or wrapper.get_world_size() <= 1:
        return False
    return get_replicator().configure(
        store,
        wrapper.get_rank(),
        wrapper.get_world_size(),
        keep_last_n=keep_last_n,
    )


def maybe_enqueue_push(
    path: str, written: Dict[str, tuple], committed: bool = True
) -> Optional[PeerPushJob]:
    """Snapshot-commit hook (every rank): queue this rank's written
    blobs for replication to its ring neighbor. ``written`` is the
    rank's checksum table (path -> integrity entry) — the digests the
    puller will verify against. No-op unless the tier is configured;
    base-referenced (``../``) locations belong to other steps and are
    skipped. Never raises."""
    if not knobs.is_peer_tier_enabled():
        return None
    with _replicator_lock:
        rep = _replicator
    if rep is None or not rep.configured:
        return None
    try:
        # Base-referenced (``../step_*``) locations belong to other
        # steps and are skipped — but content-addressed chunk refs ARE
        # this step's payload (stored once, referenced by many): they
        # push (or dedup against the neighbor's pool) like any blob.
        from ..cas import is_chunk_location

        blobs: Dict[str, Optional[tuple]] = {
            p: tuple(e)
            for p, e in written.items()
            if not p.startswith("../") or is_chunk_location(p)
        }
        if not blobs:
            if knobs.is_checksums_disabled():
                # The blob inventory IS the checksum table: with
                # checksums off there is nothing to push (and nothing
                # a puller could verify). Say so ONCE — a run with the
                # peer tier nominally on but silently inert would
                # otherwise only be discovered at the preemption it
                # failed to insure.
                global _WARNED_NO_CHECKSUMS
                if not _WARNED_NO_CHECKSUMS:
                    _WARNED_NO_CHECKSUMS = True
                    logger.warning(
                        "peer tier: checksums are disabled "
                        "(TORCHSNAPSHOT_TPU_DISABLE_CHECKSUMS), so no "
                        "blob inventory exists to push — the peer tier "
                        "is inert and preemption recovery will pay a "
                        "full storage restore"
                    )
            return None
        from ..telemetry.ledger import step_from_path

        step = step_from_path(peer_step_key(path))
        return rep.enqueue_push(
            path, blobs, committed=committed, step=step
        )
    except Exception as e:  # noqa: BLE001 - the tier degrades, never fails ops
        logger.warning("peer tier: push enqueue failed: %r", e)
        return None


def maybe_drain(timeout: Optional[float] = None) -> bool:
    """Flush pending peer pushes (preemption grace window / teardown);
    True when everything settled or the tier is inert."""
    with _replicator_lock:
        rep = _replicator
    if rep is None or not rep.configured:
        return True
    return rep.drain(timeout)


def maybe_evict_step(path: str) -> None:
    """Manager-GC hook (rank 0): best-effort eviction of a dropped
    step's peer copies from EVERY advertised endpoint — the caches
    self-bound regardless (budget LRU + keep_last_n), this just
    reclaims the RAM promptly. Runs on a detached daemon thread: GC
    sits on rank 0's save path, and a dead peer's connect timeouts
    must never stretch a save."""
    with _replicator_lock:
        rep = _replicator
    if rep is None or not rep.configured:
        return
    step_key = peer_step_key(path)
    timeout = min(5.0, knobs.get_peer_transfer_timeout_seconds())
    world = rep.world_size

    def _evict_all() -> None:
        # One batched registry resolve for the whole ring, then the
        # per-endpoint evict RPCs.
        endpoints = rep.resolve_endpoints(range(world))
        for rank in range(world):
            endpoint = endpoints.get(rank)
            if endpoint is None:
                continue
            client = PeerClient(endpoint[0], endpoint[1], timeout=timeout)
            try:
                client.evict(step_key)
            except PeerTransferError:
                pass  # dead peer: its cache died with it
            finally:
                client.close()

    threading.Thread(
        target=_evict_all, name="peer-tier-evict", daemon=True
    ).start()


def peer_state_for_path(path: str) -> Optional[Dict[str, Any]]:
    """The process replicator's state when the tier is configured, else
    None — the one state read shared by snapshot reports and the
    doctor (mirror_state_for_path's shape)."""
    with _replicator_lock:
        rep = _replicator
    if rep is None or not rep.configured:
        return None
    return rep.state()


# ---------------------------------------------------------------------------
# Restore side: the tier ladder
# ---------------------------------------------------------------------------


class PeerRestoreContext:
    """One restore's peer-tier state: the owner table over surviving
    peers (blob path -> endpoint + integrity entry) and the per-tier
    byte accounting the restore report carries as ``tier_split``."""

    def __init__(
        self,
        table: Dict[str, Tuple[int, Tuple[str, int], tuple]],
        step_key: str,
        timeout: Optional[float] = None,
    ) -> None:
        self.table = table
        self.step_key = step_key
        self.timeout = (
            timeout
            if timeout is not None
            else knobs.get_peer_transfer_timeout_seconds()
        )
        self._lock = threading.Lock()
        # Per-endpoint free-connection pool: concurrent pulls each
        # borrow a connection (creating one when none is free) and
        # return it on success, so restore reads are NOT serialized
        # onto one TCP stream per surviving peer — concurrency is
        # bounded by the read pipeline's executor, not by a shared
        # client lock. A connection that errored is closed, not
        # returned.
        self._free_clients: Dict[Tuple[str, int], List[PeerClient]] = {}
        self._endpoint_failures: Dict[Tuple[str, int], int] = {}
        self.tier_bytes: Dict[str, int] = {
            "peer": 0,
            "fast": 0,
            "durable": 0,
        }
        self.peer_failures = 0
        self.fallthrough_bytes = 0
        self.served_blobs = 0

    @property
    def eligible_blobs(self) -> int:
        return len(self.table)

    def _borrow(self, endpoint: Tuple[str, int]) -> Optional[PeerClient]:
        with self._lock:
            if (
                self._endpoint_failures.get(endpoint, 0)
                >= _PULL_DEAD_AFTER_FAILURES
            ):
                outcome = "dead"
                client = None
            else:
                free = self._free_clients.get(endpoint)
                if free:
                    outcome = "reused"
                    client = free.pop()
                else:
                    outcome = "new"
                    client = PeerClient(
                        endpoint[0], endpoint[1], timeout=self.timeout
                    )
        try:
            wire.observe_pool_checkout("peer", outcome)
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass
        return client

    def _give_back(
        self, endpoint: Tuple[str, int], client: PeerClient
    ) -> None:
        with self._lock:
            self._free_clients.setdefault(endpoint, []).append(client)

    def pull(
        self,
        path: str,
        byte_range: Optional[Tuple[int, int]] = None,
    ) -> Optional[bytes]:
        """Digest-verified pull of ``path`` (the whole blob, or exactly
        the ``byte_range`` window) from the owning peer, or None on ANY
        failure (the caller falls through a tier).

        Ranged reads of blobs with per-page digests are sliced on the
        SERVER — only the window crosses the socket — and verified via
        the page digests the range fully covers; a window covering no
        full page (or a blob with only a whole-blob digest) falls back
        to one whole-blob transfer verified end-to-end and sliced
        client-side, so no byte is ever trusted unverified."""
        owner = self.table.get(path)
        if owner is None:
            return None
        _, endpoint, entry = owner
        client = self._borrow(endpoint)
        if client is None:
            return None
        entry = tuple(entry)
        rng = None
        if byte_range is not None:
            rng = (int(byte_range[0]), int(byte_range[1]))
        # Server-side slicing only when the window is verifiable on its
        # own (paged entry, integrity.verify_range_checksum).
        ranged = rng is not None and len(entry) >= 5
        try:
            with _trace_recorder().span(
                metric_names.SPAN_PEER_PULL, blob=path
            ):
                found = client.pull(
                    self.step_key, path, rng if ranged else None
                )
                if found is not None and ranged:
                    from ..integrity import verify_range_checksum

                    if not verify_range_checksum(
                        found[1], entry, rng, path
                    ):
                        # The window fully covers no page: re-pull the
                        # whole blob so the full digest can vouch.
                        found = client.pull(self.step_key, path)
                        ranged = False
            if found is None:
                # Stale step / evicted blob: a correct miss.
                self._give_back(endpoint, client)
                with self._lock:
                    self.peer_failures += 1
                return None
            pulled_entry, data = found
            # Trust NOTHING before the integrity layer passes: verify
            # against the entry recorded at *write* time (the inventory
            # the table was built from), so a corrupted cache — or a
            # peer echoing a different step's bytes — can never reach
            # the destination buffers. (Ranged pulls were verified
            # against the covered page digests above.)
            if not ranged:
                verify_checksum(data, entry, path)
            self._give_back(endpoint, client)
            with self._lock:
                self._endpoint_failures.pop(endpoint, None)
            if rng is not None and not ranged:
                return data[rng[0] : rng[1]]
            return data
        except ChecksumError as e:
            logger.warning(
                "peer tier: checksum mismatch pulling %s (%r); falling "
                "through to the next tier",
                path,
                e,
            )
            # The transport is fine — only the bytes are wrong: the
            # connection goes back to the pool, the failure count does
            # NOT advance the endpoint toward dead.
            self._give_back(endpoint, client)
            with self._lock:
                self.peer_failures += 1
            return None
        except PeerTransferError as e:
            client.close()
            with self._lock:
                self.peer_failures += 1
                n = self._endpoint_failures.get(endpoint, 0) + 1
                self._endpoint_failures[endpoint] = n
            logger.warning(
                "peer tier: pull of %s from %s failed (%r, failure %d); "
                "falling through to the next tier",
                path,
                endpoint,
                e,
                n,
            )
            return None

    def count(self, tier: str, nbytes: int) -> None:
        with self._lock:
            self.tier_bytes[tier] = self.tier_bytes.get(tier, 0) + int(
                nbytes
            )
            if tier == "peer":
                self.served_blobs += 1

    def discount(self, tier: str, nbytes: int) -> None:
        """Take back a serve that verification later rejected (the
        corruption ladder re-served the blob from another tier): the
        split must sum to the bytes actually restored, not restored
        plus every corrupt attempt."""
        with self._lock:
            self.tier_bytes[tier] = max(
                0, self.tier_bytes.get(tier, 0) - int(nbytes)
            )

    def note_fallthrough(self, nbytes: int) -> None:
        with self._lock:
            self.fallthrough_bytes += int(nbytes)

    def pipeline_fields(self) -> Dict[str, Any]:
        """The restore report's peer-tier fields (report.py maps them
        through build_report): per-tier byte split + degradation
        evidence for the ``peer-tier-degraded`` doctor rule."""
        with self._lock:
            return {
                "tier_split": dict(self.tier_bytes),
                "peer": {
                    "eligible_blobs": self.eligible_blobs,
                    "served_blobs": self.served_blobs,
                    "failures": self.peer_failures,
                    "fallthrough_bytes": self.fallthrough_bytes,
                    "degraded": bool(
                        self.peer_failures or self.fallthrough_bytes
                    ),
                },
            }

    def wrap(self, storage: StoragePlugin) -> "StoragePlugin":
        return _PeerLadderPlugin(storage, self)

    def close(self) -> None:
        with self._lock:
            pools, self._free_clients = dict(self._free_clients), {}
        for clients in pools.values():
            for client in clients:
                client.close()


class _PeerLadderPlugin(StoragePlugin):
    """The per-shard tier ladder as a plugin view: peer RAM first for
    table-resident blobs, then the local fast tier, then durable —
    with per-tier byte accounting. Substituted for the restore's
    storage plugin wholesale, so close() DOES delegate (the ladder owns
    the inner plugin's lifecycle for the op)."""

    def __init__(self, inner: StoragePlugin, ctx: PeerRestoreContext) -> None:
        from .plugin import TieredStoragePlugin

        self.inner = inner
        self.ctx = ctx
        self._tiered = (
            inner if isinstance(inner, TieredStoragePlugin) else None
        )

    async def read(self, read_io: ReadIO) -> None:
        path = read_io.path
        eligible = path in self.ctx.table
        # A LOCAL fast-tier hit short-circuits the peer pull: the ladder
        # exists for bytes the host lost, and a surviving rank's local
        # copy is free — shipping it over the interconnect would
        # multiply restore traffic by ~world for no availability gain.
        # (The replacement rank's fast tier is empty, so its shards
        # still resolve peer-first in effect.)
        if self._tiered is not None:
            try:
                await self._tiered.fast.read(read_io)
                read_io.served_by = "fast"
                self.ctx.count(
                    "fast",
                    memoryview(read_io.buf).nbytes
                    if read_io.buf is not None
                    else 0,
                )
                return
            except FileNotFoundError:
                pass
        if eligible:
            rng = read_io.byte_range
            loop = asyncio.get_running_loop()
            chunk = await loop.run_in_executor(
                None, self.ctx.pull, path, rng
            )
            if chunk is not None:
                if read_io.dest is not None and len(read_io.dest) == len(
                    chunk
                ):
                    read_io.dest[:] = chunk
                    read_io.buf = read_io.dest
                else:
                    read_io.buf = memoryview(bytes(chunk))
                read_io.served_by = "peer"
                self.ctx.count("peer", len(chunk))
                return
        # Bottom of the ladder: durable storage (a non-tiered inner
        # plugin IS the durable tier).
        if self._tiered is not None:
            await self._tiered.durable.read(read_io)
        else:
            await self.inner.read(read_io)
        read_io.served_by = "durable"
        nbytes = (
            memoryview(read_io.buf).nbytes if read_io.buf is not None else 0
        )
        self.ctx.count("durable", nbytes)
        if eligible:
            # A peer copy existed for this blob but durable storage
            # served it: the degradation the doctor rule cites.
            self.ctx.note_fallthrough(nbytes)

    async def read_degraded(self, read_io: ReadIO) -> bool:
        """Corruption fallthrough, ladder flavor: peer pulls are
        digest-verified inside :meth:`PeerRestoreContext.pull` (corrupt
        peer bytes never escape it), so the storage tiers are the only
        sources whose bytes can reach verification corrupt — retry
        whichever of durable/fast has not served this request yet."""
        tried = getattr(read_io, "_tiers_tried", None)
        if tried is None:
            tried = {read_io.served_by} if read_io.served_by else set()
            read_io._tiers_tried = tried
        # The rejected serve was already counted by read() (or by a
        # previous healing round): take it back so tier_split sums to
        # the bytes actually restored.
        if read_io.served_by and read_io.buf is not None:
            self.ctx.discount(
                read_io.served_by, memoryview(read_io.buf).nbytes
            )
        tiers = []
        if self._tiered is not None:
            tiers = [
                ("durable", self._tiered.durable),
                ("fast", self._tiered.fast),
            ]
        else:
            tiers = [("durable", self.inner)]
        for tier, plugin in tiers:
            if tier in tried:
                continue
            tried.add(tier)
            try:
                await plugin.read(read_io)
            except (FileNotFoundError, OSError):
                continue
            read_io.served_by = tier
            self.ctx.count(
                tier,
                memoryview(read_io.buf).nbytes
                if read_io.buf is not None
                else 0,
            )
            return True
        return False

    async def read_with_checksum(self, read_io: ReadIO):
        # Decline (sticky, per the interface contract): the ladder must
        # route every read through the tier logic above.
        return None

    async def write(self, write_io: WriteIO) -> None:
        await self.inner.write(write_io)

    async def write_with_checksum(self, write_io: WriteIO):
        return await self.inner.write_with_checksum(write_io)

    async def delete(self, path: str) -> None:
        await self.inner.delete(path)

    async def close(self) -> None:
        self.ctx.close()
        await self.inner.close()


def build_restore_context(path: str) -> Optional[PeerRestoreContext]:
    """Assemble the restore-side owner table for one snapshot path by
    asking every advertised peer endpoint for its inventory of the
    step (one LIST RPC each; a dead peer is skipped with a WARN).
    Endpoint resolution is ONE batched ``multi_get`` against the
    registry (``dist_store.lookup_endpoints``) — restore setup on a
    thousand-rank world costs one store round trip, not world
    sequential lookups. Returns None when the tier is off/inert or no
    peer holds anything for the step — the restore then runs exactly
    the pre-peer path. Never raises: every failure mode degrades to
    "no peer tier"."""
    if not knobs.is_peer_tier_enabled():
        return None
    with _replicator_lock:
        rep = _replicator
    if rep is None or not rep.configured:
        return None
    try:
        from concurrent.futures import ThreadPoolExecutor

        step_key = peer_step_key(path)
        timeout = knobs.get_peer_transfer_timeout_seconds()
        endpoints = rep.resolve_endpoints(range(rep.world_size))

        def _inventory_of(rank: int):
            endpoint = endpoints.get(rank)
            if endpoint is None:
                return rank, None, {}
            client = PeerClient(endpoint[0], endpoint[1], timeout=timeout)
            try:
                return rank, endpoint, client.list_step(step_key)
            except PeerTransferError as e:
                logger.warning(
                    "peer tier: rank %d endpoint %s unreachable during "
                    "restore setup (%r); its cached shards fall through "
                    "to storage",
                    rank,
                    endpoint,
                    e,
                )
                return rank, endpoint, {}
            finally:
                client.close()

        # CONCURRENT inventory RPCs: setup cost is one timeout, not
        # world x timeout, when stale endpoints of preempted hosts
        # linger in the registry.
        with ThreadPoolExecutor(
            max_workers=min(8, max(1, rep.world_size)),
            thread_name_prefix="peer-tier-inv",
        ) as pool:
            results = list(pool.map(_inventory_of, range(rep.world_size)))
        table: Dict[str, Tuple[int, Tuple[str, int], tuple]] = {}
        for rank, endpoint, inventory in results:
            if endpoint is None:
                continue
            for blob_path, entry in inventory.items():
                table.setdefault(
                    blob_path, (rank, endpoint, tuple(entry))
                )
        if not table:
            return None
        return PeerRestoreContext(table, step_key, timeout=timeout)
    except Exception as e:  # noqa: BLE001 - degrade to storage-only restore
        logger.warning("peer tier: restore-context build failed: %r", e)
        return None
