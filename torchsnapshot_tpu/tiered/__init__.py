"""Tiered checkpointing: fast local commit + background mirror to durable
storage.

The measured problem (VERDICT.md): committing directly against the
durable tier puts its bandwidth on the take critical path — the async
take stalled 99.8 s of a 101.1 s save waiting on storage. ByteCheckpoint
and FastPersist (PAPERS.md) both decouple *commit* (fast, local) from
*durability* (background upload); this package is that decoupling for
this checkpointer:

- :class:`TieredStoragePlugin` (plugin.py) composes two ordinary storage
  plugins — a *fast tier* every take writes through and commits against,
  and a *durable tier* a background :class:`Mirror` replicates committed
  bytes to. Reads resolve fast-tier-first with per-blob durable
  fallback, so an evicted or incomplete fast tier is transparent to
  restore.
- :class:`Mirror` (mirror.py) is the background replication worker:
  per-blob resumable progress journaled crash-consistently in the fast
  tier (journal.py), retry/backoff via the shared collective-progress
  strategy, durable commit-marker-last ordering, and machine-readable
  metrics.
- ``tiered://<fast_url>|<durable_url>`` URLs dispatch here through
  ``storage_plugin.py``; ``CheckpointManager`` adds tier-aware retention
  (``keep_fast_last_n``) and a ``wait_durable(step)`` barrier.
- The **peer tier** (peer.py, docs/peer.md) is the third tier: every
  rank pushes its committed shards into a neighbor rank's host-RAM
  cache (ring placement), and restores resolve a peer RAM -> fast ->
  durable ladder per shard — preemption recovery at host-RAM copy
  speed, degrading gracefully to storage on any peer failure.
  ``CheckpointManager`` adds ``keep_peer_last_n`` and brings the tier
  up when constructed with a multi-rank ``pg``.

See docs/tiered.md for the architecture, journal format and failure
matrix; docs/peer.md for the peer tier's ladder and degradation matrix.
"""

from __future__ import annotations

from .journal import JOURNAL_BACKUP_BLOB, JOURNAL_BLOB, MirrorJournal
from .mirror import Mirror, get_mirror, reset_mirror, wait_durable
from .peer import (
    PeerCache,
    PeerClient,
    PeerReplicator,
    PeerRestoreContext,
    PeerTransferError,
    get_replicator,
    reset_peer_tier,
)
from .plugin import TieredStoragePlugin

__all__ = [
    "JOURNAL_BACKUP_BLOB",
    "JOURNAL_BLOB",
    "Mirror",
    "MirrorJournal",
    "PeerCache",
    "PeerClient",
    "PeerReplicator",
    "PeerRestoreContext",
    "PeerTransferError",
    "TieredStoragePlugin",
    "get_mirror",
    "get_replicator",
    "reset_mirror",
    "reset_peer_tier",
    "wait_durable",
]
