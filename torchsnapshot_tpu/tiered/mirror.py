"""Background durable-tier replication worker.

One :class:`Mirror` per process (``get_mirror()``), one daemon worker
thread, jobs processed strictly in order of enqueue. The ordering is
load-bearing twice over:

- within a job, the snapshot commit marker (``.snapshot_metadata``) is
  uploaded strictly LAST — the durable tier observes the same
  commit-after-data invariant the fast tier got from ``Snapshot.take``,
  so a durable-tier reader can never see a committed-looking step whose
  data is still uploading;
- across jobs, a step's blobs are enqueued (at its take-plugin's close)
  before the manager's index rewrite that names the step, so the durable
  index never points at a step the durable tier doesn't hold.

Per-blob progress is journaled in the fast tier (journal.py) after every
completed upload: a kill at ANY point leaves either a journal that
resumes the upload without re-sending completed blobs, or no journal at
all — in which case ``resume()`` rebuilds the inventory from the
fast-tier manifest and re-mirrors (safe: uploads are idempotent and the
durable commit marker still goes last).

Uploads retry under the shared collective-progress strategy
(storage_plugins/retry.py); a job whose retries exhaust keeps its
journal and surfaces its error through ``wait_durable``/metrics — the
fast-tier snapshot remains fully restorable throughout.
"""

from __future__ import annotations

import asyncio
import logging
import os
import queue
import threading
import time
from typing import Dict, List, Optional

from .. import knobs, telemetry
from ..event_loop import run_in_fresh_event_loop
from ..io_types import ReadIO, WriteIO
from ..storage_plugin import split_tiered_url, url_to_storage_plugin
from ..storage_plugins.retry import CollectiveProgressRetryStrategy
from ..telemetry import names as metric_names
from ..telemetry.trace import export_op_trace, get_recorder as _trace_recorder
from .journal import MirrorJournal

logger: logging.Logger = logging.getLogger(__name__)

# Snapshot commit-marker name, duplicated from snapshot.py to keep this
# module importable without pulling the full snapshot machinery (the
# plugin layer must stay light).
_METADATA_FNAME = ".snapshot_metadata"


class _TransientMirrorError(Exception):
    pass


class MirrorJob:
    """One directory's replication work: blob inventory + completion."""

    def __init__(
        self,
        fast_url: str,
        durable_url: str,
        blobs: Dict[str, int],
        metadata_path: Optional[str] = None,
        fresh: bool = True,
    ) -> None:
        self.fast_url = fast_url
        self.durable_url = durable_url
        self.blobs = dict(blobs)
        self.metadata_path = metadata_path
        # fresh: newly-written blobs (invalidate prior done flags) vs a
        # resumed job (the journal's done flags are the point).
        self.fresh = fresh
        self.created_ts = time.monotonic()
        self.done_evt = threading.Event()
        self.error: Optional[BaseException] = None
        self.cancelled = False
        # Per-job progress (this job only, unlike the Mirror's process
        # totals): feeds the job's SnapshotReport at completion.
        self.blobs_done = 0
        self.bytes_done = 0
        # Flight-recorder cursor, set by the worker at dequeue: the
        # job's span window for the per-job trace export.
        self.trace_mark = 0

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done_evt.wait(timeout)


class Mirror:
    """Durable-tier replication worker (one daemon thread + fresh event
    loop per job). Thread-safe: ``enqueue``/``resume``/``metrics`` may be
    called from any thread, including a storage plugin's ``close()`` on
    an async-take commit thread."""

    def __init__(self) -> None:
        self._queue: "queue.Queue[Optional[MirrorJob]]" = queue.Queue()
        self._jobs: List[MirrorJob] = []  # enqueue order, for wait/cancel
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        # Metrics (guarded by _lock).
        self._blobs_done = 0
        self._blobs_inflight = 0
        self._bytes_mirrored = 0
        self._snapshots_done = 0
        self._failures = 0

    # -- submission ------------------------------------------------------

    def enqueue(
        self,
        fast_url: str,
        durable_url: str,
        blobs: Dict[str, int],
        metadata_path: Optional[str] = None,
        fresh: bool = True,
    ) -> MirrorJob:
        """Queue one directory's blobs for replication; returns a handle
        whose ``wait()`` blocks until the job settles."""
        job = MirrorJob(fast_url, durable_url, blobs, metadata_path, fresh)
        with self._lock:
            if self._stopped:
                raise RuntimeError("Mirror is stopped")
            # Prune bookkeeping for settled jobs: successful ones carry
            # no information the durable tier doesn't (is_durable is the
            # truth), and failures for THIS url are superseded by the new
            # job. Keeps _jobs bounded to unsettled work + one standing
            # failure per other url over an arbitrarily long run.
            self._jobs = [
                j
                for j in self._jobs
                if not j.done_evt.is_set()
                or (j.error is not None and j.fast_url != fast_url)
            ]
            self._jobs.append(job)
            self._ensure_thread()
        self._queue.put(job)
        return job

    def resume(self, path_url: str) -> Optional[MirrorJob]:
        """Re-enqueue an interrupted mirror for one tiered snapshot path.

        Journal present and incomplete -> resume from it (completed blobs
        are skipped). No journal but a fast-tier commit marker -> rebuild
        the full inventory from the manifest and re-mirror. Already
        durable, or nothing committed on the fast tier -> None."""
        tiers = split_tiered_url(path_url)
        if tiers is None:
            raise ValueError(f"{path_url!r} is not a tiered URL")
        fast_url, durable_url = tiers
        plan = run_in_fresh_event_loop(_resume_plan(fast_url, durable_url))
        if plan is None:
            return None
        blobs, metadata_path = plan
        job = self.enqueue(
            fast_url, durable_url, blobs, metadata_path, fresh=False
        )
        # Journal/manifest resume count: how often this process picked up
        # interrupted mirrors — a restart-frequency signal on its own.
        telemetry.metrics().counter_inc(metric_names.MIRROR_RESUME_TOTAL)
        return job

    def cancel_path(self, fast_url: str) -> None:
        """Best-effort cancel of queued/running jobs for one fast root —
        the step is being GC'd and its fast blobs are about to vanish."""
        with self._lock:
            for job in self._jobs:
                if job.fast_url == fast_url and not job.done_evt.is_set():
                    job.cancelled = True

    # -- completion ------------------------------------------------------

    def jobs_for(self, fast_url: str) -> List[MirrorJob]:
        with self._lock:
            return [j for j in self._jobs if j.fast_url == fast_url]

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every enqueued job settles (True) or the timeout
        lapses (False). The preemption drain hook: called inside the
        eviction grace window, it pushes in-flight uploads out — and
        whatever doesn't fit the window is already journaled, so the
        restarted job resumes instead of re-uploading."""
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        with self._lock:
            jobs = list(self._jobs)
        for job in jobs:
            remaining = None
            if deadline is not None:
                remaining = max(0.0, deadline - time.monotonic())
            if not job.wait(remaining):
                return False
        return True

    def stop(self) -> None:
        """Stop the worker after the current job; queued jobs are
        abandoned (their journals make them resumable)."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            thread = self._thread
        self._queue.put(None)
        if thread is not None:
            thread.join(timeout=30)

    def metrics(self) -> Dict[str, float]:
        """Machine-readable mirror state: blob/byte progress plus the
        upload lag (age of the oldest unsettled job — how far durability
        trails the fast-tier commit)."""
        with self._lock:
            pending_jobs = [j for j in self._jobs if not j.done_evt.is_set()]
            blobs_pending = sum(
                len(j.blobs) for j in pending_jobs
            ) - self._blobs_inflight
            lag = 0.0
            if pending_jobs:
                lag = time.monotonic() - min(
                    j.created_ts for j in pending_jobs
                )
            out = {
                "blobs_pending": max(0, blobs_pending),
                "blobs_inflight": self._blobs_inflight,
                "blobs_done": self._blobs_done,
                "bytes_mirrored": self._bytes_mirrored,
                "snapshots_pending": len(pending_jobs),
                "snapshots_done": self._snapshots_done,
                "failures": self._failures,
                "upload_lag_s": round(lag, 3),
            }
        self._publish_gauges(out)
        return out

    @staticmethod
    def _publish_gauges(m: Dict[str, float]) -> None:
        """Mirror state -> registry gauges (queue depth / lag are the
        operator's 'is durability keeping up with the take cadence'
        signals). Called on every metrics() read and at job settle."""
        registry = telemetry.metrics()
        registry.gauge_set(
            metric_names.MIRROR_BLOBS_PENDING, m["blobs_pending"]
        )
        registry.gauge_set(
            metric_names.MIRROR_BLOBS_INFLIGHT, m["blobs_inflight"]
        )
        registry.gauge_set(
            metric_names.MIRROR_SNAPSHOTS_PENDING, m["snapshots_pending"]
        )
        registry.gauge_set(
            metric_names.MIRROR_UPLOAD_LAG_SECONDS, m["upload_lag_s"]
        )

    # -- worker ----------------------------------------------------------

    def _ensure_thread(self) -> None:
        # Caller holds _lock.
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._worker, name="tiered-mirror", daemon=True
            )
            self._thread.start()

    def _worker(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            began = time.monotonic()
            recorder = _trace_recorder()
            job.trace_mark = recorder.mark()
            job_span = recorder.begin(
                metric_names.SPAN_MIRROR_JOB,
                fast=job.fast_url,
                durable=job.durable_url,
                blobs=len(job.blobs),
            )
            try:
                if not job.cancelled:
                    run_in_fresh_event_loop(self._run_job(job))
                    with self._lock:
                        self._snapshots_done += 1
            except BaseException as e:  # noqa: BLE001 - surfaced via wait_durable
                job.error = e
                with self._lock:
                    self._failures += 1
                logger.error(
                    "mirror of %s -> %s failed (journal retained; a "
                    "restarted mirror resumes it): %r",
                    job.fast_url,
                    job.durable_url,
                    e,
                )
            finally:
                from ..scheduler import record_phase_timing

                recorder.end(job_span)
                elapsed = time.monotonic() - began
                record_phase_timing("mirroring", elapsed)
                # Telemetry settles BEFORE the done event: a waiter that
                # unblocks on wait_durable() must find the job's report
                # already in the event log.
                self._settle_telemetry(job, elapsed)
                job.done_evt.set()
                try:
                    # Gauge refresh AFTER the event: the queue-depth/lag
                    # gauges must not still count this settled job.
                    self.metrics()
                except Exception:  # noqa: BLE001 - telemetry is best-effort
                    pass
                self._queue.task_done()

    def _settle_telemetry(self, job: MirrorJob, elapsed: float) -> None:
        """Registry counters/gauges + the job's SnapshotReport (kind
        "mirror"): the per-job record of what replication actually cost,
        including the durability lag — how long the step's data existed
        only on the fast tier. Best-effort: telemetry never fails a job."""
        try:
            registry = telemetry.metrics()
            registry.counter_inc(metric_names.MIRROR_JOBS_DONE_TOTAL)
            if job.error is not None and not job.cancelled:
                # A GC-cancelled job is expected behavior (the step left
                # both tiers), not a failure an operator should alert on.
                registry.counter_inc(metric_names.MIRROR_JOBS_FAILED_TOTAL)
            registry.counter_inc(
                metric_names.MIRROR_BLOBS_DONE_TOTAL, job.blobs_done
            )
            registry.counter_inc(
                metric_names.MIRROR_BYTES_TOTAL, job.bytes_done
            )
            if job.cancelled:
                # No sink append for a cancelled job: the step is being
                # GC'd and the snapshot-adjacent sink would resurrect the
                # just-deleted fast step directory as an orphan (same
                # hazard _run_job guards its journal.save against).
                return
            report = telemetry.SnapshotReport(
                kind="mirror",
                path=f"tiered://{job.fast_url}|{job.durable_url}",
                unix_ts=time.time(),
                phases={"mirroring": round(elapsed, 3)},
                bytes_moved=job.bytes_done,
                blobs=job.blobs_done,
                mirror={
                    "lag_s": round(time.monotonic() - job.created_ts, 3),
                    "blobs_total": len(job.blobs),
                    "cancelled": job.cancelled,
                    "resumed": not job.fresh,
                },
                error=repr(job.error) if job.error is not None else None,
            )
            # Blocking-chain attribution over the job's span window
            # (telemetry/critpath.py): which segment — per-blob copies,
            # storage writes — actually gated the replication wall.
            try:
                from ..telemetry import critpath as _critpath
                from ..telemetry.trace import get_recorder as _rec

                report.critical_path = _critpath.critical_path_from_events(
                    _rec().events_since(job.trace_mark), "mirror"
                )
            except Exception:  # noqa: BLE001 - attribution is best-effort
                pass
            telemetry.emit_report(report, registry)
            # Run-ledger settle event: how long the step's bytes existed
            # only on the fast tier. The owned-root gate inside the post
            # keeps this rank-0-only (co-hosted non-leader ranks' mirrors
            # resolve to an un-owned ledger and never write).
            from ..telemetry import ledger as run_ledger

            run_ledger.post_mirror_settled(
                job.fast_url,
                lag_s=time.monotonic() - job.created_ts,
                nbytes=job.bytes_done,
                blobs=job.blobs_done,
                error=job.error,
            )
            # Per-job trace export: the mirror's span window (job span,
            # per-blob spans, retry instants) lands next to the fast
            # tier's take trace. The Mirror has no rank (plugins are
            # rank-agnostic), so the filename is pid-disambiguated —
            # co-hosted ranks sharing a fast root must not clobber each
            # other's mirror timelines; the merge assigns each file its
            # own pid regardless of the claimed rank.
            export_op_trace(
                f"mirror-pid{os.getpid()}", report.path, 0, job.trace_mark
            )
        except Exception as e:  # noqa: BLE001 - telemetry is best-effort
            logger.warning("mirror telemetry emission failed: %r", e)

    async def _run_job(self, job: MirrorJob) -> None:
        fast = url_to_storage_plugin(job.fast_url)
        durable = url_to_storage_plugin(job.durable_url)
        try:
            journal = await MirrorJournal.load(fast) or MirrorJournal()
            journal.register(
                job.blobs, metadata=job.metadata_path, fresh=job.fresh
            )
            if job.cancelled:
                # GC cancelled this job between dequeue and here: writing
                # the journal now would resurrect a just-deleted step dir.
                return
            await journal.save(fast)

            retry = CollectiveProgressRetryStrategy(
                progress_window_seconds=(
                    knobs.get_mirror_progress_window_seconds()
                ),
                scope="mirror",
            )
            slots = asyncio.Semaphore(knobs.get_mirror_io_concurrency())

            async def copy_one(path: str) -> int:
                async def op() -> int:
                    # Content-addressed chunks ship only when the
                    # durable tier doesn't already hold them: the chunk
                    # key IS the content AND embeds the byte length, so
                    # a ranged read of the LAST byte (one byte, no data
                    # transfer) is a full equality check — a truncated
                    # copy left by a crashed upload misses the probe and
                    # is re-shipped (overwritten whole), while dense
                    # retention mirrors one full step plus deltas
                    # instead of every retained step's bytes.
                    from ..cas import (
                        is_chunk_location,
                        key_of_location,
                        nbytes_of_key,
                    )

                    if is_chunk_location(path):
                        key = key_of_location(path)
                        want = nbytes_of_key(key) if key else None
                        held = False
                        if want:
                            probe = ReadIO(
                                path=path, byte_range=(want - 1, want)
                            )
                            try:
                                await durable.read(probe)
                                held = (
                                    memoryview(probe.buf).nbytes == 1
                                )
                            except (FileNotFoundError, OSError):
                                held = False
                        if held:
                            telemetry.metrics().counter_inc(
                                metric_names.MIRROR_CHUNKS_SKIPPED_TOTAL
                            )
                            return 0
                    read_io = ReadIO(path=path)
                    await fast.read(read_io)
                    nbytes = memoryview(read_io.buf).nbytes
                    await durable.write(WriteIO(path=path, buf=read_io.buf))
                    return nbytes

                async def guarded() -> int:
                    try:
                        return await op()
                    except FileNotFoundError:
                        # The fast blob vanished (eviction raced GC):
                        # definitive, never retried.
                        raise
                    except (OSError, asyncio.TimeoutError) as e:
                        raise _TransientMirrorError() from e

                async with slots:
                    if job.cancelled:
                        raise asyncio.CancelledError("mirror job cancelled")
                    with self._lock:
                        self._blobs_inflight += 1
                    try:
                        # Recorder-only span: blob uploads interleave as
                        # coroutines on one event-loop thread, where a
                        # thread-local jax annotation would mis-nest
                        # (utils/tracing.py module note). The plugin-level
                        # I/O spans underneath still reach both sinks.
                        with _trace_recorder().span(
                            metric_names.SPAN_MIRROR_BLOB, blob=path
                        ):
                            return await retry.run(
                                guarded,
                                retriable_exceptions=(_TransientMirrorError,),
                            )
                    finally:
                        with self._lock:
                            self._blobs_inflight -= 1

            async def copy_and_tag(path: str):
                return path, await copy_one(path)

            tasks = [
                asyncio.create_task(copy_and_tag(p)) for p in journal.pending()
            ]
            try:
                # Journal after EVERY completed blob: the crash-resume
                # granularity is one blob, and the journal is a tiny
                # fast-tier JSON — two local writes per mirrored blob.
                for fut in asyncio.as_completed(tasks):
                    path, nbytes = await fut
                    journal.done.add(path)
                    job.blobs_done += 1
                    job.bytes_done += nbytes
                    with self._lock:
                        self._blobs_done += 1
                        self._bytes_mirrored += nbytes
                    await journal.save(fast)
            except BaseException:
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
                # Persist whatever completed before the failure: the
                # in-flight completions since the last save are lost to
                # the journal only if this save also fails (then they
                # re-upload — safe). EXCEPT for a cancelled job — the
                # step is being GC'd and a save here would resurrect the
                # just-deleted journal (and its parent directory) as an
                # orphan on the fast tier.
                if not job.cancelled:
                    try:
                        await journal.save(fast)
                    except Exception:  # noqa: BLE001 - already failing
                        pass
                raise

            meta = journal.metadata
            if meta is not None and not journal.durable_committed:
                # Commit marker LAST: durable commit-after-data.
                nbytes = await copy_one(meta)
                journal.done.add(meta)
                journal.durable_committed = True
                job.blobs_done += 1
                job.bytes_done += nbytes
                with self._lock:
                    self._blobs_done += 1
                    self._bytes_mirrored += nbytes
                await journal.save(fast)
        finally:
            await fast.close()
            await durable.close()


async def _resume_plan(fast_url: str, durable_url: str):
    """``(blobs, metadata_path)`` still needing a mirror pass, or None.

    Journal-first; manifest-walk fallback when no journal survived (the
    kill landed between the fast commit and the first journal write)."""
    fast = url_to_storage_plugin(fast_url)
    durable = url_to_storage_plugin(durable_url)
    try:
        journal = await MirrorJournal.load(fast)
        if journal is not None:
            if journal.complete:
                return None
            return dict(journal.blobs), journal.metadata
        read_io = ReadIO(path=_METADATA_FNAME)
        try:
            await fast.read(read_io)
        except FileNotFoundError:
            return None  # never committed on the fast tier: nothing to do
        meta_bytes = bytes(read_io.buf)
        durable_probe = ReadIO(path=_METADATA_FNAME, byte_range=(0, 1))
        try:
            await durable.read(durable_probe)
            return None  # already durable-committed
        except (FileNotFoundError, OSError):
            pass
        from ..integrity import table_path
        from ..manifest import SnapshotMetadata

        metadata = SnapshotMetadata.from_yaml(meta_bytes.decode("utf-8"))
        blobs: Dict[str, int] = {}
        from ..manager import _entry_locations

        from ..cas import chunk_map_path, is_chunk_location

        for entry in metadata.manifest.values():
            for location in _entry_locations(entry):
                if not location:
                    continue
                # Parent-relative refs are another step's blobs; that
                # step mirrors (or mirrored) itself — EXCEPT chunk refs:
                # the chunk store belongs to every referencing step, and
                # the worker's existence probe skips whatever the
                # durable side already holds.
                if location.startswith("../") and not is_chunk_location(
                    location
                ):
                    continue
                blobs[location] = 0
        for rank in range(metadata.world_size):
            for control in (table_path(rank), chunk_map_path(rank)):
                probe = ReadIO(path=control, byte_range=(0, 1))
                try:
                    await fast.read(probe)
                except (FileNotFoundError, OSError):
                    continue
                blobs[control] = 0
        blobs[_METADATA_FNAME] = len(meta_bytes)
        return blobs, _METADATA_FNAME
    finally:
        await fast.close()
        await durable.close()


# ---------------------------------------------------------------------------
# Process-wide default mirror + durability barrier
# ---------------------------------------------------------------------------

_default_mirror: Optional[Mirror] = None
_default_mirror_lock = threading.Lock()


def get_mirror() -> Mirror:
    """The process-wide mirror every :class:`TieredStoragePlugin`
    enqueues to (plugin instances are created per operation; the upload
    backlog must outlive them all)."""
    global _default_mirror
    with _default_mirror_lock:
        if _default_mirror is None:
            _default_mirror = Mirror()
        return _default_mirror


def mirror_state_for_path(path_url: str) -> Optional[Dict[str, float]]:
    """The process mirror's queue/lag state when ``path_url`` is
    tiered, else None — the ONE tiered-path-detection + metrics-read
    used by snapshot reports, progress heartbeats, and the checkpoint
    doctor (three consumers, one implementation)."""
    try:
        if split_tiered_url(path_url) is None:
            return None
    except ValueError:
        return None
    return dict(get_mirror().metrics())


def reset_mirror() -> None:
    """Stop and discard the process-wide mirror (tests simulating a
    process restart)."""
    global _default_mirror
    with _default_mirror_lock:
        mirror, _default_mirror = _default_mirror, None
    if mirror is not None:
        mirror.stop()


async def is_durable_async(path_url: str) -> bool:
    """True when the durable tier holds the snapshot's commit marker
    (which, by mirror ordering, implies every data blob preceded it)."""
    tiers = split_tiered_url(path_url)
    if tiers is None:
        return True  # single-tier plugins are durable at commit
    _, durable_url = tiers
    durable = url_to_storage_plugin(durable_url)
    try:
        read_io = ReadIO(path=_METADATA_FNAME, byte_range=(0, 1))
        try:
            await durable.read(read_io)
        except (FileNotFoundError, OSError):
            return False
        return True
    finally:
        await durable.close()


def is_durable(path_url: str) -> bool:
    return run_in_fresh_event_loop(is_durable_async(path_url))


def wait_durable(
    path_url: str,
    timeout: Optional[float] = None,
    poll_interval: float = 0.05,
) -> None:
    """Block until the snapshot at ``path_url`` is durable-committed.

    Non-tiered URLs return immediately (their commit WAS the durable
    write). For tiered URLs: waits on the in-process mirror's jobs for
    the path (re-raising a failed job's error), resuming from the
    journal/manifest first if no job is in flight (the restarted-process
    case); then confirms the durable commit marker exists. Raises
    ``TimeoutError`` when the deadline lapses with durability not yet
    reached.

    ``timeout=None`` resolves to the
    ``TORCHSNAPSHOT_TPU_WAIT_DURABLE_TIMEOUT_SECONDS`` knob (default
    30 min) rather than waiting forever; a non-positive knob value
    opts back into the unbounded wait."""
    tiers = split_tiered_url(path_url)
    if tiers is None:
        return
    if timeout is None:
        default_timeout = knobs.get_wait_durable_timeout_seconds()
        timeout = default_timeout if default_timeout > 0 else None
    fast_url, _ = tiers
    deadline = time.monotonic() + timeout if timeout is not None else None
    mirror = get_mirror()
    if not mirror.jobs_for(fast_url) and not is_durable(path_url):
        if mirror.resume(path_url) is None:
            raise FileNotFoundError(
                f"{path_url!r} has no fast-tier commit marker and is not "
                f"durable: nothing to wait for"
            )
    while True:
        # Durability first: a stale failed job (since superseded by a
        # successful resume) must never poison the barrier once the
        # durable commit marker actually exists.
        if is_durable(path_url):
            return
        jobs = mirror.jobs_for(fast_url)
        unsettled = [j for j in jobs if not j.done_evt.is_set()]
        if unsettled:
            for job in unsettled:
                remaining = None
                if deadline is not None:
                    remaining = max(0.0, deadline - time.monotonic())
                if not job.wait(remaining):
                    raise TimeoutError(
                        f"snapshot {path_url!r} not durable within "
                        f"{timeout}s (mirror metrics: {mirror.metrics()})"
                    )
            continue  # re-probe durability
        # Everything settled yet not durable: the newest outcome is the
        # authoritative failure to surface.
        if jobs and jobs[-1].error is not None:
            raise RuntimeError(
                f"mirror of {path_url!r} failed; the fast tier remains "
                f"restorable and the journal resumes the upload"
            ) from jobs[-1].error
        if deadline is not None and time.monotonic() >= deadline:
            raise TimeoutError(
                f"snapshot {path_url!r} not durable within {timeout}s"
            )
        time.sleep(poll_interval)
