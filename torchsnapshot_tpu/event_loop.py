"""Tiny asyncio helpers.

The snapshot paths create a fresh event loop per operation (reference:
snapshot.py:206) so they work both in plain scripts and inside frameworks
that already run a loop on another thread.
"""

from __future__ import annotations

import asyncio
from typing import Any, Coroutine, TypeVar

T = TypeVar("T")


def run_in_fresh_event_loop(coro: Coroutine[Any, Any, T]) -> T:
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()
