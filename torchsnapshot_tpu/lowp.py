"""Low-precision (affine int8) codecs for float arrays.

Reference parity: the per-tensor / per-channel quantized-tensor codecs in
torchsnapshot/serialization.py:257-342 and :345-456. Torch has native
quantized tensor *types*; JAX does not — so here the codecs are an opt-in
storage transform for float arrays (f32/bf16/f16): encode to int8 with
affine (scale, zero_point) parameters, cutting checkpoint bytes 2-4x at
the cost of quantization error. Like the reference (which implements the
codecs but routes quantized tensors down the TORCH_SAVE path —
serialization.py:148-159), these are shipped as standalone codecs with
documented layouts; preparers use full-precision buffers by default.

Binary layouts (all little-endian, mirroring the reference's):

Per-tensor (reference serialization.py:257-342)::

    int8 storage (N bytes) ‖ scale (float64) ‖ zero_point (int64)

Per-channel (reference serialization.py:345-456)::

    axis (int64) ‖ int8 storage (N bytes)
    ‖ scales (float64 × C) ‖ zero_points (int64 × C)

where C = shape[axis]. Decode returns float32 (the dequantized values);
callers cast to the original dtype if desired.
"""

from __future__ import annotations

import struct
from typing import Sequence, Tuple

import numpy as np

_Q_DTYPE = np.int8
_QMIN, _QMAX = -128, 127

_FLOAT_DTYPE_NAMES = ("float16", "bfloat16", "float32", "float64")


def _check_float(arr: np.ndarray) -> np.ndarray:
    from .serialization import dtype_to_string

    name = dtype_to_string(arr.dtype)
    if name not in _FLOAT_DTYPE_NAMES:
        raise ValueError(
            f"low-precision codecs quantize float arrays; got dtype {name}"
        )
    return np.ascontiguousarray(arr, dtype=np.float32)


def _affine_params(x: np.ndarray) -> Tuple[float, int]:
    """(scale, zero_point) covering [min(x), max(x)] with 0 exactly
    representable (so sparse/zero-padded weights round-trip zeros)."""
    lo = float(np.min(x)) if x.size else 0.0
    hi = float(np.max(x)) if x.size else 0.0
    lo = min(lo, 0.0)
    hi = max(hi, 0.0)
    if hi == lo:
        return 1.0, 0
    scale = (hi - lo) / (_QMAX - _QMIN)
    zero_point = int(round(_QMIN - lo / scale))
    zero_point = max(_QMIN, min(_QMAX, zero_point))
    return scale, zero_point


def quantize_per_tensor(arr: np.ndarray) -> Tuple[np.ndarray, float, int]:
    x = _check_float(arr)
    scale, zp = _affine_params(x)
    q = np.clip(np.round(x / scale) + zp, _QMIN, _QMAX).astype(_Q_DTYPE)
    return q, scale, zp


def dequantize_per_tensor(
    q: np.ndarray, scale: float, zero_point: int
) -> np.ndarray:
    return (q.astype(np.float32) - np.float32(zero_point)) * np.float32(scale)


def encode_per_tensor(arr: np.ndarray) -> bytes:
    q, scale, zp = quantize_per_tensor(arr)
    return q.tobytes() + struct.pack("<dq", scale, zp)


def decode_per_tensor(
    buf: "bytes | memoryview", shape: Sequence[int]
) -> np.ndarray:
    mv = memoryview(buf).cast("B")
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    tail = struct.calcsize("<dq")
    if mv.nbytes != n + tail:
        raise ValueError(
            f"per-tensor q8 buffer has {mv.nbytes} bytes; shape "
            f"{tuple(shape)} needs {n} + {tail}"
        )
    scale, zp = struct.unpack("<dq", mv[n:])
    q = np.frombuffer(mv[:n], dtype=_Q_DTYPE).reshape(tuple(shape))
    return dequantize_per_tensor(q, scale, zp)


def quantize_per_channel(
    arr: np.ndarray, axis: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    x = _check_float(arr)
    if not -x.ndim <= axis < x.ndim:
        raise ValueError(f"axis {axis} out of range for rank {x.ndim}")
    axis %= x.ndim
    moved = np.moveaxis(x, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    if flat.size:
        lo = np.minimum(flat.min(axis=1), 0.0).astype(np.float64)
        hi = np.maximum(flat.max(axis=1), 0.0).astype(np.float64)
    else:
        lo = np.zeros(flat.shape[0], dtype=np.float64)
        hi = np.zeros(flat.shape[0], dtype=np.float64)
    degenerate = hi == lo
    scales = np.where(degenerate, 1.0, (hi - lo) / (_QMAX - _QMIN))
    zps = np.where(
        degenerate,
        0,
        np.clip(np.round(_QMIN - lo / scales), _QMIN, _QMAX),
    ).astype(np.int64)
    qflat = np.clip(
        np.round(flat / scales[:, None].astype(np.float32))
        + zps[:, None].astype(np.float32),
        _QMIN,
        _QMAX,
    ).astype(_Q_DTYPE)
    q = np.moveaxis(qflat.reshape(moved.shape), 0, axis)
    return q, scales, zps


def dequantize_per_channel(
    q: np.ndarray, scales: np.ndarray, zero_points: np.ndarray, axis: int
) -> np.ndarray:
    axis %= q.ndim
    bshape = [1] * q.ndim
    bshape[axis] = -1
    s = scales.astype(np.float32).reshape(bshape)
    z = zero_points.astype(np.float32).reshape(bshape)
    return (q.astype(np.float32) - z) * s


def encode_per_channel(arr: np.ndarray, axis: int) -> bytes:
    q, scales, zps = quantize_per_channel(arr, axis)
    axis %= arr.ndim
    return (
        struct.pack("<q", axis)
        + q.tobytes()
        + scales.astype("<f8").tobytes()
        + zps.astype("<i8").tobytes()
    )


def decode_per_channel(
    buf: "bytes | memoryview", shape: Sequence[int]
) -> np.ndarray:
    mv = memoryview(buf).cast("B")
    head = struct.calcsize("<q")
    if mv.nbytes < head:
        raise ValueError(
            f"per-channel q8 buffer has {mv.nbytes} bytes; too short for "
            f"the {head}-byte axis header"
        )
    (axis,) = struct.unpack("<q", mv[:head])
    shape = tuple(shape)
    if not 0 <= axis < len(shape):
        raise ValueError(f"encoded axis {axis} invalid for shape {shape}")
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    c = shape[axis]
    expected = head + n + c * (8 + 8)
    if mv.nbytes != expected:
        raise ValueError(
            f"per-channel q8 buffer has {mv.nbytes} bytes; shape {shape} "
            f"axis {axis} needs {expected}"
        )
    q = np.frombuffer(mv[head : head + n], dtype=_Q_DTYPE).reshape(shape)
    scales = np.frombuffer(mv[head + n : head + n + 8 * c], dtype="<f8")
    zps = np.frombuffer(mv[head + n + 8 * c :], dtype="<i8")
    return dequantize_per_channel(q, scales, zps, axis)
