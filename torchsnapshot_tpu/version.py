"""Version of the torchsnapshot_tpu package.

Reference parity: torchsnapshot/version.py:17 (``__version__ = "0.0.3"``).
"""

__version__ = "0.1.0"
