"""torchsnapshot_tpu: a TPU-native checkpointing framework.

A from-scratch JAX/XLA re-design of the capabilities of TorchSnapshot
(see SURVEY.md at the repo root): performant, memory-bounded, distributed
checkpointing of arbitrary pytree application state, with zero-copy
serialization, async device→host staging overlapped with storage I/O,
write-load partitioning of replicated state, shard-level persistence of
``NamedSharding``-partitioned ``jax.Array`` s with elastic resharding on
restore, atomic commit, and pluggable storage backends.
"""

from . import telemetry
from .fsck import FsckReport, verify_snapshot
from .knobs import (
    enable_batching,
    override_max_chunk_size_bytes,
    override_max_shard_size_bytes,
    override_per_rank_memory_budget_bytes,
    override_slab_size_threshold_bytes,
)
from .manager import CheckpointManager
from .preemption import PreemptionSaver
from .rng_state import RngState, RNGState
from .snapshot import PendingRestore, PendingSnapshot, Snapshot
from .state_dict import PyTreeState, StateDict
from .stateful import AppState, Stateful
from .telemetry import MetricsRegistry, SnapshotReport
from .tiered import Mirror, TieredStoragePlugin
from .version import __version__

__all__ = [
    "AppState",
    "CheckpointManager",
    "FsckReport",
    "MetricsRegistry",
    "Mirror",
    "SnapshotReport",
    "telemetry",
    "TieredStoragePlugin",
    "PendingRestore",
    "PendingSnapshot",
    "PreemptionSaver",
    "verify_snapshot",
    "PyTreeState",
    "Snapshot",
    "RngState",
    "RNGState",
    "StateDict",
    "Stateful",
    "__version__",
    "enable_batching",
    "override_max_chunk_size_bytes",
    "override_max_shard_size_bytes",
    "override_per_rank_memory_budget_bytes",
    "override_slab_size_threshold_bytes",
]
