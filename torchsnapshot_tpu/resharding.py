"""Shared N-d resharding geometry: one home for the shard-overlap algebra.

Every reader of sharded state — the native restore path
(``sharded_io_preparer.py``), the reference-format compat bridge
(``tricks/torchsnapshot_reader.py``), and the fan-out restore
distributor (``fanout.py``) — reasons about the same two geometric
questions:

- which persisted shard *boxes* overlap which destination boxes
  (``Box`` / ``box_overlap``, re-exported from ``parallel/overlap.py``),
- and which contiguous **byte windows** of a persisted blob a set of
  overlaps actually needs (row-slab planning), so a ranged read can
  fetch only those bytes instead of the whole shard.

Keeping the byte-window math here (and nowhere else) is what lets the
bridge and the native path share one definition of "row slab": a fix to
slab detection applies to both. The planners are pure geometry — no
I/O types, no dtype strings — so both data models (manifest entries vs
reference YAML dicts) map onto them.

Row-major invariant: rows ``[row_lo, row_hi)`` of an N-d shard stored
with the buffer-protocol serializer are one contiguous byte range
(``row_nbytes`` = itemsize x product of trailing dims). Overlaps that
slice *trailing* dims still ride a row-banded read — the band's bytes
contain the needed columns, and the consumer slices them out — which is
what keeps read amplification near 1.0 for partial destinations instead
of falling back to whole-shard reads.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .parallel.overlap import Box, Overlap, box_overlap, subdivide_box

__all__ = [
    "Box",
    "Overlap",
    "box_overlap",
    "subdivide_box",
    "RowSlabCopy",
    "RowSlabRead",
    "plan_row_slab_reads",
    "row_slab_byte_window",
    "target_boxes_for_sharding",
    "assign_shard_owners",
]


def target_boxes_for_sharding(
    sharding: Any, shape: Sequence[int]
) -> Dict[Box, List[Any]]:
    """Destination boxes of an arbitrary jax ``Sharding`` over ``shape``:
    each locally-addressable device's index window as a :class:`Box`,
    grouped so replicated / partially-replicated layouts assemble each
    distinct box once and place it on every device sharing it."""
    groups: Dict[Box, List[Any]] = {}
    for device, index in sharding.addressable_devices_indices_map(
        tuple(int(d) for d in shape)
    ).items():
        groups.setdefault(Box.from_index(index, shape), []).append(device)
    return groups


@dataclass(frozen=True)
class RowSlabCopy:
    """One copy out of a row-slab read buffer into a destination view.

    ``overlap_index`` names which input overlap this copy feeds;
    ``dst_rows`` slices dim 0 of that overlap's destination view (the
    rows of the overlap this slab covers); ``src_slices`` index the read
    buffer (shape ``(rows,) + shard_trailing_dims``)."""

    overlap_index: int
    dst_rows: slice
    src_slices: Tuple[slice, ...]


@dataclass(frozen=True)
class RowSlabRead:
    """One ranged read of rows ``[rows[0], rows[1])`` of a saved shard:
    the absolute ``byte_range`` within its blob, the buffer shape the
    bytes deserialize into, and every overlap copy it feeds."""

    rows: Tuple[int, int]
    byte_range: Tuple[int, int]
    buf_shape: Tuple[int, ...]
    copies: Tuple[RowSlabCopy, ...]


def plan_row_slab_reads(
    shard_sizes: Sequence[int],
    overlaps: Sequence[Overlap],
    row_nbytes: int,
    base: int = 0,
    buffer_limit_bytes: Optional[int] = None,
) -> Optional[List[RowSlabRead]]:
    """Plan ranged row-band reads of one saved shard feeding ``overlaps``.

    The band is the smallest row range ``[row_lo, row_hi)`` covering
    every overlap; under ``buffer_limit_bytes`` it splits into multiple
    reads so host memory stays bounded. Returns ``None`` when a single
    whole-shard read is already optimal (the band spans every row and
    fits the limit) or when the shard is 0-d — the caller then issues
    its ordinary whole-blob read.

    Only valid for raw row-major (buffer-protocol) payloads; callers
    must check the serializer before ranging."""
    sizes = tuple(int(s) for s in shard_sizes)
    if not sizes or not overlaps:
        return None
    row_lo = min(ov.src_slices[0].start for ov in overlaps)
    row_hi = max(ov.src_slices[0].stop for ov in overlaps)
    total = (row_hi - row_lo) * row_nbytes
    rows_per_read = row_hi - row_lo
    if buffer_limit_bytes is not None and total > buffer_limit_bytes:
        rows_per_read = max(1, buffer_limit_bytes // max(1, row_nbytes))
    if row_lo == 0 and row_hi == sizes[0] and rows_per_read >= row_hi - row_lo:
        return None
    reads: List[RowSlabRead] = []
    for p0 in range(row_lo, row_hi, rows_per_read):
        p1 = min(p0 + rows_per_read, row_hi)
        copies: List[RowSlabCopy] = []
        for idx, ov in enumerate(overlaps):
            a, b = ov.src_slices[0].start, ov.src_slices[0].stop
            m0, m1 = max(a, p0), min(b, p1)
            if m1 <= m0:
                continue
            copies.append(
                RowSlabCopy(
                    overlap_index=idx,
                    dst_rows=slice(m0 - a, m1 - a),
                    src_slices=(slice(m0 - p0, m1 - p0),) + ov.src_slices[1:],
                )
            )
        reads.append(
            RowSlabRead(
                rows=(p0, p1),
                byte_range=(base + p0 * row_nbytes, base + p1 * row_nbytes),
                buf_shape=(p1 - p0,) + sizes[1:],
                copies=tuple(copies),
            )
        )
    return reads


def row_slab_byte_window(
    shard_sizes: Sequence[int],
    overlap: Overlap,
    row_nbytes: int,
    base: int = 0,
) -> Optional[Tuple[int, int]]:
    """The absolute byte window of ONE overlap's rows, when (and only
    when) the overlap spans the full extent of every trailing dim — the
    strict "row slab" the compat bridge ranges on (its per-piece loads
    cannot column-slice a partial band the way the native consumer
    does). ``None`` for 0-d shards or trailing-sliced overlaps."""
    sizes = tuple(int(s) for s in shard_sizes)
    if not sizes:
        return None
    for d in range(1, len(sizes)):
        s = overlap.src_slices[d]
        if s.start != 0 or s.stop != sizes[d]:
            return None
    r = overlap.src_slices[0]
    return (base + r.start * row_nbytes, base + r.stop * row_nbytes)


def assign_shard_owners(
    locations: Iterable[str], world_size: int
) -> Dict[str, int]:
    """Deterministic owner rank per unique saved-shard blob: stable
    content hash (CRC32 of the location — ``hash()`` is randomized per
    process) round-robined over sorted locations so the load balances
    even for tiny shard sets. Every rank computing this over the same
    manifest gets the same table; the fan-out path still has rank 0
    decide and broadcast so a manifest-read race can never skew it."""
    world = max(1, int(world_size))
    locs = sorted(set(locations))
    if not locs:
        return {}
    start = zlib.crc32("\n".join(locs).encode("utf-8")) % world
    return {loc: (start + i) % world for i, loc in enumerate(locs)}
