"""Causal multi-head attention, written for XLA fusion on TPU.

Everything here is shape-static and expressed as large einsums so XLA tiles
the contractions onto the MXU; the mask/softmax elementwise chain fuses into
the surrounding matmuls. No data-dependent control flow.

The reference framework (torchsnapshot) carries no model code — this op
exists for the flagship benchmark/graft model that exercises the
checkpointer on realistically-sharded training state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
) -> jax.Array:
    """Causal scaled-dot-product attention.

    Args:
        q, k, v: ``(batch, seq, n_heads, head_dim)``.

    Returns:
        ``(batch, seq, n_heads, head_dim)``.

    The softmax is computed in float32 regardless of input dtype (bf16
    accumulation loses too much for attention logits) and cast back.
    """
    head_dim = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, dtype=jnp.float32))
    # (b, h, s_q, s_k) logits on the MXU.
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    logits = logits * scale
    s = logits.shape[-1]
    causal = jnp.tril(jnp.ones((s, s), dtype=jnp.bool_))
    logits = jnp.where(causal, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
