"""On-device content digests for change detection.

The incremental checkpointer (incremental.py) must answer "did this
chunk's bytes change since the base snapshot?" *without* moving the chunk
to the host — on a TPU the device→host link is exactly the resource a
checkpoint skip is trying to save. So the digest is computed **on
device** by a jitted reduction and only the 8-byte result crosses the
link.

There is no counterpart in the reference (its integrity story is
host-side only); the closest analog is the content-addressing some
checkpoint stores do after staging, which pays the full D2H first.

Digest: a 64-bit multilinear hash over the array's bytes viewed as a
vector of unsigned *lanes* (uint32 when the itemsize is a multiple of 4,
else uint16/uint8), with position-dependent weights derived from a
splitmix32-style mixer:

    w(i, seed) = mix32(i * GOLDEN + seed)
    d_seed     = mix32( (Σ_i lane_i · w(i, seed)) mod 2^32  ^  nbytes )
    digest     = "mlh64:" + hex(d_SEED1 ‖ d_SEED2)

Two independent 32-bit accumulators give a 64-bit digest; the chance a
*changed* chunk collides is ~2^-64 per comparison — far below memory
soft-error rates. (The hash is content-addressing for change detection,
not an adversarial MAC; CRC-based integrity verification on restore is a
separate subsystem, integrity.py.)

The numpy implementation is bit-identical to the jitted one (pinned by
tests/test_device_digest.py across every supported dtype), so a leaf may
move between host and device across steps without spurious rewrites.
All math is uint32 with wraparound, vectorizable on the TPU's VPU; XLA
fuses iota → mix → multiply → reduce without materializing the weights.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import numpy as np

_GOLDEN = np.uint32(0x9E3779B9)
_SEED1 = np.uint32(0x243F6A88)  # pi fractional bits
_SEED2 = np.uint32(0xB7E15162)  # e fractional bits

# numpy block size (lanes) for the host implementation: bounds the weight
# array materialization to ~16 MiB while keeping per-block overhead noise.
_HOST_BLOCK_LANES = 1 << 22

DIGEST_PREFIX = "mlh64:"


# ---------------------------------------------------------------------------
# dtype support / lane views
# ---------------------------------------------------------------------------


# Sub-byte dtypes report itemsize 1 through np.dtype but cannot be
# bitcast to uint8 lanes on device. One list, shared with
# ops/device_pack.py so pack and digest can never disagree on
# device-eligibility.
SUB_BYTE_DTYPE_NAMES: Tuple[str, ...] = (
    "int4",
    "uint4",
    "int2",
    "uint2",
    "float4_e2m1fn",
)


def bitcastable_dtype(dtype: Any) -> bool:
    """True when the dtype's memory image has a uint8-lane view usable on
    device: fixed-width, byte-aligned, non-complex. Complex dtypes are
    excluded (device bitcast of interleaved re/im pairs is not uniformly
    available); sub-byte dtypes because their lane view is
    framework-specific."""
    try:
        dt = np.dtype(dtype)
    except TypeError:
        # jax-only dtypes (bfloat16, fp8) reach here as ml_dtypes dtypes,
        # which np.dtype understands; anything else is unsupported.
        return False
    if dt.kind == "c" or dt.hasobject:
        return False
    return dt.name not in SUB_BYTE_DTYPE_NAMES


def digest_supported(dtype: Any) -> bool:
    """Digestable = bitcastable with a power-of-two lane-splittable
    itemsize."""
    if not bitcastable_dtype(dtype):
        return False
    return np.dtype(dtype).itemsize in (1, 2, 4, 8)


def _lane_dtype(itemsize: int) -> np.dtype:
    if itemsize % 4 == 0:
        return np.dtype(np.uint32)
    if itemsize == 2:
        return np.dtype(np.uint16)
    return np.dtype(np.uint8)


# ---------------------------------------------------------------------------
# numpy implementation
# ---------------------------------------------------------------------------


def _mix32_np(x: np.ndarray) -> np.ndarray:
    """splitmix32-style finalizer; input/output uint32 arrays."""
    x = x.astype(np.uint32, copy=True)
    x ^= x >> np.uint32(16)
    x *= np.uint32(0x7FEB352D)
    x ^= x >> np.uint32(15)
    x *= np.uint32(0x846CA68B)
    x ^= x >> np.uint32(16)
    return x


def digest_host(arr: np.ndarray) -> Tuple[int, int]:
    """Digest of a host array's memory image. Blockwise so the weight
    arrays stay small; block sums are exact because uint32 addition is
    associative under wraparound."""
    arr = np.ascontiguousarray(arr)
    if not digest_supported(arr.dtype):
        raise TypeError(f"digest does not support dtype {arr.dtype}")
    nbytes = arr.nbytes & 0xFFFFFFFF
    lanes = arr.reshape(-1).view(_lane_dtype(arr.dtype.itemsize))
    # Accumulators are plain ints masked to 32 bits: numpy *scalar* uint32
    # arithmetic warns on overflow even though array ops wrap silently.
    acc1 = 0
    acc2 = 0
    for start in range(0, lanes.size, _HOST_BLOCK_LANES):
        block = lanes[start : start + _HOST_BLOCK_LANES].astype(
            np.uint32, copy=False
        )
        idx = np.arange(
            start, start + block.size, dtype=np.uint64
        ).astype(np.uint32)
        base = idx * _GOLDEN
        w1 = _mix32_np(base + _SEED1)
        w2 = _mix32_np(base + _SEED2)
        # Array sums wrap in uint32, matching the device reduction.
        acc1 = (acc1 + int(np.sum(block * w1, dtype=np.uint32))) & 0xFFFFFFFF
        acc2 = (acc2 + int(np.sum(block * w2, dtype=np.uint32))) & 0xFFFFFFFF
    d1 = int(_mix32_np(np.asarray(acc1 ^ nbytes, dtype=np.uint32))[()])
    d2 = int(_mix32_np(np.asarray(acc2 ^ nbytes, dtype=np.uint32))[()])
    return d1, d2


# ---------------------------------------------------------------------------
# jax implementation
# ---------------------------------------------------------------------------


def _mix32_jnp(x):
    import jax.numpy as jnp

    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def _lanes_jnp(x):
    """Reinterpret a device array's memory image as a flat lane vector,
    mirroring the numpy ``.view`` in :func:`digest_host` (both platforms
    are little-endian; serialization.py guards the host side)."""
    import jax.numpy as jnp
    from jax import lax

    if x.dtype == jnp.bool_:
        # bool memory is one 0/1 byte per element; astype equals the view.
        return x.reshape(-1).astype(jnp.uint8)
    itemsize = np.dtype(x.dtype).itemsize
    lane = _lane_dtype(itemsize)
    if itemsize == lane.itemsize:
        if x.dtype != jnp.dtype(lane):
            x = lax.bitcast_convert_type(x, jnp.dtype(lane))
        return x.reshape(-1)
    # Wider element than lane: bitcast appends a minor dim of
    # itemsize/lane.itemsize lanes, minor-to-major == memory order.
    return lax.bitcast_convert_type(x, jnp.dtype(lane)).reshape(-1)


def _digest_jax_impl(x):
    import jax.numpy as jnp

    lanes = _lanes_jnp(x).astype(jnp.uint32)
    nbytes = jnp.uint32((x.size * np.dtype(x.dtype).itemsize) & 0xFFFFFFFF)
    idx = jnp.arange(lanes.size, dtype=jnp.uint32)
    base = idx * _GOLDEN
    acc1 = jnp.sum(lanes * _mix32_jnp(base + _SEED1), dtype=jnp.uint32)
    acc2 = jnp.sum(lanes * _mix32_jnp(base + _SEED2), dtype=jnp.uint32)
    return jnp.stack(
        [_mix32_jnp(acc1 ^ nbytes), _mix32_jnp(acc2 ^ nbytes)]
    )


@functools.lru_cache(maxsize=1)
def _digest_jit():
    import jax

    # jit caches per (shape, dtype) signature; one wrapper suffices.
    return jax.jit(_digest_jax_impl)


def digest_device_async(arr: Any, row_range: Optional[Tuple[int, int]] = None):
    """Launch the digest of a device array (or a dim-0 row range of it) on
    its own device; returns a ``jax.Array`` of shape (2,) uint32 — a
    future under JAX's async dispatch. Call :func:`materialize` (or
    ``np.asarray``) to block."""
    if row_range is not None:
        start, stop = row_range
        arr = arr[start:stop]
    return _digest_jit()(arr)


def materialize(digest_future: Any) -> Tuple[int, int]:
    host = np.asarray(digest_future)
    return int(host[0]), int(host[1])


# ---------------------------------------------------------------------------
# batched digests: one dispatch for many arrays/chunks
# ---------------------------------------------------------------------------

# (row_ranges or None) per array; None = digest the whole array.
RangeSpec = Optional[Tuple[Tuple[int, int], ...]]


@functools.lru_cache(maxsize=256)
def _digest_many_jit(n_arrays: int, range_specs: Tuple[RangeSpec, ...]):
    """Compiled program digesting every (array, row-range) pair in one
    dispatch. Per-dispatch latency is what dominates digest cost on real
    accelerators (a checkpoint's worth of chunks is hundreds of tiny
    reductions); fusing them into one XLA program pays one dispatch + one
    (n, 2) transfer per device group instead of one round-trip per chunk.
    jit retraces per input shapes/dtypes, so one cache entry per chunk
    *layout* serves every step of a training run."""
    import jax
    import jax.numpy as jnp

    def f(arrays):
        outs = []
        for x, ranges in zip(arrays, range_specs):
            if ranges is None:
                outs.append(_digest_jax_impl(x))
            else:
                for a, b in ranges:
                    outs.append(_digest_jax_impl(x[a:b]))
        return jnp.stack(outs)

    return jax.jit(f)


def digest_many_async(specs: list):
    """Digest many device arrays (each whole, or per row-range) in ONE
    dispatch. ``specs`` is ``[(arr, row_ranges|None), ...]``; all arrays
    should live on the same device (group by device set — the caller's
    job). Returns a future of shape ``(total_chunks, 2)`` uint32, rows in
    spec order (ranges expanded in order)."""
    arrays = [arr for arr, _ in specs]
    range_specs = tuple(
        tuple(r) if r is not None else None for _, r in specs
    )
    fn = _digest_many_jit(len(arrays), range_specs)
    return fn(arrays)


def materialize_many(digest_future: Any) -> np.ndarray:
    """Block on a :func:`digest_many_async` future; returns (n, 2) uint32."""
    return np.asarray(digest_future)


# ---------------------------------------------------------------------------
# string form (what manifests carry)
# ---------------------------------------------------------------------------


def format_digest(d: Tuple[int, int]) -> str:
    return f"{DIGEST_PREFIX}{d[0]:08x}{d[1]:08x}"


def digest_host_str(arr: np.ndarray) -> str:
    return format_digest(digest_host(arr))
