"""On-device slab packing: many small arrays → one uint8 buffer → one D2H.

The TPU answer to the reference's ``GPUBatchedBufferStager``
(batcher.py:102-160), which packs small GPU tensors into one GPU byte
buffer so a slab costs a single device→host copy. Here the pack is a
fused jitted program — each member is bitcast to its uint8 memory image
and concatenated — so a slab of N small arrays costs one dispatch and
one transfer instead of N, which is the win wherever per-transfer
latency dominates (torchrec-style states with 10⁴–10⁵ small leaves; any
link where D2H round-trips are expensive).

Bit-exactness: the packed bytes must equal what
``serialization.array_as_memoryview`` produces for each member
(little-endian memory image). ``lax.bitcast_convert_type`` to uint8
appends a minor dim of ``itemsize`` in memory order, and bool's storage
is one 0/1 byte per element, so ``astype(uint8)`` equals its view.
Pinned by tests/test_device_pack.py for every supported dtype.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Tuple


def pack_supported(dtype: Any) -> bool:
    """Packable = has a uint8-lane device view (the same eligibility rule
    the digest module uses, shared so the two can never drift)."""
    from .device_digest import bitcastable_dtype

    return bitcastable_dtype(dtype)


def _as_u8_flat(x):
    import jax.numpy as jnp
    from jax import lax

    if x.dtype == jnp.bool_:
        return x.reshape(-1).astype(jnp.uint8)
    if x.dtype == jnp.uint8:
        return x.reshape(-1)
    # bitcast appends a minor dim of itemsize uint8 lanes (memory order).
    return lax.bitcast_convert_type(x, jnp.uint8).reshape(-1)


# Layout element: (row_slice or None). Shapes/dtypes are carried by the
# traced inputs; jit retraces per input signature automatically.
_RowSlice = Optional[Tuple[int, int]]


@functools.lru_cache(maxsize=256)
def _pack_jit(n_arrays: int, row_slices: Tuple[_RowSlice, ...]):
    import jax
    import jax.numpy as jnp

    def f(arrays):
        parts = []
        for x, slc in zip(arrays, row_slices):
            if slc is not None:
                x = x[slc[0] : slc[1]]
            parts.append(_as_u8_flat(x))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    return jax.jit(f)


def device_group_key(arr: Any) -> Tuple[int, ...]:
    """Grouping key for 'these arrays can ride one fused device program':
    the sorted device-id set (uncommitted/odd arrays collapse to a
    default-group sentinel). Shared by the slab packer and the
    incremental digest batcher so their grouping can never drift."""
    try:
        return tuple(sorted(d.id for d in arr.devices()))
    except Exception:  # noqa: BLE001 - uncommitted/odd arrays
        return (-1,)


def pack_async(specs: List[Tuple[Any, _RowSlice]]):
    """Launch the device pack of ``[(arr, row_slice|None), ...]`` (all on
    one device group); returns a flat uint8 device array future whose
    bytes are the members' memory images concatenated in order."""
    arrays = [a for a, _ in specs]
    slices = tuple(s for _, s in specs)
    return _pack_jit(len(arrays), slices)(arrays)
