from .attention import causal_attention
from .flash_attention import flash_causal_attention
from .ring_attention import ring_causal_attention

__all__ = [
    "causal_attention",
    "flash_causal_attention",
    "ring_causal_attention",
]
