from .attention import causal_attention
from .ring_attention import ring_causal_attention

__all__ = ["causal_attention", "ring_causal_attention"]
