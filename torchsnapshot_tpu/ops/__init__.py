from .attention import causal_attention

__all__ = ["causal_attention"]
