"""Fused blockwise causal attention (flash attention forward) in Pallas.

The einsum formulation in ``attention.py`` materializes the full
``(b, h, s, s)`` logits — O(s²) HBM traffic that XLA cannot fuse away.
This kernel streams K/V through VMEM one ``(block_k, d)`` tile per grid
step with an online softmax, so VMEM residency is O(block·d) regardless
of sequence length and the two matmuls per tile run back-to-back on the
MXU: the standard memory-bound → compute-bound transformation for long
sequences (the hot op under the ring attention in ops/ring_attention.py,
whose per-step local attention this can replace on real TPUs).

Structure: grid ``(batch·heads, q_blocks, k_blocks)``; the innermost
k dimension iterates sequentially on one core, carrying the running
max / normalizer / accumulator in VMEM scratch (pallas_guide.md's
accumulator-across-minor-grid-dim pattern); tiles beyond the causal
frontier are skipped with ``pl.when``. The output block is written once,
at the last k step.

Numerics: logits/softmax in float32 regardless of input dtype; masked
positions use a large-negative constant instead of -inf so fully-masked
rows never produce NaN through the running-max rescale (at k-block 0
every causal row has its diagonal element, and for later blocks the
running max is already finite).

Tests run the kernel in interpreter mode (``interpret=True``) against
the dense einsum op — the CPU-safe way to validate Pallas kernels
(pallas_guide.md: interpret flag); the same kernel compiles natively on
TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30
# Running max/normalizer live in lanes-identical (block_q, _LANES) VMEM
# tiles: Mosaic wants vector scratch shaped to full (sublane, lane) tiles,
# so the per-row scalars are replicated across the 128-lane minor dim and
# recovered with keepdims reductions (any lanewise reduction of identical
# lanes is the identity).
_LANES = 128


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    m_ref,
    l_ref,
    acc_ref,
    *,
    block_q: int,
    block_k: int,
    n_k: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    d = q_ref.shape[-1]

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full((block_q, _LANES), _NEG_BIG, jnp.float32)
        l_ref[:] = jnp.zeros((block_q, _LANES), jnp.float32)
        acc_ref[:] = jnp.zeros((block_q, d), jnp.float32)

    # Tiles fully beyond the causal frontier contribute nothing.
    @pl.when(ki * block_k <= qi * block_q + block_q - 1)
    def _update():
        scale = 1.0 / (d**0.5)
        q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
        kb = k_ref[0].astype(jnp.float32)  # (block_k, d)
        vb = v_ref[0].astype(jnp.float32)
        logits = lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        q_pos = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        logits = jnp.where(q_pos >= k_pos, logits, _NEG_BIG)

        m_prev = m_ref[:]  # (block_q, _LANES), lanes identical
        row_max = logits.max(axis=-1, keepdims=True)  # (block_q, 1)
        m_next = jnp.maximum(m_prev, row_max)  # lanes stay identical
        m1 = m_next.max(axis=-1, keepdims=True)  # (block_q, 1)
        p = jnp.exp(logits - m1)
        alpha = jnp.exp(m_prev - m_next)  # (block_q, _LANES), lanes identical
        alpha1 = alpha.max(axis=-1, keepdims=True)  # (block_q, 1)
        m_ref[:] = m_next
        l_ref[:] = l_ref[:] * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha1 + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == n_k - 1)
    def _finalize():
        l1 = l_ref[:].max(axis=-1, keepdims=True)  # (block_q, 1)
        o_ref[0] = (acc_ref[:] / l1).astype(o_ref.dtype)


def flash_causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in for :func:`~torchsnapshot_tpu.ops.causal_attention` on
    shapes where ``seq`` divides by the block sizes.

    Args:
        q, k, v: ``(batch, seq, n_heads, head_dim)``.
        block_q, block_k: VMEM tile sizes (128 aligns with the MXU).
        interpret: run in the Pallas interpreter (CPU-safe; tests).
    """
    b, s, h, d = q.shape
    if s % block_q or s % block_k:
        raise ValueError(
            f"seq {s} must be a multiple of block_q={block_q} and "
            f"block_k={block_k}"
        )
    n_k = s // block_k
    # (b*h, s, d): one grid row per batch-head.
    to_rows = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    qf, kf, vf = to_rows(q), to_rows(k), to_rows(v)

    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, block_q=block_q, block_k=block_k, n_k=n_k
        ),
        grid=(b * h, s // block_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
