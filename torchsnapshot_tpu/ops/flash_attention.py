"""Fused blockwise causal attention (flash attention forward) in Pallas.

The einsum formulation in ``attention.py`` materializes the full
``(b, h, s, s)`` logits — O(s²) HBM traffic that XLA cannot fuse away.
This kernel streams K/V through VMEM one ``(block_k, d)`` tile per grid
step with an online softmax, so VMEM residency is O(block·d) regardless
of sequence length and the two matmuls per tile run back-to-back on the
MXU: the standard memory-bound → compute-bound transformation for long
sequences (the hot op under the ring attention in ops/ring_attention.py,
whose per-step local attention this can replace on real TPUs).

Structure: grid ``(batch·heads, q_blocks, k_blocks)``; the innermost
k dimension iterates sequentially on one core, carrying the running
max / normalizer / accumulator in VMEM scratch (pallas_guide.md's
accumulator-across-minor-grid-dim pattern); tiles beyond the causal
frontier are skipped with ``pl.when``. The output block is written once,
at the last k step.

Numerics: logits/softmax in float32 regardless of input dtype; masked
positions use a large-negative constant instead of -inf so fully-masked
rows never produce NaN through the running-max rescale (at k-block 0
every causal row has its diagonal element, and for later blocks the
running max is already finite).

Tests run the kernel in interpreter mode (``interpret=True``) against
the dense einsum op — the CPU-safe way to validate Pallas kernels
(pallas_guide.md: interpret flag); the same kernel compiles natively on
TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30
# Running max/normalizer live in lanes-identical (block_q, _LANES) VMEM
# tiles: Mosaic wants vector scratch shaped to full (sublane, lane) tiles,
# so the per-row scalars are replicated across the 128-lane minor dim and
# recovered with keepdims reductions (any lanewise reduction of identical
# lanes is the identity).
_LANES = 128


def _init_stats(m_ref, l_ref, acc_ref, block_q: int, d: int) -> None:
    m_ref[:] = jnp.full((block_q, _LANES), _NEG_BIG, jnp.float32)
    l_ref[:] = jnp.zeros((block_q, _LANES), jnp.float32)
    acc_ref[:] = jnp.zeros((block_q, d), jnp.float32)


def _online_softmax_update(
    q_ref,
    k_ref,
    v_ref,
    m_ref,
    l_ref,
    acc_ref,
    *,
    qi,
    ki,
    block_q: int,
    block_k: int,
    causal: bool,
) -> None:
    """The ONE shared online-softmax tile update both kernels run: logits
    for this K/V tile, (masked) running max/normalizer rescale, MXU
    accumulate. Any numerics change here reaches the standalone causal
    kernel and the ring-merge chunk kernel alike."""
    d = q_ref.shape[-1]
    scale = 1.0 / (d**0.5)
    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
    kb = k_ref[0].astype(jnp.float32)  # (block_k, d)
    vb = v_ref[0].astype(jnp.float32)
    logits = lax.dot_general(
        q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if causal:
        q_pos = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        logits = jnp.where(q_pos >= k_pos, logits, _NEG_BIG)

    m_prev = m_ref[:]  # (block_q, _LANES), lanes identical
    row_max = logits.max(axis=-1, keepdims=True)  # (block_q, 1)
    m_next = jnp.maximum(m_prev, row_max)  # lanes stay identical
    m1 = m_next.max(axis=-1, keepdims=True)  # (block_q, 1)
    p = jnp.exp(logits - m1)
    alpha = jnp.exp(m_prev - m_next)  # (block_q, _LANES), lanes identical
    alpha1 = alpha.max(axis=-1, keepdims=True)  # (block_q, 1)
    m_ref[:] = m_next
    l_ref[:] = l_ref[:] * alpha + p.sum(axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha1 + lax.dot_general(
        p, vb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    m_ref,
    l_ref,
    acc_ref,
    *,
    block_q: int,
    block_k: int,
    n_k: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    d = q_ref.shape[-1]

    @pl.when(ki == 0)
    def _init():
        _init_stats(m_ref, l_ref, acc_ref, block_q, d)

    # Tiles fully beyond the causal frontier contribute nothing.
    @pl.when(ki * block_k <= qi * block_q + block_q - 1)
    def _update():
        _online_softmax_update(
            q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
            qi=qi, ki=ki, block_q=block_q, block_k=block_k, causal=True,
        )

    @pl.when(ki == n_k - 1)
    def _finalize():
        l1 = l_ref[:].max(axis=-1, keepdims=True)  # (block_q, 1)
        o_ref[0] = (acc_ref[:] / l1).astype(o_ref.dtype)


def _flash_chunk_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    m_out_ref,
    l_out_ref,
    m_ref,
    l_ref,
    acc_ref,
    *,
    block_q: int,
    block_k: int,
    n_k: int,
    causal: bool,
):
    """Blockwise attention over one local K/V chunk, emitting the
    UNNORMALIZED accumulator plus the (max, normalizer) stats, so an outer
    loop (the sp ring in ops/ring_attention.py) can merge chunks with the
    online-softmax recurrence instead of materializing s_local² logits."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    d = q_ref.shape[-1]

    @pl.when(ki == 0)
    def _init():
        _init_stats(m_ref, l_ref, acc_ref, block_q, d)

    def _update():
        _online_softmax_update(
            q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
            qi=qi, ki=ki, block_q=block_q, block_k=block_k, causal=causal,
        )

    if causal:
        pl.when(ki * block_k <= qi * block_q + block_q - 1)(_update)
    else:
        _update()

    @pl.when(ki == n_k - 1)
    def _finalize():
        o_ref[0] = acc_ref[:]
        m_out_ref[0] = m_ref[:].max(axis=-1, keepdims=True)
        l_out_ref[0] = l_ref[:].max(axis=-1, keepdims=True)


def flash_attention_chunk(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """Streamed blockwise attention of ``q`` against one K/V chunk.

    Args:
        q: ``(batch, s_q, n_heads, head_dim)``.
        k, v: ``(batch, s_k, n_heads, head_dim)``.
        causal: apply the *local* causal mask (chunk diagonal); ``False``
            means every position of the chunk is visible (a ring step whose
            K/V block lies entirely in the past).

    Returns:
        ``(o, m, l)``: unnormalized f32 accumulator ``(batch, n_heads,
        s_q, head_dim)`` and the per-row running max / normalizer
        ``(batch, n_heads, s_q)``. Normalize with ``o / l[..., None]`` or
        merge with another chunk via the online-softmax recurrence.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lengths ({sq}, {sk}) must divide by blocks "
            f"({block_q}, {block_k})"
        )
    n_k = sk // block_k
    to_rows = lambda x, s: x.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    qf, kf, vf = to_rows(q, sq), to_rows(k, sk), to_rows(v, sk)

    o, m, l = pl.pallas_call(
        functools.partial(
            _flash_chunk_kernel,
            block_q=block_q,
            block_k=block_k,
            n_k=n_k,
            causal=causal,
        ),
        grid=(b * h, sq // block_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    o = o.reshape(b, h, sq, d)
    m = m.reshape(b, h, sq)
    l = l.reshape(b, h, sq)
    return o, m, l


def _flash_bwd_blockwise(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    m: jax.Array,
    l: jax.Array,
    o: jax.Array,
    do: jax.Array,
    block_k: int,
):
    """Flash attention backward in pure lax, blockwise over key tiles:
    recomputes each tile's probabilities from the saved (max, normalizer)
    stats instead of keeping s² anything — O(s·block_k) temporaries, so
    training at the sequence lengths where the dense backward would OOM
    stays feasible. Standard recurrence: with P = softmax tile and
    D = rowsum(dO ∘ O), dS = P ∘ (dO Vᵀ − D), dQ = scale·dS K,
    dK = scale·dSᵀ Q, dV = Pᵀ dO.

    Shapes: q/k/v/o/do ``(b, h, s, d)`` f32, m/l ``(b, h, s)``.
    """
    b, h, s, d = q.shape
    scale = 1.0 / (d**0.5)
    n_k = s // block_k
    pos = jnp.arange(s)
    D = jnp.sum(do * o, axis=-1)  # (b, h, s)

    def kblock(carry, j):
        dq = carry
        kj = lax.dynamic_slice_in_dim(k, j * block_k, block_k, axis=2)
        vj = lax.dynamic_slice_in_dim(v, j * block_k, block_k, axis=2)
        k_pos = j * block_k + jnp.arange(block_k)
        sj = scale * jnp.einsum(
            "bhqd,bhkd->bhqk", q, kj, preferred_element_type=jnp.float32
        )
        mask = pos[:, None] >= k_pos[None, :]
        p = jnp.where(mask, jnp.exp(sj - m[..., None]) / l[..., None], 0.0)
        dp = jnp.einsum(
            "bhqd,bhkd->bhqk", do, vj, preferred_element_type=jnp.float32
        )
        ds = p * (dp - D[..., None])
        dq = dq + scale * jnp.einsum(
            "bhqk,bhkd->bhqd", ds, kj, preferred_element_type=jnp.float32
        )
        dkj = scale * jnp.einsum(
            "bhqk,bhqd->bhkd", ds, q, preferred_element_type=jnp.float32
        )
        dvj = jnp.einsum(
            "bhqk,bhqd->bhkd", p, do, preferred_element_type=jnp.float32
        )
        return dq, (dkj, dvj)

    dq, (dks, dvs) = lax.scan(kblock, jnp.zeros_like(q), jnp.arange(n_k))
    # (n_k, b, h, block_k, d) → (b, h, s, d)
    dk = dks.transpose(1, 2, 0, 3, 4).reshape(b, h, s, d)
    dv = dvs.transpose(1, 2, 0, 3, 4).reshape(b, h, s, d)
    return dq, dk, dv


@functools.lru_cache(maxsize=None)
def _flash_causal_vjp_fn(block_q: int, block_k: int, interpret: bool):
    """A custom_vjp-wrapped flash attention for one static block config
    (cached so jit sees a stable function identity). Primal: the fused
    normalize-in-VMEM kernel. Under differentiation: the chunk kernel
    (which also emits the (max, normalizer) stats) + the blockwise lax
    backward above."""

    @jax.custom_vjp
    def f(q, k, v):
        return _flash_causal_forward(q, k, v, block_q, block_k, interpret)

    def fwd(q, k, v):
        o_u, m, l = flash_attention_chunk(
            q, k, v, causal=True, block_q=block_q, block_k=block_k,
            interpret=interpret,
        )
        o = o_u / l[..., None]  # (b, h, s, d) f32, normalized
        out = o.transpose(0, 2, 1, 3).astype(q.dtype)
        return out, (q, k, v, m, l, o)

    def bwd(res, g):
        q, k, v, m, l, o = res
        to_h = lambda x: x.transpose(0, 2, 1, 3).astype(jnp.float32)
        dq, dk, dv = _flash_bwd_blockwise(
            to_h(q), to_h(k), to_h(v), m, l, o, to_h(g),
            block_k=min(block_k, q.shape[1]),
        )
        back = lambda x, like: x.transpose(0, 2, 1, 3).astype(like.dtype)
        return back(dq, q), back(dk, k), back(dv, v)

    f.defvjp(fwd, bwd)
    return f


def flash_causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in for :func:`~torchsnapshot_tpu.ops.causal_attention` on
    shapes where ``seq`` divides by the block sizes.

    Differentiable: reverse-mode goes through a blockwise backward that
    recomputes probability tiles from saved (max, normalizer) stats —
    no s² residuals (see :func:`_flash_bwd_blockwise`).

    Args:
        q, k, v: ``(batch, seq, n_heads, head_dim)``.
        block_q, block_k: VMEM tile sizes (128 aligns with the MXU).
        interpret: run in the Pallas interpreter (CPU-safe; tests).
    """
    return _flash_causal_vjp_fn(block_q, block_k, interpret)(q, k, v)


def _flash_causal_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    b, s, h, d = q.shape
    if s % block_q or s % block_k:
        raise ValueError(
            f"seq {s} must be a multiple of block_q={block_q} and "
            f"block_k={block_k}"
        )
    n_k = s // block_k
    # (b*h, s, d): one grid row per batch-head.
    to_rows = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    qf, kf, vf = to_rows(q), to_rows(k), to_rows(v)

    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, block_q=block_q, block_k=block_k, n_k=n_k
        ),
        grid=(b * h, s // block_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
