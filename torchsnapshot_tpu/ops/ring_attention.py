"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context scaling is first-class in this framework: activations stay
sequence-sharded across the ``sp`` mesh axis end to end, and attention —
the one op that mixes positions — is computed by rotating key/value blocks
around the ``sp`` ring with ``jax.lax.ppermute`` while each device keeps
its resident query block. Per-step partial results merge with the online
(flash-style) softmax recurrence, so the full ``(seq, seq)`` score matrix
never materializes anywhere: memory per device is O(seq_local^2) and the
KV transfers ride the ICI ring, overlapping with each step's einsums.

This is the RingAttention construction (Liu et al., 2023; see PAPERS.md)
expressed in idiomatic JAX: ``shard_map`` makes the per-device program
explicit, the ring step is an ``lax.scan`` (static trip count → reverse-mode
differentiable, compiler-schedulable), and the blockwise math is einsums
that tile onto the MXU with f32 accumulation.

The reference framework (torchsnapshot) has no sequence-parallel support at
all (SURVEY.md §2.12: absent); this op is part of the flagship workload
that produces the sequence-sharded training state the checkpointer must
persist, and makes multi-million-token contexts reachable without the
all-to-all resharding the Ulysses path in ``ops.attention`` needs.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .attention import causal_attention

_NEG_INF = -1e30  # finite "masked" value: keeps exp() exact-zero-free and
# the running max finite even for fully-masked (future) blocks.


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    use_flash: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """Per-device program: local blocks ``(b, s_local, h, d)``.

    Device ``r`` holds query block ``r``; at ring step ``t`` it holds the
    KV block originally owned by device ``(r - t) mod n`` and merges that
    block's contribution into the (max, sum, acc) online-softmax carry.

    With ``use_flash`` each step's blockwise attention runs in the Pallas
    kernel (ops/flash_attention.py ``flash_attention_chunk``) instead of
    einsums that materialize ``(b, h, s_local, s_local)`` logits in HBM:
    per-step memory drops to O(block·d) VMEM, which is what makes
    s_local in the tens of thousands (multi-million-token global context)
    fit. The step's mask mode depends on where the wandering KV block sits
    relative to the resident queries: fully behind → no mask, the diagonal
    step → local causal mask, fully ahead → skipped.
    """
    r = jax.lax.axis_index(axis_name)
    b, s, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, dtype=jnp.float32))
    qf = q.astype(jnp.float32) * scale

    local_pos = jnp.arange(s)
    q_pos = r * s + local_pos  # global positions of resident queries

    def _contrib_einsum(k_t, v_t, src):
        k_pos = src * s + local_pos
        # (b, h, s_q, s_k) logits on the MXU, f32 accumulation.
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk",
            qf,
            k_t.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        mask = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(mask, logits, _NEG_INF)
        m_c = jnp.max(logits, axis=-1)
        p = jnp.exp(logits - m_c[..., None])
        # A fully-masked block contributes p == exp(_NEG_INF - m) == 0.
        p = jnp.where(mask, p, 0.0)
        l_c = jnp.sum(p, axis=-1)
        o_c = jnp.einsum(
            "bhqk,bkhd->bhqd",
            p,
            v_t.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        return o_c, m_c, l_c

    def _contrib_flash(k_t, v_t, src):
        from .flash_attention import flash_attention_chunk

        def _full(_):
            return flash_attention_chunk(
                q, k_t, v_t, causal=False, interpret=interpret
            )

        def _diag(_):
            return flash_attention_chunk(
                q, k_t, v_t, causal=True, interpret=interpret
            )

        def _skip(_):
            return (
                jnp.zeros((b, h, s, d), jnp.float32),
                jnp.full((b, h, s), _NEG_INF, jnp.float32),
                jnp.zeros((b, h, s), jnp.float32),
            )

        branch = jnp.where(src < r, 0, jnp.where(src == r, 1, 2))
        return jax.lax.switch(branch, [_full, _diag, _skip], None)

    def ring_step(carry, t):
        o, m, l, k_t, v_t = carry
        src = (r - t) % axis_size
        contrib = _contrib_flash if use_flash else _contrib_einsum
        o_c, m_c, l_c = contrib(k_t, v_t, src)
        # Merge the chunk's (unnormalized acc, max, normalizer) into the
        # carry with the two-way online-softmax recurrence.
        m_new = jnp.maximum(m, m_c)
        corr = jnp.exp(m - m_new)
        corr_c = jnp.exp(m_c - m_new)
        l_new = l * corr + l_c * corr_c
        o_new = o * corr[..., None] + o_c * corr_c[..., None]
        # Rotate KV around the ring: i → i+1, so next step holds src-1's
        # block. XLA overlaps this ppermute with the next step's einsums.
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_t, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_t, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    o0 = jnp.zeros((b, h, s, d), dtype=jnp.float32)
    m0 = jnp.full((b, h, s), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, s), dtype=jnp.float32)
    (o, _, l, _, _), _ = jax.lax.scan(
        ring_step, (o0, m0, l0, k, v), jnp.arange(axis_size)
    )
    out = o / l[..., None]  # every query sees ≥ its own position ⇒ l > 0
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis_name", "use_flash", "interpret")
)
def ring_causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    axis_name: str = "sp",
    use_flash: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """Exact causal attention with sequence sharded over ``axis_name``.

    Args:
        q, k, v: ``(batch, seq, n_heads, head_dim)``; ``seq`` must divide
            evenly over ``mesh.shape[axis_name]``.
        mesh: mesh containing ``axis_name`` (and optionally ``dp``/``tp``
            for batch/head parallelism — those partitions need no
            collectives here). ``None`` falls back to the dense op.
        use_flash: run each ring step's blockwise attention in the Pallas
            flash kernel instead of HBM-materializing einsums (long local
            sequences). ``interpret`` runs that kernel in the Pallas
            interpreter (CPU tests).

    Returns:
        ``(batch, seq, n_heads, head_dim)``, numerically equal (up to f32
        roundoff) to :func:`~torchsnapshot_tpu.ops.attention.causal_attention`.
    """
    if mesh is None:
        return causal_attention(q, k, v)
    axis_size = mesh.shape[axis_name]
    has_dp = "dp" in mesh.axis_names
    has_tp = "tp" in mesh.axis_names
    spec = P("dp" if has_dp else None, axis_name, "tp" if has_tp else None, None)

    def mapped(flash: bool):
        from ..utils import shard_map_compat

        return shard_map_compat(
            functools.partial(
                _ring_attention_local,
                axis_name=axis_name,
                axis_size=axis_size,
                use_flash=flash,
                interpret=interpret,
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )

    if not use_flash:
        return mapped(False)(q, k, v)

    # The Pallas chunk kernel has no autodiff rule; the einsum ring
    # computes the same function, so its vjp IS this function's vjp.
    # Forward runs the kernel (no s_local² HBM intermediate); backward
    # rematerializes through the einsum ring — the same backward cost the
    # non-flash ring path pays.
    @jax.custom_vjp
    def rca(q, k, v):
        return mapped(True)(q, k, v)

    def fwd(q, k, v):
        return mapped(True)(q, k, v), (q, k, v)

    def bwd(res, g):
        _, vjp = jax.vjp(mapped(False), *res)
        return vjp(g)

    rca.defvjp(fwd, bwd)
    return rca(q, k, v)


def ring_attention_block_specs(
    mesh: Mesh, axis_name: str = "sp"
) -> Tuple[P, P]:
    """(activation, qkv) PartitionSpecs a model should constrain to so the
    ring path sees sequence-sharded inputs without resharding."""
    del mesh
    return P("dp", axis_name, None), P("dp", axis_name, None, None)
