"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context scaling is first-class in this framework: activations stay
sequence-sharded across the ``sp`` mesh axis end to end, and attention —
the one op that mixes positions — is computed by rotating key/value blocks
around the ``sp`` ring with ``jax.lax.ppermute`` while each device keeps
its resident query block. Per-step partial results merge with the online
(flash-style) softmax recurrence, so the full ``(seq, seq)`` score matrix
never materializes anywhere: memory per device is O(seq_local^2) and the
KV transfers ride the ICI ring, overlapping with each step's einsums.

This is the RingAttention construction (Liu et al., 2023; see PAPERS.md)
expressed in idiomatic JAX: ``shard_map`` makes the per-device program
explicit, the ring step is an ``lax.scan`` (static trip count → reverse-mode
differentiable, compiler-schedulable), and the blockwise math is einsums
that tile onto the MXU with f32 accumulation.

The reference framework (torchsnapshot) has no sequence-parallel support at
all (SURVEY.md §2.12: absent); this op is part of the flagship workload
that produces the sequence-sharded training state the checkpointer must
persist, and makes multi-million-token contexts reachable without the
all-to-all resharding the Ulysses path in ``ops.attention`` needs.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .attention import causal_attention

_NEG_INF = -1e30  # finite "masked" value: keeps exp() exact-zero-free and
# the running max finite even for fully-masked (future) blocks.


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
) -> jax.Array:
    """Per-device program: local blocks ``(b, s_local, h, d)``.

    Device ``r`` holds query block ``r``; at ring step ``t`` it holds the
    KV block originally owned by device ``(r - t) mod n`` and merges that
    block's contribution into the (max, sum, acc) online-softmax carry.
    """
    r = jax.lax.axis_index(axis_name)
    b, s, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, dtype=jnp.float32))
    qf = q.astype(jnp.float32) * scale

    local_pos = jnp.arange(s)
    q_pos = r * s + local_pos  # global positions of resident queries

    def ring_step(carry, t):
        o, m, l, k_t, v_t = carry
        src = (r - t) % axis_size
        k_pos = src * s + local_pos
        # (b, h, s_q, s_k) logits on the MXU, f32 accumulation.
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk",
            qf,
            k_t.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        mask = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(mask, logits, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        # A fully-masked block contributes p == exp(_NEG_INF - m) == 0.
        p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd",
            p,
            v_t.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        # Rotate KV around the ring: i → i+1, so next step holds src-1's
        # block. XLA overlaps this ppermute with the next step's einsums.
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_t, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_t, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    o0 = jnp.zeros((b, h, s, d), dtype=jnp.float32)
    m0 = jnp.full((b, h, s), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, s), dtype=jnp.float32)
    (o, _, l, _, _), _ = jax.lax.scan(
        ring_step, (o0, m0, l0, k, v), jnp.arange(axis_size)
    )
    out = o / l[..., None]  # every query sees ≥ its own position ⇒ l > 0
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("mesh", "axis_name"))
def ring_causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    axis_name: str = "sp",
) -> jax.Array:
    """Exact causal attention with sequence sharded over ``axis_name``.

    Args:
        q, k, v: ``(batch, seq, n_heads, head_dim)``; ``seq`` must divide
            evenly over ``mesh.shape[axis_name]``.
        mesh: mesh containing ``axis_name`` (and optionally ``dp``/``tp``
            for batch/head parallelism — those partitions need no
            collectives here). ``None`` falls back to the dense op.

    Returns:
        ``(batch, seq, n_heads, head_dim)``, numerically equal (up to f32
        roundoff) to :func:`~torchsnapshot_tpu.ops.attention.causal_attention`.
    """
    if mesh is None:
        return causal_attention(q, k, v)
    axis_size = mesh.shape[axis_name]
    has_dp = "dp" in mesh.axis_names
    has_tp = "tp" in mesh.axis_names
    spec = P("dp" if has_dp else None, axis_name, "tp" if has_tp else None, None)
    fn = jax.shard_map(
        functools.partial(
            _ring_attention_local, axis_name=axis_name, axis_size=axis_size
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def ring_attention_block_specs(
    mesh: Mesh, axis_name: str = "sp"
) -> Tuple[P, P]:
    """(activation, qkv) PartitionSpecs a model should constrain to so the
    ring path sees sequence-sharded inputs without resharding."""
    del mesh
    return P("dp", axis_name, None), P("dp", axis_name, None, None)
