"""Snapshot metadata schema: entry taxonomy, YAML/JSON codec, per-rank views.

Reference parity: torchsnapshot/manifest.py. Entries are tagged unions of
primitive YAML types; backward/forward compatibility is defined on the YAML
form, not on the Python dataclasses (reference: manifest.py:32-35). Tags:

- ``Array`` / ``ShardedArray`` / ``ChunkedArray`` — the jax.Array analogs of
  the reference's Tensor/ShardedTensor/ChunkedTensor (manifest.py:40-151)
- ``object`` — pickled opaque leaves (manifest.py:154-168)
- ``list`` / ``dict`` / ``OrderedDict`` — container structure (:171-192)
- ``int``/``float``/``str``/``bool``/``bytes`` — primitives stored inline in
  the metadata itself (:195-290); floats carry an exact ``float.hex()``
  encoding next to a human-readable repr.

Global manifest keys are ``"{rank}/{logical_path}"``; storage locations are
``sharded/...``, ``replicated/...``, ``{rank}/...`` and ``batched/{uuid}``.

The metadata file is committed as YAML but must stay loadable when emitted
as JSON (YAML's superset property) — the escape hatch for huge manifests
(reference invariant tested at tests/test_manifest.py:259-281).
"""

from __future__ import annotations

import base64
import copy
import json
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple, Union
from urllib.parse import unquote as _unquote

import yaml

try:
    from yaml import CSafeDumper as _Dumper, CSafeLoader as _Loader
except ImportError:  # pragma: no cover - libyaml is present in this image
    from yaml import SafeDumper as _Dumper, SafeLoader as _Loader


@dataclass
class Entry:
    """Base of all manifest entries; ``type`` is the YAML tag."""

    type: str


_FROM_YAML: Dict[str, Callable[[Dict[str, Any]], "Entry"]] = {}


def _register(tag: str):
    def deco(fn):
        _FROM_YAML[tag] = fn
        return fn

    return deco


@dataclass(init=False)
class ArrayEntry(Entry):
    """A dense array persisted at ``location`` (reference TensorEntry,
    manifest.py:40-72). ``byte_range`` is set when the bytes live inside a
    batched slab or a subdivided shard file. ``digest`` is an optional
    content digest of the payload (ops/device_digest.py format) recorded
    by digest-enabled takes; incremental takes compare against it to skip
    rewriting unchanged chunks. A ``location`` may be *snapshot-relative
    with parent refs* (``../step_.../...``) when the bytes live in a base
    snapshot this one was taken incrementally against."""

    location: str
    serializer: str
    dtype: str
    shape: List[int]
    replicated: bool
    byte_range: Optional[List[int]]
    digest: Optional[str]

    def __init__(
        self,
        location: str,
        serializer: str,
        dtype: str,
        shape: List[int],
        replicated: bool,
        byte_range: Optional[List[int]] = None,
        digest: Optional[str] = None,
    ) -> None:
        super().__init__(type="Array")
        self.location = location
        self.serializer = serializer
        self.dtype = dtype
        self.shape = list(shape)
        self.replicated = replicated
        self.byte_range = list(byte_range) if byte_range is not None else None
        self.digest = digest

    @property
    def byte_range_tuple(self) -> Optional[Tuple[int, int]]:
        if self.byte_range is None:
            return None
        return (self.byte_range[0], self.byte_range[1])


@_register("Array")
def _array_from_yaml(obj: Dict[str, Any]) -> ArrayEntry:
    return ArrayEntry(
        location=obj["location"],
        serializer=obj["serializer"],
        dtype=obj["dtype"],
        shape=obj["shape"],
        replicated=obj["replicated"],
        byte_range=obj.get("byte_range"),
        digest=obj.get("digest"),
    )


@dataclass
class Shard:
    """A hyper-rectangular piece of a logical array: N-d ``offsets`` +
    ``sizes`` plus the dense entry holding its bytes (reference
    manifest.py:75-79)."""

    offsets: List[int]
    sizes: List[int]
    array: ArrayEntry

    @classmethod
    def from_yaml(cls, obj: Dict[str, Any]) -> "Shard":
        return cls(
            offsets=list(obj["offsets"]),
            sizes=list(obj["sizes"]),
            array=_array_from_yaml(obj["array"]),
        )


@dataclass(init=False)
class ShardedArrayEntry(Entry):
    """An array partitioned across processes by its GSPMD sharding; shards
    from all ranks are merged into one entry on restore (reference
    ShardedTensorEntry, manifest.py:82-107). ``shape``/``dtype`` describe
    the full logical array — needed to allocate a differently-sharded
    destination when resharding elastically."""

    dtype: str
    shape: List[int]
    shards: List[Shard]

    def __init__(self, dtype: str, shape: List[int], shards: List[Shard]) -> None:
        super().__init__(type="ShardedArray")
        self.dtype = dtype
        self.shape = list(shape)
        self.shards = shards


@_register("ShardedArray")
def _sharded_from_yaml(obj: Dict[str, Any]) -> ShardedArrayEntry:
    return ShardedArrayEntry(
        dtype=obj["dtype"],
        shape=obj["shape"],
        shards=[Shard.from_yaml(s) for s in obj["shards"]],
    )


@dataclass(init=False)
class ChunkedArrayEntry(Entry):
    """A large *unsharded* array split into chunks so staging/writes stream
    under the memory budget (reference ChunkedTensorEntry,
    manifest.py:110-151)."""

    dtype: str
    shape: List[int]
    chunks: List[Shard]
    replicated: bool

    def __init__(
        self, dtype: str, shape: List[int], chunks: List[Shard], replicated: bool
    ) -> None:
        super().__init__(type="ChunkedArray")
        self.dtype = dtype
        self.shape = list(shape)
        self.chunks = chunks
        self.replicated = replicated


@_register("ChunkedArray")
def _chunked_from_yaml(obj: Dict[str, Any]) -> ChunkedArrayEntry:
    return ChunkedArrayEntry(
        dtype=obj["dtype"],
        shape=obj["shape"],
        chunks=[Shard.from_yaml(c) for c in obj["chunks"]],
        replicated=obj["replicated"],
    )


@dataclass(init=False)
class ObjectEntry(Entry):
    """A pickled opaque leaf (reference manifest.py:154-168)."""

    location: str
    serializer: str
    obj_type: str
    replicated: bool

    def __init__(
        self, location: str, serializer: str, obj_type: str, replicated: bool
    ) -> None:
        super().__init__(type="object")
        self.location = location
        self.serializer = serializer
        self.obj_type = obj_type
        self.replicated = replicated


@_register("object")
def _object_from_yaml(obj: Dict[str, Any]) -> ObjectEntry:
    return ObjectEntry(
        location=obj["location"],
        serializer=obj["serializer"],
        obj_type=obj["obj_type"],
        replicated=obj["replicated"],
    )


@dataclass(init=False)
class ListEntry(Entry):
    def __init__(self) -> None:
        super().__init__(type="list")


_FROM_YAML["list"] = lambda obj: ListEntry()


@dataclass(init=False)
class DictEntry(Entry):
    keys: List[Union[str, int]]

    def __init__(self, keys: List[Union[str, int]]) -> None:
        super().__init__(type="dict")
        self.keys = list(keys)


_FROM_YAML["dict"] = lambda obj: DictEntry(keys=obj["keys"])


@dataclass(init=False)
class OrderedDictEntry(Entry):
    keys: List[Union[str, int]]

    def __init__(self, keys: List[Union[str, int]]) -> None:
        super().__init__(type="OrderedDict")
        self.keys = list(keys)


_FROM_YAML["OrderedDict"] = lambda obj: OrderedDictEntry(keys=obj["keys"])


PRIMITIVE_TYPE_NAMES: Tuple[str, ...] = ("int", "float", "str", "bool", "bytes")


@dataclass(init=False)
class PrimitiveEntry(Entry):
    """A primitive value stored inline in the metadata (reference
    manifest.py:195-290). ``serialized_value`` is exact (``float.hex()`` for
    floats, base64 for bytes); ``readable`` is a best-effort human-friendly
    rendering."""

    serialized_value: str
    replicated: bool
    readable: Optional[str]

    def __init__(
        self,
        type: str,
        serialized_value: str,
        replicated: bool,
        readable: Optional[str] = None,
    ) -> None:
        super().__init__(type=type)
        self.serialized_value = serialized_value
        self.replicated = replicated
        self.readable = readable

    @classmethod
    def from_object(cls, obj: Any, replicated: bool = False) -> "PrimitiveEntry":
        type_name = type(obj).__name__
        if type_name == "int":
            return cls("int", str(obj), replicated)
        if type_name == "bool":
            return cls("bool", str(obj), replicated)
        if type_name == "str":
            return cls("str", obj, replicated)
        if type_name == "bytes":
            return cls("bytes", base64.b64encode(obj).decode("ascii"), replicated)
        if type_name == "float":
            return cls("float", float(obj).hex(), replicated, readable=repr(obj))
        raise TypeError(f"Unsupported primitive type: {type_name}")

    def get_value(self) -> Union[int, float, str, bool, bytes]:
        if self.type == "int":
            return int(self.serialized_value)
        if self.type == "bool":
            if self.serialized_value not in ("True", "False"):
                raise RuntimeError(
                    f"Corrupt bool serialized_value: {self.serialized_value!r}"
                )
            return self.serialized_value == "True"
        if self.type == "str":
            return self.serialized_value
        if self.type == "bytes":
            return base64.b64decode(self.serialized_value.encode("ascii"))
        if self.type == "float":
            return float.fromhex(self.serialized_value)
        raise ValueError(f"Not a primitive entry type: {self.type}")


def _primitive_from_yaml(tag: str) -> Callable[[Dict[str, Any]], PrimitiveEntry]:
    def build(obj: Dict[str, Any]) -> PrimitiveEntry:
        return PrimitiveEntry(
            type=tag,
            serialized_value=obj["serialized_value"],
            replicated=obj["replicated"],
            readable=obj.get("readable"),
        )

    return build


for _tag in PRIMITIVE_TYPE_NAMES:
    _FROM_YAML[_tag] = _primitive_from_yaml(_tag)


Manifest = Dict[str, Entry]


def entry_from_yaml_obj(obj: Dict[str, Any]) -> Entry:
    tag = obj["type"]
    try:
        builder = _FROM_YAML[tag]
    except KeyError:
        raise ValueError(f"Unknown manifest entry type: {tag!r}") from None
    return builder(obj)


def entry_to_yaml_obj(entry: Entry) -> Dict[str, Any]:
    """Shallow, type-aware encoding. ``dataclasses.asdict`` deep-copies
    recursively with per-field introspection — the dominant planning cost
    for 1e5-leaf manifests; entries are flat except Shard lists, handled
    explicitly. The returned dict aliases the entry's lists, which is fine
    for immediate json/yaml dumping (neither mutates its input)."""
    d = dict(entry.__dict__)
    # ``digest`` stays out of the YAML form when unset so non-digest
    # snapshots keep their exact metadata bytes (and 1e5-leaf manifests
    # don't carry dead null fields).
    if d.get("digest") is None:
        d.pop("digest", None)
    for key in ("shards", "chunks"):
        shards = d.get(key)
        if shards:
            d[key] = [
                {
                    "offsets": s.offsets,
                    "sizes": s.sizes,
                    "array": _array_yaml_obj(s.array),
                }
                for s in shards
            ]
    return d


def _array_yaml_obj(array: ArrayEntry) -> Dict[str, Any]:
    a = dict(array.__dict__)
    if a.get("digest") is None:
        a.pop("digest", None)
    return a


@dataclass
class SnapshotMetadata:
    version: str
    world_size: int
    manifest: Manifest
    # Non-reference extension: records how many processes *wrote* (the nccl
    # local-world analog is unneeded; restore elasticity only needs this).

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": self.version,
            "world_size": self.world_size,
            "manifest": {k: entry_to_yaml_obj(v) for k, v in self.manifest.items()},
        }

    def to_yaml(self) -> str:
        return yaml.dump(self.to_dict(), sort_keys=False, Dumper=_Dumper)

    def to_json(self) -> str:
        """JSON emission for very large manifests; stays loadable by
        :meth:`from_yaml` because JSON is a YAML subset."""
        return json.dumps(self.to_dict())

    @classmethod
    def from_yaml(cls, yaml_str: str) -> "SnapshotMetadata":
        # JSON is the fast path (snapshots are committed as JSON, which is
        # a YAML subset — reference manifest.py:19-22 invariant); anything
        # json can't parse goes through the YAML loader.
        try:
            d = json.loads(yaml_str)
        except json.JSONDecodeError:
            d = yaml.load(yaml_str, Loader=_Loader)
        return cls(
            version=d["version"],
            world_size=d["world_size"],
            manifest={
                path: entry_from_yaml_obj(obj) for path, obj in d["manifest"].items()
            },
        )


def sharded_blob_windows(manifest: Manifest) -> Dict[str, Tuple[int, int]]:
    """Unique storage blobs holding ShardedArray shard payloads eligible
    for single-reader fan-out: ``location -> [start, end)`` absolute byte
    window of the shard's bytes within its blob.

    Restricted to dedicated shard blobs (``sharded/...`` path segment,
    incremental base refs included) holding raw buffer-protocol payloads
    with no ``byte_range``: a batched-slab member shares its
    ``batched/{uuid}`` file with arbitrary other entries, so fanning it
    out would ship unrelated bytes — those reads stay every-rank-local.
    The window for an eligible blob is always ``(0, nbytes)`` (one shard
    per file by construction of ``_shard_location``)."""
    from .serialization import Serializer, array_size_bytes

    out: Dict[str, Tuple[int, int]] = {}
    for entry in manifest.values():
        if not isinstance(entry, ShardedArrayEntry):
            continue
        for shard in entry.shards:
            arr = shard.array
            if (
                "sharded/" not in arr.location
                or arr.serializer != Serializer.BUFFER_PROTOCOL.value
                or arr.byte_range is not None
            ):
                continue
            out[arr.location] = (0, array_size_bytes(arr.shape, arr.dtype))
    return out


def entry_locations(entry: Entry) -> List[str]:
    """Every storage location a manifest entry's bytes live at (batched
    slab members and deduplicated chunks share locations; callers
    dedupe). The one location walk shared by the manager's GC, the
    mirror's resume planner, and the CAS refcount derivation."""
    if isinstance(entry, ShardedArrayEntry):
        return [shard.array.location for shard in entry.shards]
    if isinstance(entry, ChunkedArrayEntry):
        return [chunk.array.location for chunk in entry.chunks]
    location = getattr(entry, "location", None)
    return [location] if location else []


def is_replicated(entry: Entry) -> bool:
    return bool(getattr(entry, "replicated", False))


def is_container_entry(entry: Entry) -> bool:
    return isinstance(entry, (ListEntry, DictEntry, OrderedDictEntry))


def is_dict_entry(entry: Entry) -> bool:
    return isinstance(entry, (DictEntry, OrderedDictEntry))


def get_manifest_for_rank(metadata: SnapshotMetadata, rank: int) -> Manifest:
    """Derive the entries available to ``rank`` from a global manifest.

    Availability rules (reference manifest.py:333-371):

    - *per-rank* entries are visible only to the rank that saved them;
    - *replicated* entries are visible to every rank;
    - *ShardedArray* entries are merged across ranks (union of shards,
      sorted by offsets) and visible to every rank.

    When an entry is copied into a rank that lacks its ancestor containers,
    fresh container entries are created listing only the copied children
    (the reference mutates shared entries in place — manifest.py:397-419;
    we build new ones to keep the global manifest pristine).
    """
    per_rank: Dict[int, Manifest] = {i: {} for i in range(metadata.world_size)}
    for path, entry in metadata.manifest.items():
        rnk_str, _, logical_path = path.partition("/")
        per_rank.setdefault(int(rnk_str), {})[logical_path] = entry

    local: Manifest = dict(per_rank.get(rank, {}))

    for src_rank, src_manifest in sorted(per_rank.items()):
        if src_rank == rank:
            continue
        for logical_path, entry in src_manifest.items():
            if isinstance(entry, ShardedArrayEntry):
                if logical_path not in local or not isinstance(
                    local.get(logical_path), ShardedArrayEntry
                ):
                    _graft_entry(local, src_manifest, logical_path, entry)
                else:
                    merged = local[logical_path].shards + entry.shards
                    local[logical_path] = ShardedArrayEntry(
                        dtype=entry.dtype,
                        shape=entry.shape,
                        shards=sorted(merged, key=lambda s: s.offsets),
                    )
            elif is_replicated(entry) and logical_path not in local:
                _graft_entry(local, src_manifest, logical_path, entry)
    return local


def _original_key(container: Entry, segment: str) -> Union[str, int]:
    """Map an encoded path segment back to the container's original key
    object so int dict keys keep their type in grafted manifests."""
    decoded = _unquote(segment)
    if is_dict_entry(container):
        for k in container.keys:
            if str(k) == decoded:
                return k
    return decoded


def _graft_entry(
    dst: Manifest, src: Manifest, logical_path: str, entry: Entry
) -> None:
    """Copy ``entry`` into ``dst`` and ensure its ancestor containers exist,
    extending (copies of) dict-entry key lists as needed."""
    dst[logical_path] = entry
    child = logical_path
    while "/" in child:
        parent, _, segment = child.rpartition("/")
        src_parent = src.get(parent)
        if parent in dst:
            existing = dst[parent]
            if is_dict_entry(existing):
                key = _original_key(
                    src_parent if src_parent is not None else existing, segment
                )
                if key not in existing.keys:
                    extended = copy.copy(existing)
                    extended.keys = list(existing.keys) + [key]
                    dst[parent] = extended
            break
        if src_parent is None:
            break
        if is_dict_entry(src_parent):
            trimmed = copy.copy(src_parent)
            trimmed.keys = [_original_key(src_parent, segment)]
            dst[parent] = trimmed
        else:
            dst[parent] = copy.copy(src_parent)
        child = parent
