"""Zero-copy (de)serialization between host arrays and byte buffers.

Reference parity: torchsnapshot/serialization.py. The reference needs an
``UntypedStorage`` escape hatch for bf16 (serialization.py:191-233) and a
``torch.save`` fallback for exotic dtypes; on the JAX/TPU side every dtype we
care about — including bfloat16 and the fp8 formats the MXU consumes — is a
numpy-registered ``ml_dtypes`` dtype, so a single buffer-protocol path covers
everything. PEP 3118 does not know the ml_dtypes formats, so buffer export
goes through a uint8 *view* (no copy) instead of ``memoryview(arr)``.

All buffers are little-endian on disk. TPU hosts are little-endian; a
big-endian host would need byteswaps and is rejected loudly.
"""

from __future__ import annotations

import pickle
import sys
from enum import Enum
from typing import Any, Dict, List, Sequence

import ml_dtypes
import numpy as np

if sys.byteorder != "little":  # pragma: no cover - TPU hosts are LE
    raise RuntimeError(
        "torchsnapshot_tpu serializes buffers little-endian and requires a "
        "little-endian host."
    )


class Serializer(Enum):
    """How a leaf's bytes were produced.

    Reference parity: serialization.py:141-146. ``TORCH_SAVE`` has no reason
    to exist here (every supported dtype is buffer-protocol friendly); the
    object fallback is plain pickle, as torch.save is for the reference.
    """

    BUFFER_PROTOCOL = "buffer_protocol"
    PICKLE = "pickle"


# Deliberately exhaustive, explicit dtype table (reference: the str<->dtype
# maps at serialization.py:32-138 are intentionally spelled out rather than
# derived, so that support is a conscious decision per dtype).
_SUPPORTED_DTYPE_NAMES: List[str] = [
    "bool",
    "int8",
    "int16",
    "int32",
    "int64",
    "uint8",
    "uint16",
    "uint32",
    "uint64",
    "float16",
    "bfloat16",
    "float32",
    "float64",
    "complex64",
    "complex128",
    # TPU-native low-precision formats (ml_dtypes); absent in the reference.
    "float8_e4m3fn",
    "float8_e5m2",
    "float8_e4m3b11fnuz",
    "int4",
    "uint4",
]

STRING_TO_DTYPE: Dict[str, np.dtype] = {}
for _name in _SUPPORTED_DTYPE_NAMES:
    try:
        STRING_TO_DTYPE[_name] = np.dtype(_name)
    except TypeError:
        # Names numpy doesn't resolve directly come from ml_dtypes.
        scalar_type = getattr(ml_dtypes, _name, None)
        if scalar_type is not None:  # pragma: no branch
            STRING_TO_DTYPE[_name] = np.dtype(scalar_type)

DTYPE_TO_STRING: Dict[np.dtype, str] = {v: k for k, v in STRING_TO_DTYPE.items()}

SUPPORTED_DTYPES = frozenset(STRING_TO_DTYPE.values())


def dtype_to_string(dtype: Any) -> str:
    """Canonical string for a numpy/JAX dtype. Raises on unsupported dtypes."""
    dt = np.dtype(dtype)
    try:
        return DTYPE_TO_STRING[dt]
    except KeyError:
        raise ValueError(
            f"Unsupported dtype for checkpointing: {dt!r}. "
            f"Supported: {sorted(STRING_TO_DTYPE)}"
        ) from None


def string_to_dtype(s: str) -> np.dtype:
    try:
        return STRING_TO_DTYPE[s]
    except KeyError:
        raise ValueError(
            f"Unknown dtype string {s!r} in snapshot metadata. "
            f"Supported: {sorted(STRING_TO_DTYPE)}"
        ) from None


def dtype_size_bytes(s: str) -> int:
    """Element size in bytes for a dtype string (int4/uint4 are byte-packed
    by numpy/ml_dtypes: one element per byte)."""
    return string_to_dtype(s).itemsize


def array_size_bytes(shape: Sequence[int], dtype_str: str) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype_size_bytes(dtype_str)


def array_as_memoryview(arr: np.ndarray) -> memoryview:
    """Zero-copy export of a host array's bytes as a C-order memoryview.

    The array must be C-contiguous (callers materialize contiguity during
    staging, where the copy is accounted against the memory budget). Works
    for every supported dtype, including the ml_dtypes formats PEP 3118
    can't describe, by viewing the buffer as uint8 first.

    Reference parity: tensor_as_memoryview (serialization.py:162-188); the
    uint8 view plays the role of the UntypedStorage trick (:216-233) but is
    uniform across dtypes rather than a bf16 special case.
    """
    if not isinstance(arr, np.ndarray):
        raise TypeError(f"array_as_memoryview expects np.ndarray, got {type(arr)}")
    if not arr.flags.c_contiguous:
        raise ValueError(
            "array_as_memoryview requires a C-contiguous array; stage a "
            "contiguous copy first"
        )
    if arr.dtype not in SUPPORTED_DTYPES:
        raise ValueError(f"Unsupported dtype: {arr.dtype!r}")
    if arr.size == 0:
        # memoryview cannot cast views with zeros in shape/strides.
        return memoryview(b"")
    if arr.ndim == 0:
        # 0-d arrays cannot change itemsize via .view; reshape is free.
        arr = arr.reshape(1)
    return memoryview(arr.view(np.uint8)).cast("B")


def try_writable_byte_view(arr: Any) -> "memoryview | None":
    """A writable uint8 view of ``arr``'s bytes, or ``None`` when the array
    can't serve as a direct read destination (non-ndarray, non-contiguous,
    read-only, unsupported dtype). Used for direct-into-destination storage
    reads that skip the intermediate buffer."""
    if (
        not isinstance(arr, np.ndarray)
        or arr.size == 0  # zero bytes: nothing to read directly into
        or not arr.flags.c_contiguous
        or not arr.flags.writeable
        or arr.dtype not in SUPPORTED_DTYPES
    ):
        return None
    return array_as_memoryview(arr)


def array_from_memoryview(
    mv: "memoryview | bytes | bytearray", dtype: str, shape: Sequence[int]
) -> np.ndarray:
    """Zero-copy reconstruction of an array from bytes.

    Reference parity: tensor_from_memoryview (serialization.py:236-244).
    Accepts any buffer (storage reads hand back ``bytes``); the returned
    array aliases it — writable iff the buffer is.
    """
    if not isinstance(mv, memoryview):
        mv = memoryview(mv)
    dt = string_to_dtype(dtype)
    expected = array_size_bytes(shape, dtype)
    if mv.nbytes != expected:
        raise ValueError(
            f"Buffer has {mv.nbytes} bytes but dtype={dtype} shape={tuple(shape)} "
            f"needs {expected}"
        )
    return np.frombuffer(mv, dtype=dt).reshape(tuple(shape))


def pickle_save_as_bytes(obj: Any) -> bytes:
    """Serialize an arbitrary object (reference: torch_save_as_bytes,
    serialization.py:247-254). Protocol 5 enables out-of-band-capable
    buffers and is supported by every Python this package runs on."""
    return pickle.dumps(obj, protocol=5)


def pickle_load_from_bytes(data: bytes) -> Any:
    return pickle.loads(data)


def obj_type_name(obj: Any) -> str:
    t = type(obj)
    mod = getattr(t, "__module__", "builtins")
    return f"{mod}.{t.__qualname__}" if mod != "builtins" else t.__qualname__
