"""Hot weight swap: shadow-stage an announced step, flip pointers.

The restore path's lesson (``snapshot._PlacementBatch``) applied to
serving: never mutate the arrays a model is serving from. ``stage``
assembles the announce's chunk bytes into a complete *shadow* set of
host buffers — the served weights are untouched, so a subscriber killed
mid-stage (the ``cdn-swap-staged`` crash point) still serves the
previous fully-applied step. ``swap`` then moves the whole shadow set
device-side in ONE batched ``jax.device_put`` (per-leaf puts pay
dispatch latency once per leaf; the batch pays it once per step) onto
each old array's own sharding, flips the pointers, and ``delete()``s
the old device buffers — the donation discipline: the pause inference
observes is a pointer swap, and peak device memory is old + new for
only the instant between placement and delete.

The chunk-bytes-to-leaves mapping is the serving binary's knowledge,
injected as ``assemble(announce, chunk_bytes) -> {leaf: host_array}``;
:func:`concat_assembler` covers the common dense layout (chunks
concatenated in sorted-key order, sliced per template leaf) used by the
storm/bench harnesses."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ..telemetry import names as metric_names
from ..telemetry.trace import get_recorder as _trace_recorder
from .topic import Announce


class SwapError(RuntimeError):
    """The assembled update does not cover the serving template."""


def concat_assembler(
    template: Dict[str, Any],
) -> Callable[[Announce, Dict[str, bytes]], Dict[str, Any]]:
    """Assembler for the dense concat layout: the announce's chunks,
    concatenated in sorted-key order, are the template's leaves
    flattened in sorted-name order. Exact-size checked — a short or
    long byte stream is a torn update and must never stage."""
    import numpy as np

    # Snapshot shapes/dtypes NOW: after a donation swap the template's
    # jax leaves are deleted buffers, so touching them at assemble time
    # would crash the second update of every serving run.
    spec = []
    for name in sorted(template):
        leaf = template[name]
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            shape, dtype = tuple(leaf.shape), np.dtype(leaf.dtype)
        else:
            arr = np.asarray(leaf)
            shape, dtype = arr.shape, arr.dtype
        nbytes = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        spec.append((name, shape, dtype, nbytes))

    def assemble(
        ann: Announce, chunk_bytes: Dict[str, bytes]
    ) -> Dict[str, Any]:
        stream = b"".join(chunk_bytes[k] for k in sorted(chunk_bytes))
        out: Dict[str, Any] = {}
        offset = 0
        for name, shape, dtype, nbytes in spec:
            window = stream[offset : offset + nbytes]
            if len(window) != nbytes:
                raise SwapError(
                    f"announced step covers {len(stream)} bytes; leaf "
                    f"{name!r} needs [{offset}, {offset + nbytes})"
                )
            out[name] = np.frombuffer(window, dtype=dtype).reshape(shape)
            offset += nbytes
        if offset != len(stream):
            raise SwapError(
                f"announced step has {len(stream) - offset} bytes past "
                "the template's layout"
            )
        return out

    return assemble


class _StagedUpdate:
    """A fully assembled shadow set, not yet visible to serving."""

    __slots__ = ("announce", "host_arrays")

    def __init__(
        self, announce: Announce, host_arrays: Dict[str, Any]
    ) -> None:
        self.announce = announce
        self.host_arrays = host_arrays


class WeightSwapper:
    """Serve one weight set; atomically replace it per announce.

    ``weights`` is the served leaf map (jax arrays on an accelerator,
    plain numpy in host-only tests — both flavors swap; only jax
    leaves take the batched device placement)."""

    def __init__(
        self,
        weights: Dict[str, Any],
        assemble: Optional[
            Callable[[Announce, Dict[str, bytes]], Dict[str, Any]]
        ] = None,
    ) -> None:
        self._weights = dict(weights)
        self._assemble = (
            assemble if assemble is not None else concat_assembler(weights)
        )
        self.swapped_step: Optional[int] = None

    @property
    def weights(self) -> Dict[str, Any]:
        """The currently served leaf map (post last completed swap)."""
        return self._weights

    def stage(
        self, ann: Announce, chunk_bytes: Dict[str, bytes]
    ) -> _StagedUpdate:
        host = self._assemble(ann, chunk_bytes)
        missing = set(self._weights) - set(host)
        if missing:
            raise SwapError(
                f"assembled update misses leaves: {sorted(missing)[:5]}"
            )
        return _StagedUpdate(ann, host)

    def swap(self, staged: _StagedUpdate) -> None:
        with _trace_recorder().span(
            metric_names.SPAN_CDN_SWAP,
            topic=staged.announce.topic,
            step=staged.announce.step,
        ):
            old = self._weights
            jax_names = [
                n
                for n in sorted(staged.host_arrays)
                if _is_jax_array(old.get(n))
            ]
            fresh: Dict[str, Any] = dict(staged.host_arrays)
            if jax_names:
                import jax

                placed = jax.device_put(
                    [staged.host_arrays[n] for n in jax_names],
                    [old[n].sharding for n in jax_names],
                )
                for name, arr in zip(jax_names, placed):
                    fresh[name] = arr
            # The pointer swap IS the cutover; everything before this
            # line left the served set untouched.
            self._weights = fresh
            self.swapped_step = staged.announce.step
            for name in jax_names:
                try:
                    old[name].delete()  # donation: free the old buffers
                except Exception:  # noqa: BLE001 - already-donated is fine
                    pass


def _is_jax_array(value: Any) -> bool:
    if value is None:
        return False
    try:
        import jax

        return isinstance(value, jax.Array)
    except Exception:  # noqa: BLE001 - jax-less host is a valid server
        return False
