"""Checkpoint CDN (docs/cdn.md): pub/sub weight streaming.

The training job's CheckpointManager *publishes* each committed step —
manifest digest plus CAS chunk keys — to a topic riding the
coordination store; a serving fleet *subscribes*, pulls only novel
chunks peer-to-peer with a one-durable-read-per-chunk owner election,
and hot-swaps them in behind a pointer flip. Default OFF
(``TORCHSNAPSHOT_TPU_CDN=1`` + a manager ``cdn_topic`` turns the
publish side on; subscribers are explicit objects, no knob needed).
"""

from .publisher import CdnPublisher
from .subscriber import (
    CdnSubscriber,
    CdnSyncError,
    SubscriberStats,
    durable_chunk_reader,
)
from .swap import SwapError, WeightSwapper, concat_assembler
from .topic import (
    CDN_SERVICE,
    TOPIC_PREFIX,
    Announce,
    announce_key,
    head_key,
    manifest_digest,
    read_announce,
    read_head,
    verify_chunk_bytes,
)

__all__ = [
    "Announce",
    "CDN_SERVICE",
    "CdnPublisher",
    "CdnSubscriber",
    "CdnSyncError",
    "SubscriberStats",
    "SwapError",
    "TOPIC_PREFIX",
    "WeightSwapper",
    "announce_key",
    "concat_assembler",
    "durable_chunk_reader",
    "head_key",
    "manifest_digest",
    "read_announce",
    "read_head",
    "verify_chunk_bytes",
]
