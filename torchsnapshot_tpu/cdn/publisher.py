"""The training-job side of the CDN: announce committed steps.

One :class:`CdnPublisher` per manager root per topic, driven from rank
0's post-commit hook (manager.py). Publishing is two store writes —
announce record, then head bump — with the crash point between them:
the only torn state a mid-publish kill can leave is an announce record
no subscriber will ever read (head still names the previous seq), which
the next publish simply overwrites. No barrier, no ack wait: the
training job never blocks on the serving fleet.

Publishing is strictly additive metadata — the chunks themselves were
already made durable by the commit the announce describes. Best-effort
by design: a publish failure degrades the serving fleet's freshness,
never the training job's checkpoint."""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional

from .. import telemetry
from ..chaos import crashpoint
from ..dist_store import Store
from ..telemetry import ledger
from ..telemetry import names as metric_names
from ..telemetry import wire
from ..telemetry.trace import get_recorder as _trace_recorder
from .topic import Announce, announce_key, head_key, manifest_digest, read_head

logger = logging.getLogger(__name__)

# Announce records kept behind the head. Subscribers only ever read the
# announce the head names, so one extra record is already enough slack
# for a poll that read the head just before a publish; two keeps a
# record around for post-mortem reads of "what did the previous publish
# say" without growing the store.
_ANNOUNCE_RETAIN = 2


class CdnPublisher:
    """Publish committed steps' chunk sets to one topic.

    ``root`` (the manager root URL) routes typed ledger events through
    the owned-root gate; omit it for store-only publishing (tests,
    external publishers)."""

    def __init__(
        self,
        store: Store,
        topic: str,
        publisher_id: str = "",
        root: Optional[str] = None,
    ) -> None:
        self._store = store
        self.topic = topic
        self.publisher_id = publisher_id
        self._root = root
        # Cache the head locally: the publisher is the topic's single
        # writer, so after the first read it alone knows the tip.
        self._seq: Optional[int] = None
        from .. import knobs

        self._fleet: Optional[wire.FleetReporter] = None
        if knobs.is_fleet_obs_enabled():
            self._fleet = wire.FleetReporter(
                store, "publisher", publisher_id or topic
            )

    @property
    def last_seq(self) -> int:
        if self._seq is None:
            self._seq = read_head(self._store, self.topic)
        return self._seq

    def publish(self, step: int, chunks: Dict[str, int]) -> Optional[Announce]:
        """Announce one committed step. Returns the announce, or None
        when the store rejected the writes (logged, never raised —
        freshness degrades, training does not)."""
        seq = self.last_seq + 1
        ann = Announce(
            topic=self.topic,
            seq=seq,
            step=int(step),
            digest=manifest_digest(step, chunks),
            chunks=dict(chunks),
            published_ts=time.time(),
            publisher=self.publisher_id,
        )
        encoded = ann.encode()
        try:
            with wire.propagate(
                metric_names.RPC_CDN_PUBLISH
            ), _trace_recorder().span(
                metric_names.SPAN_CDN_PUBLISH, topic=self.topic, step=int(step)
            ):
                # Announce-record-first, head-bump-second: the head is
                # the commit marker, so a kill between the writes tears
                # nothing a subscriber can observe.
                self._store.set(announce_key(self.topic, seq), encoded)
                crashpoint(metric_names.CRASH_CDN_PUBLISH_ANNOUNCED)
                # One head key per topic, overwritten in place: bounded
                # by topic count, and deleting it would un-commit the
                # topic for every subscriber.
                # snaplint: disable=store-key-leak
                self._store.set(head_key(self.topic), str(seq).encode())
        except Exception as e:  # noqa: BLE001 - never fail the training job
            logger.warning(
                "cdn: publish of step %d to topic %r failed: %r",
                step,
                self.topic,
                e,
            )
            self._seq = None  # head state unknown: re-read next publish
            return None
        self._seq = seq
        # Reap the announce that just fell out of the retention window.
        # The publisher is the topic's single writer and ``seq`` is
        # continuous across restarts (``last_seq`` re-reads the head),
        # so this one delete per publish eventually covers every record
        # ever written — the store holds at most ``_ANNOUNCE_RETAIN``
        # announces per topic instead of one per publish forever.
        if seq > _ANNOUNCE_RETAIN:
            try:
                self._store.delete(
                    announce_key(self.topic, seq - _ANNOUNCE_RETAIN)
                )
            except Exception:  # noqa: BLE001 - retention is best-effort
                pass
        registry = telemetry.metrics()
        registry.counter_inc(metric_names.CDN_PUBLISHES_TOTAL)
        registry.counter_inc(
            metric_names.CDN_ANNOUNCE_BYTES_TOTAL, float(len(encoded))
        )
        if self._fleet is not None:
            try:
                extra = {"seq": seq, "chunks": len(chunks)}
                # The training job's SLO burn rides the plane so
                # ``telemetry fleet`` shows which member is spending
                # its error budget (the BURN column).
                try:
                    from ..telemetry import slo

                    burn = slo.current_burn()
                    if burn is not None:
                        extra["slo_burn"] = round(burn, 4)
                except Exception:  # noqa: BLE001
                    pass
                self._fleet.publish(
                    phase=f"published:{int(step)}",
                    extra=extra,
                )
            except Exception:  # noqa: BLE001 - observability never blocks
                pass
        if self._root is not None:
            ledger.post_event(
                self._root,
                metric_names.EVENT_CDN_PUBLISHED,
                topic=self.topic,
                seq=seq,
                step=int(step),
                chunks=len(chunks),
                bytes_in_step=ann.bytes_in_step,
                published_ts=round(ann.published_ts, 6),
            )
        return ann

    def close(self) -> None:
        """Reap this publisher's fleet-plane snapshot (if any)."""
        if self._fleet is not None:
            try:
                self._fleet.close()
            except Exception:  # noqa: BLE001
                pass
            self._fleet = None
