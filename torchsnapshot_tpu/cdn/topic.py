"""CDN topic registry: the announce/head key schema on the store.

A topic is a tiny single-writer log riding the coordination store's
plain KV primitives — no barriers, no collectives, nothing that couples
the serving fleet to the training job's schedule:

- ``__cdn/<topic>/announce/<seq>`` — one immutable announce record per
  published step: the step number, a manifest digest, and the full CAS
  chunk set (``digest key -> nbytes``) the step's manifest references.
- ``__cdn/<topic>/head`` — the highest *fully published* sequence
  number. Written strictly AFTER the announce record (the commit-
  marker-last discipline every layer of this stack uses): a publisher
  killed between the two writes leaves a record no subscriber will
  ever observe, never a torn announce that one will.

Subscribers poll the single head key with the world-scaled
:class:`~torchsnapshot_tpu.dist_store._PollPacer` backoff, so an idle
fleet of N subscribers costs O(N) low-QPS polls, not a collective. All
keys are ordinary store keys — ``ShardedStore`` routes them by crc32
like any other, so topic traffic spreads across store shards.

The announce codec is JSON (not pickle): a serving fleet on a different
package version must be able to read a training job's announces, and a
damaged record must decode to ``None``, never to arbitrary objects.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, Optional

from ..dist_store import Store

# Key namespace on the coordination store (alongside __endpoint etc.).
TOPIC_PREFIX = "__cdn"
# Endpoint-registry service namespace for subscriber chunk servers
# (dist_store.publish_endpoint / lookup_endpoints).
CDN_SERVICE = "cdn-fleet"


def head_key(topic: str) -> str:
    return f"{TOPIC_PREFIX}/{topic}/head"


def announce_key(topic: str, seq: int) -> str:
    return f"{TOPIC_PREFIX}/{topic}/announce/{int(seq)}"


def manifest_digest(step: int, chunks: Dict[str, int]) -> str:
    """Deterministic digest of one announced step's chunk set. The
    chunk keys already embed per-chunk digests, so hashing the sorted
    key set (plus the step) commits to the full content; subscribers
    re-derive it from the decoded record to detect field-level damage
    a well-formed JSON parse would let through."""
    h = hashlib.sha256()
    h.update(str(int(step)).encode())
    for key in sorted(chunks):
        h.update(b"\0")
        h.update(key.encode())
    return h.hexdigest()


@dataclasses.dataclass
class Announce:
    """One published step, as subscribers see it."""

    topic: str
    seq: int
    step: int
    digest: str
    # CAS digest key -> nbytes (the step's full chunk set; subscribers
    # diff it against what they already hold).
    chunks: Dict[str, int]
    # Publisher wall-clock at publish: the staleness anchor. Cross-host
    # clock skew folds into every subscriber's staleness identically,
    # so the *distribution* stays comparable even when the absolute
    # numbers carry the offset.
    published_ts: float
    publisher: str = ""

    @property
    def bytes_in_step(self) -> int:
        return int(sum(self.chunks.values()))

    def encode(self) -> bytes:
        return json.dumps(
            dataclasses.asdict(self), sort_keys=True
        ).encode("utf-8")

    @classmethod
    def decode(cls, raw: bytes) -> Optional["Announce"]:
        """None for any damage — a subscriber must treat a corrupt
        record as not-yet-published, never crash on it."""
        try:
            doc = json.loads(raw.decode("utf-8"))
            ann = cls(
                topic=str(doc["topic"]),
                seq=int(doc["seq"]),
                step=int(doc["step"]),
                digest=str(doc["digest"]),
                chunks={
                    str(k): int(v) for k, v in doc["chunks"].items()
                },
                published_ts=float(doc["published_ts"]),
                publisher=str(doc.get("publisher", "")),
            )
        except (ValueError, KeyError, TypeError, UnicodeDecodeError):
            return None
        if ann.digest != manifest_digest(ann.step, ann.chunks):
            return None  # field-level damage: digest disagrees
        return ann


def read_head(store: Store, topic: str) -> int:
    """The highest fully published sequence number (0 = nothing
    published yet). Unreadable/garbage heads read as 0 — a subscriber
    facing a flaky store must idle, not crash."""
    try:
        raw = store.try_get(head_key(topic))
    except Exception:  # noqa: BLE001 - poll path must never raise
        return 0
    if raw is None:
        return 0
    try:
        return int(raw.decode())
    except (ValueError, UnicodeDecodeError):
        return 0


def read_announce(
    store: Store, topic: str, seq: int
) -> Optional[Announce]:
    try:
        raw = store.try_get(announce_key(topic, seq))
    except Exception:  # noqa: BLE001 - poll path must never raise
        return None
    if raw is None:
        return None
    return Announce.decode(raw)


def verify_chunk_bytes(key: str, data: bytes) -> bool:
    """Verify chunk bytes against the self-describing CAS digest key
    (size + whole-blob CRC — the same judgment ``fsck --cas --deep``
    applies to on-disk copies). Every byte a subscriber accepts — from
    a peer OR from durable storage — passes through this."""
    from ..cas import parse_key
    from ..integrity import _alg_available, _crc_of

    parsed = parse_key(key)
    if parsed is None:
        return False
    alg, want_n, want_crc = parsed
    if len(data) != want_n:
        return False
    if not _alg_available(alg):
        return True  # cannot judge the bytes; size is all we have
    return _crc_of(memoryview(data), alg, seed=0) == want_crc


def fleet_member_id(doc: Any) -> str:
    """A stable printable id for ledger/log fields."""
    return str(doc)
