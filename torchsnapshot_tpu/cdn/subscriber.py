"""The serving-fleet side of the CDN: track a topic, pull novel chunks.

Each subscriber runs the same peer-cache server the training tier uses
(`tiered/peer.py` — length-prefixed frames, pooled content-addressed
chunks) and advertises it in the ``cdn-fleet`` endpoint registry. On a
new announce it diffs the announced chunk set against what it already
holds, then fetches only the novel chunks with a two-tier discipline:

- **owner** — ``resharding.assign_shard_owners`` elects exactly one
  subscriber per chunk (deterministic over the announce's chunk set, so
  every fleet member computes the same table with zero coordination);
  the owner reads the chunk from durable storage ONCE and pools it.
- **everyone else** — pulls the chunk peer-to-peer from its owner's
  cache server, backing off with the world-scaled poll pacer until the
  owner has it, and falling back to durable storage only after the
  pull-timeout knob expires (a dead owner degrades to extra durable
  reads, never to a stuck fleet).

Every accepted byte — peer or durable — is verified against the chunk
key's embedded digest before it is pooled or swapped in; a fleet of N
subscribers costs ~1x durable reads per published step, not Nx.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from .. import telemetry
from ..cas import chunk_location
from ..chaos import crashpoint
from ..dist_store import (
    Store,
    _PollPacer,
    lookup_endpoints,
    publish_endpoint,
    scaled_poll_cap,
)
from ..resharding import assign_shard_owners
from ..telemetry import ledger
from ..telemetry import names as metric_names
from ..telemetry import wire
from ..telemetry.trace import get_recorder as _trace_recorder
from ..tiered.peer import PeerCache, PeerClient, PeerTransferError, _PeerServer
from .topic import CDN_SERVICE, Announce, read_announce, read_head, verify_chunk_bytes

logger = logging.getLogger(__name__)

# Opaque checksum-table stand-in for CDN-pooled chunks: integrity is
# carried by the self-describing chunk key, not a table entry.
_CDN_ENTRY = ("cdn",)


class CdnSyncError(RuntimeError):
    """A chunk could not be obtained from any tier (peer AND durable)."""


# Per-tier pull-latency samples retained per subscriber (newest kept):
# enough for a stable p95 without letting a long-lived subscriber grow
# an unbounded float list.
_PULL_LATENCY_SAMPLES = 4096


@dataclasses.dataclass
class SubscriberStats:
    """Per-subscriber byte/chunk split by serving tier, plus staleness
    samples (publish-to-swap, seconds) — the bench leg's raw signal."""

    updates_applied: int = 0
    chunks_held: int = 0
    chunks_from_peer: int = 0
    chunks_from_durable: int = 0
    bytes_from_peer: int = 0
    bytes_from_durable: int = 0
    peer_fallbacks: int = 0
    staleness_s: List[float] = dataclasses.field(default_factory=list)
    # tier ("peer" | "durable") -> pull wall-clock samples in seconds
    # (the peer samples include pacer retries — the latency the serving
    # process actually saw, not just the winning attempt).
    pull_latency_s: Dict[str, List[float]] = dataclasses.field(
        default_factory=dict
    )

    @property
    def bytes_on_wire(self) -> int:
        return self.bytes_from_peer + self.bytes_from_durable

    def observe_pull(self, tier: str, seconds: float) -> None:
        samples = self.pull_latency_s.setdefault(tier, [])
        samples.append(seconds)
        if len(samples) > _PULL_LATENCY_SAMPLES:
            del samples[: len(samples) - _PULL_LATENCY_SAMPLES]


class CdnSubscriber:
    """One serving process tracking one topic.

    ``subscriber_id`` must be unique in ``[0, fleet_size)`` — it is the
    subscriber's rank in the owner table and its slot in the endpoint
    registry. ``durable_fetch(key) -> bytes`` is the storage escape
    hatch (owners always use it; non-owners only on pull timeout); the
    bench wraps it in a counting shim to pin read amplification.
    ``cas_store`` (optional) records this subscriber's held chunk set
    as a refcount lease so the training job's GC never deletes chunks
    the fleet still serves from."""

    def __init__(
        self,
        store: Store,
        topic: str,
        subscriber_id: int,
        fleet_size: int,
        durable_fetch: Optional[Callable[[str], bytes]] = None,
        cache_budget_bytes: Optional[int] = None,
        host: str = "127.0.0.1",
        root: Optional[str] = None,
        cas_store: Optional[object] = None,
    ) -> None:
        from ..scheduler import PeerCacheBudget

        self._store = store
        self.topic = topic
        self.subscriber_id = int(subscriber_id)
        self.fleet_size = max(1, int(fleet_size))
        self._durable_fetch = durable_fetch
        self._root = root
        self._cas_store = cas_store
        self.stats = SubscriberStats()
        self.applied_seq = 0
        self.applied_step: Optional[int] = None
        self._held: Dict[str, int] = {}  # chunk key -> nbytes pooled
        self._pacer = _PollPacer(cap=scaled_poll_cap(self.fleet_size))
        self._clients: Dict[int, PeerClient] = {}
        self._cache = PeerCache(
            budget=(
                PeerCacheBudget(cache_budget_bytes)
                if cache_budget_bytes is not None
                else None
            ),
            keep_last_n=2,
        )
        self._server = _PeerServer((host, 0), self._cache)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.05},
            daemon=True,
            name=f"cdn-sub-{subscriber_id}",
        )
        self._thread.start()
        self.host, self.port = self._server.server_address[:2]
        publish_endpoint(
            store, CDN_SERVICE, self.subscriber_id, self.host, self.port
        )
        from .. import knobs

        self._fleet: Optional[wire.FleetReporter] = None
        if knobs.is_fleet_obs_enabled():
            self._fleet = wire.FleetReporter(
                store,
                "subscriber",
                str(self.subscriber_id),
                world=self.fleet_size,
            )

    # -- topic tracking --------------------------------------------------

    def poll_once(self) -> Optional[Announce]:
        """One head read: the newest unapplied announce, or None."""
        head = read_head(self._store, self.topic)
        if head <= self.applied_seq:
            return None
        return read_announce(self._store, self.topic, head)

    def wait_for_update(
        self, timeout: Optional[float] = None
    ) -> Optional[Announce]:
        """Poll the head with pacer backoff until a new announce lands
        (or the deadline passes). The cheap steady state: one key read
        per backoff interval, no collective with the publisher."""
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        self._pacer.reset()
        while True:
            ann = self.poll_once()
            if ann is not None:
                return ann
            if deadline is not None and time.monotonic() >= deadline:
                return None
            self._pacer.sleep(deadline)

    # -- chunk sync ------------------------------------------------------

    def _step_key(self, ann: Announce) -> str:
        return f"cdn/{self.topic}/{ann.seq}"

    def _client_for(self, owner: int) -> Optional[PeerClient]:
        client = self._clients.get(owner)
        if client is not None:
            return client
        endpoints = lookup_endpoints(self._store, CDN_SERVICE, [owner])
        ep = endpoints.get(owner)
        if ep is None:
            return None
        from .. import knobs

        client = PeerClient(
            ep[0], ep[1], timeout=knobs.get_cdn_pull_timeout_seconds()
        )
        self._clients[owner] = client
        return client

    def _fetch_durable(self, key: str) -> bytes:
        if self._durable_fetch is None:
            raise CdnSyncError(
                f"chunk {key}: no peer copy and no durable_fetch configured"
            )
        t0 = time.monotonic()
        data = self._durable_fetch(key)
        if not verify_chunk_bytes(key, data):
            raise CdnSyncError(f"chunk {key}: durable copy fails digest")
        self.stats.observe_pull("durable", time.monotonic() - t0)
        self.stats.chunks_from_durable += 1
        self.stats.bytes_from_durable += len(data)
        telemetry.metrics().counter_inc(
            metric_names.CDN_PULL_BYTES_TOTAL, float(len(data)), tier="durable"
        )
        return data

    def _fetch_from_peer(
        self, key: str, owner: int, step_key: str
    ) -> Optional[bytes]:
        """Pull one chunk from its owner, pacer-retried until the
        pull-timeout knob; None means every attempt missed/failed (the
        caller falls back to durable)."""
        from .. import knobs

        t0 = time.monotonic()
        deadline = t0 + knobs.get_cdn_pull_timeout_seconds()
        path = chunk_location(key)
        pacer = _PollPacer(cap=scaled_poll_cap(self.fleet_size))
        while True:
            client = self._client_for(owner)
            if client is not None:
                try:
                    found = client.pull(step_key, path)
                except PeerTransferError:
                    found = None
                if found is not None:
                    data = found[1]
                    if verify_chunk_bytes(key, data):
                        self.stats.observe_pull(
                            "peer", time.monotonic() - t0
                        )
                        self.stats.chunks_from_peer += 1
                        self.stats.bytes_from_peer += len(data)
                        telemetry.metrics().counter_inc(
                            metric_names.CDN_PULL_BYTES_TOTAL,
                            float(len(data)),
                            tier="peer",
                        )
                        return data
                    # Damaged frame: drop the connection and retry —
                    # never pool bytes the key disowns.
                    client.close()
                    self._clients.pop(owner, None)
            if time.monotonic() >= deadline:
                return None
            pacer.sleep(deadline)

    def sync(self, ann: Announce) -> Dict[str, bytes]:
        """Materialize the announce's full chunk set locally, fetching
        only what this subscriber doesn't already hold. Returns ``key
        -> bytes`` for every chunk of the step."""
        step_key = self._step_key(ann)
        out: Dict[str, bytes] = {}
        wanted = sorted(set(ann.chunks) - set(self._held))
        owners = assign_shard_owners(
            (chunk_location(k) for k in wanted), self.fleet_size
        )
        # One wire context for the whole sync round: every peer pull
        # (and the durable fallback's store frames) nests under a
        # single trace id, so the merged trace shows the round as one
        # causally-linked tree instead of unrelated per-chunk RPCs.
        with wire.propagate(metric_names.RPC_CDN_SYNC), _trace_recorder().span(
            metric_names.SPAN_CDN_SYNC,
            topic=self.topic,
            seq=ann.seq,
            novel=len(wanted),
        ):
            for key in sorted(ann.chunks):
                path = chunk_location(key)
                if key not in wanted:
                    held = self._cache.get(step_key, path)
                    if held is not None:
                        self.stats.chunks_held += 1
                        telemetry.metrics().counter_inc(
                            metric_names.CDN_CHUNKS_HELD_TOTAL
                        )
                        self._cache.put(
                            step_key, ann.step, path, _CDN_ENTRY, held[1]
                        )
                        out[key] = held[1]
                        continue
                    # Held-set bookkeeping outlived the cache copy
                    # (budget eviction): treat as novel.
                    self._held.pop(key, None)
                owner = owners.get(path, self.subscriber_id)
                if owner == self.subscriber_id:
                    data = self._fetch_durable(key)
                else:
                    data = self._fetch_from_peer(key, owner, step_key)
                    if data is None:
                        self.stats.peer_fallbacks += 1
                        data = self._fetch_durable(key)
                self._cache.put(step_key, ann.step, path, _CDN_ENTRY, data)
                self._held[key] = len(data)
                out[key] = data
        self._cache.commit(step_key, ann.step)
        return out

    # -- apply (sync + hot swap) -----------------------------------------

    def apply(self, ann: Announce, swapper: Optional[object] = None) -> bool:
        """Sync the announce and hot-swap it in. The crash point sits
        between staging and the swap — a subscriber killed there has
        staged buffers but its served weights are still the previous
        fully-applied step (no torn swap). Returns True on success."""
        chunk_bytes = self.sync(ann)
        swap_started = time.monotonic()
        if swapper is not None:
            staged = swapper.stage(ann, chunk_bytes)
            crashpoint(metric_names.CRASH_CDN_SWAP_STAGED)
            swapper.swap(staged)
        else:
            crashpoint(metric_names.CRASH_CDN_SWAP_STAGED)
        swap_s = time.monotonic() - swap_started
        self.applied_seq = ann.seq
        self.applied_step = ann.step
        staleness = max(0.0, time.time() - ann.published_ts)
        self.stats.updates_applied += 1
        self.stats.staleness_s.append(staleness)
        registry = telemetry.metrics()
        registry.counter_inc(metric_names.CDN_UPDATES_APPLIED_TOTAL)
        registry.histogram_observe(
            metric_names.CDN_STALENESS_SECONDS, staleness
        )
        registry.histogram_observe(metric_names.CDN_SWAP_SECONDS, swap_s)
        if self._fleet is not None:
            try:
                self._fleet.publish(
                    phase=f"serving:{ann.step}",
                    written_bytes=self.stats.bytes_on_wire,
                    extra={
                        "seq": ann.seq,
                        "staleness_s": round(staleness, 3),
                    },
                )
            except Exception:  # noqa: BLE001 - observability never blocks
                pass
        self._lease_held()
        if self._root is not None:
            ledger.post_event(
                self._root,
                metric_names.EVENT_CDN_SWAPPED,
                topic=self.topic,
                seq=ann.seq,
                step=ann.step,
                subscriber=self.subscriber_id,
                staleness_s=round(staleness, 6),
                swap_s=round(swap_s, 6),
                bytes_on_wire=self.stats.bytes_on_wire,
            )
        return True

    def track_once(
        self,
        swapper: Optional[object] = None,
        timeout: Optional[float] = None,
    ) -> Optional[Announce]:
        """One wait-sync-swap cycle: the storm/bench driver's unit of
        work. None when no update arrived within the timeout."""
        ann = self.wait_for_update(timeout)
        if ann is None:
            return None
        self.apply(ann, swapper)
        return ann

    # -- CAS lease (GC pin) ----------------------------------------------

    @property
    def lease_id(self) -> str:
        return f"cdn/{self.topic}/{self.subscriber_id}"

    def _lease_held(self) -> None:
        """Re-lease the currently held chunk set (replaces this
        subscriber's previous lease): the training job's GC unions
        leased chunks into its live set, so fleet-held chunks survive
        step retention. Best-effort — a lease failure risks a re-fetch
        from durable later, never a torn swap now."""
        if self._cas_store is None:
            return
        try:
            self._cas_store.lease(self.lease_id, dict(self._held))
        except Exception as e:  # noqa: BLE001
            logger.warning(
                "cdn: lease update for %r failed: %r", self.lease_id, e
            )

    def close(self, release_lease: bool = True) -> None:
        if self._fleet is not None:
            try:
                self._fleet.close()
            except Exception:  # noqa: BLE001
                pass
            self._fleet = None
        if release_lease and self._cas_store is not None:
            try:
                self._cas_store.unlease(self.lease_id)
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "cdn: unlease of %r failed: %r", self.lease_id, e
                )
        for client in self._clients.values():
            client.close()
        self._clients.clear()
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:  # noqa: BLE001
            pass
        self._thread.join(timeout=5.0)


def durable_chunk_reader(root_url: str) -> Callable[[str], bytes]:
    """A ``durable_fetch`` reading ``chunks/<key>`` from a snapshot
    root through its storage plugin (one plugin + event loop per
    reader, reused across fetches — the serving fleet's cold-start
    cost is paid once)."""
    import asyncio

    from ..io_types import ReadIO
    from ..storage_plugin import url_to_storage_plugin

    lock = threading.Lock()
    state: Dict[str, object] = {}

    def fetch(key: str) -> bytes:
        from ..cas import CHUNKS_DIRNAME

        with lock:
            if "plugin" not in state:
                state["plugin"] = url_to_storage_plugin(root_url)
                state["loop"] = asyncio.new_event_loop()
            plugin = state["plugin"]
            loop = state["loop"]
            read_io = ReadIO(path=f"{CHUNKS_DIRNAME}/{key}")
            loop.run_until_complete(plugin.read(read_io))  # type: ignore[union-attr]
            return bytes(read_io.buf)

    return fetch
