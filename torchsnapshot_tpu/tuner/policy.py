"""Rule-mapped hill climbing: doctor verdict -> bounded knob move.

Not ML — a policy table. Each doctor verdict names a *direction*
(docs/tuning.md has the same table in prose):

- ``budget-starved``: requests sat blocked on the host-memory budget —
  raise the budget fraction, then widen the staging pool.
- ``write-tail-stall``: one blob's write dominated the op — more I/O
  streams first, then O_DIRECT (a page-cache writeback storm is the
  classic single-blob tail), then smaller tail chunks so no single
  write can hold the drain hostage.
- ``storage-tier-slow``: the post-staging drain dominates — raise I/O
  concurrency, re-enable the zero-pack vectorized write if something
  turned it off, try O_DIRECT, then deepen the pool so staging can run
  further ahead.
- ``retry-storm``: the backend is throwing under load — *back off* the
  I/O concurrency.
- ``d2h-bound``: staging (D2H) is the wall — that's the physical
  ceiling; hold rather than thrash knobs that cannot move it.

With no verdict the policy explores: one round-robin parallelism move
per take (threads, streams, pool), until every candidate is saturated,
env-pinned, or cooling down after a revert. One move per take, one
step per move — the step sizes live on the tunables themselves.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from ..telemetry import names
from . import tunables

# verdict id -> ordered candidate moves (tunable short name, direction).
# First applicable candidate wins; an empty list means "hold".
VERDICT_ACTIONS: Dict[str, List[Tuple[str, int]]] = {
    names.RULE_BUDGET_STARVED: [
        ("memory_budget_fraction", +1),
        ("staging_pool_slab_bytes", +1),
        ("staging_pool_slabs", +1),
    ],
    names.RULE_WRITE_TAIL_STALL: [
        ("io_concurrency", +1),
        ("fs_direct_io", +1),
        ("max_chunk_size_bytes", -1),
    ],
    names.RULE_STORAGE_TIER_SLOW: [
        ("io_concurrency", +1),
        ("write_vectorized", +1),
        ("fs_direct_io", +1),
        ("staging_pool_slabs", +1),
    ],
    names.RULE_RETRY_STORM: [
        ("io_concurrency", -1),
    ],
    names.RULE_D2H_BOUND: [],
}

# Verdicts are consulted in this priority order (most actionable first;
# d2h-bound last so a starved-AND-d2h take still gets its budget fix).
VERDICT_PRIORITY: List[str] = [
    names.RULE_BUDGET_STARVED,
    names.RULE_WRITE_TAIL_STALL,
    names.RULE_STORAGE_TIER_SLOW,
    names.RULE_RETRY_STORM,
    names.RULE_D2H_BOUND,
]

# A reverted move is not retried for this many subsequent decisions.
COOLDOWN_DECISIONS = 8


@dataclasses.dataclass
class Decision:
    """One tuning decision, fully replayable from the log record: what
    was done (``action``: adjust | hold | revert), to which tunable, in
    which direction, from/to which value, and why (the verdict or
    reason string that named the direction)."""

    action: str
    reason: str
    tunable: Optional[str] = None
    direction: int = 0
    from_value: Optional[float] = None
    to_value: Optional[float] = None
    verdicts: List[str] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def move_key(tunable: str, direction: int) -> str:
    return f"{tunable}:{'+' if direction > 0 else '-'}"


def _applicable(
    tunable: str,
    direction: int,
    vector: Dict[str, float],
    cooldowns: Dict[str, int],
    decision_count: int,
) -> bool:
    t = tunables.TUNABLES[tunable]
    if tunables.env_pinned(tunable):
        return False
    if t.saturated(vector[tunable], direction):
        return False
    rejected_at = cooldowns.get(move_key(tunable, direction))
    if (
        rejected_at is not None
        and decision_count - rejected_at < COOLDOWN_DECISIONS
    ):
        return False
    return True


def decide(
    verdict_ids: Sequence[str],
    vector: Dict[str, float],
    cooldowns: Dict[str, int],
    decision_count: int,
    explore_idx: int,
) -> Tuple[Decision, int]:
    """Pick the next move given this take's verdicts and the current
    effective vector. Returns the decision and the advanced exploration
    index (unchanged unless an exploration move was taken)."""
    seen = set(verdict_ids)
    for rule in VERDICT_PRIORITY:
        if rule not in seen:
            continue
        candidates = VERDICT_ACTIONS[rule]
        if not candidates:
            return (
                Decision(
                    action="hold",
                    reason=f"{rule}: at the D2H ceiling",
                    verdicts=sorted(seen),
                ),
                explore_idx,
            )
        for tunable, direction in candidates:
            if _applicable(
                tunable, direction, vector, cooldowns, decision_count
            ):
                t = tunables.TUNABLES[tunable]
                return (
                    Decision(
                        action="adjust",
                        reason=rule,
                        tunable=tunable,
                        direction=direction,
                        from_value=vector[tunable],
                        to_value=t.move(vector[tunable], direction),
                        verdicts=sorted(seen),
                    ),
                    explore_idx,
                )
        return (
            Decision(
                action="hold",
                reason=f"{rule}: every mapped move saturated/pinned/cooling",
                verdicts=sorted(seen),
            ),
            explore_idx,
        )
    # No mapped verdict: explore one parallelism lever per take.
    order = tunables.explore_order()
    for i in range(len(order)):
        tunable = order[(explore_idx + i) % len(order)]
        if _applicable(tunable, +1, vector, cooldowns, decision_count):
            t = tunables.TUNABLES[tunable]
            return (
                Decision(
                    action="adjust",
                    reason="explore",
                    tunable=tunable,
                    direction=+1,
                    from_value=vector[tunable],
                    to_value=t.move(vector[tunable], +1),
                    verdicts=sorted(seen),
                ),
                (explore_idx + i + 1) % len(order),
            )
    return (
        Decision(
            action="hold",
            reason="converged: no verdicts, exploration exhausted",
            verdicts=sorted(seen),
        ),
        explore_idx,
    )
