"""Crash-safe, replayable tuner state: ``<root>/.tuner-state.json``.

Rank 0 rewrites the whole document atomically after every decision
(``telemetry.sink.atomic_write_text`` — the same primitive behind every
other rewritten telemetry artifact), so a crash mid-decision leaves the
previous complete state and a restarted run resumes from its last
applied vector instead of re-climbing from the defaults.

The document is an audit log first: every decision record carries the
step, the verdicts that named the direction, the move (tunable,
direction, from -> to), and the observed metrics — enough to replay the
whole trajectory by hand (docs/tuning.md "Replaying a decision log")
and enough for the checkpoint doctor's ``tuner-thrashing`` rule to cite
concrete oscillating entries.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Any, Dict, List, Optional

logger: logging.Logger = logging.getLogger(__name__)

TUNER_STATE_BASENAME = ".tuner-state.json"
SCHEMA_VERSION = 1

# Bounds: the newest N decision / observation records are kept. The
# observation window feeds the MAD trend math; 64 decisions is weeks of
# checkpoint cadence and keeps the file trivially small.
MAX_DECISIONS = 64
MAX_OBSERVATIONS = 64


@dataclasses.dataclass
class TunerState:
    """The autotuner's whole memory. ``vector`` is the currently-applied
    tunable vector; ``known_good`` the last vector that survived a take
    without a trend regression (the revert target); ``cooldowns`` maps
    ``tunable:+|-`` move keys to the decision index they were rejected
    at; ``observations`` the rolling per-step metric rows the MAD-based
    regression check runs over."""

    vector: Dict[str, float] = dataclasses.field(default_factory=dict)
    known_good: Dict[str, float] = dataclasses.field(default_factory=dict)
    decisions: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    observations: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list
    )
    cooldowns: Dict[str, int] = dataclasses.field(default_factory=dict)
    decision_count: int = 0
    explore_idx: int = 0
    schema_version: int = SCHEMA_VERSION

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "TunerState":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})

    def record_decision(self, record: Dict[str, Any]) -> None:
        self.decisions.append(record)
        self.decision_count += 1
        if len(self.decisions) > MAX_DECISIONS:
            self.decisions = self.decisions[-MAX_DECISIONS:]

    def record_observation(self, row: Dict[str, Any]) -> None:
        self.observations.append(row)
        if len(self.observations) > MAX_OBSERVATIONS:
            self.observations = self.observations[-MAX_OBSERVATIONS:]


def state_path_for(root: str) -> Optional[str]:
    """Where a manager root's tuner state lives, or None for
    object-store roots (like the step history, the decision log is a
    local operator aid — the tuner still runs, it just cannot persist
    its memory across restarts)."""
    from ..telemetry.sink import local_fs_root

    local = local_fs_root(root)
    if local is None:
        return None
    return os.path.join(local, TUNER_STATE_BASENAME)


def load_state(root: str) -> Optional[TunerState]:
    """The persisted state, or None when absent/non-local/corrupt (a
    corrupt file logs and restarts the climb — tuning must never fail
    a save)."""
    path = state_path_for(root)
    if path is None or not os.path.exists(path):
        return None
    try:
        with open(path, "r", encoding="utf-8") as f:
            return TunerState.from_dict(json.load(f))
    except (OSError, ValueError, TypeError) as e:
        logger.warning("tuner: corrupt state at %r (%r); restarting", path, e)
        return None


def save_state(root: str, state: TunerState) -> Optional[str]:
    """Atomic rewrite; best-effort (returns the path, or None when the
    root is non-local or the write failed)."""
    path = state_path_for(root)
    if path is None:
        return None
    try:
        from ..telemetry.sink import atomic_write_text

        atomic_write_text(
            path, json.dumps(state.to_dict(), sort_keys=True, indent=1)
        )
        return path
    except Exception as e:  # noqa: BLE001 - state persist must not fail a save
        logger.warning("tuner: could not persist state to %r: %r", path, e)
        return None
