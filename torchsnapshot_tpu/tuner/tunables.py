"""The declared tunable set: what the autotuner is allowed to touch.

Each :class:`Tunable` names one knob from ``knobs.py``'s tunable
surface, its bounds, and its (multiplicative) step factor. The tuner
never writes env vars — it installs values through
``knobs.set_tuner_override``, which the override-aware accessors read
*below* any env var of the same name, so a hand-set knob is simply
outside the tuner's reach (``env_pinned``).

Bounds are guard rails, not performance claims: they keep a runaway
hill-climb from requesting absurd geometries (a 4 GiB chunk, 1024
staging threads) regardless of what the policy decides. The staging
pool is additionally clamped so ``slabs x slab_bytes`` never exceeds
the process memory budget it is accounted against.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Union

from .. import knobs

Value = Union[int, float]

MIB = 1024 * 1024


@dataclasses.dataclass(frozen=True)
class Tunable:
    """One adjustable knob: short name (the decision-log / report key),
    the env var the override layer keys off, bounds, and the step
    factor one hill-climb move multiplies (or divides) by."""

    name: str
    env: str
    lo: Value
    hi: Value
    step: float
    kind: str = "int"  # "int" | "float"

    def clamp(self, value: Value) -> Value:
        value = min(max(value, self.lo), self.hi)
        return int(round(value)) if self.kind == "int" else float(value)

    def move(self, value: Value, direction: int) -> Value:
        """One bounded step from ``value``: up multiplies by the step
        factor, down divides. int tunables always move by at least 1 so
        a small value (e.g. 2 threads) cannot get stuck rounding back
        onto itself."""
        if direction > 0:
            moved = value * self.step
            if self.kind == "int":
                moved = max(moved, value + 1)
        else:
            moved = value / self.step
            if self.kind == "int":
                moved = min(moved, value - 1)
        return self.clamp(moved)

    def saturated(self, value: Value, direction: int) -> bool:
        return self.clamp(value) == self.move(value, direction)


# Declaration order doubles as the exploration round-robin order for the
# first three entries (policy.EXPLORE_ACTIONS).
TUNABLES: Dict[str, Tunable] = {
    t.name: t
    for t in (
        Tunable("staging_threads", knobs._STAGING_THREADS_ENV, 1, 32, 2.0),
        Tunable(
            "io_concurrency", knobs._PER_RANK_IO_CONCURRENCY_ENV, 2, 128, 2.0
        ),
        Tunable(
            "staging_pool_slab_bytes",
            knobs._STAGING_POOL_SLAB_BYTES_ENV,
            16 * MIB,
            1024 * MIB,
            2.0,
        ),
        Tunable(
            "staging_pool_slabs", knobs._STAGING_POOL_SLABS_ENV, 2, 8, 2.0
        ),
        Tunable(
            "memory_budget_fraction",
            knobs._MEMORY_BUDGET_FRACTION_ENV,
            0.2,
            0.9,
            1.25,
            kind="float",
        ),
        Tunable(
            "max_chunk_size_bytes",
            knobs._MAX_CHUNK_SIZE_BYTES_ENV,
            32 * MIB,
            2048 * MIB,
            2.0,
        ),
        Tunable(
            "max_shard_size_bytes",
            knobs._MAX_SHARD_SIZE_BYTES_ENV,
            32 * MIB,
            2048 * MIB,
            2.0,
        ),
        Tunable(
            "slab_size_threshold_bytes",
            knobs._SLAB_SIZE_THRESHOLD_BYTES_ENV,
            4 * MIB,
            512 * MIB,
            2.0,
        ),
        # Binary write-path selectors (0/1): one "up" move enables, one
        # "down" move disables — the int-move floor of +-1 makes the
        # multiplicative step degenerate into a clean toggle, and
        # revert-on-regression gives a flip that hurt its normal undo.
        Tunable("write_vectorized", knobs._WRITE_VECTORIZED_ENV, 0, 1, 2.0),
        Tunable("fs_direct_io", knobs._FS_DIRECT_IO_ENV, 0, 1, 2.0),
        # Coordination topology (docs/scaling.md): the tree barrier's
        # branching factor, and the coordination-store shard count
        # (effective at the next store bootstrap — moving it mid-run is
        # safe but inert until a new process group forms).
        Tunable("barrier_fanout", knobs._BARRIER_FANOUT_ENV, 2, 64, 2.0),
        Tunable("store_shards", knobs._STORE_SHARDS_ENV, 1, 16, 2.0),
    )
}


def env_pinned(name: str) -> bool:
    """True when the operator hand-set this tunable's env var — the
    tuner must leave it alone (env always wins)."""
    import os

    return os.environ.get(TUNABLES[name].env) is not None


def current_vector() -> Dict[str, Value]:
    """The effective value of every tunable right now (env > override >
    default) — keys align with ``knobs.tunable_snapshot()``."""
    snap = knobs.tunable_snapshot()
    return {name: snap[name] for name in TUNABLES}


def clamp_vector(
    vector: Dict[str, Value],
    memory_budget_bytes: Optional[int] = None,
) -> Dict[str, Value]:
    """Clamp a vector to the declared bounds and — when a budget is
    given — shrink the staging pool so ``slabs x slab_bytes`` fits
    inside it (slab bytes first, then the slab count, so a tiny budget
    can't be over-committed by the slab-bytes lower bound). The one
    clamp both the decision path and direct apply callers share."""
    vector = {
        name: TUNABLES[name].clamp(vector[name])
        for name in TUNABLES
        if name in vector
    }
    if memory_budget_bytes is not None and memory_budget_bytes > 0:
        slabs = int(vector.get("staging_pool_slabs", 0) or 0)
        slab_bytes = int(vector.get("staging_pool_slab_bytes", 0) or 0)
        if slabs and slab_bytes and slabs * slab_bytes > memory_budget_bytes:
            slab_bytes = int(
                TUNABLES["staging_pool_slab_bytes"].clamp(
                    memory_budget_bytes // slabs
                )
            )
            if slabs * slab_bytes > memory_budget_bytes:
                slabs = int(
                    TUNABLES["staging_pool_slabs"].clamp(
                        memory_budget_bytes // slab_bytes
                    )
                )
            vector["staging_pool_slab_bytes"] = slab_bytes
            vector["staging_pool_slabs"] = slabs
    return vector


def apply_vector(
    vector: Dict[str, Value],
    memory_budget_bytes: Optional[int] = None,
) -> Dict[str, Value]:
    """Install a decided vector through the programmatic override layer.
    Env-pinned tunables are skipped (their env value stays effective);
    everything else goes through :func:`clamp_vector`. The autotuner
    broadcasts an ALREADY-clamped vector and applies it without a
    budget here — a per-rank clamp against per-rank memory readings
    would diverge geometries across ranks. Returns the vector as
    applied (the effective values, env-pinned entries included)."""
    vector = clamp_vector(vector, memory_budget_bytes)
    for name, value in vector.items():
        if env_pinned(name):
            continue
        knobs.set_tuner_override(TUNABLES[name].env, value)
    return current_vector()


def reset_overrides() -> None:
    """Drop this process's programmatic overrides for the declared set
    (kill switch / teardown)."""
    for t in TUNABLES.values():
        knobs.clear_tuner_override(t.env)


def explore_order() -> List[str]:
    """Tunables the no-verdict exploration round-robin cycles through:
    the parallelism levers (threads, I/O streams, pool size) — the ones
    that trade host resources for pipeline overlap."""
    return ["staging_threads", "io_concurrency", "staging_pool_slab_bytes"]
