"""Doctor-driven write-path autotuner.

Closes the loop PRs 2-5 opened: the telemetry stack can *diagnose* a
slow checkpoint (per-phase timings, budget-wait fraction, doctor
verdicts, rolling history); this package wires the diagnosis to the
throttle. After every committed manager step the tuner adjusts a
declared set of tunables (tunables.py) for the next take via the
programmatic override layer in ``knobs.py`` — env vars always win,
every applied value is recorded in the SnapshotReport and the
``.tuner-state.json`` decision log, rank 0 decides and broadcasts so
ranks never run mixed geometries, and a move that regresses the take is
reverted with the same MAD trend math ``doctor --trend`` uses.

Kill switch: ``TORCHSNAPSHOT_TPU_AUTOTUNE=0``. See docs/tuning.md.
"""

from __future__ import annotations

from .autotuner import Autotuner, observation_from_report
from .policy import COOLDOWN_DECISIONS, Decision, VERDICT_ACTIONS, decide
from .state import (
    TUNER_STATE_BASENAME,
    TunerState,
    load_state,
    save_state,
    state_path_for,
)
from .tunables import (
    TUNABLES,
    Tunable,
    apply_vector,
    current_vector,
    env_pinned,
    reset_overrides,
)

__all__ = [
    "Autotuner",
    "COOLDOWN_DECISIONS",
    "Decision",
    "TUNABLES",
    "TUNER_STATE_BASENAME",
    "Tunable",
    "TunerState",
    "VERDICT_ACTIONS",
    "apply_vector",
    "current_vector",
    "decide",
    "env_pinned",
    "load_state",
    "observation_from_report",
    "reset_overrides",
    "save_state",
    "state_path_for",
]
