"""The closed-loop write-path autotuner.

Each recurring checkpoint is one training example: after a manager step
commits, the tuner reads the step's just-emitted SnapshotReport, runs
the checkpoint doctor's report-scope rules over it, consults its own
rolling observation window, and decides ONE bounded move for the *next*
take (policy.py). Rank 0 decides; the decided vector is broadcast over
the ``dist_store`` coordinator and applied identically on every rank —
ranks never run mixed geometries (pinned by test).

Guard rails:

- **env always wins** — a hand-set knob is simply outside the tuner's
  reach (tunables.env_pinned);
- **bounded steps** — one move per take, one declared step factor per
  move, values clamped to declared bounds and the staging pool to the
  process memory budget;
- **revert-on-regression** — after an adjust, the next observation is
  checked against the rolling median ± MAD baseline with the exact
  trend math ``doctor --trend`` ships
  (``history.detect_trend_regressions``); a flagged ``take_s`` /
  ``mb_s`` restores the prior known-good vector and puts the offending
  move on cooldown;
- **crash-safe, replayable** — every decision lands in
  ``<root>/.tuner-state.json`` (state.py) before it takes effect.

Kill switch: ``TORCHSNAPSHOT_TPU_AUTOTUNE=0`` — the manager never
constructs an Autotuner (no state reads/writes, no broadcast, no
overrides; byte-identical to a build without the tuner).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, Optional

from ..telemetry.history import TREND_WINDOW, detect_trend_regressions
from . import policy, state as tuner_state, tunables

logger: logging.Logger = logging.getLogger(__name__)

# The regression check watches the metrics a bad knob move actually
# damages: wall clock up, throughput down.
REGRESSION_METRICS = ("take_s", "mb_s")


def observation_from_report(
    step: int, report_dict: Dict[str, Any]
) -> Dict[str, Any]:
    """One rolling-window row from a take's SnapshotReport dict — the
    same metric keys ``history.summarize_report`` records, so the MAD
    trend math reads both identically."""
    from ..telemetry import safe_rate_mb_s

    phases = dict(report_dict.get("phases") or {})
    take_s = max((float(v) for v in phases.values()), default=0.0)
    return {
        "step": step,
        "kind": report_dict.get("kind"),
        "take_s": round(take_s, 3),
        "phases": phases,
        "bytes_moved": report_dict.get("bytes_moved", 0),
        "mb_s": round(
            safe_rate_mb_s(report_dict.get("bytes_moved", 0), take_s), 3
        ),
        "budget_wait_s": float(report_dict.get("budget_wait_s", 0.0)),
        "visible_s": report_dict.get("visible_s"),
        "tunables": dict(report_dict.get("tunables") or {}),
    }


class Autotuner:
    """One per CheckpointManager. ``tune_after_step`` is the only entry
    point; it is called on every rank after every committed step."""

    def __init__(self, root: str) -> None:
        self.root = root
        self._state: Optional[tuner_state.TunerState] = None

    # -- rank-0 decision --------------------------------------------------

    def _load_or_init(self) -> tuner_state.TunerState:
        if self._state is None:
            loaded = tuner_state.load_state(self.root)
            if loaded is None:
                vec = tunables.current_vector()
                loaded = tuner_state.TunerState(
                    vector=dict(vec), known_good=dict(vec)
                )
            self._state = loaded
        return self._state

    def _regressed(
        self, st: tuner_state.TunerState, row: Dict[str, Any]
    ) -> Optional[Dict[str, Any]]:
        """The new row against the rolling baseline of prior
        observations — the same median ± MAD math as ``doctor --trend``.
        Returns the first flagged evidence row (take_s/mb_s only), or
        None."""
        records = st.observations + [row]
        new_index = len(records) - 1
        for flagged in detect_trend_regressions(records, window=TREND_WINDOW):
            if (
                flagged["index"] == new_index
                and flagged["metric"] in REGRESSION_METRICS
            ):
                return flagged
        return None

    def _decide(
        self,
        step: int,
        report_dict: Optional[Dict[str, Any]],
        memory_budget_bytes: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Rank 0's half: observe, maybe revert, else consult the
        policy; clamp (bounds + pool-vs-budget); log the decision;
        return the vector to broadcast."""
        st = self._load_or_init()
        if not st.vector:
            st.vector = dict(tunables.current_vector())
            st.known_good = dict(st.vector)
        if report_dict is None:
            # Nothing observed (telemetry failed?): hold the vector.
            return dict(st.vector)
        row = observation_from_report(step, report_dict)

        last = st.decisions[-1] if st.decisions else None
        regression = None
        if last is not None and last["decision"]["action"] == "adjust":
            regression = self._regressed(st, row)

        if regression is not None:
            prev = last["decision"]
            decision = policy.Decision(
                action="revert",
                reason=(
                    f"regression on {regression['metric']} "
                    f"({regression['value']} vs baseline median "
                    f"{regression['baseline_median']}) after "
                    f"{prev['tunable']}"
                    f"{'+' if prev['direction'] > 0 else '-'}"
                ),
                tunable=prev["tunable"],
                direction=-prev["direction"],
                from_value=st.vector.get(prev["tunable"]),
                to_value=st.known_good.get(prev["tunable"]),
            )
            st.cooldowns[
                policy.move_key(prev["tunable"], prev["direction"])
            ] = st.decision_count
            st.vector = dict(st.known_good)
        else:
            # The current vector survived its first observation: it is
            # the new known-good (the revert target).
            st.known_good = dict(st.vector)
            verdict_ids = self._verdicts(report_dict)
            decision, st.explore_idx = policy.decide(
                verdict_ids,
                st.vector,
                st.cooldowns,
                st.decision_count,
                st.explore_idx,
            )
            if decision.action == "adjust":
                st.vector[decision.tunable] = decision.to_value

        # Clamp ONCE here, against rank 0's (symmetrically measured)
        # budget: the clamped vector is what gets logged, broadcast,
        # and applied verbatim everywhere.
        st.vector = tunables.clamp_vector(st.vector, memory_budget_bytes)
        st.record_observation(row)
        st.record_decision(
            {
                "step": step,
                "unix_ts": round(time.time(), 3),
                "decision": decision.to_dict(),
                "vector": dict(st.vector),
                "observed": {
                    "take_s": row["take_s"],
                    "mb_s": row["mb_s"],
                    "budget_wait_s": row["budget_wait_s"],
                },
            }
        )
        tuner_state.save_state(self.root, st)
        logger.info(
            "autotuner step %d: %s %s (%s)",
            step,
            decision.action,
            decision.tunable or "",
            decision.reason,
        )
        return dict(st.vector)

    @staticmethod
    def _verdicts(report_dict: Dict[str, Any]) -> list:
        from ..telemetry import doctor

        return [v.rule for v in doctor.diagnose_reports([report_dict])]

    # -- every-rank entry point -------------------------------------------

    def tune_after_step(
        self, step: int, report: Optional[Any], pg_wrapper: Any
    ) -> Optional[Dict[str, Any]]:
        """Decide (rank 0), broadcast, apply. ``report`` is rank 0's
        SnapshotReport for the step (ignored elsewhere). Every rank that
        committed the step must call this — the broadcast is symmetric
        whether or not rank 0 produced a decision (a failed decision
        broadcasts the unchanged vector). Returns the vector as applied
        on this rank."""
        from ..scheduler import get_process_memory_budget_bytes

        # Measured on EVERY rank (the local_world_size hostname
        # exchange inside is symmetric store traffic all ranks must
        # reach); only rank 0's reading is used — it clamps the decided
        # vector, so ranks apply one geometry even when their memory
        # readings differ.
        try:
            budget = get_process_memory_budget_bytes(pg_wrapper)
        except Exception as e:  # noqa: BLE001 - clamp input is best-effort
            logger.warning("autotuner: budget measurement failed: %r", e)
            budget = None
        decided: Optional[Dict[str, Any]] = None
        if pg_wrapper.get_rank() == 0:
            try:
                report_dict = (
                    report.to_dict()
                    if report is not None and hasattr(report, "to_dict")
                    else report
                )
                decided = self._decide(
                    step, report_dict, memory_budget_bytes=budget
                )
            except Exception as e:  # noqa: BLE001 - tuning never fails a save
                logger.warning("autotuner: decision failed: %r", e)
                decided = None
        if pg_wrapper.get_world_size() > 1:
            # Store-based broadcast (never a collective): safe on the
            # async-save commit thread, same transport every other
            # rank-0-decides path in the manager uses.
            decided = pg_wrapper.broadcast_object(decided)
        if decided is None:
            return None
        return tunables.apply_vector(decided)
