"""Distributed test harness, shipped as part of the package.

Reference parity: torchsnapshot/test_utils.py (290 LoC). The load-bearing
trick there is ``@run_with_pet(nproc=N)`` relaunching a test under
torchelastic with a gloo rendezvous so N-rank semantics run on one CPU box
(test_utils.py:205-238). The TPU-native equivalent fans out plain
``multiprocessing`` spawn workers that rendezvous on a :class:`TCPStore`
hosted by rank 0 — no cluster, no torch. Workers run on the CPU backend (the
coordination layer never touches devices; array content tests pair this with
the 8-device virtual mesh).

Also exports the equality/rand helpers the reference ships
(assert_state_dict_eq / rand_tensor analogs, test_utils.py:72-144).
"""

from __future__ import annotations

import functools
import multiprocessing as mp
import os
import pickle
import socket
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .dist_store import ProcessGroup, Store, TCPStore  # noqa: F401 - re-export


def get_free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def faulty_fs_plugin(
    should_fail: Callable[[str], bool],
    ops: Sequence[str] = ("write",),
    exc_msg: str = "injected storage failure",
    delay_s: float = 0.0,
    mode: str = "fail",
    seed: int = 0,
):
    """An ``FSStoragePlugin`` subclass whose listed ``ops`` ("write",
    "read", "delete" — "write"/"read" covering their fused
    ``*_with_checksum`` variants too, which the chaos wrapper declines
    so every op funnels through the injected path) misbehave when
    ``should_fail(io.path)`` is truthy.

    Since the chaos engine landed this is a thin shim over ONE fault
    plan (chaos/plan.py): each listed op becomes a predicate-triggered
    :class:`~torchsnapshot_tpu.chaos.FaultSpec`, so the crash tests and
    the declarative fault plans replay through the same mechanism.
    ``mode`` extends the legacy raise-only behavior:

    - ``"fail"`` (default): raise ``OSError(exc_msg)``, after
      ``delay_s`` if set — byte-compatible with the legacy shim.
    - ``"corrupt"``: size-preserving bit damage (written bytes or read
      buffer) — only digest verification catches it.
    - ``"delay"``: sleep ``delay_s``, then proceed normally.
    - plus any other chaos mode (``"torn"``, ``"drop"``, ``"crash"``).

    ``should_fail`` may filter by path (data blobs only) or close over
    a counter (fault at the N-th storage op). Pair with
    :func:`patch_storage_plugin`. Returns the subclass; its
    ``chaos_engine`` attribute exposes the backing engine (the
    ``fired`` log pins replay determinism)."""
    from .chaos import ChaosEngine, FaultPlan, FaultSpec, chaotic_plugin_type
    from .storage_plugins.fs import FSStoragePlugin

    point_of = {
        "write": "storage-write",
        "read": "storage-read",
        "delete": "storage-delete",
    }
    # "fail" keeps the legacy shape: an optional sleep and then the
    # raise. Chaos-wise that is mode="delay"+raise, which plain "fail"
    # specs don't model — so a failing spec with delay keeps delay_s
    # and the engine path sleeps before raising via the "fail" arm
    # below (asyncio.sleep lives in the injectors).
    plan = FaultPlan(
        seed=seed,
        faults=[
            FaultSpec(
                point=point_of[op],
                mode=mode,
                times=None,
                predicate=should_fail,
                exc_msg=exc_msg,
                delay_s=delay_s,
            )
            for op in ops
        ],
    )
    engine = ChaosEngine(plan)
    cls = chaotic_plugin_type(FSStoragePlugin, engine)
    cls.chaos_engine = engine
    return cls


def patch_storage_plugin(cls):
    """Route ``Snapshot``'s plugin resolution to ``cls`` for the scope of
    the returned context manager."""
    from unittest import mock

    return mock.patch(
        "torchsnapshot_tpu.snapshot.url_to_storage_plugin",
        side_effect=lambda url: cls(root=url.split("://")[-1]),
    )


class ByteCountingStore(Store):
    """Delegating store wrapper that meters this rank's coordination
    traffic: payload bytes sent (``set`` values) and received (``try_get``
    results). Used by the manifest-gather scale test and the
    protocol-traffic benchmark to prove non-leader ranks pay O(own
    manifest), not O(world x manifest)."""

    def __init__(self, inner: Store) -> None:
        self.inner = inner
        self.sent_bytes = 0
        self.received_bytes = 0

    def set(self, key: str, value: bytes) -> None:
        self.sent_bytes += len(value)
        self.inner.set(key, value)

    def try_get(self, key: str):
        out = self.inner.try_get(key)
        if out is not None:
            self.received_bytes += len(out)
        return out

    def add(self, key: str, amount: int) -> int:
        return self.inner.add(key, amount)

    def delete(self, key: str) -> None:
        self.inner.delete(key)


def _worker_main(
    conn,
    fn_module: str,
    fn_qualname: str,
    fn_file: Optional[str],
    rank: int,
    world_size: int,
    port: int,
    args: bytes,
) -> None:
    try:
        # Workers must not grab the (single-tenant) TPU chip; pin them to
        # the CPU backend. The environment's sitecustomize pre-imports jax
        # with the TPU platform in jax.config (env vars are ignored), so the
        # config must be updated too — before any backend is created.
        os.environ["JAX_PLATFORMS"] = "cpu"
        import importlib
        import sys

        import jax

        jax.config.update("jax_platforms", "cpu")

        if fn_file is not None:
            sys.path.insert(0, os.path.dirname(os.path.abspath(fn_file)))
        module = importlib.import_module(fn_module)
        fn = module
        for part in fn_qualname.split("."):
            fn = getattr(fn, part)
        fn = getattr(fn, "_ts_inner_fn", fn)

        store = TCPStore("127.0.0.1", port, is_server=(rank == 0))
        pg = ProcessGroup(store=store, rank=rank, world_size=world_size)
        extra_args, extra_kwargs = pickle.loads(args)
        result = fn(pg, *extra_args, **extra_kwargs)
        conn.send(("ok", pickle.dumps(result)))
        # Rank 0 hosts the store server: no worker may exit until every
        # worker reported, or stragglers' store ops hit a dead socket. The
        # parent acks once all results are in.
        conn.recv()
    except BaseException as e:  # noqa: BLE001 - reported to the parent
        conn.send(("error", f"rank {rank}: {e!r}\n{traceback.format_exc()}"))
    finally:
        conn.close()


def run_multiprocess(
    fn: Callable[..., Any],
    nproc: int,
    args: Sequence[Any] = (),
    kwargs: Optional[Dict[str, Any]] = None,
    timeout: float = 180.0,
    port: Optional[int] = None,
) -> List[Any]:
    """Run ``fn(pg, *args, **kwargs)`` in ``nproc`` spawned processes with a
    shared TCP store; returns per-rank results, raises on any rank failure.

    ``fn`` must be a module-level callable (spawned workers re-import it by
    qualified name, the same constraint as the reference's launch pad,
    test_utils.py:221-224). Callers juggling additional listeners should
    pass an explicit ``port`` allocated alongside theirs (two sequential
    get_free_port calls can return the same just-released port).
    """
    if port is None:
        port = get_free_port()
    ctx = mp.get_context("spawn")
    payload = pickle.dumps((tuple(args), kwargs or {}))
    import importlib

    fn_file = getattr(
        importlib.import_module(fn.__module__), "__file__", None
    )
    procs = []
    conns = []
    for rank in range(nproc):
        parent_conn, child_conn = ctx.Pipe()
        p = ctx.Process(
            target=_worker_main,
            args=(
                child_conn,
                fn.__module__,
                fn.__qualname__,
                fn_file,
                rank,
                nproc,
                port,
                payload,
            ),
            daemon=True,
        )
        p.start()
        procs.append(p)
        conns.append(parent_conn)

    results: List[Any] = [None] * nproc
    errors: List[str] = []
    for rank, conn in enumerate(conns):
        if conn.poll(timeout):
            status, payload_out = conn.recv()
            if status == "ok":
                results[rank] = pickle.loads(payload_out)
            else:
                errors.append(payload_out)
        else:
            errors.append(f"rank {rank}: timed out after {timeout}s")
    # Release the workers only after every rank reported (the rank-0 worker
    # hosts the store server for the others).
    for conn in conns:
        try:
            conn.send("exit")
        except (BrokenPipeError, OSError):
            pass
    for p in procs:
        p.join(timeout=10)
        if p.is_alive():
            p.terminate()
    if errors:
        raise AssertionError(
            "Multiprocess run failed:\n" + "\n".join(errors)
        )
    return results


def multiprocess_test(nproc: int):
    """Decorator: ``@multiprocess_test(nproc=2)`` turns
    ``def test_x(pg): ...`` into a fan-out test (reference ``run_with_pet``,
    test_utils.py:227-265)."""

    def deco(fn: Callable[..., Any]) -> Callable[..., Any]:
        # No functools.wraps: pytest would follow __wrapped__ and treat the
        # inner function's ``pg`` parameter as a fixture. The inner function
        # is re-imported by workers via the _ts_inner_fn attribute instead.
        def wrapper() -> None:
            # Per-rank return values are discarded: pytest warns on tests
            # returning non-None. Use run_multiprocess directly when the
            # rank results matter.
            run_multiprocess(wrapper, nproc=nproc)

        wrapper.__name__ = fn.__name__
        wrapper.__qualname__ = fn.__qualname__
        wrapper.__module__ = fn.__module__
        wrapper.__doc__ = fn.__doc__
        wrapper._ts_inner_fn = fn
        return wrapper

    return deco


def drive_preemption_loop(
    pg,
    saver,
    save_fn: Callable[[int], None],
    evict_rank: int,
    evict_step: int = 2,
    steps: int = 200,
    pace_s: float = 0.02,
) -> Optional[int]:
    """Shared preemption-agreement exercise: run a paced step loop, inject
    an eviction notice on one rank, save via ``save_fn(step)`` at the
    agreed step; returns it (None if no agreement fired). The pacing is
    load-bearing — real steps take wall time on every rank; without it an
    unflagged rank exhausts its loop before the flag even lands."""
    import time

    saved_at: Optional[int] = None
    for step in range(steps):
        time.sleep(pace_s)
        if pg.rank == evict_rank and step == evict_step:
            saver.request_save()
        if saver.should_save(step):
            save_fn(step)
            saved_at = step
            break
    saver.close()
    return saved_at


# ---------------------------------------------------------------------------
# Equality / random-data helpers
# ---------------------------------------------------------------------------


def _to_comparable(x: Any) -> Any:
    if hasattr(x, "__array__"):
        return np.asarray(x)
    return x


def tree_eq(a: Any, b: Any) -> bool:
    """Deep equality over nested dict/list structures with array leaves
    (reference check_state_dict_eq, test_utils.py:95-101)."""
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a) == set(b) and all(tree_eq(a[k], b[k]) for k in a)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(tree_eq(x, y) for x, y in zip(a, b))
    ca, cb = _to_comparable(a), _to_comparable(b)
    if isinstance(ca, np.ndarray) or isinstance(cb, np.ndarray):
        ca, cb = np.asarray(ca), np.asarray(cb)
        return (
            ca.shape == cb.shape
            and ca.dtype == cb.dtype
            and bool(np.array_equal(ca, cb))
        )
    return bool(ca == cb)


def assert_tree_eq(a: Any, b: Any) -> None:
    if not tree_eq(a, b):
        raise AssertionError(f"Trees differ:\n{a!r}\n---\n{b!r}")


def rand_array(shape: Sequence[int], dtype: Any = "float32", seed: int = 0):
    """Random array covering the full supported dtype table (reference
    rand_tensor, test_utils.py:104-144)."""
    rng = np.random.default_rng(seed)
    dt = np.dtype(dtype)
    if dt.kind == "b":
        return rng.integers(0, 2, shape).astype(bool)
    if dt.kind in "iu" or dt.name in ("int4", "uint4"):
        return rng.integers(0, 8, shape).astype(dt)
    if dt.kind == "c":
        return (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)).astype(dt)
    return rng.standard_normal(shape).astype(dt)


@functools.lru_cache(maxsize=None)
def backend_materializes_dtype(dtype_str: str) -> bool:
    """True when the current jax backend can materialize + transfer arrays
    of this dtype. Some dev backends (e.g. the tunneled axon TPU) raise
    UNIMPLEMENTED for float16/fp8/complex programs; dtype-matrix tests
    skip those cases there (they run fully on CPU and real TPU hosts).

    Off CPU the probe runs in a SUBPROCESS: a failed program can wedge
    the tunnel client for the rest of the parent process (even
    ``jax.random.PRNGKey`` starts raising UNIMPLEMENTED, and
    clear_backends does not recover), so the parent must never attempt
    the materialization itself.
    """
    import jax

    if jax.default_backend() == "cpu":
        import jax.numpy as jnp
        import numpy as np

        try:
            np.asarray(jnp.zeros((1,), dtype_str))
            return True
        except Exception:
            return False

    import subprocess
    import sys

    parent_backend = jax.default_backend()
    # Exit codes: 0 = materializable, 1 = dtype UNIMPLEMENTED, 3 = child
    # could not reach the parent's backend (single-process accelerators):
    # then we cannot know, and the useful default is True — real TPU
    # hosts support the full matrix; skipping everything there would
    # silently hollow out the dtype tests.
    code = "\n".join(
        [
            "import sys",
            "import jax",
            f"if jax.default_backend() != {parent_backend!r}:",
            "    sys.exit(3)",
            "import jax.numpy as jnp",
            "import numpy as np",
            "try:",
            f"    np.asarray(jnp.zeros((1,), {dtype_str!r}))",
            "except Exception:",
            "    sys.exit(1)",
        ]
    )
    env = dict(os.environ, JAX_PLATFORMS=parent_backend)
    try:
        rc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            timeout=180,
            env=env,
        ).returncode
    except Exception:
        return True  # probe infrastructure failure: assume supported
    if rc == 1:
        return False
    return True
