"""Content-addressed chunk store: layout, digest keys, refcount journal.

The store is a sibling ``chunks/`` directory next to a manager root's
``step_*`` directories, holding every data blob exactly once, keyed by
the content digest the integrity layer already computes
(``integrity.compute_checksum_entry``). Manifest entries reference
chunks through ordinary parent-relative locations
(``../chunks/<digest>``) — the exact mechanism incremental snapshots
already use for ``../step_*/...`` refs — so **restore, fsck, checksum
verification, ranged reads, the tiered fallback and the mirror all
resolve chunk refs with zero new read-path code**: every storage plugin
already resolves ``../`` lexically.

Digest keys embed the algorithm, byte length, whole-blob CRC and (for
multi-page blobs) a fold of the per-page CRCs::

    cas-crc32c-<nbytes hex>-<crc 8hex>[-p<page-fold 8hex>]

Two blobs collide only at equal length AND equal CRC32-C (~2^-32 per
equal-sized candidate pair; multi-page blobs add 32 more bits via the
page fold). Restore-side verification cannot catch a true collision
(the digests match by construction), which is the inherent trade of
CRC-keyed content addressing — a deployment wanting cryptographic
certainty would swap the key derivation here for a strong hash; every
other part of the subsystem is digest-agnostic.

**Refcounts** are step-level pins in an append-only journal
(``chunks/.refcounts.jsonl``), written only by the manager's rank-0
commit path — single writer, one short line per record, so a kill
mid-append leaves at most one torn tail line which load skips and the
next append heals (the ``.ledger.jsonl`` discipline). The journal is a
*cache* of manifest-derivable truth: a committed step's chunk refs are
exactly the ``../chunks/`` locations in its manifest, so a lost or
stale journal is rebuilt from the index + manifests
(:meth:`CASStore.reconcile`) — crash between chunk write and refcount
append heals on the next manager load.

**GC** deletes a chunk when no pinned step references it AND its mtime
is older than the grace window (``TORCHSNAPSHOT_TPU_CAS_GC_GRACE_SECONDS``).
The grace window is what makes concurrent take + GC safe: a take that
dedups against an existing chunk *touches* it (mtime) before relying on
it, so an in-flight (not-yet-pinned) step's chunks are always younger
than the grace window when a concurrent GC pass runs. Dead-but-young
chunks are deferred as journaled orphans and reclaimed by a later pass.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
import time
import zlib
from typing import Dict, Iterable, List, Optional, Set, Tuple

logger: logging.Logger = logging.getLogger(__name__)

CHUNKS_DIRNAME = "chunks"
# Manifest locations of chunk blobs are step-relative parent refs —
# resolved lexically by every storage plugin, like incremental refs.
CHUNK_LOCATION_PREFIX = "../" + CHUNKS_DIRNAME + "/"
# The per-rank path -> digest maps each writing rank commits next to
# its checksum table (read back by rank 0's manifest rewrite).
CAS_MAP_DIR = "cas"
REFCOUNTS_BASENAME = ".refcounts.jsonl"

_KEY_PREFIX = "cas-"

# Serializes journal appends/rewrites within the process (rank 0 is the
# only writer across processes; tests run several managers in-process).
_JOURNAL_LOCK = threading.RLock()


def digest_key(entry: Tuple) -> str:
    """The chunk store key for one integrity-table entry
    (``(alg, crc, nbytes)`` or the paged form). Deterministic, filename-
    safe, and self-describing: the key embeds the byte length (so a
    partial chunk left by a crash can never satisfy an existence check)
    and the digest (so fsck verifies a chunk's bytes against its own
    name)."""
    alg, crc, nbytes = entry[0], entry[1], int(entry[2])
    crc_val = int(crc) & 0xFFFFFFFF if crc is not None else 0
    key = f"{_KEY_PREFIX}{alg}-{nbytes:x}-{crc_val:08x}"
    if len(entry) >= 5 and entry[4]:
        fold = (
            zlib.crc32(
                b"".join(
                    struct.pack("<I", int(p) & 0xFFFFFFFF) for p in entry[4]
                )
            )
            & 0xFFFFFFFF
        )
        key += f"-p{fold:08x}"
    return key


def is_chunk_key(name: str) -> bool:
    return name.startswith(_KEY_PREFIX)


def is_chunk_location(location: str) -> bool:
    """True for manifest/storage locations that address a chunk blob
    (step-relative ``../chunks/<key>``)."""
    return location.startswith(CHUNK_LOCATION_PREFIX)


def chunk_location(key: str) -> str:
    """The step-relative storage location of a chunk."""
    return CHUNK_LOCATION_PREFIX + key


def key_of_location(location: str) -> Optional[str]:
    if not is_chunk_location(location):
        return None
    key = location[len(CHUNK_LOCATION_PREFIX) :]
    return key if is_chunk_key(key) and "/" not in key else None


def nbytes_of_key(key: str) -> Optional[int]:
    """The byte length a chunk key claims (embedded at key derivation),
    or None for a malformed key."""
    parts = key.split("-")
    # cas-<alg>-<nbytes>-<crc>[-p<fold>]
    if len(parts) < 4 or parts[0] != "cas":
        return None
    try:
        return int(parts[2], 16)
    except ValueError:
        return None


def parse_key(key: str) -> Optional[Tuple[str, int, int]]:
    """``(alg, nbytes, crc)`` from a chunk key, or None."""
    parts = key.split("-")
    if len(parts) < 4 or parts[0] != "cas":
        return None
    try:
        return parts[1], int(parts[2], 16), int(parts[3], 16)
    except ValueError:
        return None


def chunk_refs(manifest) -> Dict[str, int]:
    """Every chunk a manifest references: ``digest key -> nbytes``
    (length decoded from the key itself — a manifest is a complete
    refcount input on its own, no side table needed)."""
    from ..manifest import entry_locations

    out: Dict[str, int] = {}
    for entry in manifest.values():
        for location in entry_locations(entry):
            key = key_of_location(location)
            if key is not None:
                out[key] = nbytes_of_key(key) or 0
    return out


def root_url_of_snapshot(path_url: str) -> str:
    """The manager-root URL a snapshot path's chunk store hangs off:
    the parent directory, per tier for ``tiered://`` URLs."""
    from ..storage_plugin import split_tiered_url

    tiers = split_tiered_url(path_url)
    if tiers is not None:
        fast, durable = tiers
        return (
            f"tiered://{_parent_of_url(fast)}|{_parent_of_url(durable)}"
        )
    return _parent_of_url(path_url)


def _parent_of_url(url: str) -> str:
    if "://" in url:
        scheme, _, path = url.partition("://")
        return f"{scheme}://{os.path.dirname(path.rstrip('/'))}"
    return os.path.dirname(os.path.abspath(url.rstrip("/")))


def local_chunks_dir(root_url: str) -> Optional[str]:
    """The local filesystem directory of a root's chunk store, or None
    when the root has no local tier (object-store roots — ineligible
    for CAS; the journal and existence checks need a local fs)."""
    from ..telemetry.sink import local_fs_root

    local = local_fs_root(root_url)
    if local is None:
        return None
    return os.path.join(local, CHUNKS_DIRNAME)


def cas_eligible(path_url: str) -> bool:
    """Whether the CAS layout can serve a snapshot at ``path_url``:
    the knob is on AND the root resolves to a local filesystem tier
    (fs, or tiered with an fs fast tier). Object-store-only roots fall
    back to the legacy layout with a one-time warning — their lexical
    ``../`` resolution would serve reads, but the refcount journal and
    dedup existence checks are local-fs constructs."""
    from .. import knobs

    if not knobs.is_cas_enabled():
        return False
    try:
        root = root_url_of_snapshot(path_url)
    except ValueError:
        return False
    if local_chunks_dir(root) is None:
        _warn_ineligible_once(path_url)
        return False
    return True


_WARNED_INELIGIBLE = False


def _warn_ineligible_once(path_url: str) -> None:
    global _WARNED_INELIGIBLE
    if not _WARNED_INELIGIBLE:
        _WARNED_INELIGIBLE = True
        logger.warning(
            "TORCHSNAPSHOT_TPU_CAS is on but %r has no local filesystem "
            "tier; taking snapshots in the legacy (non-deduplicated) "
            "layout",
            path_url,
        )


class CASStore:
    """Rank-0 view of one root's chunk store: the refcount journal and
    the chunk-file inventory. All journal mutation happens here (the
    manager's commit path); writers of chunk *bytes* never touch it."""

    def __init__(self, root_url: str) -> None:
        self.root_url = root_url
        local = local_chunks_dir(root_url)
        if local is None:
            raise ValueError(
                f"{root_url!r} has no local filesystem tier; the CAS "
                f"refcount journal requires one"
            )
        self.local_dir = local
        self.journal_path = os.path.join(local, REFCOUNTS_BASENAME)

    # -- journal ---------------------------------------------------------

    def load(self) -> Tuple[Dict[int, Dict[str, int]], Dict[str, int]]:
        """``(pins, orphans)`` from the journal; a torn tail line (kill
        mid-append) is skipped — the next append heals it."""
        pins, orphans, _ = self.load_full()
        return pins, orphans

    def load_full(
        self,
    ) -> Tuple[
        Dict[int, Dict[str, int]], Dict[str, int], Dict[str, Dict[str, int]]
    ]:
        """``(pins, orphans, leases)``. Leases are non-step pins — a
        CDN subscriber's (or any external reader's) held chunk set,
        keyed by lease id; the newest lease record per id wins (a
        re-lease IS the release of the chunks the new set dropped)."""
        pins: Dict[int, Dict[str, int]] = {}
        orphans: Dict[str, int] = {}
        leases: Dict[str, Dict[str, int]] = {}
        try:
            with open(self.journal_path, "r", encoding="utf-8") as f:
                raw = f.read()
        except OSError:
            return pins, orphans, leases
        for line in raw.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn append; heals on next write
            op = rec.get("op")
            if op == "pin":
                pins[int(rec["step"])] = {
                    str(k): int(v) for k, v in rec.get("chunks", {}).items()
                }
            elif op == "unpin":
                pins.pop(int(rec["step"]), None)
            elif op == "orphan":
                for k, v in rec.get("chunks", {}).items():
                    orphans[str(k)] = int(v)
            elif op == "unorphan":
                for k in rec.get("chunks", []):
                    orphans.pop(str(k), None)
            elif op == "lease":
                leases[str(rec["id"])] = {
                    str(k): int(v) for k, v in rec.get("chunks", {}).items()
                }
            elif op == "unlease":
                leases.pop(str(rec["id"]), None)
        return pins, orphans, leases

    def _append(self, record: Dict) -> None:
        with _JOURNAL_LOCK:
            os.makedirs(self.local_dir, exist_ok=True)
            heal = ""
            try:
                with open(self.journal_path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    if f.read(1) not in (b"\n", b""):
                        heal = "\n"  # torn tail from a previous crash
            except OSError:
                pass
            line = heal + json.dumps(record, sort_keys=True) + "\n"
            with open(self.journal_path, "a", encoding="utf-8") as f:
                f.write(line)

    def pin(self, step: int, chunks: Dict[str, int]) -> None:
        self._append({"op": "pin", "step": int(step), "chunks": chunks})

    def unpin(self, step: int) -> None:
        self._append({"op": "unpin", "step": int(step)})

    def lease(self, lease_id: str, chunks: Dict[str, int]) -> None:
        """Pin ``chunks`` outside step retention under ``lease_id`` (a
        CDN subscriber's held set, an external reader's working set).
        Replaces this id's previous lease — callers re-lease their full
        current set, they never diff."""
        self._append(
            {"op": "lease", "id": str(lease_id), "chunks": chunks}
        )

    def unlease(self, lease_id: str) -> None:
        self._append({"op": "unlease", "id": str(lease_id)})

    def record_orphans(self, chunks: Dict[str, int]) -> None:
        if chunks:
            self._append({"op": "orphan", "chunks": chunks})

    def clear_orphans(self, keys: Iterable[str]) -> None:
        keys = sorted(keys)
        if keys:
            self._append({"op": "unorphan", "chunks": keys})

    def maybe_compact(self, max_bytes: int = 256 * 1024) -> None:
        """Opportunistic journal compaction: once the append log outgrows
        ``max_bytes``, rewrite it to the canonical state (one pin record
        per live step + one orphan record)."""
        try:
            if os.path.getsize(self.journal_path) <= max_bytes:
                return
        except OSError:
            return
        pins, orphans = self.load()
        self.compact(pins, orphans)

    def compact(
        self,
        pins: Dict[int, Dict[str, int]],
        orphans: Dict[str, int],
        leases: Optional[Dict[str, Dict[str, int]]] = None,
    ) -> None:
        """Atomic rewrite to the canonical state (bounds journal growth
        over long runs; called opportunistically by the manager's GC).
        Leases default to whatever the journal currently holds — a
        compaction driven by step state must never drop a subscriber's
        outstanding pin."""
        with _JOURNAL_LOCK:
            if leases is None:
                _, _, leases = self.load_full()
            os.makedirs(self.local_dir, exist_ok=True)
            tmp = self.journal_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                for step in sorted(pins):
                    f.write(
                        json.dumps(
                            {"op": "pin", "step": step, "chunks": pins[step]},
                            sort_keys=True,
                        )
                        + "\n"
                    )
                for lease_id in sorted(leases):
                    f.write(
                        json.dumps(
                            {
                                "op": "lease",
                                "id": lease_id,
                                "chunks": leases[lease_id],
                            },
                            sort_keys=True,
                        )
                        + "\n"
                    )
                if orphans:
                    f.write(
                        json.dumps(
                            {"op": "orphan", "chunks": orphans},
                            sort_keys=True,
                        )
                        + "\n"
                    )
            os.replace(tmp, self.journal_path)

    # -- inventory -------------------------------------------------------

    @staticmethod
    def live_chunks(
        pins: Dict[int, Dict[str, int]],
        leases: Optional[Dict[str, Dict[str, int]]] = None,
    ) -> Set[str]:
        """Chunks GC must not delete: every step-pinned chunk, plus —
        when ``leases`` is given — every chunk a lease still holds
        (a serving fleet's copy source outlives step retention)."""
        live: Set[str] = set()
        for chunks in pins.values():
            live.update(chunks)
        for chunks in (leases or {}).values():
            live.update(chunks)
        return live

    def list_chunks(self) -> Dict[str, int]:
        """``key -> on-disk byte size`` of every chunk file present
        locally (the journal and tmp files excluded)."""
        out: Dict[str, int] = {}
        try:
            names = os.listdir(self.local_dir)
        except OSError:
            return out
        for name in names:
            if not is_chunk_key(name):
                continue
            try:
                out[name] = os.path.getsize(
                    os.path.join(self.local_dir, name)
                )
            except OSError:
                continue
        return out

    def chunk_age_seconds(self, key: str) -> Optional[float]:
        try:
            return max(
                0.0,
                time.time()
                - os.path.getmtime(os.path.join(self.local_dir, key)),
            )
        except OSError:
            return None

    # -- reconcile (crash healing) --------------------------------------

    def reconcile(self, indexed: Dict[int, Dict[str, int]]) -> bool:
        """Bring the journal in line with manifest-derived truth:
        ``indexed`` maps every committed-or-pinned step to its chunk
        refs. Steps missing a pin record get one (the crash-between-
        chunk-write-and-refcount-append heal); pinned steps no longer in
        the index are unpinned (their chunks become GC candidates).
        Returns True when anything changed."""
        pins, orphans = self.load()
        changed = False
        for step, chunks in indexed.items():
            if not chunks:
                # A legacy-layout step: its absence from the journal IS
                # the canonical state (pins exist only for chunky steps).
                if step in pins:
                    self.unpin(step)
                    changed = True
                continue
            if pins.get(step) != chunks:
                self.pin(step, chunks)
                changed = True
        for step in list(pins):
            if step not in indexed:
                self.unpin(step)
                changed = True
        return changed
