"""Content-addressed chunk store (docs/cas.md).

Blobs keyed by the integrity layer's content digest live once in a
root-level ``chunks/`` directory; manifests reference them through
ordinary parent-relative locations, the manager refcounts them through
a crash-safe journal, and the mirror/peer tiers ship only chunks their
destination doesn't hold. ``TORCHSNAPSHOT_TPU_CAS=1`` turns the layout
on for new takes; either layout restores everywhere.
"""

from .plugin import (
    CASStoragePlugin,
    chunk_map_path,
    is_data_path,
    load_chunk_maps,
    maybe_rewrite_manifest,
    rewrite_manifest_locations,
)
from .store import (
    CAS_MAP_DIR,
    CHUNK_LOCATION_PREFIX,
    CHUNKS_DIRNAME,
    REFCOUNTS_BASENAME,
    CASStore,
    cas_eligible,
    chunk_location,
    chunk_refs,
    digest_key,
    is_chunk_key,
    is_chunk_location,
    key_of_location,
    local_chunks_dir,
    nbytes_of_key,
    parse_key,
    root_url_of_snapshot,
)

__all__ = [
    "CAS_MAP_DIR",
    "CHUNK_LOCATION_PREFIX",
    "CHUNKS_DIRNAME",
    "REFCOUNTS_BASENAME",
    "CASStore",
    "CASStoragePlugin",
    "cas_eligible",
    "chunk_location",
    "chunk_map_path",
    "chunk_refs",
    "digest_key",
    "is_chunk_key",
    "is_chunk_location",
    "is_data_path",
    "key_of_location",
    "load_chunk_maps",
    "local_chunks_dir",
    "maybe_rewrite_manifest",
    "nbytes_of_key",
    "parse_key",
    "rewrite_manifest_locations",
    "root_url_of_snapshot",
]
