"""CAS write interception and the rank-0 manifest rewrite.

``CASStoragePlugin`` wraps a take's storage plugin. Data-blob writes
(``{rank}/...``, ``sharded/...``, ``replicated/...``, ``batched/...``)
are *diverted*: the integrity entry is computed first (one pass over the
bytes — the same entry the checksum table records, so nothing is hashed
twice), the digest key derived, and the bytes written to
``../chunks/<key>`` **only if the store does not already hold that
key** — dedup across steps, across replicated ranks (identical bytes
from any writer resolve to one stored blob; concurrent same-key writers
are idempotent because the content is the key), and across consumers
(the mirror and peer tier see the chunk once). Control blobs
(``.snapshot_metadata``, ``checksums/``, telemetry dotfiles) pass
through untouched.

The manifest fix-up happens once, on rank 0, at commit time: every
writing rank persists its ``path -> digest`` map as ``cas/{rank}``
before the commit barrier (next to its checksum table), and rank 0's
metadata write reads the maps back and rewrites entry locations to
``../chunks/<key>`` — after which the snapshot is indistinguishable
from any other parent-ref-bearing snapshot to every reader. A rank
whose knob/skew kept CAS off simply contributes no map, and its paths
stay step-local: the two layouts compose per blob.

Crash safety of the chunk write itself: the digest key embeds the byte
length, and the existence check requires an exact on-disk size match —
a partial chunk left by a kill mid-write can never satisfy dedup and is
simply overwritten by the next writer of the same content. Dedup hits
*touch* the chunk's mtime, which is what the manager GC's grace window
keys off (an in-flight step's reused chunks are always fresh).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
from typing import Dict, Optional, Tuple

from .. import telemetry
from ..integrity import compute_checksum_entry
from ..io_types import ReadIO, StoragePlugin, WriteIO, payload_nbytes
from ..telemetry import names as metric_names
from .store import (
    CAS_MAP_DIR,
    CHUNKS_DIRNAME,
    chunk_location,
    digest_key,
    local_chunks_dir,
    root_url_of_snapshot,
)

logger: logging.Logger = logging.getLogger(__name__)

# Entries at or under this size are hashed inline on the event loop;
# larger ones hop to an executor (same threshold rationale as the
# scheduler's checksum_off_slot).
_INLINE_DIGEST_BYTES = 1 * 1024 * 1024

_CONTROL_TOP_SEGMENTS = frozenset(("checksums", CAS_MAP_DIR, CHUNKS_DIRNAME))


def is_data_path(path: str) -> bool:
    """Paths whose bytes belong in the chunk store: everything a take's
    write pipeline emits except control/metadata blobs (dotfiles,
    checksum tables, the cas maps themselves)."""
    if path.startswith("../"):
        return False
    first = path.split("/", 1)[0]
    if not first or first.startswith("."):
        return False
    return first not in _CONTROL_TOP_SEGMENTS


def chunk_map_path(rank: int) -> str:
    return f"{CAS_MAP_DIR}/{rank}"


class CASStoragePlugin(StoragePlugin):
    """Write-side CAS interception for one take. Reads, deletes and
    control writes delegate to the inner plugin unchanged."""

    def __init__(self, inner: StoragePlugin, snapshot_url: str) -> None:
        self.inner = inner
        self.snapshot_url = snapshot_url
        root_url = root_url_of_snapshot(snapshot_url)
        local = local_chunks_dir(root_url)
        assert local is not None  # gated by cas_eligible at install
        self._local_dir = local
        # original write path -> (digest key, nbytes, newly written?)
        self.records: Dict[str, Tuple[str, int, bool]] = {}
        self._written_keys: set = set()

    # -- capability passthrough -----------------------------------------

    @property
    def supports_multibuffer(self) -> bool:  # type: ignore[override]
        return getattr(self.inner, "supports_multibuffer", False)

    # -- writes ----------------------------------------------------------

    async def _entry_of(self, buf) -> Tuple:
        if payload_nbytes(buf) <= _INLINE_DIGEST_BYTES:
            return compute_checksum_entry(buf)
        return await asyncio.get_running_loop().run_in_executor(
            None, compute_checksum_entry, buf
        )

    def _has(self, key: str, nbytes: int) -> bool:
        """Exact-size local existence check; a hit touches the chunk's
        mtime (the GC grace window's liveness signal)."""
        if key in self._written_keys:
            return True
        path = os.path.join(self._local_dir, key)
        try:
            if os.path.getsize(path) != nbytes:
                return False
        except OSError:
            return False
        try:
            os.utime(path)
        except OSError:
            pass  # touch is best-effort; grace default dwarfs a take
        return True

    async def _divert(self, write_io: WriteIO, entry: Tuple) -> None:
        key = digest_key(entry)
        nbytes = payload_nbytes(write_io.buf)
        registry = telemetry.metrics()
        if self._has(key, nbytes):
            write_io.variant = "deduped"
            self.records[write_io.path] = (key, nbytes, False)
            # Tiered roots: a dedup hit writes nothing, but this step's
            # durability still covers the chunk — if its original
            # writer crashed before mirroring, no other job would ever
            # ship it. Record it for mirror enqueue; the durable-side
            # probe skips already-held chunks at one ranged byte each.
            note = getattr(self.inner, "note_written", None)
            if note is not None:
                note(chunk_location(key), nbytes)
            registry.counter_inc(metric_names.CAS_CHUNKS_DEDUPED_TOTAL)
            registry.counter_inc(
                metric_names.CAS_BYTES_DEDUPED_TOTAL, nbytes
            )
            return
        inner_io = WriteIO(path=chunk_location(key), buf=write_io.buf)
        await self.inner.write(inner_io)
        write_io.variant = inner_io.variant
        self._written_keys.add(key)
        self.records[write_io.path] = (key, nbytes, True)
        registry.counter_inc(metric_names.CAS_CHUNKS_WRITTEN_TOTAL)
        registry.counter_inc(metric_names.CAS_BYTES_WRITTEN_TOTAL, nbytes)
        # Kill point: the chunk's bytes exist, but no map/manifest/pin
        # references them yet — the stray-sweep + grace-window case the
        # crash matrix must prove safe.
        from ..chaos import crashpoint

        crashpoint(metric_names.CRASH_CAS_CHUNK_WRITTEN)

    async def write(self, write_io: WriteIO) -> None:
        if not is_data_path(write_io.path):
            await self.inner.write(write_io)
            return
        # Checksums may be globally disabled, but content addressing IS
        # a digest: compute the entry regardless (it just stays out of
        # the table).
        entry = await self._entry_of(write_io.buf)
        await self._divert(write_io, entry)

    async def write_with_checksum(self, write_io: WriteIO):
        if not is_data_path(write_io.path):
            return await self.inner.write_with_checksum(write_io)
        # The digest must exist BEFORE the bytes can be addressed, so
        # the fused single-pass kernel cannot serve CAS writes; the
        # entry computed here doubles as the table entry, so the total
        # hash work is unchanged (one pass).
        entry = await self._entry_of(write_io.buf)
        await self._divert(write_io, entry)
        return entry

    # -- reads / deletes / close: delegate -------------------------------

    async def read(self, read_io: ReadIO) -> None:
        await self.inner.read(read_io)

    async def read_with_checksum(self, read_io: ReadIO):
        return await self.inner.read_with_checksum(read_io)

    async def read_degraded(self, read_io: ReadIO) -> bool:
        return await self.inner.read_degraded(read_io)

    async def delete(self, path: str) -> None:
        await self.inner.delete(path)

    async def close(self) -> None:
        await self.inner.close()

    # -- take-commit plumbing --------------------------------------------

    def rekey_checksums(self, checksums: Dict[str, Tuple]) -> None:
        """Re-home this rank's checksum-table entries from the original
        write paths to the chunk locations the manifest will name, so
        restore-time verification keys match read paths. Runs in the
        checksum finalizer, before the table is persisted."""
        for orig, (key, _nbytes, _new) in self.records.items():
            entry = checksums.pop(orig, None)
            if entry is not None:
                checksums[chunk_location(key)] = entry

    async def write_chunk_map(self, rank: int) -> None:
        """Persist this rank's ``path -> digest`` map (``cas/{rank}``)
        — the input of rank 0's manifest rewrite; committed alongside
        the checksum table, before the commit barrier."""
        if not self.records:
            return
        doc = {
            "paths": {
                path: {"k": key, "n": nbytes, "new": new}
                for path, (key, nbytes, new) in sorted(self.records.items())
            }
        }
        await self.inner.write(
            WriteIO(
                path=chunk_map_path(rank),
                buf=json.dumps(doc, sort_keys=True).encode(),
            )
        )


async def load_chunk_maps(
    storage: StoragePlugin, world_size: int
) -> Dict[str, Tuple[str, int, bool]]:
    """Merge every rank's committed ``cas/{rank}`` map:
    ``original path -> (digest key, nbytes, newly written)``. Ranks
    without a map (nothing diverted — empty rank, or CAS off there)
    contribute nothing; the rewrite is per-blob."""
    merged: Dict[str, Tuple[str, int, bool]] = {}
    for rank in range(world_size):
        read_io = ReadIO(path=chunk_map_path(rank))
        try:
            await storage.read(read_io)
        except FileNotFoundError:
            continue
        try:
            doc = json.loads(bytes(read_io.buf))
        except ValueError as e:
            # A corrupt map would leave this rank's manifest entries
            # pointing at step-local paths holding no bytes — fail the
            # commit loudly rather than commit a broken snapshot.
            raise RuntimeError(
                f"CAS chunk map {chunk_map_path(rank)} is unparseable"
            ) from e
        for path, rec in doc.get("paths", {}).items():
            prev = merged.get(path)
            new = bool(rec.get("new")) or bool(prev and prev[2])
            merged[path] = (str(rec["k"]), int(rec["n"]), new)
    return merged


def rewrite_manifest_locations(
    manifest, merged: Dict[str, Tuple[str, int, bool]]
) -> int:
    """Point every manifest entry whose original location appears in
    ``merged`` at its chunk (``../chunks/<key>``), preserving byte
    ranges (batched-slab members share one chunk and keep their
    windows). Returns the number of locations rewritten."""
    from ..manifest import ChunkedArrayEntry, ShardedArrayEntry

    rewritten = 0

    def _fix(dense) -> None:
        nonlocal rewritten
        hit = merged.get(dense.location)
        if hit is not None:
            dense.location = chunk_location(hit[0])
            rewritten += 1

    for entry in manifest.values():
        if isinstance(entry, ShardedArrayEntry):
            for shard in entry.shards:
                _fix(shard.array)
        elif isinstance(entry, ChunkedArrayEntry):
            for chunk in entry.chunks:
                _fix(chunk.array)
        elif getattr(entry, "location", None) is not None:
            _fix(entry)
    return rewritten


async def maybe_rewrite_manifest(metadata, storage: StoragePlugin) -> None:
    """Rank-0 commit hook: when the take ran through a CAS wrapper,
    fold every rank's chunk map into the global manifest before the
    metadata blob is written. No-op for legacy takes."""
    if not isinstance(storage, CASStoragePlugin):
        return
    merged = await load_chunk_maps(storage, metadata.world_size)
    if merged:
        n = rewrite_manifest_locations(metadata.manifest, merged)
        logger.debug(
            "CAS commit: rewrote %d manifest locations onto %d chunks",
            n,
            len({k for k, _, _ in merged.values()}),
        )
