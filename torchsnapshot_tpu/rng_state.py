"""RNG state capture for JAX programs.

Reference parity: torchsnapshot/rng_state.py:13-38 (``RNGState`` wrapping
``torch.get_rng_state``). JAX has no global RNG — randomness flows through
explicit ``jax.random`` keys — so the TPU-native equivalent holds the user's
current key(s). ``Snapshot.take`` treats at most one :class:`RngState` in the
app state specially: it is saved first and restored afterwards so taking a
snapshot has no RNG side effect (reference invariant: snapshot.py:340-346,
858-877). With explicit keys there is no hidden global to protect, but the
ordering contract is preserved so the semantics match.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .state_dict import pytree_to_state_dict, state_dict_to_pytree

_KEY_DATA = "__prng_key_data__"


def _is_typed_key(leaf: Any) -> bool:
    import jax

    return hasattr(leaf, "dtype") and jax.dtypes.issubdtype(
        leaf.dtype, jax.dtypes.prng_key
    )


def _encode_keys(keys: Any) -> Any:
    """Map typed PRNG-key leaves to serializable {key_data, impl} records."""
    import jax

    def conv(leaf: Any) -> Any:
        if _is_typed_key(leaf):
            return {
                _KEY_DATA: np.asarray(jax.random.key_data(leaf)),
                "impl": str(jax.random.key_impl(leaf)),
            }
        return leaf

    return jax.tree_util.tree_map(conv, keys)


class RngState:
    """Stateful holding one or more ``jax.random`` keys (any key pytree).

    Raw uint32 keys are plain arrays and serialize via the regular array
    path; typed keys (``jax.random.key``) are persisted as their key data
    plus impl name and re-wrapped on restore. ``.keys`` holds the live
    pytree; after ``restore`` it contains the checkpointed keys.
    """

    def __init__(self, keys: Any) -> None:
        self.keys = keys

    def state_dict(self) -> Dict[str, Any]:
        return {"keys": pytree_to_state_dict(_encode_keys(self.keys))}

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        import jax

        target = _encode_keys(self.keys)
        restored = state_dict_to_pytree(state_dict["keys"], target)

        def unconv(x: Any) -> Any:
            if isinstance(x, dict) and _KEY_DATA in x:
                return jax.random.wrap_key_data(
                    np.asarray(x[_KEY_DATA]), impl=x["impl"]
                )
            return x

        self.keys = jax.tree_util.tree_map(
            unconv,
            restored,
            is_leaf=lambda x: isinstance(x, dict) and _KEY_DATA in x,
        )


# Alias matching the reference class name (torchsnapshot/rng_state.py:13).
RNGState = RngState
