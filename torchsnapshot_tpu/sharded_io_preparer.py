"""Sharded-array preparer: GSPMD-partitioned ``jax.Array`` checkpointing
with elastic resharding on restore.

Reference parity: ShardedTensorIOPreparer (io_preparer.py:167-391) — but
where the reference walks torch ``ShardedTensor`` chunk specs on one
dimension, a single ``NamedSharding``-driven preparer covers every GSPMD
layout uniformly (FSDP, TP, row/column-wise embedding sharding, sequence-dim
sharding, replicated × sharded mixes, uneven remainders): the analysis in
SURVEY.md §2.12.

Write side:
- ``addressable_shards`` yields this process's device shards; exactly one
  *global* copy of each distinct shard box is written, elected by
  ``replica_id == 0`` (each box's replica-0 device lives on exactly one
  process, so no coordination round is needed for deduplication — the
  write-once analog of the reference's replicated partitioning).
- Boxes larger than the shard-size knob subdivide along dim 0 (reference
  subdivide_shard, io_preparer.py:168-198).
- The device→host DMA is started asynchronously at prepare time
  (``copy_to_host_async``), so all shards' transfers overlap each other and
  storage I/O.

Read side (resharding):
- The destination layout comes from the *current* leaf's sharding (or a
  host array for ``read_object``); every persisted shard that overlaps a
  locally-addressable destination box is read once and its overlap regions
  copied out (reference groups reads the same way, io_preparer.py:317-391).
- When an overlap is a contiguous row range of the saved shard, a ranged
  read fetches only those bytes.
- ``finalize`` assembles the restored host boxes into a ``jax.Array`` via
  ``jax.make_array_from_single_device_arrays`` — one H2D per addressable
  device, no full-array host materialization.
"""

from __future__ import annotations

import asyncio
from concurrent.futures import Executor
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .io_types import BufferConsumer, BufferType, ReadReq, WriteReq
from .manifest import ArrayEntry, Shard, ShardedArrayEntry
from .resharding import (
    Box,
    Overlap,
    box_overlap,
    plan_row_slab_reads,
    subdivide_box,
    target_boxes_for_sharding,
)
from .serialization import (
    Serializer,
    array_from_memoryview,
    array_size_bytes,
    dtype_to_string,
)
from .telemetry import names as metric_names
from .utils.tracing import trace_annotation


# Sentinel: assembly was registered into a placement batch and will land
# via its deferred callback, not the immediate return value.
_DEFERRED = object()


def _shard_location(logical_path: str, box: Box) -> str:
    """Storage path for one shard box: ``sharded/{path}_{offsets}``
    (reference uses a ``sharded/`` prefix too, io_preparer.py:849-855)."""
    suffix = "_".join(str(o) for o in box.offsets) or "scalar"
    return f"sharded/{logical_path}_{suffix}"


class _OverlapConsumer(BufferConsumer):
    """Deserializes one saved shard (or a row range of it) and copies every
    overlap region into its destination view (reference
    ShardedTensorBufferConsumer, io_preparer.py:460-492)."""

    def __init__(
        self,
        dtype: str,
        buf_shape: Tuple[int, ...],
        copies: List[Tuple[np.ndarray, Tuple[slice, ...]]],
        dest_owned: bool = False,
    ) -> None:
        self.dtype = dtype
        self.buf_shape = buf_shape
        self.copies = copies  # (dst_view, src_slices into the read buffer)
        self.dest_owned = dest_owned

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(executor, self._consume_sync, buf)

    def _consume_sync(self, buf: BufferType) -> None:
        with trace_annotation(metric_names.SPAN_LEAF_CONSUME):
            src = array_from_memoryview(buf, self.dtype, self.buf_shape)
            for dst_view, src_slices in self.copies:
                np.copyto(dst_view, src[src_slices], casting="no")

    def get_consuming_cost_bytes(self) -> int:
        return array_size_bytes(self.buf_shape, self.dtype)

    def destination_nbytes(self) -> int:
        """Bytes of destination this consumer actually fills — the
        read-amplification denominator (``bytes_needed``). Distinct
        from the consuming cost: a whole-shard read serving a partial
        destination has a buffer larger than the bytes it delivers,
        and that gap is exactly what the doctor's
        ``restore-read-amplified`` rule exists to see."""
        return sum(int(v.nbytes) for v, _ in self.copies)

    def direct_destination(self) -> Optional[memoryview]:
        # Direct read only when this is a straight whole-buffer copy into
        # one framework-owned destination view (the no-resharding fast
        # path); user-owned in-place arrays keep copy-on-success semantics.
        if not self.dest_owned:
            return None
        if len(self.copies) != 1:
            return None
        dst_view, src_slices = self.copies[0]
        if tuple(dst_view.shape) != self.buf_shape or src_slices != tuple(
            slice(0, s) for s in self.buf_shape
        ):
            return None
        from .serialization import try_writable_byte_view

        if dtype_to_string(dst_view.dtype) != self.dtype:
            return None
        return try_writable_byte_view(dst_view)


class ShardedArrayIOPreparer:
    # ------------------------------------------------------------------
    # write
    # ------------------------------------------------------------------

    @staticmethod
    def prepare_write(
        obj: Any,
        logical_path: str,
        is_async_snapshot: bool,
        array_prepare_func: Optional[Callable[..., Any]] = None,
        incremental: Optional[Any] = None,
    ) -> Tuple[ShardedArrayEntry, List[WriteReq]]:
        dtype_str = dtype_to_string(obj.dtype)
        itemsize = np.dtype(obj.dtype).itemsize
        shards: List[Shard] = []
        write_reqs: List[WriteReq] = []

        from .io_preparer import (
            ArrayBufferStager,
            effective_max_shard_size_bytes,
        )

        max_shard = effective_max_shard_size_bytes(incremental)

        for dev_shard in obj.addressable_shards:
            # Write-once election: the replica-0 copy of each box exists on
            # exactly one device globally.
            if dev_shard.replica_id != 0:
                continue
            box = Box.from_index(dev_shard.index, obj.shape)
            for piece in subdivide_box(box, max_shard, itemsize):
                if incremental is not None:
                    # Unchanged since the incremental base: reference its
                    # blob; no stager, no D2H for this piece.
                    ref = incremental.ref_entry(
                        piece.offsets, piece.sizes, False
                    )
                    if ref is not None:
                        shards.append(
                            Shard(
                                offsets=list(piece.offsets),
                                sizes=list(piece.sizes),
                                array=ref,
                            )
                        )
                        continue
                location = _shard_location(logical_path, piece)
                slc: Optional[slice] = None
                if piece != box:
                    row0 = piece.offsets[0] - box.offsets[0]
                    slc = slice(row0, row0 + piece.sizes[0])
                shards.append(
                    Shard(
                        offsets=list(piece.offsets),
                        sizes=list(piece.sizes),
                        array=ArrayEntry(
                            location=location,
                            serializer=Serializer.BUFFER_PROTOCOL.value,
                            dtype=dtype_str,
                            shape=list(piece.sizes),
                            replicated=False,
                            digest=(
                                incremental.digest_for(
                                    piece.offsets, piece.sizes
                                )
                                if incremental is not None
                                else None
                            ),
                        ),
                    )
                )
                # ArrayBufferStager prefetches D2H only for whole-shard
                # writes (slc None); subdivided pieces transfer lazily so
                # the shard-size knob's memory bound holds.
                write_reqs.append(
                    WriteReq(
                        path=location,
                        buffer_stager=ArrayBufferStager(
                            dev_shard.data,
                            is_async_snapshot,
                            slc=slc,
                            array_prepare_func=array_prepare_func,
                        ),
                    )
                )

        entry = ShardedArrayEntry(
            dtype=dtype_str,
            shape=[int(d) for d in obj.shape],
            shards=shards,
        )
        return entry, write_reqs

    # ------------------------------------------------------------------
    # read
    # ------------------------------------------------------------------

    @staticmethod
    def _sharding_destination(
        sharding: Any, shape: Tuple[int, ...], np_dtype: Any
    ) -> Tuple[
        Dict[Box, np.ndarray],
        Callable[..., Any],
        bool,
    ]:
        """Destination boxes + assembler for an arbitrary target
        ``Sharding`` over ``shape`` — the elastic core: the sharding
        need not match the one the array was saved under, nor the saved
        world size (each process only allocates/assembles the boxes its
        addressable devices cover)."""
        import jax

        groups = target_boxes_for_sharding(sharding, shape)
        boxes: Dict[Box, np.ndarray] = {
            box: np.empty(box.sizes, dtype=np_dtype) for box in groups
        }
        device_to_box: Dict[Any, Box] = {
            device: box for box, devices in groups.items() for device in devices
        }

        def assemble(
            filled: Dict[Box, np.ndarray], batch=None, on_done=None
        ) -> Any:
            # One batched H2D dispatch for all shards (a per-device
            # device_put loop pays per-call dispatch latency 8x over);
            # with a shared ``batch`` the shards ride the restore-wide
            # dispatch instead, and assembly defers until it runs.
            devices = list(device_to_box)
            if batch is not None and on_done is not None:
                slots = [
                    batch.put(filled[device_to_box[d]], d) for d in devices
                ]
                batch.defer(
                    lambda: on_done(
                        jax.make_array_from_single_device_arrays(
                            shape, sharding, [s.value for s in slots]
                        )
                    )
                )
                return _DEFERRED
            arrays = jax.device_put(
                [filled[device_to_box[d]] for d in devices], devices
            )
            return jax.make_array_from_single_device_arrays(
                shape, sharding, arrays
            )

        return boxes, assemble, True

    @staticmethod
    def _destination_boxes(
        entry: ShardedArrayEntry,
        current_leaf: Any,
        target_sharding: Optional[Any] = None,
    ) -> Tuple[
        Dict[Box, np.ndarray],
        Optional[Callable[[Dict[Box, np.ndarray]], Any]],
        bool,
    ]:
        """Host buffers to read into, keyed by destination box, plus an
        assembler back to the application's leaf flavor, plus whether the
        buffers are framework-allocated (owned) — only owned buffers may be
        direct-read targets; a user's in-place array must keep
        copy-on-success semantics so a failed restore never tears it.
        An explicit ``target_sharding`` wins over the current leaf's
        layout (restore-into-a-new-topology without a template leaf)."""
        from .serialization import string_to_dtype

        np_dtype = string_to_dtype(entry.dtype)
        shape = tuple(entry.shape)

        from .io_preparer import is_jax_array

        if target_sharding is not None:
            return ShardedArrayIOPreparer._sharding_destination(
                target_sharding, shape, np_dtype
            )

        if is_jax_array(current_leaf):
            sharding = current_leaf.sharding
            target_shape = tuple(current_leaf.shape)
            if target_shape != shape:
                raise ValueError(
                    f"Cannot reshard a saved array of shape {list(shape)} "
                    f"into a leaf of shape {list(target_shape)}"
                )

            # Uncommitted destination leaves (e.g. optax step counters
            # created by plain jnp ops) must stay uncommitted — the same
            # rule as snapshot._restore_destination: committing them to a
            # concrete device makes the restored state unusable in a jit
            # alongside differently-placed arrays. An uncommitted array is
            # single-device by construction, so it has exactly one box.
            if not getattr(current_leaf, "_committed", True):
                groups = target_boxes_for_sharding(sharding, shape)
                if len(groups) == 1:
                    boxes = {
                        box: np.empty(box.sizes, dtype=np_dtype)
                        for box in groups
                    }

                    def assemble_uncommitted(
                        filled: Dict[Box, np.ndarray], batch=None, on_done=None
                    ) -> Any:
                        import jax.numpy as jnp

                        return jnp.asarray(next(iter(filled.values())))

                    return boxes, assemble_uncommitted, True

            return ShardedArrayIOPreparer._sharding_destination(
                sharding, shape, np_dtype
            )

        # Host destination (np.ndarray in-place, or fresh allocation).
        if isinstance(current_leaf, np.ndarray):
            if tuple(current_leaf.shape) != shape or current_leaf.dtype != np_dtype:
                raise ValueError(
                    f"Destination array (shape {current_leaf.shape}, dtype "
                    f"{current_leaf.dtype}) does not match saved sharded "
                    f"array (shape {list(shape)}, dtype {entry.dtype})"
                )
            full = current_leaf
            owned = False
        else:
            full = np.empty(shape, dtype=np_dtype)
            owned = True
        full_box = Box(tuple(0 for _ in shape), shape)
        return (
            {full_box: full},
            (lambda filled, batch=None, on_done=None: filled[full_box]),
            owned,
        )

    @staticmethod
    def prepare_read_into(
        entry: ShardedArrayEntry,
        current_leaf: Any,
        restored: Dict[str, Any],
        path: str,
        buffer_size_limit_bytes: Optional[int] = None,
        dest_owned: Optional[bool] = None,
        target_sharding: Optional[Any] = None,
    ) -> Tuple[List[ReadReq], Optional[Callable[[], None]]]:
        """Build resharding reads into ``restored[path]``; the returned
        finalize callback must run after the reads complete. ``dest_owned``
        overrides the derived ownership (a caller reading into a buffer it
        allocated itself may declare it framework-owned to keep direct
        reads). ``target_sharding`` restores under an arbitrary jax
        ``Sharding`` — any layout, any world size — regardless of what
        ``current_leaf`` is (the template-free elastic entry point)."""
        boxes, assemble, derived_owned = ShardedArrayIOPreparer._destination_boxes(
            entry, current_leaf, target_sharding=target_sharding
        )
        if dest_owned is None:
            dest_owned = derived_owned
        read_reqs: List[ReadReq] = []

        for saved in entry.shards:
            saved_box = Box(tuple(saved.offsets), tuple(saved.sizes))
            overlaps: List[Tuple[np.ndarray, Overlap]] = []
            for dst_box, dst_buf in boxes.items():
                ov = box_overlap(saved_box, dst_box)
                if ov is not None:
                    overlaps.append((dst_buf[ov.dst_slices], ov))
            if not overlaps:
                continue
            read_reqs.extend(
                ShardedArrayIOPreparer._reqs_for_saved_shard(
                    saved, saved_box, overlaps, buffer_size_limit_bytes,
                    dest_owned=dest_owned,
                )
            )

        def finalize(batch=None) -> None:
            def on_done(arr: Any) -> None:
                restored[path] = arr

            out = assemble(boxes, batch, on_done)
            if out is not _DEFERRED:
                restored[path] = out

        return read_reqs, finalize

    @staticmethod
    def _reqs_for_saved_shard(
        saved: Shard,
        saved_box: Box,
        overlaps: List[Tuple[np.ndarray, Overlap]],
        buffer_size_limit_bytes: Optional[int] = None,
        dest_owned: bool = False,
    ) -> List[ReadReq]:
        """Reads for one saved shard feeding all its overlap regions.

        The read shrinks to the smallest row band covering every overlap
        (``resharding.plan_row_slab_reads`` — the shared geometry the
        compat bridge ranges with too) and — under a buffer size limit —
        splits into multiple ranged reads so host memory stays bounded.
        Overlaps that slice *trailing* dims still ride the row band: the
        band's bytes contain the needed columns and the consumer slices
        them out, so a partial destination never pays a whole-shard read
        just because it is column-partial (read amplification stays near
        1.0 for the dominant dim-0 resharding pattern, and at one row
        band otherwise). A band spanning the whole shard degenerates to
        the single whole-blob read it always was."""
        entry = saved.array
        shard_shape = tuple(saved_box.sizes)

        plan = None
        if shard_shape and entry.serializer == Serializer.BUFFER_PROTOCOL.value:
            plan = plan_row_slab_reads(
                shard_shape,
                [ov for _, ov in overlaps],
                row_nbytes=array_size_bytes(shard_shape[1:], entry.dtype),
                base=entry.byte_range_tuple[0] if entry.byte_range_tuple else 0,
                buffer_limit_bytes=buffer_size_limit_bytes,
            )
        if plan is not None:
            views = [dst_view for dst_view, _ in overlaps]
            return [
                ReadReq(
                    path=entry.location,
                    buffer_consumer=_OverlapConsumer(
                        entry.dtype,
                        read.buf_shape,
                        [
                            (views[c.overlap_index][c.dst_rows], c.src_slices)
                            for c in read.copies
                        ],
                        dest_owned=dest_owned,
                    ),
                    byte_range=read.byte_range,
                )
                for read in plan
            ]

        copies = [(dst_view, ov.src_slices) for dst_view, ov in overlaps]
        return [
            ReadReq(
                path=entry.location,
                buffer_consumer=_OverlapConsumer(
                    entry.dtype, shard_shape, copies, dest_owned=dest_owned
                ),
                byte_range=entry.byte_range_tuple,
            )
        ]

    @staticmethod
    def prepare_read(
        entry: ShardedArrayEntry,
        obj_out: Optional[Any],
        buffer_size_limit_bytes: Optional[int] = None,
        dest_owned: bool = False,
    ) -> List[ReadReq]:
        """Reference-shaped API: reads in place into an ``np.ndarray``.
        Callers needing jax assembly must use :meth:`prepare_read_into`
        (whose finalize callback this entry point cannot run)."""
        if not isinstance(obj_out, np.ndarray):
            raise ValueError(
                f"Reading a sharded entry through prepare_read requires an "
                f"np.ndarray destination (got {type(obj_out)}); use "
                f"prepare_read_into for jax.Array assembly"
            )
        restored: Dict[str, Any] = {}
        reqs, _ = ShardedArrayIOPreparer.prepare_read_into(
            entry,
            obj_out,
            restored,
            "__out__",
            buffer_size_limit_bytes,
            dest_owned=dest_owned or None,
        )
        return reqs
