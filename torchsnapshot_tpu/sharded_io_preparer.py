"""Sharded-array preparer: NamedSharding shards -> per-shard writes, elastic
resharding on restore. (Implementation lands with the distributed layer;
this placeholder keeps dispatch importable.)

Reference parity target: ShardedTensorIOPreparer (io_preparer.py:167-391).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from .io_types import ReadReq, WriteReq
from .manifest import Entry, ShardedArrayEntry


class ShardedArrayIOPreparer:
    @staticmethod
    def prepare_write(
        obj: Any, logical_path: str, is_async_snapshot: bool
    ) -> Tuple[Entry, List[WriteReq]]:
        raise NotImplementedError(
            "Sharded jax.Array checkpointing lands with the distributed layer"
        )

    @staticmethod
    def prepare_read(
        entry: ShardedArrayEntry,
        obj_out: Optional[Any],
        buffer_size_limit_bytes: Optional[int] = None,
    ) -> List[ReadReq]:
        raise NotImplementedError(
            "Sharded jax.Array checkpointing lands with the distributed layer"
        )

    @staticmethod
    def prepare_read_into(
        entry: ShardedArrayEntry,
        current_leaf: Optional[Any],
        restored: dict,
        path: str,
    ):
        raise NotImplementedError(
            "Sharded jax.Array checkpointing lands with the distributed layer"
        )
