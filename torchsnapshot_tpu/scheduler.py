"""Pipelined write/read execution under a host-memory budget.

Reference parity: torchsnapshot/scheduler.py. Same contract, different
machinery: instead of explicit state-set juggling (scheduler.py:237-330),
each request runs as its own coroutine —

    write:  acquire budget -> stage (device->host + serialize, on a thread
            pool) -> re-price budget to actual buffer size -> acquire an I/O
            slot -> storage.write -> release budget
    read:   acquire budget -> acquire I/O slot -> storage.read -> release
            slot -> consume (deserialize + copy, on a thread pool) -> release

Admission control lives in :class:`MemoryBudget`: a request larger than the
whole budget is admitted only when nothing else is in flight (reference rule,
scheduler.py:266-271), so huge buffers serialize instead of deadlocking.

``execute_write_reqs`` returns a :class:`PendingIOWork` as soon as *staging*
has finished for every request (scheduler.py:224-234): from then on the
application may mutate/free device arrays while storage I/O drains in the
background. Device-snapshot async takes go further: :class:`DeferredIOWork`
defers the WHOLE pipeline to the background commit thread, running it
through a :class:`StagingPool` (a slab-bounded admission controller) so
host staging memory never scales with checkpoint size — the training-
visible span ends at capture, before any staging ran (docs/async.md).
"""

from __future__ import annotations

import asyncio
import logging
import math
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import TYPE_CHECKING, Callable, List, Optional

import psutil

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .telemetry.progress import ProgressTracker

from . import knobs, telemetry
from .telemetry.trace import get_recorder as _trace_recorder
from .integrity import (
    ChecksumError,
    ChecksumTable,
    compute_checksum_entry,
    verify_checksum,
    verify_page_crcs,
    verify_range_checksum,
)
from .io_types import (
    BufferList,
    ReadIO,
    ReadReq,
    StoragePlugin,
    WriteIO,
    WriteReq,
)

logger: logging.Logger = logging.getLogger(__name__)

# Observability: wall-clock phase completions (seconds since the
# pipeline's reporter started) of the most recent write/read pipeline run
# in this process, keyed by phase name ("staging"/"writing"/"loading").
# Historically a module-level dict here; now a compatibility shim over
# the telemetry registry's phase-timing channel (telemetry/registry.py),
# which also feeds the snapshot_phase_seconds histogram. Semantics are
# unchanged: last-writer-wins across concurrent pipelines — callers that
# care (bench.py's in-take stall diagnosis) run one pipeline at a time.


def reset_phase_timings() -> None:
    telemetry.metrics().reset_phase_timings()


def last_phase_timings() -> dict:
    return telemetry.metrics().last_phase_timings()


def record_phase_timing(phase: str, elapsed_s: float) -> None:
    """Publish a phase completion into the machine-readable channel from
    outside the pipeline (the tiered mirror records its "mirroring" phase
    here, next to the pipeline's staging/writing/loading entries)."""
    telemetry.record_phase(phase, elapsed_s)


# Near-zero-elapsed throughput guard (div-by-~0 would print inf MB/s);
# one shared threshold with the snapshot-stats renderer.
safe_rate_mb_s = telemetry.safe_rate_mb_s

_MAX_PER_RANK_MEMORY_BUDGET_BYTES: int = 32 * 1024 * 1024 * 1024
_LOG_LINE_LIMIT = 8
# Non-fused checksum compute runs inline (on the event loop) below this
# size: even on the slicing-by-8 software CRC (~0.4 GB/s) 64 KiB stalls
# the loop well under a millisecond, while an executor round-trip costs
# ~0.1 ms per request regardless of size.
_INLINE_CHECKSUM_BYTES = 64 * 1024


def get_process_memory_budget_bytes(pg=None) -> int:
    """Per-process host-memory budget for staging/consuming buffers.

    ``min(available_host_memory * fraction / local_world_size, 32 GiB)``
    with an env-var override (reference: scheduler.py:45-65). The
    fraction defaults to the historical 0.6 and is a tunable knob
    (TORCHSNAPSHOT_TPU_MEMORY_BUDGET_FRACTION — the autotuner's
    budget-starved lever). ``local_world_size`` counts co-hosted
    processes via a hostname all-gather on ``pg`` — on TPU pods this is
    processes per host, not chips per host.
    """
    override = knobs.get_per_rank_memory_budget_bytes_override()
    if override is not None:
        logger.info("Memory budget manually set to %d bytes", override)
        return override
    available = int(
        psutil.virtual_memory().available * knobs.get_memory_budget_fraction()
    )
    local_world_size = 1
    if pg is not None and pg.get_world_size() > 1:
        import socket

        hostnames = pg.all_gather_object(socket.gethostname())
        local_world_size = sum(1 for h in hostnames if h == socket.gethostname())
    budget = min(available // local_world_size, _MAX_PER_RANK_MEMORY_BUDGET_BYTES)
    logger.info("Memory budget set to %d bytes", budget)
    return budget


class MemoryBudget:
    """Async counting budget with an idle-admission escape hatch.

    ``acquire(cost)`` waits until ``cost`` fits, or until the pipeline is
    completely idle (in which case an oversized request is admitted alone).
    ``adjust(delta)`` re-prices a held reservation (staging cost vs actual
    buffer size can differ, e.g. non-contiguous arrays); ``release`` returns
    the final amount.
    """

    def __init__(self, total_bytes: int) -> None:
        self.total_bytes = total_bytes
        self.available_bytes = total_bytes
        self.inflight = 0
        self._cond: asyncio.Condition = asyncio.Condition()
        # Telemetry: cumulative admission-wait seconds (how long requests
        # sat blocked on the budget — the FastPersist-style signal for
        # "the budget, not the storage, is the bottleneck") and the peak
        # concurrently-reserved bytes this budget ever carried.
        self.wait_s = 0.0
        self.peak_reserved_bytes = 0

    def _note_reserved(self) -> None:
        reserved = self.total_bytes - self.available_bytes
        if reserved > self.peak_reserved_bytes:
            self.peak_reserved_bytes = reserved

    async def acquire(self, cost_bytes: int) -> None:
        t0 = time.monotonic()
        async with self._cond:
            await self._cond.wait_for(
                lambda: cost_bytes <= self.available_bytes or self.inflight == 0
            )
            self.available_bytes -= cost_bytes
            self.inflight += 1
            self._note_reserved()
        waited = time.monotonic() - t0
        self.wait_s += waited
        telemetry.metrics().histogram_observe(
            telemetry.names.MEMORY_BUDGET_WAIT_SECONDS, waited
        )

    async def adjust(self, delta_bytes: int) -> None:
        async with self._cond:
            self.available_bytes -= delta_bytes
            self._note_reserved()
            if delta_bytes < 0:
                self._cond.notify_all()

    async def release(self, cost_bytes: int) -> None:
        async with self._cond:
            self.available_bytes += cost_bytes
            self.inflight -= 1
            self._cond.notify_all()


class StagingPool(MemoryBudget):
    """Double-buffered host staging pool for background D2H drains.

    A device-snapshot async take runs its whole staging pipeline on the
    background commit thread; this pool is that pipeline's admission
    controller. Capacity is ``slabs x slab_bytes`` (knob-set; default
    2 x 128 MiB — classic double buffering: one slab's worth of
    requests stages D2H while the previous slab's worth drains to
    storage), clamped to the process memory budget it is accounted
    against — so a 1 GiB checkpoint drains through ~256 MiB of host
    headroom instead of materializing entirely. Inherits the
    idle-admission escape hatch: a single request larger than the whole
    pool is admitted alone (it serializes instead of deadlocking), and
    all of MemoryBudget's wait/peak telemetry.
    """

    def __init__(
        self,
        memory_budget_bytes: int,
        slab_bytes: Optional[int] = None,
        slabs: Optional[int] = None,
    ) -> None:
        self.slab_bytes = (
            slab_bytes
            if slab_bytes is not None
            else knobs.get_staging_pool_slab_bytes()
        )
        self.slabs = (
            slabs if slabs is not None else knobs.get_staging_pool_slabs()
        )
        self.memory_budget_bytes = memory_budget_bytes
        super().__init__(
            min(memory_budget_bytes, max(1, self.slab_bytes * self.slabs))
        )

    def geometry(self) -> dict:
        return {
            "capacity_bytes": self.total_bytes,
            "slab_bytes": self.slab_bytes,
            "slabs": self.slabs,
        }


class PeerCacheBudget:
    """Synchronous counting budget for the peer-RAM checkpoint cache
    (tiered/peer.py) — :class:`MemoryBudget`'s accounting model
    (total/available/peak) without the event-loop coupling: the peer
    server's handler threads reserve and release under a plain lock,
    and an oversized reservation is *refused* rather than queued — a
    push that does not fit (even after the cache's LRU eviction) must
    degrade to storage-only durability, never block the pusher or grow
    the cache past its bound."""

    def __init__(self, total_bytes: int) -> None:
        self.total_bytes = max(0, int(total_bytes))
        self.available_bytes = self.total_bytes
        self.peak_reserved_bytes = 0
        self._lock = threading.Lock()

    def try_reserve(self, cost_bytes: int) -> bool:
        """Reserve ``cost_bytes`` if they fit; False otherwise (the
        caller evicts and retries, or refuses the push)."""
        cost = int(cost_bytes)
        with self._lock:
            if cost > self.available_bytes:
                return False
            self.available_bytes -= cost
            reserved = self.total_bytes - self.available_bytes
            if reserved > self.peak_reserved_bytes:
                self.peak_reserved_bytes = reserved
            return True

    def release(self, cost_bytes: int) -> None:
        with self._lock:
            self.available_bytes = min(
                self.total_bytes, self.available_bytes + int(cost_bytes)
            )

    def reserved_bytes(self) -> int:
        with self._lock:
            return self.total_bytes - self.available_bytes


class _PipelineStats:
    """Live counters backing the progress reporter."""

    def __init__(self) -> None:
        self.pending = 0
        self.staging = 0
        self.waiting_io = 0
        self.io = 0
        self.done = 0
        self.bytes_moved = 0
        self.bytes_staged = 0
        # Write pipelines: bytes served per write-path variant
        # ("vectorized" | "direct" | "fused" | "buffered"), as stamped
        # by the storage plugin on each WriteIO — the per-take record
        # that lets doctor --trend correlate a write-path knob flip
        # with an efficiency move.
        self.write_variant_bytes: dict = {}
        # Read pipelines only: how many of the moved bytes were pulled
        # from the storage plugin itself ("fetched") versus served from
        # a peer-exchanged cache (fan-out restore; those bytes were
        # accounted as fetched/received by the exchange that shipped
        # them, not here). bytes_moved - bytes_fetched = locally-served.
        self.bytes_fetched = 0
        # Self-healing reads (docs/chaos.md): requests whose first copy
        # failed digest verification and were re-served from an
        # alternate tier — count/bytes totals plus bytes by the tier
        # that finally vouched (folded into the report's tier_split).
        self.degraded_reads = 0
        self.degraded_bytes = 0
        self.degraded_tier_bytes: dict = {}


# report_phase_done -> the phase the op is IN once that one completed,
# published to the live-progress heartbeat (telemetry/progress.py).
_NEXT_PHASE = {"staging": "writing", "writing": "committing", "loading": "applying"}


class _ProgressReporter:
    """Rank-0 header + per-rank progress rows with RSS delta, budget and GB
    moved (reference _WriteReporter, scheduler.py:96-175)."""

    _ROW = (
        "{rank:>4} {pending:>9} {staging:>9} {waiting:>9} {io:>9} "
        "{rss_delta:>15} {budget:>19} {moved:>15}"
    )

    def __init__(
        self,
        stats: _PipelineStats,
        budget: MemoryBudget,
        rank: int,
        total: int,
        progress: Optional["ProgressTracker"] = None,
    ) -> None:
        self.stats = stats
        self.budget = budget
        self.rank = rank
        # Live-progress tracker for the enclosing operation (None when
        # the caller runs no heartbeat, e.g. read_object).
        self.progress = progress
        # Per-pipeline phase completions (phase -> seconds since start):
        # unlike the process-global last_phase_timings channel this can
        # never leak a previous run's phases into this run's report.
        self.phase_s: dict = {}
        self.begin_ts = time.monotonic()
        self._process = psutil.Process()
        self.baseline_rss = self._process.memory_info().rss
        self.report_every = max(1, math.ceil(total / _LOG_LINE_LIMIT))
        self._header = self._ROW.format(
            rank="Rank",
            pending="Pending",
            staging="Staging",
            waiting="Writable",
            io="I/O",
            rss_delta="RSS Delta (GB)",
            budget="Budget (GB)",
            moved="Moved (GB)",
        )

    def print_header(self) -> None:
        if self.rank == 0:
            logger.info(self._header)
            logger.info("-" * len(self._header))

    def report(self) -> None:
        rss_delta_gb = (self._process.memory_info().rss - self.baseline_rss) / 1024**3
        logger.info(
            self._ROW.format(
                rank=self.rank,
                pending=self.stats.pending,
                staging=self.stats.staging,
                waiting=self.stats.waiting_io,
                io=self.stats.io,
                rss_delta=f"{rss_delta_gb:.2f}",
                budget=(
                    f"{self.budget.available_bytes / 1024**3:.2f}/"
                    f"{self.budget.total_bytes / 1024**3:.2f}"
                ),
                moved=f"{self.stats.bytes_moved / 1024**3:.2f}",
            )
        )

    def maybe_report(self) -> None:
        if self.stats.done % self.report_every == 0:
            self.report()

    def publish_progress(self) -> None:
        """Feed the op's live-progress tracker from this pipeline's
        counters. Called on every request completion and phase
        transition; the tracker's file writes are interval-gated, the
        in-memory view updates every time."""
        if self.progress is None:
            return
        self.progress.update_pipeline(
            pending=self.stats.pending,
            staging=self.stats.staging,
            inflight=self.stats.waiting_io + self.stats.io,
            done=self.stats.done,
            staged_bytes=self.stats.bytes_staged,
            done_bytes=self.stats.bytes_moved,
            budget_wait_s=self.budget.wait_s,
        )

    def report_phase_done(self, phase: str) -> None:
        elapsed = time.monotonic() - self.begin_ts
        self.phase_s[phase] = round(elapsed, 3)
        telemetry.record_phase(phase, elapsed)
        self.publish_progress()
        if self.progress is not None and phase in _NEXT_PHASE:
            self.progress.set_phase(_NEXT_PHASE[phase])
        mbps = safe_rate_mb_s(self.stats.bytes_moved, elapsed)
        msg = (
            f"Rank {self.rank} completed {phase} in {elapsed:.2f}s "
            f"(throughput {mbps:.2f} MB/s)"
        )
        pad = max(0, len(self._header) - len(msg) - 2) / 2
        logger.info(f"{'-' * math.ceil(pad)} {msg} {'-' * math.floor(pad)}")

    def pipeline_telemetry(self) -> dict:
        """This run's exact numbers for SnapshotReport assembly."""
        out = {
            "phases": dict(self.phase_s),
            "bytes_moved": self.stats.bytes_moved,
            "blobs": self.stats.done,
            "budget_wait_s": round(self.budget.wait_s, 6),
            "peak_staged_bytes": self.budget.peak_reserved_bytes,
        }
        if isinstance(self.budget, StagingPool):
            out["staging_pool"] = self.budget.geometry()
        if self.stats.write_variant_bytes:
            out["write_path"] = dict(self.stats.write_variant_bytes)
        return out


class PendingIOWork:
    """Handle over storage I/O still draining after staging completed
    (reference scheduler.py:178-217). ``complete`` re-raises the first
    failure; the commit marker must not be written in that case."""

    def __init__(
        self,
        io_tasks: List["asyncio.Task[None]"],
        reporter: _ProgressReporter,
        executor: ThreadPoolExecutor,
        checksums: Optional[ChecksumTable] = None,
    ) -> None:
        self.io_tasks = io_tasks
        self.reporter = reporter
        self._executor = executor
        # Filled in as writes complete; stable only after complete().
        self.checksums: ChecksumTable = checksums if checksums is not None else {}
        # Optional hook run after complete() and before the checksum table
        # is persisted (incremental takes inherit base-table entries here —
        # storage reads that must stay off the staging-critical path so
        # async_take returns at staging-done as promised).
        self.checksum_finalizer: Optional[Callable[[], None]] = None

    def finalize_checksums(self) -> None:
        if self.checksum_finalizer is not None:
            try:
                self.checksum_finalizer()
            finally:
                self.checksum_finalizer = None

    def pipeline_telemetry(self) -> dict:
        """The write pipeline's exact per-run numbers (phases, bytes,
        blob count, budget wait, peak staged); stable after complete()."""
        return self.reporter.pipeline_telemetry()

    async def complete(self) -> None:
        # Recorder-only span (not trace_annotation): this coroutine
        # awaits across the whole I/O drain and a thread-local jax
        # annotation would mis-nest with interleaved tasks.
        drain_span = _trace_recorder().begin(
            telemetry.names.SPAN_PIPELINE_WRITE_DRAIN,
            tasks=len(self.io_tasks),
        )
        try:
            if self.io_tasks:
                try:
                    await asyncio.gather(*self.io_tasks)
                except BaseException:
                    # Settle the sibling writes before re-raising: gather
                    # propagates on the FIRST failure while the rest keep
                    # running, and the caller's failure path closes the
                    # event loop — leaving tasks to die mid-write with
                    # "Task was destroyed but it is pending" noise (and
                    # buffers whose budget releases never ran).
                    for t in self.io_tasks:
                        t.cancel()
                    await asyncio.gather(*self.io_tasks, return_exceptions=True)
                    raise
        finally:
            _trace_recorder().end(drain_span)
            self._executor.shutdown(wait=False)
        self.reporter.report_phase_done("writing")
        telemetry.metrics().gauge_set(
            telemetry.names.MEMORY_BUDGET_PEAK_STAGED_BYTES,
            self.reporter.budget.peak_reserved_bytes,
        )

    def sync_complete(self, event_loop: asyncio.AbstractEventLoop) -> None:
        event_loop.run_until_complete(self.complete())


async def execute_write_reqs(
    write_reqs: List[WriteReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    progress: Optional["ProgressTracker"] = None,
    staging_pool: Optional[MemoryBudget] = None,
) -> PendingIOWork:
    """Run the staged write pipeline; returns once every request is past
    staging, with storage I/O continuing inside the returned handle.
    ``progress`` (the enclosing op's live-progress tracker) receives the
    pipeline's plan and per-request counter updates. ``staging_pool``
    substitutes a (typically much tighter) admission controller for the
    raw budget — the background-drain path of device-snapshot async
    takes, whose host staging footprint must be pool-bounded, not
    checkpoint-sized."""
    budget = (
        staging_pool
        if staging_pool is not None
        else MemoryBudget(memory_budget_bytes)
    )
    stats = _PipelineStats()
    stats.pending = len(write_reqs)
    reporter = _ProgressReporter(stats, budget, rank, len(write_reqs), progress)
    reporter.print_header()
    if progress is not None:
        progress.begin_pipeline(
            len(write_reqs),
            sum(r.buffer_stager.get_staging_cost_bytes() for r in write_reqs),
            phase="staging",
        )

    executor = ThreadPoolExecutor(
        max_workers=knobs.get_staging_threads(), thread_name_prefix="ts-stage"
    )
    io_slots = asyncio.Semaphore(knobs.get_per_rank_io_concurrency())
    io_tasks: List[asyncio.Task] = []
    record_checksums = not knobs.is_checksums_disabled()
    checksums: ChecksumTable = {}
    # Sticky runtime-decline: a plugin that overrides write_with_checksum
    # but declines (native runtime unavailable) declines for the whole
    # run — remember it so later writes keep checksum compute OFF the
    # bounded I/O slots.
    fused_declined = False

    async def checksum_off_slot(buf):
        """Checksum compute for the non-fused path. Small buffers run
        inline: the executor round-trip costs ~0.1 ms, an order of
        magnitude more than hashing the bytes themselves — at torchrec
        scale (1e5 tiny leaves, batching off) the hop, not the CRC, was
        the per-request floor. Large buffers keep the hop so a multi-MiB
        CRC never stalls the event loop."""
        if len(buf) <= _INLINE_CHECKSUM_BYTES:
            return compute_checksum_entry(buf)
        return await asyncio.get_running_loop().run_in_executor(
            executor, compute_checksum_entry, buf
        )

    async def write_one(req: WriteReq, buf) -> None:
        nonlocal fused_declined
        buf_len = len(buf)
        try:
            # Zero-pack payloads only reach plugins that can vector-write
            # them; for the rest, consolidate here — paying exactly the
            # pack pass the old path always paid, never more. The copy
            # transiently holds parts + contiguous buffer, so re-price
            # the reservation for its duration (adjust never blocks —
            # bounded overshoot now, later admissions wait it out), and
            # run the full-slab memcpy in the executor like the pack
            # pass it replaces.
            if isinstance(buf, BufferList) and not getattr(
                storage, "supports_multibuffer", False
            ):
                await budget.adjust(buf_len)
                try:
                    buf = await asyncio.get_running_loop().run_in_executor(
                        executor, buf.consolidate
                    )
                finally:
                    await budget.adjust(-buf_len)
            # Fused write+checksum (one cache-hot memory pass) when the
            # plugin overrides it; otherwise checksum first (off the I/O
            # slot), then write.
            fused = (
                record_checksums
                and not fused_declined
                and type(storage).write_with_checksum
                is not StoragePlugin.write_with_checksum
            )
            if record_checksums and not fused:
                checksums[req.path] = await checksum_off_slot(buf)
            declined = False
            # One WriteIO for the whole request: the plugin stamps the
            # write-path variant that actually served it (vectorized /
            # direct / fused / buffered) onto this object.
            write_io = WriteIO(path=req.path, buf=buf)
            async with io_slots:
                stats.waiting_io -= 1
                stats.io += 1
                try:
                    # I/O spans are emitted inside the storage plugin's
                    # executor work (fs.py): wrapping the await here would
                    # record suspension time of interleaved tasks, not I/O.
                    if fused:
                        entry = await storage.write_with_checksum(write_io)
                        if entry is not None:
                            checksums[req.path] = entry
                        else:
                            # Plugin declined at runtime (native lib
                            # unavailable; nothing written): fall back
                            # OUTSIDE the slot — checksum compute must
                            # not serialize the bounded I/O streams.
                            declined = True
                    else:
                        await storage.write(write_io)
                finally:
                    stats.io -= 1
            if declined:
                # Two-step fallback for this and (sticky) all later
                # writes: checksum off the I/O slots, then re-acquire a
                # slot for the plain write.
                fused_declined = True
                checksums[req.path] = await checksum_off_slot(buf)
                stats.waiting_io += 1
                async with io_slots:
                    stats.waiting_io -= 1
                    stats.io += 1
                    try:
                        await storage.write(write_io)
                    finally:
                        stats.io -= 1
            variant = write_io.variant or "buffered"
            stats.write_variant_bytes[variant] = (
                stats.write_variant_bytes.get(variant, 0) + buf_len
            )
        finally:
            del buf
            await budget.release(buf_len)
        stats.done += 1
        stats.bytes_moved += buf_len
        reporter.maybe_report()
        reporter.publish_progress()

    async def stage_one(req: WriteReq) -> None:
        """Budget-admitted staging; hands the staged buffer straight to a
        background write task so I/O overlaps other requests' staging.
        Recorder spans per phase (budget wait, then the D2H/serialize
        stage itself): the per-request timeline the flight recorder
        exports. Recorder-only — these spans cross awaits."""
        recorder = _trace_recorder()
        cost = req.buffer_stager.get_staging_cost_bytes()
        with recorder.span(
            telemetry.names.SPAN_PIPELINE_BUDGET_ACQUIRE,
            blob=req.path,
            bytes=cost,
        ):
            await budget.acquire(cost)
        stats.pending -= 1
        stats.staging += 1
        stage_span = recorder.begin(
            telemetry.names.SPAN_PIPELINE_STAGE, blob=req.path, bytes=cost
        )
        try:
            buf = await req.buffer_stager.stage_buffer(executor)
        except BaseException:
            recorder.end(stage_span)
            stats.staging -= 1
            await budget.release(cost)
            raise
        recorder.end(stage_span, staged_bytes=len(buf))
        stats.staging -= 1
        stats.waiting_io += 1
        stats.bytes_staged += len(buf)
        reporter.publish_progress()
        # Re-price the reservation: actual buffer size can differ from the
        # staging cost (e.g. pickled objects).
        await budget.adjust(len(buf) - cost)
        io_tasks.append(asyncio.create_task(write_one(req, buf)))
        del buf

    staging_tasks = [asyncio.create_task(stage_one(r)) for r in write_reqs]
    try:
        if staging_tasks:
            await asyncio.gather(*staging_tasks)
    except BaseException:
        for t in staging_tasks + io_tasks:
            t.cancel()
        await asyncio.gather(*staging_tasks, *io_tasks, return_exceptions=True)
        executor.shutdown(wait=False)
        raise

    reporter.report_phase_done("staging")
    return PendingIOWork(
        io_tasks=io_tasks,
        reporter=reporter,
        executor=executor,
        checksums=checksums,
    )


def sync_execute_write_reqs(
    write_reqs: List[WriteReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    event_loop: asyncio.AbstractEventLoop,
    progress: Optional["ProgressTracker"] = None,
) -> PendingIOWork:
    return event_loop.run_until_complete(
        execute_write_reqs(
            write_reqs=write_reqs,
            storage=storage,
            memory_budget_bytes=memory_budget_bytes,
            rank=rank,
            progress=progress,
        )
    )


class DeferredIOWork:
    """Write work whose staging has NOT run yet — the device-snapshot
    async take's handle. ``async_take`` constructs one right after the
    capture pass (on-device clones dispatched, mutable host leaves
    copied) and returns; the background commit thread then calls
    ``sync_complete``, which runs the WHOLE pipeline: staging (D2H +
    serialize) through a :class:`StagingPool` so host memory stays
    slab-bounded, overlapped with the storage writes by the ordinary
    stage/write machinery of :func:`execute_write_reqs`.

    Mirrors :class:`PendingIOWork`'s surface (``sync_complete`` /
    ``finalize_checksums`` / ``checksums`` / ``checksum_finalizer`` /
    ``pipeline_telemetry``) so ``PendingSnapshot`` drives either handle
    identically. ``on_staged`` fires on the drain thread the moment
    staging finished — the take's ``staged`` phase boundary
    (``PendingSnapshot.wait(phase="staged")``).
    """

    def __init__(
        self,
        write_reqs: List[WriteReq],
        storage: StoragePlugin,
        memory_budget_bytes: int,
        rank: int,
        progress: Optional["ProgressTracker"] = None,
    ) -> None:
        self.write_reqs = write_reqs
        self._storage = storage
        self._memory_budget_bytes = memory_budget_bytes
        self._rank = rank
        self._progress = progress
        # Same contract as PendingIOWork: filled as writes complete
        # (rebound to the live pipeline's table once staging starts),
        # stable only after sync_complete() returns.
        self.checksums: ChecksumTable = {}
        self.checksum_finalizer: Optional[Callable[[], None]] = None
        self.on_staged: Optional[Callable[[], None]] = None
        self._inner: Optional[PendingIOWork] = None

    def sync_complete(self, event_loop: asyncio.AbstractEventLoop) -> None:
        pool = StagingPool(self._memory_budget_bytes)
        inner = event_loop.run_until_complete(
            execute_write_reqs(
                write_reqs=self.write_reqs,
                storage=self._storage,
                memory_budget_bytes=self._memory_budget_bytes,
                rank=self._rank,
                progress=self._progress,
                staging_pool=pool,
            )
        )
        self._inner = inner
        # The inner pipeline's table is the live one; expose it so the
        # caller's checksum-table write (and an incremental take's
        # inherit closure, which reads ``self.checksums`` at call time)
        # see every recorded digest.
        self.checksums = inner.checksums
        self.write_reqs = []
        if self.on_staged is not None:
            self.on_staged()
        inner.sync_complete(event_loop)

    def finalize_checksums(self) -> None:
        if self.checksum_finalizer is not None:
            try:
                self.checksum_finalizer()
            finally:
                self.checksum_finalizer = None

    def pipeline_telemetry(self) -> dict:
        return (
            self._inner.pipeline_telemetry() if self._inner is not None else {}
        )


async def execute_read_reqs(
    read_reqs: List[ReadReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    checksum_table: Optional[ChecksumTable] = None,
    on_req_complete: Optional[Callable[[ReadReq], None]] = None,
    progress: Optional["ProgressTracker"] = None,
    classify_read: Optional[Callable[[ReadReq], Optional[str]]] = None,
) -> dict:
    """Read pipeline: storage read -> deserialize/copy, budgeted by each
    request's consuming cost (reference scheduler.py:357-444). Returns
    the run's pipeline-telemetry dict (phases, bytes, budget wait) for
    SnapshotReport assembly.

    ``on_req_complete`` fires on the event loop after a request's bytes
    are verified and consumed — the hook streaming restore placement
    hangs device_put flushes on while other reads are still in flight.

    ``classify_read`` attributes each completed request's bytes for the
    fetched-vs-received accounting restore reports carry: return
    ``"fetched"`` (the default for every request when no classifier is
    given) to count the bytes as pulled from the storage plugin, or
    ``None`` for bytes served from a local cache (fan-out restore's
    exchanged shards — the exchange already accounted those). The
    telemetry dict reports the sum as ``bytes_fetched``."""
    budget = MemoryBudget(memory_budget_bytes)
    stats = _PipelineStats()
    stats.pending = len(read_reqs)
    reporter = _ProgressReporter(stats, budget, rank, len(read_reqs), progress)
    if progress is not None:
        progress.begin_pipeline(
            len(read_reqs),
            sum(
                r.buffer_consumer.get_consuming_cost_bytes() for r in read_reqs
            ),
            phase="loading",
        )

    executor = ThreadPoolExecutor(
        max_workers=knobs.get_staging_threads(), thread_name_prefix="ts-consume"
    )
    io_slots = asyncio.Semaphore(knobs.get_per_rank_io_concurrency())
    verify_skipped = [0]
    # Sticky runtime-decline for the fused read+CRC path (mirrors the
    # write pipeline's flag): once a plugin declines, later reads skip
    # the attempt. Plugins that never overrode the hook start declined.
    fused_read_declined = (
        type(storage).read_with_checksum
        is StoragePlugin.read_with_checksum
    )

    async def read_one(req: ReadReq) -> None:
        nonlocal fused_read_declined
        cost = req.buffer_consumer.get_consuming_cost_bytes()
        await budget.acquire(cost)
        stats.pending -= 1
        try:
            entry = (
                checksum_table.get(req.path)
                if checksum_table is not None
                else None
            )
            fused_pages = None
            async with io_slots:
                stats.io += 1
                read_io = ReadIO(
                    path=req.path,
                    byte_range=req.byte_range,
                    dest=req.buffer_consumer.direct_destination(),
                )
                try:
                    # Fused read+verify source: one cache-hot pass
                    # computes the page digests during the disk read.
                    if (
                        entry is not None
                        and entry[0] == "crc32c"
                        and req.byte_range is None
                        and not fused_read_declined
                    ):
                        fused_pages = await storage.read_with_checksum(read_io)
                        if fused_pages is None:
                            fused_read_declined = True
                    if fused_pages is None:
                        await storage.read(read_io)
                finally:
                    stats.io -= 1
            buf = read_io.buf
            if buf is None:
                raise AssertionError(
                    f"Storage plugin did not populate buffer for {req.path}"
                )
            # Whole-blob reads verify against the blob digest; ranged reads
            # verify every page their range fully covers (recorded for
            # blobs larger than one page). Reads that end up with no
            # verification at all are counted and reported below so
            # 'checksums on' is never silently hollow. Runs before the
            # value is handed to the application either way (direct reads
            # land in framework-owned buffers only).
            if entry is not None:
                loop_ = asyncio.get_running_loop()

                async def _verify_current(
                    cur_buf, use_fused_pages=None
                ) -> None:
                    verified_from_pages = False
                    if use_fused_pages is not None:
                        # Pure GF(2) fold over the pages read — O(pages),
                        # no second pass over the bytes, no executor hop.
                        # False = this entry needs the bytes (foreign alg
                        # / mismatched interim granularity): verify below.
                        verified_from_pages = verify_page_crcs(
                            use_fused_pages,
                            memoryview(cur_buf).nbytes,
                            entry,
                            req.path,
                        )
                    # Small buffers verify inline: the executor
                    # round-trip costs ~0.1 ms against sub-microsecond
                    # hashing (same rationale as checksum_off_slot).
                    small = (
                        memoryview(cur_buf).nbytes <= _INLINE_CHECKSUM_BYTES
                    )
                    if verified_from_pages:
                        pass
                    elif req.byte_range is None:
                        if small:
                            verify_checksum(cur_buf, entry, req.path)
                        else:
                            await loop_.run_in_executor(
                                executor,
                                verify_checksum,
                                cur_buf,
                                entry,
                                req.path,
                            )
                    else:
                        if small:
                            page_verified = verify_range_checksum(
                                cur_buf, entry, req.byte_range, req.path
                            )
                        else:
                            page_verified = await loop_.run_in_executor(
                                executor,
                                verify_range_checksum,
                                cur_buf,
                                entry,
                                req.byte_range,
                                req.path,
                            )
                        if not page_verified:
                            verify_skipped[0] += 1

                try:
                    await _verify_current(buf, use_fused_pages=fused_pages)
                except ChecksumError as first_err:
                    # Self-healing ladder (docs/chaos.md): a corrupt
                    # tier copy must not fail a restore the OTHER tiers
                    # could serve. Multi-source plugins re-read from
                    # alternates (tiered: the other tier; the peer
                    # ladder: durable/fast) until one verifies;
                    # single-source plugins have none and the original
                    # error stands — corruption is never served
                    # silently either way.
                    healed = False
                    async with io_slots:
                        while await storage.read_degraded(read_io):
                            buf = read_io.buf
                            try:
                                await _verify_current(buf)
                            except ChecksumError:
                                continue
                            healed = True
                            break
                    if not healed:
                        raise
                    tier = read_io.served_by or "unknown"
                    nbytes = memoryview(buf).nbytes
                    stats.degraded_reads += 1
                    stats.degraded_bytes += nbytes
                    stats.degraded_tier_bytes[tier] = (
                        stats.degraded_tier_bytes.get(tier, 0) + nbytes
                    )
                    registry = telemetry.metrics()
                    registry.counter_inc(
                        telemetry.names.STORAGE_DEGRADED_READS_TOTAL,
                        tier=tier,
                    )
                    registry.counter_inc(
                        telemetry.names.STORAGE_DEGRADED_READ_BYTES_TOTAL,
                        nbytes,
                        tier=tier,
                    )
                    logger.warning(
                        "read of %s failed verification (%s); healed "
                        "from the %r tier copy",
                        req.path,
                        first_err,
                        tier,
                    )
            if read_io.dest is not None and buf is read_io.dest:
                # The plugin read straight into the destination; nothing
                # left to deserialize or copy.
                pass
            else:
                stats.staging += 1
                try:
                    with _trace_recorder().span(
                        telemetry.names.SPAN_PIPELINE_CONSUME,
                        blob=req.path,
                        bytes=memoryview(buf).nbytes,
                    ):
                        await req.buffer_consumer.consume_buffer(buf, executor)
                finally:
                    stats.staging -= 1
            stats.done += 1
            stats.bytes_moved += buf.nbytes
            kind = (
                classify_read(req) if classify_read is not None else "fetched"
            )
            if kind == "fetched":
                stats.bytes_fetched += buf.nbytes
            del buf, read_io
            if on_req_complete is not None:
                on_req_complete(req)
            reporter.maybe_report()
            reporter.publish_progress()
        finally:
            await budget.release(cost)

    tasks = [asyncio.create_task(read_one(r)) for r in read_reqs]
    try:
        await asyncio.gather(*tasks)
    except BaseException:
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        raise
    finally:
        executor.shutdown(wait=False)
    if verify_skipped[0]:
        logger.info(
            "%d of %d reads were ranged with no fully-covered pages and "
            "skipped checksum verification",
            verify_skipped[0],
            len(read_reqs),
        )
    reporter.report_phase_done("loading")
    out = reporter.pipeline_telemetry()
    # Read pipelines always report their plugin-fetched bytes: the
    # fallback (no classifier) counts every request, so a plain restore's
    # bytes_fetched equals bytes_moved and the read-amplification math
    # works whether or not fan-out ran.
    out["bytes_fetched"] = stats.bytes_fetched
    if stats.degraded_reads:
        # Corruption-rerouted reads: the count/bytes summary the
        # storage-corruption doctor rule cites, plus the serving tiers
        # folded into the report's tier_split so the reroute is visible
        # in the same split the peer ladder reports.
        out["degraded_reads"] = {
            "blobs": stats.degraded_reads,
            "bytes": stats.degraded_bytes,
        }
        out["tier_split"] = dict(stats.degraded_tier_bytes)
    return out


def sync_execute_read_reqs(
    read_reqs: List[ReadReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    event_loop: asyncio.AbstractEventLoop,
    checksum_table: Optional[ChecksumTable] = None,
    on_req_complete: Optional[Callable[[ReadReq], None]] = None,
    progress: Optional["ProgressTracker"] = None,
    classify_read: Optional[Callable[[ReadReq], Optional[str]]] = None,
) -> dict:
    return event_loop.run_until_complete(
        execute_read_reqs(
            read_reqs=read_reqs,
            storage=storage,
            memory_budget_bytes=memory_budget_bytes,
            rank=rank,
            checksum_table=checksum_table,
            on_req_complete=on_req_complete,
            progress=progress,
            classify_read=classify_read,
        )
    )
