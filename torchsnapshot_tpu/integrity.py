"""Blob-level integrity: CRC32-C checksums recorded at write time and
verified on read.

This subsystem has no counterpart in the reference (its durability story
ends at the atomic commit marker, snapshot.py:230-237); it exists here
because the native I/O runtime already computes CRC32-C at memory speed
with the GIL released (native/ts_io.cpp), so end-to-end bit-rot detection
costs a small fraction of storage bandwidth.

Layout: each rank writes a ``checksums/{rank}`` JSON table after all its
storage writes are durable and *before* the commit barrier — a committed
snapshot therefore always has complete tables. Keys are storage paths
(globally unique per blob); values are ``[alg, crc, nbytes]``. Readers
merge every rank's table (shards/replicated blobs may be read by any
rank, see manifest.get_manifest_for_rank) and verify whole-blob reads;
ranged reads (chunked/batched restores) cannot be checked against a
whole-blob digest and are skipped.

Algorithms: ``crc32c`` via the native lib; if it is unavailable the
writer falls back to zlib's ``crc32`` and tags the table accordingly, so
a reader verifies with whichever algorithm the writer used. Tables are
optional on read — snapshots written with checksums disabled (or by
older versions) restore without verification.
"""

from __future__ import annotations

import asyncio
import json
import logging
import zlib
from typing import Dict, Optional, Tuple

from . import _native, knobs
from .io_types import BufferType, ReadIO, StoragePlugin, WriteIO

logger: logging.Logger = logging.getLogger(__name__)

CHECKSUM_DIR = "checksums"

# path -> (alg, crc, nbytes)
ChecksumTable = Dict[str, Tuple[str, int, int]]


def table_path(rank: int) -> str:
    return f"{CHECKSUM_DIR}/{rank}"


def compute_checksum(buf: BufferType) -> Tuple[str, int]:
    """Digest of ``buf``: native CRC32-C when available (GIL-free, fast),
    else zlib CRC32. Returns ``(alg, value)``."""
    crc = _native.crc32c(buf)
    if crc is not None:
        return ("crc32c", crc)
    mv = memoryview(buf)
    if mv.format != "B":
        mv = mv.cast("B")
    return ("crc32", zlib.crc32(mv) & 0xFFFFFFFF)


def verify_checksum(buf: BufferType, expected: Tuple[str, int, int], path: str) -> None:
    """Raise :class:`ChecksumError` when ``buf`` does not match the
    recorded digest. Algorithm mismatches (table written with crc32c but
    the native lib is unavailable here, or vice versa) are skipped — a
    missing implementation must not fail restores."""
    alg, crc, nbytes = expected
    mv = memoryview(buf)
    if mv.nbytes != nbytes:
        raise ChecksumError(
            f"{path}: size mismatch (expected {nbytes} bytes, read {mv.nbytes})"
        )
    if alg == "crc32c":
        actual: Optional[int] = _native.crc32c(buf)
        if actual is None:
            return  # native lib unavailable on the reading host
    elif alg == "crc32":
        if mv.format != "B":
            mv = mv.cast("B")
        actual = zlib.crc32(mv) & 0xFFFFFFFF
    else:
        return  # unknown algorithm from a future version
    if actual != crc:
        raise ChecksumError(
            f"{path}: {alg} mismatch (expected {crc:#010x}, got {actual:#010x})"
        )


class ChecksumError(RuntimeError):
    """A blob's bytes do not match the digest recorded at write time."""


async def write_checksum_table(
    checksums: ChecksumTable, rank: int, storage: StoragePlugin
) -> None:
    payload = json.dumps(
        {path: list(entry) for path, entry in sorted(checksums.items())}
    ).encode()
    await storage.write(WriteIO(path=table_path(rank), buf=payload))


def sync_write_checksum_table(
    checksums: ChecksumTable,
    rank: int,
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
) -> None:
    event_loop.run_until_complete(write_checksum_table(checksums, rank, storage))


def load_checksum_tables(
    world_size: int,
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
) -> Optional[ChecksumTable]:
    """Merge every rank's table; ``None`` when the snapshot has no tables
    (written with checksums disabled, or predates them)."""

    async def _load_one(rank: int) -> Optional[ChecksumTable]:
        read_io = ReadIO(path=table_path(rank))
        try:
            await storage.read(read_io)
        except FileNotFoundError:
            return None  # table never written (checksums disabled / old snapshot)
        except Exception as e:
            # Integrity must not silently turn off exactly when storage is
            # unhealthy: make degraded verification visible.
            logger.warning(
                "Could not read checksum table %s (%r); blobs it covers "
                "will restore UNVERIFIED",
                table_path(rank),
                e,
            )
            return None
        if read_io.buf is None:
            return None
        try:
            raw = json.loads(bytes(read_io.buf).decode())
        except (ValueError, UnicodeDecodeError) as e:
            logger.warning(
                "Checksum table %s is unparseable (%r); blobs it covers "
                "will restore UNVERIFIED",
                table_path(rank),
                e,
            )
            return None
        return {path: (str(e[0]), int(e[1]), int(e[2])) for path, e in raw.items()}

    async def _load_all() -> Optional[ChecksumTable]:
        # Bounded like every other storage op: world_size unbounded GETs per
        # reading rank is O(world^2) simultaneous requests fleet-wide at the
        # barrier-synchronized start of a restore — enough to trip cloud
        # throttling precisely when verification is wanted.
        slots = asyncio.Semaphore(knobs.get_per_rank_io_concurrency())

        async def _bounded(rank: int) -> Optional[ChecksumTable]:
            async with slots:
                return await _load_one(rank)

        tables = await asyncio.gather(*(_bounded(r) for r in range(world_size)))
        if all(t is None for t in tables):
            return None
        merged: ChecksumTable = {}
        for t in tables:
            if t:
                merged.update(t)
        return merged

    return event_loop.run_until_complete(_load_all())
