"""Blob-level integrity: CRC32-C checksums recorded at write time and
verified on read.

This subsystem has no counterpart in the reference (its durability story
ends at the atomic commit marker, snapshot.py:230-237); it exists here
because the native I/O runtime already computes CRC32-C at memory speed
with the GIL released (native/ts_io.cpp), so end-to-end bit-rot detection
costs a small fraction of storage bandwidth.

Layout: each rank writes a ``checksums/{rank}`` JSON table after all its
storage writes are durable and *before* the commit barrier — a committed
snapshot therefore always has complete tables. Keys are storage paths
(globally unique per blob); values are ``[alg, crc, nbytes]`` or, for
blobs larger than one page, ``[alg, crc, nbytes, page_size, [page
crcs...]]``. Readers merge every rank's table (shards/replicated blobs
may be read by any rank, see manifest.get_manifest_for_rank). Whole-blob
reads verify against the blob digest; *ranged* reads (memory-budgeted
chunked restores, batched slabs) verify every page their byte range
fully covers — a range only loses coverage of its up-to-two partial edge
pages, so "checksums on" is never hollow for large-blob restores.

Algorithms: ``crc32c`` via the native lib; if it is unavailable the
writer falls back to zlib's ``crc32`` and tags the table accordingly, so
a reader verifies with whichever algorithm the writer used. Tables are
optional on read — snapshots written with checksums disabled (or by
older versions) restore without verification.
"""

from __future__ import annotations

import asyncio
import functools
import json
import logging
import zlib
from typing import Dict, Optional, Tuple

from . import _native, knobs
from .io_types import (
    BufferList,
    BufferType,
    ReadIO,
    StoragePlugin,
    WriteIO,
    as_bytes_view as _as_bytes_view,
)

logger: logging.Logger = logging.getLogger(__name__)

CHECKSUM_DIR = "checksums"

# Page granularity for ranged-read verification. 4 MiB: small enough that
# memory-budgeted chunk reads (typically >= tens of MiB) cover many full
# pages, large enough that per-page crc call overhead is noise.
PAGE_SIZE = 4 * 1024 * 1024

# path -> (alg, crc, nbytes) | (alg, crc, nbytes, page_size, [page crcs])
ChecksumTable = Dict[str, Tuple]


def table_path(rank: int) -> str:
    return f"{CHECKSUM_DIR}/{rank}"


def _pick_alg() -> str:
    return "crc32c" if _native.crc32c(b"") is not None else "crc32"


# Reflected polynomials for CRC combination (zlib crc32_combine algorithm).
_POLY = {"crc32c": 0x82F63B78, "crc32": 0xEDB88320}


def _gf2_times(mat, vec: int) -> int:
    out = 0
    i = 0
    while vec:
        if vec & 1:
            out ^= mat[i]
        vec >>= 1
        i += 1
    return out


def _gf2_square(mat):
    return [_gf2_times(mat, mat[i]) for i in range(32)]


@functools.lru_cache(maxsize=64)
def _crc_shift_operator(length: int, alg: str):
    """GF(2) operator advancing a CRC over ``length`` zero bytes — the
    zlib crc32_combine construction, parametrized by polynomial. Applying
    it to crc(a) and XORing crc(b) yields crc(a ‖ b) for len(b)=length.
    Cached: the construction is ~22 pure-Python matrix squarings and the
    write path needs it once per (length, alg), not once per blob."""
    poly = _POLY[alg]
    # operator for one zero BIT
    odd = [poly] + [1 << (i - 1) for i in range(1, 32)]
    even = _gf2_square(odd)   # two bits
    odd = _gf2_square(even)   # four bits
    # Walk ``length`` in BYTES (zlib crc32_combine): the first squaring
    # below yields the 8-bit (one-byte) operator, matching bit 0 of the
    # byte count; each further squaring doubles the byte weight.
    op = None
    mat = odd
    n = length
    while n:
        mat = _gf2_square(mat)
        if n & 1:
            op = mat if op is None else [_gf2_times(mat, op[i]) for i in range(32)]
        n >>= 1
    if op is None:  # length 0
        op = [1 << i for i in range(32)]
    return op


def crc_combine(crc1: int, crc2: int, len2: int, alg: str, _op=None) -> int:
    """crc(a ‖ b) from crc(a)=crc1, crc(b)=crc2, len(b)=len2."""
    op = _op if _op is not None else _crc_shift_operator(len2, alg)
    return _gf2_times(op, crc1) ^ crc2


def _crc_of(mv: memoryview, alg: str, seed: int = 0) -> int:
    """Running digest: ``seed`` is the digest of the preceding bytes, so
    page digests chain into the whole-blob digest (both the native
    CRC32-C and zlib CRC32 support continuation)."""
    if alg == "crc32c":
        crc = _native.crc32c(mv, seed=seed)
        assert crc is not None  # caller picked the alg from availability
        return crc
    return zlib.crc32(mv, seed) & 0xFFFFFFFF


def compute_checksum(buf: BufferType) -> Tuple[str, int]:
    """Digest of ``buf``: native CRC32-C when available (GIL-free, fast),
    else zlib CRC32. Returns ``(alg, value)``."""
    alg = _pick_alg()
    return (alg, _crc_of(_as_bytes_view(buf), alg))


def compute_checksum_entry(buf) -> Tuple:
    """Full table entry for one staged blob. Single-page blobs get the
    whole-blob digest; larger blobs additionally get per-page digests for
    ranged-read verification. The whole-blob digest is folded from the
    page digests with GF(2) shift operators (the zlib crc32_combine
    construction) — O(1) per page, so each byte is CRC'd exactly once.
    Accepts a :class:`BufferList` (the zero-pack vectorized payload):
    page digests then chain across part boundaries, yielding the exact
    entry the consolidated bytes would — bit-identical tables on both
    write paths, without consolidating."""
    if isinstance(buf, BufferList):
        return _entry_from_parts(buf.parts, buf.nbytes)
    mv = _as_bytes_view(buf)
    nbytes = mv.nbytes
    alg = _pick_alg()
    if nbytes <= PAGE_SIZE:
        return (alg, _crc_of(mv, alg), nbytes)
    pages: list = [
        _crc_of(mv[off : off + PAGE_SIZE], alg)
        for off in range(0, nbytes, PAGE_SIZE)
    ]
    return entry_from_page_crcs(pages, nbytes, alg)


def _entry_from_parts(parts, nbytes: int) -> Tuple:
    """Table entry for a logically-concatenated multi-part blob: per-page
    digests over the concatenated stream (both CRC implementations
    support continuation, so a page straddling parts chains its running
    digest through the seed), folded exactly like the contiguous path."""
    alg = _pick_alg()
    pages: list = []
    cur = 0
    cur_len = 0
    for mv in parts:
        off = 0
        while off < mv.nbytes:
            take = min(PAGE_SIZE - cur_len, mv.nbytes - off)
            cur = _crc_of(mv[off : off + take], alg, seed=cur)
            cur_len += take
            off += take
            if cur_len == PAGE_SIZE:
                pages.append(cur)
                cur, cur_len = 0, 0
    if cur_len:
        pages.append(cur)
    if nbytes <= PAGE_SIZE:
        return (alg, pages[0] if pages else _crc_of(memoryview(b""), alg), nbytes)
    return entry_from_page_crcs(pages, nbytes, alg)


def entry_from_page_crcs(pages: list, nbytes: int, alg: str = "crc32c") -> Tuple:
    """Table entry from per-page digests (the shared tail of both the
    two-step path, :func:`compute_checksum_entry`, and the fused native
    write+CRC path): the whole-blob digest is folded from the page
    digests in O(1) per page (GF(2) shift operators) — each byte is
    CRC'd exactly once, wherever the pages came from."""
    if nbytes <= PAGE_SIZE:
        return (alg, pages[0] if pages else _crc_of(memoryview(b""), alg), nbytes)
    assert len(pages) == (nbytes + PAGE_SIZE - 1) // PAGE_SIZE
    full_op = _crc_shift_operator(PAGE_SIZE, alg)
    tail = nbytes - (len(pages) - 1) * PAGE_SIZE
    tail_op = full_op if tail == PAGE_SIZE else _crc_shift_operator(tail, alg)
    whole = pages[0]
    for i, page_crc in enumerate(pages[1:], start=1):
        op = tail_op if i == len(pages) - 1 else full_op
        whole = crc_combine(whole, page_crc, 0, alg, _op=op)
    return (alg, whole, nbytes, PAGE_SIZE, pages)


def _alg_available(alg: str) -> bool:
    if alg == "crc32c":
        return _native.crc32c(b"") is not None
    return alg == "crc32"


def verify_checksum(buf: BufferType, expected: Tuple, path: str) -> None:
    """Raise :class:`ChecksumError` when ``buf`` does not match the
    recorded digest. Paged entries carry a real whole-blob digest (folded
    from the page digests) and verify through the normal whole-CRC path;
    only interim-format tables whose whole-blob field is None fall back
    to page-by-page verification. Algorithm mismatches (table written
    with crc32c but the native lib is unavailable here, or vice versa)
    are skipped — a missing implementation must not fail restores."""
    alg, crc, nbytes = expected[0], expected[1], expected[2]
    mv = _as_bytes_view(buf)
    if mv.nbytes != nbytes:
        raise ChecksumError(
            f"{path}: size mismatch (expected {nbytes} bytes, read {mv.nbytes})"
        )
    if not _alg_available(alg):
        return  # unknown algorithm / native lib unavailable on this host
    if crc is None and len(expected) >= 5:
        # Interim paged format carried no whole-blob digest: verify
        # page-by-page (covers every byte plus the size check above).
        verify_range_checksum(mv, expected, (0, nbytes), path)
        return
    actual = _crc_of(mv, alg)
    if actual != crc:
        raise ChecksumError(
            f"{path}: {alg} mismatch (expected {crc:#010x}, got {actual:#010x})"
        )


def verify_page_crcs(
    pages: list, nbytes: int, expected: Tuple, path: str
) -> bool:
    """Verify a whole blob from per-page digests computed during its read
    (the fused native read+CRC path) — no second pass over the bytes.
    Pure GF(2) arithmetic: O(pages), independent of blob size.

    Returns True when verification ran (raising :class:`ChecksumError`
    on mismatch); False when the entry cannot be checked from these
    pages (non-crc32c table, or an interim-format entry recorded with a
    different page size) — the caller then verifies the buffer itself."""
    alg, crc, exp_nbytes = expected[0], expected[1], expected[2]
    if nbytes != exp_nbytes:
        raise ChecksumError(
            f"{path}: size mismatch (expected {exp_nbytes} bytes, "
            f"read {nbytes})"
        )
    if alg != "crc32c":
        return False  # pages are crc32c; a foreign-alg table needs the bytes
    if crc is None:
        # Interim paged format (no whole digest): page lists compare only
        # at matching granularity.
        if len(expected) >= 5 and expected[3] == PAGE_SIZE:
            if list(expected[4]) != list(pages):
                raise ChecksumError(f"{path}: crc32c page digests mismatch")
            return True
        return False
    folded = entry_from_page_crcs(pages, nbytes, alg)
    if folded[1] != crc:
        raise ChecksumError(
            f"{path}: {alg} mismatch (expected {crc:#010x}, "
            f"got {folded[1]:#010x})"
        )
    return True


def verify_range_checksum(
    buf: BufferType, expected: Tuple, byte_range: Tuple[int, int], path: str
) -> bool:
    """Verify a ranged read of ``path`` covering blob bytes
    ``[byte_range[0], byte_range[1])`` against the entry's per-page
    digests: a short read raises (a truncated blob must fail loudly here,
    not as an opaque consumer error), every fully-covered page is
    checked, and up-to-two partial edge pages are skipped. Returns True
    when at least one page was verified (False = entry has no pages or
    the range covers none fully)."""
    start, end = byte_range
    mv = _as_bytes_view(buf)
    # Size check first: a truncated ranged read of a *non-paged* entry
    # (len(expected) < 5) must still fail loudly here.
    if mv.nbytes != end - start:
        raise ChecksumError(
            f"{path}: ranged read [{start}, {end}) returned {mv.nbytes} "
            f"bytes (expected {end - start})"
        )
    if len(expected) < 5:
        return False
    alg, _, nbytes, page_size, pages = expected[:5]
    if not _alg_available(alg):
        return False
    first_page = (start + page_size - 1) // page_size  # first fully-covered
    verified = False
    for page in range(first_page, len(pages)):
        p0 = page * page_size
        p1 = min(p0 + page_size, nbytes)
        if p1 > end:
            break
        actual = _crc_of(mv[p0 - start : p1 - start], alg)
        if actual != pages[page]:
            raise ChecksumError(
                f"{path}: {alg} mismatch in page {page} "
                f"(blob bytes [{p0}, {p1})): expected "
                f"{pages[page]:#010x}, got {actual:#010x}"
            )
        verified = True
    return verified


class ChecksumError(RuntimeError):
    """A blob's bytes do not match the digest recorded at write time."""


async def write_checksum_table(
    checksums: ChecksumTable, rank: int, storage: StoragePlugin
) -> None:
    payload = json.dumps(
        {path: list(entry) for path, entry in sorted(checksums.items())}
    ).encode()
    await storage.write(WriteIO(path=table_path(rank), buf=payload))


def sync_write_checksum_table(
    checksums: ChecksumTable,
    rank: int,
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
) -> None:
    event_loop.run_until_complete(write_checksum_table(checksums, rank, storage))


def load_checksum_tables(
    world_size: int,
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
) -> Optional[ChecksumTable]:
    """Merge every rank's table; ``None`` when the snapshot has no tables
    (written with checksums disabled, or predates them)."""

    async def _load_one(rank: int) -> Optional[ChecksumTable]:
        read_io = ReadIO(path=table_path(rank))
        try:
            await storage.read(read_io)
        except FileNotFoundError:
            return None  # table never written (checksums disabled / old snapshot)
        except Exception as e:
            # Integrity must not silently turn off exactly when storage is
            # unhealthy: make degraded verification visible.
            logger.warning(
                "Could not read checksum table %s (%r); blobs it covers "
                "will restore UNVERIFIED",
                table_path(rank),
                e,
            )
            return None
        if read_io.buf is None:
            return None
        try:
            raw = json.loads(bytes(read_io.buf).decode())
        except (ValueError, UnicodeDecodeError) as e:
            logger.warning(
                "Checksum table %s is unparseable (%r); blobs it covers "
                "will restore UNVERIFIED",
                table_path(rank),
                e,
            )
            return None
        return {path: tuple(e) for path, e in raw.items()}

    async def _load_all() -> Optional[ChecksumTable]:
        # Bounded like every other storage op: world_size unbounded GETs per
        # reading rank is O(world^2) simultaneous requests fleet-wide at the
        # barrier-synchronized start of a restore — enough to trip cloud
        # throttling precisely when verification is wanted.
        slots = asyncio.Semaphore(knobs.get_per_rank_io_concurrency())

        async def _bounded(rank: int) -> Optional[ChecksumTable]:
            async with slots:
                return await _load_one(rank)

        tables = await asyncio.gather(*(_bounded(r) for r in range(world_size)))
        if all(t is None for t in tables):
            return None
        merged: ChecksumTable = {}
        for t in tables:
            if t:
                merged.update(t)
        return merged

    return event_loop.run_until_complete(_load_all())
