"""Write-load balancing of replicated state across ranks.

Reference parity: torchsnapshot/partitioner.py (302 LoC). Replicated state
exists identically on every rank; writing it from all of them wastes
bandwidth, writing it all from rank 0 serializes the save. Instead the
write requests for replicated entries are partitioned across ranks with a
greedy argmin bin-packing (reference ``_partition_write_loads``,
partitioner.py:42-79), seeded with each rank's unavoidable non-replicated
write load. Chunked entries are sub-partitionable: their chunks can land on
different ranks (reference ``_is_subpartitionable``, :31-39); everything
else is assigned whole.

Rank 0 computes the assignment and broadcasts it, so every rank agrees
without trusting floating-point reductions. Entries are *not* trimmed to
the owned chunks (the reference trims then re-merges, :147-166 + :236-292);
keeping complete entries everywhere and deduplicating at manifest-gather
time yields the same committed metadata with less bookkeeping.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Tuple

from . import knobs
from .io_types import WriteReq
from .manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    Entry,
    Manifest,
    is_container_entry,
    is_replicated,
)
from .pg_wrapper import PGWrapper

logger: logging.Logger = logging.getLogger(__name__)


def _estimate_write_req_size(req: WriteReq) -> int:
    """Staging cost is a faithful stand-in for bytes-on-storage for arrays
    and a best-effort one for pickled objects (reference
    _estimate_write_req_storage_size, partitioner.py:82-90)."""
    return max(1, req.buffer_stager.get_staging_cost_bytes())


def partition_write_reqs(
    entries: Manifest, write_reqs: List[WriteReq], pg_wrapper: PGWrapper
) -> Tuple[Manifest, List[WriteReq]]:
    """Drop this rank's replicated write requests that other ranks will
    write instead; returns (entries, kept_write_reqs).

    Reference parity: partition_write_reqs (partitioner.py:169-233).
    """
    if pg_wrapper.get_world_size() == 1:
        return entries, write_reqs
    if knobs.is_partitioner_disabled():
        raise NotImplementedError(
            "TORCHSNAPSHOT_TPU_DISABLE_PARTITIONER is set; the reference "
            "raises here too (partitioner.py:199-202)"
        )

    replicated_reqs: Dict[str, WriteReq] = {}
    kept: List[WriteReq] = []
    base_load = 0
    for req in write_reqs:
        if req.path.startswith("replicated/"):
            replicated_reqs[req.path] = req
        else:
            kept.append(req)
            base_load += _estimate_write_req_size(req)

    # (path -> size) for this rank's replicated write requests; identical
    # across ranks by construction (same state, same chunking knobs).
    local_items = {
        path: _estimate_write_req_size(req)
        for path, req in replicated_reqs.items()
    }

    # Gather-to-leader: only rank 0 consumes the per-rank item/load lists
    # (it computes the assignment and broadcasts it) — non-leaders must
    # not each pull O(world x items) through the coordinator.
    gathered_items = pg_wrapper.gather_object(sorted(local_items.items()))
    gathered_loads = pg_wrapper.gather_object(base_load)

    assignment: Dict[str, int] = {}
    if pg_wrapper.get_rank() == 0:
        assert gathered_items is not None and gathered_loads is not None
        # Union of items across ranks (a path replicated on a strict subset
        # of ranks was already rejected by replication verification, but be
        # permissive here); each item is assignable to any rank that has it.
        item_holders: Dict[str, List[int]] = {}
        item_sizes: Dict[str, int] = {}
        for rnk, items in enumerate(gathered_items):
            for path, size in items:
                item_holders.setdefault(path, []).append(rnk)
                item_sizes[path] = size
        loads = list(gathered_loads)
        for path in sorted(
            item_sizes, key=lambda p: item_sizes[p], reverse=True
        ):
            holders = item_holders[path]
            target = min(holders, key=lambda r: loads[r])
            assignment[path] = target
            loads[target] += item_sizes[path]
    assignment = pg_wrapper.broadcast_object(assignment)

    rank = pg_wrapper.get_rank()
    for path, req in replicated_reqs.items():
        if assignment.get(path, 0) == rank:
            kept.append(req)
    logger.debug(
        "Rank %d keeps %d/%d replicated write reqs after partitioning",
        rank,
        len(kept) + len(replicated_reqs) - len(write_reqs),
        len(replicated_reqs),
    )
    return entries, kept


def consolidate_replicated_entries(
    gathered_manifests: List[Manifest],
) -> Dict[str, Entry]:
    """Merge replicated entries across gathered rank manifests into one
    complete entry per logical path (reference partitioner.py:236-292).

    With untrimmed entries this is mostly an equality assertion; chunked
    entries are unioned by chunk offsets for safety.
    """
    merged: Dict[str, Entry] = {}
    for manifest in gathered_manifests:
        for path, entry in manifest.items():
            if not is_replicated(entry) or is_container_entry(entry):
                continue
            if path not in merged:
                merged[path] = entry
                continue
            existing = merged[path]
            if isinstance(entry, ChunkedArrayEntry) and isinstance(
                existing, ChunkedArrayEntry
            ):
                by_offsets = {tuple(c.offsets): c for c in existing.chunks}
                for chunk in entry.chunks:
                    key = tuple(chunk.offsets)
                    if key not in by_offsets or _prefer_rewritten(
                        chunk.array, by_offsets[key].array
                    ):
                        by_offsets[key] = chunk
                merged[path] = ChunkedArrayEntry(
                    dtype=entry.dtype,
                    shape=entry.shape,
                    chunks=[by_offsets[k] for k in sorted(by_offsets)],
                    replicated=True,
                )
            elif entry != existing:
                # Slab batching rewrites an entry's location/byte_range on
                # the one rank that owns the write; that rewritten entry is
                # the authoritative one (the original location was never
                # written by anybody).
                if _is_entry_rewritten(entry, existing):
                    merged[path] = entry
                elif _is_entry_rewritten(existing, entry):
                    pass  # existing already authoritative
                else:
                    raise AssertionError(
                        f"Replicated entry mismatch across ranks for {path!r}: "
                        f"{existing} != {entry}"
                    )
    return merged


def _prefer_rewritten(candidate: ArrayEntry, incumbent: ArrayEntry) -> bool:
    """True when ``candidate`` is the batch-rewritten flavor of
    ``incumbent`` (same logical payload, slab location)."""
    return candidate.location.startswith(
        "batched/"
    ) and not incumbent.location.startswith("batched/")


def _is_entry_rewritten(entry: Entry, other: Entry) -> bool:
    if not isinstance(entry, ArrayEntry) or not isinstance(other, ArrayEntry):
        return False
    if not _prefer_rewritten(entry, other):
        return False
    # Payload-identifying fields must still agree.
    return (
        entry.dtype == other.dtype
        and entry.shape == other.shape
        and entry.serializer == other.serializer
        and entry.replicated == other.replicated
    )
