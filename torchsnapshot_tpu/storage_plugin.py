"""Storage plugin registry: URL scheme -> plugin, plus entry-point extension.

Reference parity: torchsnapshot/storage_plugin.py:17-59. ``fs://`` is the
default scheme for bare paths; ``memory://`` is a TPU-repo addition used by
tests and scratch runs; ``s3://`` / ``gs://`` map to the cloud plugins
(import-gated on their optional dependencies);
``tiered://<fast_url>|<durable_url>`` composes two of the above into a
fast-commit + background-durable-mirror pair (tiered/). Third-party
plugins register via the ``torchsnapshot_tpu.storage_plugins``
entry-point group.
"""

from __future__ import annotations

from importlib.metadata import entry_points
from typing import Optional, Tuple

from .io_types import StoragePlugin

_ENTRY_POINT_GROUP = "torchsnapshot_tpu.storage_plugins"


def normalize_object_key(prefix: str, path: str) -> str:
    """Join an object-store prefix with a blob path, resolving any
    parent-relative components lexically. Incremental snapshots reference
    base-step blobs through ``../step_.../...`` locations; object keys
    have no directory semantics, so ``..`` must be collapsed here (shared
    by the s3 and gcs plugins so ref resolution cannot diverge)."""
    key = f"{prefix}/{path}" if prefix else path
    if ".." in path:
        import posixpath

        key = posixpath.normpath(key)
    return key


def _parse_url(url_path: str) -> Tuple[str, str]:
    if "://" in url_path:
        scheme, _, path = url_path.partition("://")
        return (scheme or "fs", path)
    return ("fs", url_path)


def split_tiered_url(url_path: str) -> Optional[Tuple[str, str]]:
    """``(fast_url, durable_url)`` for a ``tiered://fast|durable`` URL,
    None for any other scheme. Each side is itself a full snapshot URL
    (bare paths mean ``fs://``); nesting tiered inside tiered is
    rejected — the mirror topology is exactly two tiers."""
    scheme, path = _parse_url(url_path)
    if scheme != "tiered":
        return None
    fast, sep, durable = path.partition("|")
    if not sep or not fast or not durable:
        raise ValueError(
            f"tiered URL {url_path!r} must be "
            f"'tiered://<fast_url>|<durable_url>'"
        )
    for side in (fast, durable):
        if _parse_url(side)[0] == "tiered":
            raise ValueError(
                f"tiered URL {url_path!r} nests a tiered tier; only two "
                f"tiers are supported"
            )
    return fast, durable


def join_path(url_path: str, segment: str) -> str:
    """Append a path segment to a snapshot location URL. For tiered URLs
    the segment applies to BOTH tiers (the two trees mirror each other
    blob-for-blob); for every other scheme this is the plain
    ``rstrip('/') + '/' + segment`` join the manager has always used."""
    tiers = split_tiered_url(url_path)
    if tiers is not None:
        fast, durable = tiers
        return f"tiered://{join_path(fast, segment)}|{join_path(durable, segment)}"
    return f"{url_path.rstrip('/')}/{segment}"


def url_to_storage_plugin(url_path: str) -> StoragePlugin:
    """Build the storage plugin for a snapshot location URL.

    A bare path is treated as ``fs://``. Unknown schemes fall through to the
    entry-point registry so external backends can plug in without touching
    this package.
    """
    scheme, path = _parse_url(url_path)

    if scheme == "fs":
        from .storage_plugins.fs import FSStoragePlugin

        return FSStoragePlugin(root=path)
    if scheme == "memory":
        from .storage_plugins.memory import MemoryStoragePlugin

        return MemoryStoragePlugin(name=path or "default")
    if scheme == "s3":
        from .storage_plugins.s3 import S3StoragePlugin

        return S3StoragePlugin(root=path)
    if scheme in ("gs", "gcs"):
        from .storage_plugins.gcs import GCSStoragePlugin

        return GCSStoragePlugin(root=path)
    if scheme == "tiered":
        from .tiered.plugin import TieredStoragePlugin

        fast_url, durable_url = split_tiered_url(url_path)
        return TieredStoragePlugin(fast_url=fast_url, durable_url=durable_url)

    eps = entry_points(group=_ENTRY_POINT_GROUP)
    for ep in eps:
        if ep.name == scheme:
            return ep.load()(path)
    raise RuntimeError(
        f"Unsupported storage scheme {scheme!r} in {url_path!r} "
        f"(built-in: fs, memory, s3, gs, tiered; "
        f"entry-point group: {_ENTRY_POINT_GROUP})"
    )


def url_to_storage_plugin_in_event_loop(
    url_path: str, event_loop: Optional["object"] = None
) -> StoragePlugin:
    """Reference-parity alias (storage_plugin.py:62); plugin construction is
    synchronous here, so the event loop is unused but kept for API shape."""
    return url_to_storage_plugin(url_path)
