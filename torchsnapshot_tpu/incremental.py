"""Incremental takes: skip unchanged chunks using on-device digests.

No counterpart exists in the reference — its every take rewrites all
bytes. On TPU the dominant cost of a checkpoint is the device→host copy
followed by storage writes; for real training states much of that traffic
is redundant (embedding tables with sparse updates, frozen towers, EMA
copies, optimizer moments of frozen params). This module detects
unchanged chunks *on device* — a jitted 64-bit digest per chunk
(ops/device_digest.py), so only 8 bytes cross the link per unchanged
chunk — and rewrites neither their D2H nor their storage bytes. The new
manifest instead carries entries whose ``location`` points into the base
snapshot (``../step_.../...``), which every storage plugin resolves
lexically.

Granularity is exactly the write granularity the preparers already use
(whole dense arrays, dim-0 chunks of large dense arrays, replica-0
subdivided shard boxes of sharded arrays), so a skipped chunk references
a blob whose bytes are byte-identical to what a full take would have
written. Digest equality is probabilistic (~2^-64 false-skip per chunk
comparison — far below memory error rates); restore-side CRC
verification (integrity.py) is unaffected because the referenced blob's
checksum entries are inherited into the new snapshot's table.

Interplay with the rest of the take pipeline:

- The skip decision happens *before* stagers are constructed, so no
  ``copy_to_host_async`` prefetch fires for skipped chunks.
- Digest computations for every leaf are launched in one pass before any
  comparison blocks (JAX async dispatch pipelines them); the comparison
  pass then materializes results.
- Replicated entries skip identically on every rank (digests are
  functions of bytes only), so partitioning and replicated-entry
  consolidation see consistent manifests.
- If chunking/shard knobs or shardings changed between steps, chunk keys
  (offsets, sizes) stop matching and the affected leaves are simply
  rewritten in full — never incorrect, just not incremental.
"""

from __future__ import annotations

import logging
import os
import posixpath
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from . import knobs
from .manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    Entry,
    Manifest,
    ShardedArrayEntry,
    get_manifest_for_rank,
)
from .ops import device_digest as dd
from .serialization import Serializer, dtype_to_string

logger: logging.Logger = logging.getLogger(__name__)

ChunkKey = Tuple[Tuple[int, ...], Tuple[int, ...]]  # (offsets, sizes)


# Schemes whose plugins resolve parent-relative (``../``) locations: the
# filesystem natively, s3/gs by lexical key normalization. ``memory://``
# stores are flat per-name dicts with no cross-snapshot namespace, and
# unknown entry-point schemes can't be assumed to normalize — refs to
# either would take fine and then fail to restore.
_REF_CAPABLE_SCHEMES = ("fs", "s3", "gs")


def relative_ref_prefix(new_path: str, base_path: str) -> Optional[str]:
    """Relative prefix from the new snapshot root to the base snapshot
    root, or None when no resolvable lexical relation exists (different
    storage scheme, different s3/gs bucket, or a scheme whose plugin
    can't resolve parent refs). ``../step_0000000005``-style prefixes
    compose with base locations via ``posixpath.normpath``; chained refs
    collapse to the originating snapshot."""
    from .storage_plugin import _parse_url

    new_scheme, new_root = _parse_url(new_path)
    base_scheme, base_root = _parse_url(base_path)
    if new_scheme != base_scheme or new_scheme not in _REF_CAPABLE_SCHEMES:
        return None
    new_root = new_root.rstrip("/")
    base_root = base_root.rstrip("/")
    if not new_root or not base_root:
        return None
    if new_scheme == "fs":
        # relpath between a relative and an absolute fs path resolves
        # through the process cwd at *take* time; the resulting ref would
        # not survive a restore from a different cwd. Anchor both.
        new_root = os.path.abspath(new_root)
        base_root = os.path.abspath(base_root)
    if new_root == base_root:
        return None
    if new_scheme in ("s3", "gs"):
        # Object keys resolve lexically within one bucket only: a ref
        # must never climb past it.
        if new_root.split("/", 1)[0] != base_root.split("/", 1)[0]:
            return None
    rel = posixpath.relpath(base_root, new_root)
    if rel.startswith(("/", "./")) or rel == ".":
        return None
    return rel


class LeafIncrementalPlan:
    """Digest-comparison results for one leaf, consumed by the array
    preparers chunk-by-chunk: ``ref_entry`` returns a base-referencing
    entry for an unchanged chunk (the preparer then constructs no
    stager), ``digest_for`` the digest to record on a written chunk."""

    def __init__(
        self,
        refs: Dict[ChunkKey, Tuple[ArrayEntry, str]],
        digests: Dict[ChunkKey, str],
        on_ref_used: Callable[[str, str], None],
    ) -> None:
        # refs: chunk key -> (ref entry template, base-manifest location)
        self._refs = refs
        self._digests = digests
        self._on_ref_used = on_ref_used

    def ref_entry(
        self,
        offsets: Tuple[int, ...] | List[int],
        sizes: Tuple[int, ...] | List[int],
        replicated: bool,
    ) -> Optional[ArrayEntry]:
        hit = self._refs.get((tuple(offsets), tuple(sizes)))
        if hit is None:
            return None
        template, base_location = hit
        clone = ArrayEntry(
            location=template.location,
            serializer=template.serializer,
            dtype=template.dtype,
            shape=list(template.shape),
            replicated=replicated,
            byte_range=template.byte_range,
            digest=template.digest,
        )
        self._on_ref_used(clone.location, base_location)
        return clone

    def digest_for(
        self,
        offsets: Tuple[int, ...] | List[int],
        sizes: Tuple[int, ...] | List[int],
    ) -> Optional[str]:
        return self._digests.get((tuple(offsets), tuple(sizes)))


class _DigestBatch:
    """Digest work for one device group, dispatched as a single fused
    program (device_digest.digest_many_async): per-chunk dispatch
    round-trips dominate digest cost on real accelerators, so a take
    issues O(device groups) dispatches, not O(chunks)."""

    def __init__(self) -> None:
        self.specs: List[Tuple[Any, Optional[Tuple[Tuple[int, int], ...]]]] = []
        # Output-row mapping: one (logical_path, chunk_key) per digest row.
        self.rows: List[Tuple[str, ChunkKey]] = []


def _base_chunk_map(entry: Entry) -> Dict[ChunkKey, ArrayEntry]:
    """Every (offsets, sizes) box the base snapshot holds bytes for, with
    its dense entry — uniform across the three array flavors, so a leaf
    may change flavor between steps (dense → sharded, resharded meshes)
    and still match boxes that survived identically."""
    out: Dict[ChunkKey, ArrayEntry] = {}
    if isinstance(entry, ArrayEntry):
        shape = tuple(entry.shape)
        out[(tuple(0 for _ in shape), shape)] = entry
    elif isinstance(entry, ChunkedArrayEntry):
        for chunk in entry.chunks:
            out[(tuple(chunk.offsets), tuple(chunk.sizes))] = chunk.array
    elif isinstance(entry, ShardedArrayEntry):
        for shard in entry.shards:
            out[(tuple(shard.offsets), tuple(shard.sizes))] = shard.array
    return out


class IncrementalTakeContext:
    """Take-scoped digest state: launched futures, the base snapshot's
    chunk map, and the refs actually used (for checksum inheritance and
    the manager's retention bookkeeping)."""

    def __init__(
        self,
        base_available: Optional[Manifest],
        ref_prefix: Optional[str],
        base_path: Optional[str],
        base_world_size: int,
    ) -> None:
        self._base_available = base_available or {}
        self._ref_prefix = ref_prefix
        self._base_path = base_path
        self._base_world_size = base_world_size
        # logical_path -> ordered chunk keys (the leaf's digest layout);
        # presence of a path means its digests were (or are being)
        # computed — the analog of a "launch" having happened.
        self._layouts: Dict[str, List[ChunkKey]] = {}
        # (logical_path, chunk_key) -> (d1, d2); host digests land here at
        # launch, device digests at first plan_for (materialization).
        self._results: Dict[Tuple[str, ChunkKey], Tuple[int, int]] = {}
        # In-flight device groups: (future, output-row mapping).
        self._group_futs: List[Tuple[Any, List[Tuple[str, ChunkKey]]]] = []
        self._materialized = False
        self._current_leaves: Dict[str, Any] = {}
        self._replicated_paths: Set[str] = set()
        # new (normalized) ref location -> base-manifest location
        self.used_refs: Dict[str, str] = {}

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------

    @classmethod
    def build(
        cls,
        path: str,
        incremental_base: Optional[Any],
        rank: int,
    ) -> "IncrementalTakeContext":
        """``incremental_base`` is a snapshot path or Snapshot; None (or a
        base whose location can't be referenced relatively) yields a
        digest-record-only context — the take writes everything but its
        manifest can serve as a base for the next one."""
        if incremental_base is None:
            return cls(None, None, None, 0)
        from .snapshot import Snapshot

        base = (
            incremental_base
            if isinstance(incremental_base, Snapshot)
            else Snapshot(str(incremental_base))
        )
        try:
            metadata = base.metadata
        except Exception as e:  # noqa: BLE001 - base gone: full take
            logger.warning(
                "Incremental base %s unreadable (%r); taking a full snapshot",
                base.path,
                e,
            )
            return cls(None, None, None, 0)
        ref_prefix = relative_ref_prefix(path, base.path)
        if ref_prefix is None:
            logger.warning(
                "Incremental base %s is not relatively addressable from %s; "
                "taking a full snapshot (digests still recorded)",
                base.path,
                path,
            )
            return cls(None, None, None, 0)
        return cls(
            get_manifest_for_rank(metadata, rank),
            ref_prefix,
            base.path,
            metadata.world_size,
        )

    # ------------------------------------------------------------------
    # pass 1: launch digests
    # ------------------------------------------------------------------

    def launch(
        self,
        flattened: Dict[str, Any],
        array_prepare_func: Optional[Callable[..., Any]],
    ) -> None:
        """Kick off digest computation for every eligible array leaf.
        Device digests dispatch asynchronously; host digests compute
        inline. Must run before any stager construction so skip decisions
        precede D2H prefetches."""
        self._current_leaves = flattened
        if array_prepare_func is not None:
            # Written bytes are a function of the hook, not the leaf;
            # digests of the leaf would lie.
            return
        # Device digest work batches per device group — one fused dispatch
        # per group instead of one round-trip per chunk.
        batches: Dict[Tuple[int, ...], _DigestBatch] = {}
        for logical_path, leaf in flattened.items():
            try:
                self._collect_leaf(logical_path, leaf, batches)
            except Exception as e:  # noqa: BLE001 - digest is an optimization
                logger.warning(
                    "Digest launch failed for %r (%r); leaf will be "
                    "written in full",
                    logical_path,
                    e,
                )
                self._layouts.pop(logical_path, None)
        for batch in batches.values():
            if not batch.specs:
                continue
            try:
                fut = dd.digest_many_async(batch.specs)
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "Batched digest dispatch failed (%r); %d leaves will "
                    "be written in full",
                    e,
                    len({p for p, _ in batch.rows}),
                )
                for p, _ in batch.rows:
                    self._layouts.pop(p, None)
                continue
            self._group_futs.append((fut, batch.rows))

    @staticmethod
    def _device_group(arr: Any) -> Tuple[int, ...]:
        from .ops.device_pack import device_group_key

        return device_group_key(arr)

    def _collect_leaf(
        self,
        logical_path: str,
        leaf: Any,
        batches: Dict[Tuple[int, ...], _DigestBatch],
    ) -> None:
        from .io_preparer import (
            ChunkedArrayIOPreparer,
            PrimitivePreparer,
            _is_dense_array,
            chunk_shapes,
            effective_max_chunk_size_bytes,
            is_jax_array,
            is_sharded_array,
        )

        if PrimitivePreparer.should_inline(leaf):
            return
        if is_sharded_array(leaf):
            if dd.digest_supported(leaf.dtype):
                self._collect_sharded(logical_path, leaf, batches)
            return
        if not _is_dense_array(leaf) or not dd.digest_supported(leaf.dtype):
            return

        shape = tuple(int(d) for d in leaf.shape)
        keys: List[ChunkKey] = []
        # ``incremental=True`` sentinel: the collected chunk layout must
        # equal what the preparers will use when handed a non-None plan.
        if ChunkedArrayIOPreparer.should_chunk(leaf, incremental=True):
            ranges = chunk_shapes(
                list(shape),
                dtype_to_string(leaf.dtype),
                effective_max_chunk_size_bytes(True),
            )
            for start, stop in ranges:
                keys.append(
                    (
                        (start,) + tuple(0 for _ in shape[1:]),
                        (stop - start,) + shape[1:],
                    )
                )
            if is_jax_array(leaf):
                batch = batches.setdefault(
                    self._device_group(leaf), _DigestBatch()
                )
                batch.specs.append((leaf, tuple(ranges)))
                batch.rows.extend((logical_path, k) for k in keys)
            else:
                host = np.asarray(leaf)
                for (start, stop), key in zip(ranges, keys):
                    self._results[(logical_path, key)] = dd.digest_host(
                        host[start:stop]
                    )
        else:
            key = (tuple(0 for _ in shape), shape)
            keys.append(key)
            if is_jax_array(leaf):
                batch = batches.setdefault(
                    self._device_group(leaf), _DigestBatch()
                )
                batch.specs.append((leaf, None))
                batch.rows.append((logical_path, key))
            else:
                self._results[(logical_path, key)] = dd.digest_host(
                    np.asarray(leaf)
                )
        self._layouts[logical_path] = keys

    def _collect_sharded(
        self,
        logical_path: str,
        leaf: Any,
        batches: Dict[Tuple[int, ...], _DigestBatch],
    ) -> None:
        from .io_preparer import effective_max_shard_size_bytes
        from .parallel.overlap import Box, subdivide_box

        itemsize = np.dtype(leaf.dtype).itemsize
        max_shard = effective_max_shard_size_bytes(True)
        keys: List[ChunkKey] = []
        for dev_shard in leaf.addressable_shards:
            if dev_shard.replica_id != 0:
                continue
            box = Box.from_index(dev_shard.index, leaf.shape)
            shard_keys: List[ChunkKey] = []
            shard_ranges: List[Tuple[int, int]] = []
            whole = True
            for piece in subdivide_box(box, max_shard, itemsize):
                key = (tuple(piece.offsets), tuple(piece.sizes))
                shard_keys.append(key)
                row0 = piece.offsets[0] - box.offsets[0]
                shard_ranges.append((row0, row0 + piece.sizes[0]))
                whole = whole and piece == box
            batch = batches.setdefault(
                self._device_group(dev_shard.data), _DigestBatch()
            )
            batch.specs.append(
                (dev_shard.data, None if whole else tuple(shard_ranges))
            )
            batch.rows.extend((logical_path, k) for k in shard_keys)
            keys.extend(shard_keys)
        if keys:
            self._layouts[logical_path] = keys

    def _materialize_all(self) -> None:
        """Block on every device group's digest future (first plan_for
        call). A failed group degrades its leaves to full writes."""
        if self._materialized:
            return
        self._materialized = True
        for fut, rows in self._group_futs:
            try:
                values = dd.materialize_many(fut)
            except Exception as e:  # noqa: BLE001 - digest is an optimization
                logger.warning(
                    "Digest materialization failed (%r); %d leaves will "
                    "be written in full",
                    e,
                    len({p for p, _ in rows}),
                )
                for p, _ in rows:
                    self._layouts.pop(p, None)
                continue
            for (path, key), row in zip(rows, values):
                self._results[(path, key)] = (int(row[0]), int(row[1]))
        self._group_futs = []

    # ------------------------------------------------------------------
    # cross-rank agreement
    # ------------------------------------------------------------------

    def synchronize(self, pg_wrapper: Any, replicated_paths: Set[str]) -> None:
        """Align skip decisions across ranks for replicated leaves.

        Replicated manifest entries are asserted equal at consolidation
        (partitioner.consolidate_replicated_entries), so any per-rank
        divergence — a rank whose base metadata read failed, or whose
        digest launch errored for one leaf — must degrade *all* ranks to
        the same full-write (or digest-less) treatment, not crash the
        take. Two collective facts settle it: whether every rank has a
        usable base, and which replicated leaves every rank managed to
        launch digests for."""
        self._replicated_paths = set(replicated_paths)
        if pg_wrapper.get_world_size() == 1:
            return
        # Materialize before gathering so late (materialize-time) digest
        # failures are part of the agreement, not a divergence after it.
        self._materialize_all()
        local = (
            self._ref_prefix is not None,
            sorted(p for p in self._layouts if p in replicated_paths),
        )
        # Gather-to-leader + broadcast of the two decided facts: every
        # rank applies the same decision without pulling every rank's
        # launched-leaf list (O(world x leaves) per rank at torchrec
        # scale) through the coordinator.
        gathered = pg_wrapper.gather_object(local)
        decision = None
        if gathered is not None:
            all_have_base = all(has_base for has_base, _ in gathered)
            common_set = set(gathered[0][1])
            for _, launched in gathered[1:]:
                common_set &= set(launched)
            decision = (all_have_base, sorted(common_set))
        all_have_base, common_list = pg_wrapper.broadcast_object(decision)
        common = set(common_list)
        if not all_have_base:
            # Some rank can't reference the base: nobody may.
            self._base_available = {}
            self._ref_prefix = None
        for path in list(self._layouts):
            if path in replicated_paths and path not in common:
                del self._layouts[path]

    # ------------------------------------------------------------------
    # pass 2: materialize + compare
    # ------------------------------------------------------------------

    def plan_for(self, logical_path: str) -> Optional[LeafIncrementalPlan]:
        if logical_path not in self._layouts:
            return None
        self._materialize_all()
        keys = self._layouts.get(logical_path)
        if keys is None:  # group failed during materialization
            return None
        digests: Dict[ChunkKey, str] = {}
        for key in keys:
            value = self._results.get((logical_path, key))
            if value is None:
                return None
            digests[key] = dd.format_digest(value)

        refs: Dict[ChunkKey, Tuple[ArrayEntry, str]] = {}
        base_entry = self._base_available.get(logical_path)
        current_dtype = self._current_dtype(logical_path)
        current_replicated = self._is_replicated_dense(logical_path)
        if (
            base_entry is not None
            and self._ref_prefix is not None
            and current_dtype is not None
        ):
            for key, base_chunk in _base_chunk_map(base_entry).items():
                # The digest covers bytes, not the type tag — require the
                # base chunk to match the current leaf's dtype and the
                # box's shape exactly, on top of digest equality. The
                # base's replicated *placement* must also match the
                # current take's: a leaf promoted to (or demoted from)
                # replicated between steps would otherwise produce
                # rank-divergent refs into per-rank base locations, which
                # the replicated-entry consolidation assert rejects.
                if (
                    key in digests
                    and base_chunk.digest == digests[key]
                    and base_chunk.dtype == current_dtype
                    and base_chunk.serializer == Serializer.BUFFER_PROTOCOL.value
                    and list(base_chunk.shape) == list(key[1])
                    and base_chunk.replicated == current_replicated
                ):
                    template = ArrayEntry(
                        location=posixpath.normpath(
                            posixpath.join(self._ref_prefix, base_chunk.location)
                        ),
                        serializer=base_chunk.serializer,
                        dtype=base_chunk.dtype,
                        shape=list(base_chunk.shape),
                        replicated=base_chunk.replicated,
                        byte_range=base_chunk.byte_range,
                        digest=base_chunk.digest,
                    )
                    # Second element: the location as the *base manifest*
                    # spells it — the key its checksum table uses.
                    refs[key] = (template, base_chunk.location)
        if not refs and not digests:
            return None

        def on_ref_used(ref_location: str, base_location: str) -> None:
            self.used_refs[ref_location] = base_location

        return LeafIncrementalPlan(refs, digests, on_ref_used)

    def _is_replicated_dense(self, logical_path: str) -> bool:
        """The replicated flag the preparers will stamp on this leaf's
        dense entries: True only for non-sharded leaves matched by the
        verified replication set (sharded entries always carry False)."""
        if logical_path not in self._replicated_paths:
            return False
        from .io_preparer import is_sharded_array

        return not is_sharded_array(self._current_leaves.get(logical_path))

    def _current_dtype(self, logical_path: str) -> Optional[str]:
        leaf = self._current_leaves.get(logical_path)
        if leaf is None:
            return None
        try:
            return dtype_to_string(leaf.dtype)
        except Exception:  # noqa: BLE001
            return None

    # ------------------------------------------------------------------
    # checksum inheritance
    # ------------------------------------------------------------------

    def inherit_checksums(self, checksums: Dict[str, tuple]) -> None:
        """Copy the base snapshot's checksum entries for every referenced
        blob into this take's table (keyed by the new ref location), so
        restore-time verification covers unwritten bytes too."""
        if not self.used_refs or self._base_path is None:
            return
        if knobs.is_checksums_disabled():
            return
        import asyncio

        from .integrity import load_checksum_tables
        from .storage_plugin import url_to_storage_plugin

        # Fail-soft: every data blob and the manifest are already durable
        # by the time this runs; a transient error reading the base's
        # tables must degrade the referenced blobs to UNVERIFIED restores
        # (with a warning), not fail the whole checkpoint.
        base_table = None
        event_loop = asyncio.new_event_loop()
        try:
            try:
                storage = url_to_storage_plugin(self._base_path)
                try:
                    base_table = load_checksum_tables(
                        self._base_world_size, storage, event_loop
                    )
                finally:
                    try:
                        event_loop.run_until_complete(storage.close())
                    except Exception as close_exc:  # noqa: BLE001
                        # Close failures don't affect the already-loaded
                        # tables — inheritance proceeds normally.
                        logger.warning(
                            "Error closing base storage plugin after "
                            "checksum inheritance: %r",
                            close_exc,
                        )
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "Could not inherit checksum tables from base %s (%r); "
                    "referenced blobs will restore UNVERIFIED",
                    self._base_path,
                    e,
                )
        finally:
            event_loop.close()
        if not base_table:
            return
        for ref_loc, base_loc in self.used_refs.items():
            entry = base_table.get(base_loc)
            if entry is not None:
                checksums[ref_loc] = entry
