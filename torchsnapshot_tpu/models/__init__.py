from .transformer import (
    TransformerConfig,
    TrainState,
    forward,
    init_params,
    init_train_state,
    make_mesh,
    make_train_step,
    param_shardings,
    state_shardings,
)

__all__ = [
    "TransformerConfig",
    "TrainState",
    "forward",
    "init_params",
    "init_train_state",
    "make_mesh",
    "make_train_step",
    "param_shardings",
    "state_shardings",
]
