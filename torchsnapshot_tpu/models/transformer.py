"""Flagship model: a sharded decoder-only transformer LM (pure pytree).

The checkpointing framework itself carries no model (the reference,
torchsnapshot, is model-free — SURVEY.md §0); this module provides the
*workload* that exercises it: realistic multi-axis-sharded training state
(params + optax optimizer state + step counter + PRNG key) over a
``jax.sharding.Mesh``, which is exactly the state layout the sharded-array
preparers (sharded_io_preparer.py) must persist and elastically restore.

Parallelism layout (GSPMD — shardings annotated, XLA inserts collectives):

- mesh axes ``('dp', 'sp', 'tp')``:
  - **dp**  — data parallel over batch; also ZeRO/FSDP-style parameter
    sharding: every 2-d weight shards its non-tp dim over ``dp``.
  - **tp**  — Megatron-style tensor parallel: qkv / mlp-in are
    column-parallel (output features over ``tp``), out-proj / mlp-out are
    row-parallel (input features over ``tp``).
  - **sp**  — sequence/context parallel: activations between blocks are
    constrained to ``P('dp', 'sp', None)`` (sequence dim sharded); inside
    attention the constraint flips to heads-sharded
    ``P('dp', None, 'tp', None)``, so XLA inserts the sp↔tp all-to-alls
    (Ulysses-style sequence parallelism).
  - **ep**  — expert parallel for MoE blocks: expert-stacked weights shard
    their expert dim over the ``sp`` axis (the standard ep=sp axis-sharing:
    both exist to scale the same per-token dimension).

Pipeline parallelism is intentionally not modeled via GSPMD annotations
(it is a schedule, not a sharding); see parallel/pipeline.py.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import causal_attention
from ..ops.flash_attention import flash_causal_attention
from ..ops.ring_attention import ring_causal_attention


def _pallas_interpret() -> bool:
    """Pallas kernels compile natively only on TPU; everywhere else (CPU
    meshes in tests, the virtual-device dryrun) they run interpreted."""
    return jax.devices()[0].platform != "tpu"

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32768
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    n_experts: int = 0  # 0 = dense MLP in every block
    moe_every: int = 2  # every k-th block is MoE (when n_experts > 0)
    dtype: Any = jnp.bfloat16
    learning_rate: float = 1e-3
    # "ulysses": heads-sharded attention, sp↔tp all-to-alls at the block
    # boundary (short/medium context). "flash": same layout, but the dense
    # einsum is replaced by the Pallas flash kernel
    # (ops/flash_attention.py — O(block·d) VMEM instead of s² HBM logits;
    # requires seq % 128 == 0 on TPU). "ring": sequence stays sharded and
    # KV blocks rotate the sp ring (ops/ring_attention.py — long context,
    # O(seq_local^2) memory per device). "ring_flash": ring whose
    # per-step blockwise attention runs in the flash kernel (long context
    # without the O(seq_local^2) HBM intermediate either).
    attn_impl: str = "ulysses"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def make_mesh(
    n_devices: Optional[int] = None, devices: Optional[list] = None
) -> Mesh:
    """Build a ``('dp', 'sp', 'tp')`` mesh over ``n_devices``.

    Factors are assigned tp-first (tensor parallel wants the fastest ICI
    hops), then sp, then dp — e.g. 8 devices → (dp=2, sp=2, tp=2),
    4 → (1, 2, 2), 2 → (1, 1, 2), 1 → (1, 1, 1).
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    n = len(devices)
    tp = 2 if n % 2 == 0 else 1
    rem = n // tp
    sp = 2 if rem % 2 == 0 else 1
    dp = rem // sp
    arr = np.asarray(devices).reshape(dp, sp, tp)
    return Mesh(arr, axis_names=("dp", "sp", "tp"))


def _is_moe_layer(cfg: TransformerConfig, i: int) -> bool:
    return cfg.n_experts > 0 and (i % cfg.moe_every == cfg.moe_every - 1)


def param_shardings(cfg: TransformerConfig, mesh: Mesh) -> Params:
    """NamedSharding pytree matching :func:`init_params` structure."""

    def ns(*spec: Any) -> NamedSharding:
        return NamedSharding(mesh, P(*spec))

    layers = []
    for i in range(cfg.n_layers):
        block = {
            "ln1_scale": ns(None),
            "ln2_scale": ns(None),
            # column-parallel fused qkv: (d_model, 3 * d_model)
            "wqkv": ns("dp", "tp"),
            # row-parallel out proj: (d_model, d_model)
            "wo": ns("tp", "dp"),
        }
        if _is_moe_layer(cfg, i):
            block["router"] = ns(None, None)  # (d_model, n_experts)
            block["w_in"] = ns("sp", "dp", "tp")  # (E, d_model, d_ff)
            block["w_out"] = ns("sp", "tp", "dp")  # (E, d_ff, d_model)
        else:
            block["w_in"] = ns("dp", "tp")  # (d_model, d_ff)
            block["w_out"] = ns("tp", "dp")  # (d_ff, d_model)
        layers.append(block)
    return {
        # d_model over tp: the token gather is then local on every device
        # (vocab-dim sharding would force a masked-gather + collective).
        "embed": ns(None, "tp"),  # (vocab, d_model)
        "layers": layers,
        "ln_f_scale": ns(None),
        "unembed": ns("dp", "tp"),  # (d_model, vocab)
    }


def init_params(
    cfg: TransformerConfig,
    rng: jax.Array,
    mesh: Optional[Mesh] = None,
) -> Params:
    """Initialize parameters; sharded onto ``mesh`` when given.

    Init math runs inside ``jax.jit`` with ``out_shardings`` so each device
    materializes only its own shard (no full-model host copy — matters for
    the 20 GB-class benchmark configs).
    """

    def _init(rng: jax.Array) -> Params:
        n_keys = 3 + 5 * cfg.n_layers
        keys = iter(jax.random.split(rng, n_keys))

        def dense(key: jax.Array, shape: Tuple[int, ...]) -> jax.Array:
            fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
            std = 1.0 / math.sqrt(fan_in)
            return (jax.random.normal(key, shape, dtype=jnp.float32) * std).astype(
                cfg.dtype
            )

        layers = []
        for i in range(cfg.n_layers):
            block = {
                "ln1_scale": jnp.ones((cfg.d_model,), dtype=cfg.dtype),
                "ln2_scale": jnp.ones((cfg.d_model,), dtype=cfg.dtype),
                "wqkv": dense(next(keys), (cfg.d_model, 3 * cfg.d_model)),
                "wo": dense(next(keys), (cfg.d_model, cfg.d_model)),
            }
            if _is_moe_layer(cfg, i):
                block["router"] = dense(next(keys), (cfg.d_model, cfg.n_experts))
                block["w_in"] = dense(
                    next(keys), (cfg.n_experts, cfg.d_model, cfg.d_ff)
                )
                block["w_out"] = dense(
                    next(keys), (cfg.n_experts, cfg.d_ff, cfg.d_model)
                )
            else:
                next(keys)  # keep key schedule layer-count-stable
                block["w_in"] = dense(next(keys), (cfg.d_model, cfg.d_ff))
                block["w_out"] = dense(next(keys), (cfg.d_ff, cfg.d_model))
            layers.append(block)
        return {
            "embed": dense(next(keys), (cfg.vocab_size, cfg.d_model)),
            "layers": layers,
            "ln_f_scale": jnp.ones((cfg.d_model,), dtype=cfg.dtype),
            "unembed": dense(next(keys), (cfg.d_model, cfg.vocab_size)),
        }

    if mesh is None:
        return jax.jit(_init)(rng)
    shardings = param_shardings(cfg, mesh)
    return jax.jit(_init, out_shardings=shardings)(rng)


def _rmsnorm(x: jax.Array, scale: jax.Array) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6).astype(x.dtype)) * scale


def _constrain(x: jax.Array, mesh: Optional[Mesh], *spec: Any) -> jax.Array:
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def _moe_mlp(block: Params, x: jax.Array) -> jax.Array:
    """Soft-routed MoE: every expert computed, outputs gate-combined.

    Shape-static (no dynamic dispatch), so it jits cleanly and the expert
    einsums shard over the ``sp`` (=ep) axis via the stacked-weight
    shardings. Token-dropping top-k dispatch with all_to_all is a later
    optimization; for checkpointing purposes the state layout is identical.
    """
    gates = jax.nn.softmax(
        jnp.einsum("bsd,de->bse", x, block["router"].astype(jnp.float32)), axis=-1
    ).astype(x.dtype)
    h = jnp.einsum("bsd,edf->ebsf", x, block["w_in"])
    h = jax.nn.gelu(h)
    y = jnp.einsum("ebsf,efd->ebsd", h, block["w_out"])
    return jnp.einsum("ebsd,bse->bsd", y, gates)


def _flash_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh],
    interpret: bool,
) -> jax.Array:
    """Flash attention under GSPMD: a ``pallas_call`` is a custom call XLA
    cannot partition, so on a mesh it must be wrapped in ``shard_map`` over
    the batch/head axes (sequence replicated — the Ulysses layout) to run
    per-device; single-device calls go straight through."""
    if mesh is None:
        return flash_causal_attention(q, k, v, interpret=interpret)
    has_dp = "dp" in mesh.axis_names
    has_tp = "tp" in mesh.axis_names
    spec = P("dp" if has_dp else None, None, "tp" if has_tp else None, None)
    from ..utils import shard_map_compat

    fn = shard_map_compat(
        functools.partial(flash_causal_attention, interpret=interpret),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def forward(
    cfg: TransformerConfig,
    params: Params,
    tokens: jax.Array,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """Token ids ``(batch, seq)`` → logits ``(batch, seq, vocab)``.

    Between blocks activations are sequence-sharded (sp); inside attention
    they are heads-sharded (tp). With ``mesh=None`` the same trace runs
    single-device (the graft ``entry()`` path).
    """
    if cfg.attn_impl not in ("ulysses", "flash", "ring", "ring_flash"):
        # A typo must not silently run the dense path the user was
        # explicitly opting out of.
        raise ValueError(
            f"unknown attn_impl {cfg.attn_impl!r}; expected one of "
            f"'ulysses', 'flash', 'ring', 'ring_flash'"
        )
    x = jnp.take(params["embed"], tokens, axis=0)
    x = _constrain(x, mesh, "dp", "sp", None)
    b, s, d = x.shape
    for i, block in enumerate(params["layers"]):
        h = _rmsnorm(x, block["ln1_scale"])
        qkv = jnp.einsum("bsd,dz->bsz", h, block["wqkv"])
        qkv = qkv.reshape(b, s, 3, cfg.n_heads, cfg.head_dim)
        if cfg.attn_impl in ("ring", "ring_flash") and mesh is not None:
            # Sequence stays sp-sharded; KV blocks rotate the ring.
            qkv = _constrain(qkv, mesh, "dp", "sp", None, "tp", None)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            attn = ring_causal_attention(
                q,
                k,
                v,
                mesh=mesh,
                use_flash=(cfg.attn_impl == "ring_flash"),
                interpret=_pallas_interpret(),
            )
        else:
            # Ulysses: resharding to heads-over-tp makes XLA insert the
            # sp↔tp all-to-alls around the attention op.
            qkv = _constrain(qkv, mesh, "dp", None, None, "tp", None)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            if cfg.attn_impl == "flash":
                if s % 128:
                    # Never degrade silently: the user chose flash to avoid
                    # the s² logits tensor; a quiet dense fallback would
                    # reintroduce exactly that (OOM at long seq).
                    raise ValueError(
                        f"attn_impl='flash' requires seq % 128 == 0, got "
                        f"seq={s}; pad the sequence or use attn_impl="
                        f"'ulysses'"
                    )
                attn = _flash_attention_sharded(
                    q, k, v, mesh, interpret=_pallas_interpret()
                )
            else:
                attn = causal_attention(q, k, v)
        attn = attn.reshape(b, s, d)
        x = x + _constrain(
            jnp.einsum("bsz,zd->bsd", attn, block["wo"]), mesh, "dp", "sp", None
        )
        h = _rmsnorm(x, block["ln2_scale"])
        if "router" in block:
            y = _moe_mlp(block, h)
        else:
            y = jnp.einsum(
                "bsf,fd->bsd", jax.nn.gelu(jnp.einsum("bsd,df->bsf", h, block["w_in"])),
                block["w_out"],
            )
        x = x + _constrain(y, mesh, "dp", "sp", None)
    x = _rmsnorm(x, params["ln_f_scale"])
    return jnp.einsum("bsd,dv->bsv", x, params["unembed"]).astype(jnp.float32)


# ----------------------------------------------------------------------
# Training state + step
# ----------------------------------------------------------------------


@dataclasses.dataclass
class TrainState:
    """The checkpointable unit: what Snapshot.take persists for this model."""

    params: Params
    opt_state: Any
    step: jax.Array  # scalar int32
    rng: jax.Array  # PRNGKey

    def as_pytree(self) -> Dict[str, Any]:
        return {
            "params": self.params,
            "opt_state": self.opt_state,
            "step": self.step,
            "rng": self.rng,
        }


jax.tree_util.register_dataclass(
    TrainState, ["params", "opt_state", "step", "rng"], []
)


def _optimizer(cfg: TransformerConfig) -> optax.GradientTransformation:
    return optax.adamw(cfg.learning_rate)


def init_train_state(
    cfg: TransformerConfig,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
) -> TrainState:
    rng = jax.random.PRNGKey(seed)
    params = init_params(cfg, rng, mesh=mesh)
    opt = _optimizer(cfg)
    # Adam moments are zeros_like(params): GSPMD propagation shards them
    # like the params; the scalar count replicates. No manual out_shardings.
    opt_state = jax.jit(opt.init)(params)
    step = jnp.zeros((), dtype=jnp.int32)
    return TrainState(params=params, opt_state=opt_state, step=step, rng=rng)


def state_shardings(state: TrainState) -> Dict[str, Any]:
    """Sharding pytree of a live train state (restore destinations)."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.sharding, state.as_pytree()
    )


def make_train_step(
    cfg: TransformerConfig,
    mesh: Optional[Mesh] = None,
) -> Callable[[TrainState, jax.Array], Tuple[TrainState, jax.Array]]:
    """Build the jitted full training step (fwd + loss + bwd + adamw)."""
    opt = _optimizer(cfg)

    def loss_fn(params: Params, tokens: jax.Array) -> jax.Array:
        logits = forward(cfg, params, tokens, mesh=mesh)
        targets = tokens[:, 1:]
        logits = logits[:, :-1]
        losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        return jnp.mean(losses)

    def train_step(
        state: TrainState, tokens: jax.Array
    ) -> Tuple[TrainState, jax.Array]:
        if mesh is not None:
            tokens = jax.lax.with_sharding_constraint(
                tokens, NamedSharding(mesh, P("dp", None))
            )
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens)
        updates, new_opt_state = opt.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_rng = jax.random.fold_in(state.rng, state.step)
        return (
            TrainState(
                params=new_params,
                opt_state=new_opt_state,
                step=state.step + 1,
                rng=new_rng,
            ),
            loss,
        )

    return jax.jit(train_step, donate_argnums=(0,))
