"""Core I/O request/response types and the storage plugin interface.

Reference parity: torchsnapshot/io_types.py:29-103. A *write request* pairs a
storage path with a :class:`BufferStager` that produces the bytes (device →
host staging + serialization); a *read request* pairs a path (and optional
byte range) with a :class:`BufferConsumer` that absorbs the bytes
(deserialization + copy into the destination). The scheduler owns when each
stage runs; storage plugins own how bytes hit the backing store.
"""

from __future__ import annotations

import abc
from concurrent.futures import Executor
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple, Union

BufferType = Union[bytes, bytearray, memoryview]


def as_bytes_view(buf: BufferType) -> memoryview:
    """The one contiguous-byte-view normalization (flat ``B``-format
    memoryview) the Python layers share — batcher, plugins and the
    integrity module funnel through here, so a future change (e.g.
    non-contiguous handling) has one home. ``_native`` keeps its own
    inline copy: it is the dependency-free bottom layer."""
    mv = memoryview(buf)
    if mv.format != "B" or mv.ndim != 1:
        mv = mv.cast("B")
    return mv


class BufferList:
    """An ordered list of byte buffers forming ONE logical blob — the
    zero-pack write payload. The batcher's vectorized slab stage hands
    its members' staged buffers straight to the storage plugin as a
    ``BufferList`` instead of packing them into a staging bytearray
    (one full memory pass over every staged byte, eliminated); plugins
    that declare ``supports_multibuffer`` gather-write the parts in one
    vectorized kernel (fs: ``pwritev`` + fused CRC), and the scheduler
    consolidates for plugins that don't — paying exactly the old pack,
    never more.

    ``len()`` is the total byte count (scheduler budget accounting);
    ``parts`` are contiguous B-format memoryviews in blob order (the
    originals are kept referenced so the views stay valid)."""

    __slots__ = ("parts", "nbytes", "_keepalive")

    def __init__(self, parts: Sequence[BufferType]) -> None:
        self._keepalive = list(parts)
        self.parts: List[memoryview] = []
        total = 0
        for part in self._keepalive:
            mv = as_bytes_view(part)
            if mv.nbytes == 0:
                continue  # zero-length parts add nothing to the stream
            self.parts.append(mv)
            total += mv.nbytes
        self.nbytes = total

    def __len__(self) -> int:
        return self.nbytes

    def consolidate(self) -> memoryview:
        """One contiguous copy of the logical blob — the pack pass the
        zero-pack path avoids, kept as the compatibility fallback for
        plugins without multi-buffer support."""
        out = bytearray(self.nbytes)
        off = 0
        for mv in self.parts:
            out[off : off + mv.nbytes] = mv
            off += mv.nbytes
        return memoryview(out)


WritePayload = Union[BufferType, BufferList]


def payload_nbytes(buf: WritePayload) -> int:
    """Total byte count of a write payload, single-buffer or vectorized."""
    if isinstance(buf, BufferList):
        return buf.nbytes
    return as_bytes_view(buf).nbytes


@dataclass
class WriteIO:
    """A fully-staged write: raw bytes destined for ``path``. ``buf`` is
    a single contiguous buffer or a :class:`BufferList` (zero-pack
    vectorized form — only handed to plugins whose
    ``supports_multibuffer`` is true; the scheduler consolidates first
    otherwise). ``variant`` is set by the plugin after the write with
    the path that actually served it (``vectorized`` | ``direct`` |
    ``fused`` | ``buffered``) — the per-take write-path accounting
    SnapshotReports carry."""

    path: str
    buf: WritePayload
    variant: Optional[str] = field(default=None, compare=False)


@dataclass
class ReadIO:
    """A read of ``path``; ``byte_range`` is a half-open ``[start, end)``
    window, or ``None`` for the whole blob. ``buf`` is populated by the
    storage plugin.

    ``dest``, when set, is a writable view of the read's final destination
    (exactly the requested length). Plugins MAY read straight into it and
    set ``buf = dest`` — skipping the intermediate allocation and the
    consumer's copy — or ignore it and fill ``buf`` as usual.

    ``served_by`` is stamped by multi-source plugins (tiered, the peer
    ladder) with the tier that produced ``buf`` — the state
    :meth:`StoragePlugin.read_degraded` needs to try the *other*
    sources when verification rejects these bytes.
    """

    path: str
    byte_range: Optional[Tuple[int, int]] = None
    buf: Optional[memoryview] = None
    dest: Optional[memoryview] = None
    served_by: Optional[str] = field(default=None, compare=False)


class BufferStager(abc.ABC):
    """Produces the bytes for a write request.

    ``stage_buffer`` may run expensive work (device→host transfer,
    serialization) on ``executor``; the scheduler admits it only when the
    staging cost fits the host-memory budget.
    """

    @abc.abstractmethod
    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType: ...

    @abc.abstractmethod
    def get_staging_cost_bytes(self) -> int: ...

    def capture(self, cache: dict) -> None:
        """Pin a consistent snapshot of this stager's source *before*
        ``async_take`` returns, so the application may mutate (or
        donate) the live state while staging runs on the background
        drain. ``cache`` is shared across one take's stagers, keyed by
        ``id(source)``, so several stagers over one leaf (chunked
        writes, shard pieces) snapshot it once. Default: no-op —
        stagers whose source cannot change under them (or that stage
        before the take returns) need nothing."""
        return None


class BufferConsumer(abc.ABC):
    @abc.abstractmethod
    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None: ...

    @abc.abstractmethod
    def get_consuming_cost_bytes(self) -> int: ...

    def direct_destination(self) -> Optional[memoryview]:
        """A writable byte view of this consumer's final destination, or
        ``None`` when consuming involves more than a straight byte copy
        (deserialization, scatter into multiple views, dtype conversion).
        When a plugin fills it, ``consume_buffer`` is skipped entirely."""
        return None


@dataclass
class WriteReq:
    path: str
    buffer_stager: BufferStager


@dataclass
class ReadReq:
    path: str
    buffer_consumer: BufferConsumer
    byte_range: Optional[Tuple[int, int]] = None


class StoragePlugin(abc.ABC):
    """Abstract storage backend (reference: io_types.py:67-103).

    Implementations are used from a single asyncio event loop; blocking work
    must be dispatched to executors/threads internally. ``read`` fills
    ``read_io.buf`` (respecting ``byte_range``); ``write`` persists
    ``write_io.buf`` at ``write_io.path`` relative to the plugin root.
    """

    # Capability flag: plugins that can persist a BufferList payload
    # without consolidating it (fs: pwritev) set this true; for all
    # others the scheduler consolidates before the write ever reaches
    # the plugin, so write()/write_with_checksum() implementations may
    # assume a single contiguous buffer unless they opt in.
    supports_multibuffer: bool = False

    @abc.abstractmethod
    async def write(self, write_io: WriteIO) -> None: ...

    async def write_with_checksum(self, write_io: WriteIO):
        """Optional fused write + integrity pass: persist ``write_io`` AND
        return its checksum-table entry (``integrity.ChecksumTable``
        value), computed in the same pass over the bytes. Return ``None``
        to decline — having written NOTHING: the scheduler then computes
        the checksum separately and calls :meth:`write` (the default for
        every plugin without a native fused path). Declining is STICKY
        for the rest of the pipeline run (it signals a capability, e.g.
        "no native runtime here", not a per-request choice)."""
        return None

    @abc.abstractmethod
    async def read(self, read_io: ReadIO) -> None: ...

    async def read_with_checksum(self, read_io: ReadIO):
        """Optional fused whole-blob read + integrity pass: fill
        ``read_io.buf`` AND return the CRC32-C of each integrity page
        (``integrity.PAGE_SIZE``), computed in the same pass. Return
        ``None`` (having read nothing) to decline — the scheduler then
        calls :meth:`read` and verifies separately. Declining is STICKY
        for the rest of the pipeline run (a capability signal, not a
        per-request choice); ranged reads never reach this hook."""
        return None

    async def read_degraded(self, read_io: ReadIO) -> bool:
        """Self-healing hook: the bytes a prior :meth:`read` of
        ``read_io`` produced failed digest verification — re-serve the
        request from an alternate source (another tier's copy) if one
        remains untried. Returns True when an alternate produced bytes
        (``buf`` refilled, ``served_by`` restamped; the caller
        re-verifies and may call again on another mismatch), False when
        no alternates remain — the caller then raises the original
        ``ChecksumError``. Single-source plugins keep this default:
        there is nowhere else to turn."""
        return False

    @abc.abstractmethod
    async def delete(self, path: str) -> None: ...

    @abc.abstractmethod
    async def close(self) -> None: ...

    def sync_close(self) -> None:
        """Convenience for callers without a running loop."""
        from .event_loop import run_in_fresh_event_loop

        run_in_fresh_event_loop(self.close())
