"""Chaos engineering for the checkpoint stack (docs/chaos.md).

Three pieces, one adversary description:

- **Fault plans** (:mod:`~torchsnapshot_tpu.chaos.plan`): a seed plus a
  declarative fault list, serialized to ONE JSON line — every red run
  is replayable from a copy-paste.
- **The engine** (:mod:`~torchsnapshot_tpu.chaos.engine`): evaluates a
  plan against injection events from any wrapped
  :class:`~torchsnapshot_tpu.io_types.StoragePlugin`, coordination
  ``Store``, or the shared socket framing (TCP store + peer transport).
- **Crash points** (:mod:`~torchsnapshot_tpu.chaos.crashpoints`): named
  kill points (``CRASH_*`` in telemetry/names.py) threaded through the
  take/commit/GC/mirror paths; the **crash-matrix harness**
  (:mod:`~torchsnapshot_tpu.chaos.harness`) kills a take at every
  declared point and asserts the store's global invariants — fsck
  clean, newest committed step bit-identical, refcounts reconciled,
  journals healed, mirror resumed.
"""

from .crashpoints import (  # noqa: F401
    SimulatedCrash,
    arm,
    arm_engine,
    crashpoint,
    declared_crashpoints,
    disarm,
    hits,
)
from .engine import (  # noqa: F401
    ChaosEngine,
    ChaosStore,
    ChaosStoragePlugin,
    chaotic_plugin_type,
    corrupt_bytes,
    install_wire_chaos,
    uninstall_wire_chaos,
    wrap_plugin,
)
from .plan import (  # noqa: F401
    FaultPlan,
    FaultSpec,
    crash_plan,
    seeded_failure_plan,
)
