"""The crash matrix: kill a take at every declared crash point, then
prove the store's global invariants.

Each case builds a fresh manager root (plain or tiered, legacy or CAS
layout), commits two clean steps, arms ONE declared crash point, and
runs a third save — which the armed point kills mid-flight (take,
commit window, index write, retention GC, chunk GC, or mirror enqueue,
wherever the point lives). The case then asserts what PR after PR has
claimed piecewise, together and mechanically:

1. a fresh manager loads (journals heal, CAS refcounts reconcile);
2. the newest *indexed* step restores bit-identical;
3. ``fsck --deep`` of that step finds nothing;
4. CAS roots: ``fsck --cas --deep`` over the whole store finds nothing
   critical (pre-GC strays are informational by design);
5. tiered roots: the mirror resumes and ``wait_durable`` completes;
6. a clean retake over the damaged root commits and restores.

Every case is driven by a seeded fault plan; a failing case's result
carries the ONE JSON line (:meth:`CrashCaseResult.replay`) that
reproduces the identical fault schedule.

The point set is :func:`~torchsnapshot_tpu.chaos.declared_crashpoints`
— the ``CRASH_*`` registry in telemetry/names.py. Points that are
structurally unreachable in a configuration (CAS points under the
legacy layout, the mirror point on a plain root) are recorded as
inapplicable, and the full matrix asserts every point FIRES in at
least the tiered+CAS configuration, so a renamed or unthreaded point
can never silently leave the matrix.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import os
import shutil
from typing import Dict, List, Optional, Sequence

import numpy as np

from .crashpoints import (
    SimulatedCrash,
    arm_engine,
    declared_crashpoints,
    disarm,
    hits,
)
from .engine import ChaosEngine
from .plan import crash_plan

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class MatrixConfig:
    """One store configuration a crash point is exercised under."""

    name: str
    tiered: bool
    cas: bool

    def applicable(self, point: str) -> bool:
        if point.startswith("cas-") or point in (
            "refcount-pinned",
            "gc-unpinned",
        ):
            return self.cas
        if point == "mirror-enqueued":
            return self.tiered
        return True


CONFIGS = (
    MatrixConfig("plain-legacy", tiered=False, cas=False),
    MatrixConfig("plain-cas", tiered=False, cas=True),
    MatrixConfig("tiered-legacy", tiered=True, cas=False),
    MatrixConfig("tiered-cas", tiered=True, cas=True),
)
FULL_CONFIG = CONFIGS[3]  # tiered+CAS: every point must fire here


@dataclasses.dataclass
class CrashCaseResult:
    point: str
    config: str
    seed: int
    fired: bool
    applicable: bool
    failures: List[str] = dataclasses.field(default_factory=list)
    latest_step: Optional[int] = None

    @property
    def ok(self) -> bool:
        return not self.failures

    @property
    def replay(self) -> str:
        """The deterministic reproduction line: seed + fault plan."""
        return crash_plan(self.point, seed=self.seed).to_json()

    def describe(self) -> str:
        status = "ok" if self.ok else "FAILED"
        fired = "fired" if self.fired else (
            "inapplicable" if not self.applicable else "NEVER FIRED"
        )
        out = f"[{status}] {self.config} × {self.point} ({fired})"
        if self.failures:
            out += "".join(f"\n    - {f}" for f in self.failures)
            out += f"\n    replay: {self.replay}"
        return out


def _state_for(seed: int, step: int) -> Dict[str, np.ndarray]:
    """Deterministic per-step state: a dense leaf that changes every
    step and a static leaf (the CAS dedup case)."""
    rng = np.random.default_rng(seed)
    return {
        "w": (np.arange(4096, dtype=np.float32) + step),
        "b": rng.standard_normal(512).astype(np.float32),
        "step": np.asarray([step], dtype=np.int64),
    }


def _zeros_like(state: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    return {k: np.zeros_like(v) for k, v in state.items()}


def run_crash_case(
    base_dir: str,
    point: str,
    config: MatrixConfig,
    seed: int = 0,
    durable_timeout_s: float = 120.0,
) -> CrashCaseResult:
    """One matrix cell: fresh root, two clean saves, one save killed at
    ``point``, then the invariant battery. Never raises for store
    damage — every violation lands in ``result.failures``."""
    import torchsnapshot_tpu as ts
    from .. import knobs
    from ..fsck import verify_cas_store, verify_snapshot

    case_dir = os.path.join(
        base_dir, f"{config.name}-{point}".replace("/", "_")
    )
    shutil.rmtree(case_dir, ignore_errors=True)
    os.makedirs(case_dir, exist_ok=True)
    if config.tiered:
        fast = os.path.join(case_dir, "fast")
        durable = os.path.join(case_dir, "durable")
        root = f"tiered://{fast}|{durable}"
    else:
        root = os.path.join(case_dir, "root")

    result = CrashCaseResult(
        point=point,
        config=config.name,
        seed=seed,
        fired=False,
        applicable=config.applicable(point),
    )
    states = {step: _state_for(seed, step) for step in range(4)}
    cas_ctx = knobs.enable_cas() if config.cas else contextlib.nullcontext()
    with cas_ctx:
        mgr = ts.CheckpointManager(root, keep_last_n=2)
        try:
            for step in (0, 1):
                mgr.save(step, {"m": ts.PyTreeState(dict(states[step]))})
        except BaseException as e:  # noqa: BLE001 - setup must be clean
            result.failures.append(f"clean setup save failed: {e!r}")
            return result

        engine = ChaosEngine(crash_plan(point, seed=seed))
        arm_engine(engine)
        try:
            mgr.save(2, {"m": ts.PyTreeState(dict(states[2]))})
        except SimulatedCrash:
            result.fired = True
        except BaseException as e:  # noqa: BLE001
            result.failures.append(
                f"killed save raised {e!r} instead of SimulatedCrash"
            )
        finally:
            disarm()
        if not result.fired:
            if result.applicable:
                result.failures.append(
                    f"crash point {point!r} never fired under "
                    f"{config.name} (hits recorded: {hits()})"
                )
            return result

        # -- invariants over the damaged store --------------------------
        try:
            mgr2 = ts.CheckpointManager(root, keep_last_n=2)
        except BaseException as e:  # noqa: BLE001
            result.failures.append(f"manager reload failed: {e!r}")
            return result
        latest = mgr2.latest_step()
        result.latest_step = latest
        if latest not in (1, 2):
            result.failures.append(
                f"latest indexed step is {latest!r}, expected 1 or 2"
            )
            return result
        dest = {"m": ts.PyTreeState(_zeros_like(states[latest]))}
        try:
            restored = mgr2.restore_latest(dest)
        except BaseException as e:  # noqa: BLE001
            result.failures.append(f"restore of step {latest} failed: {e!r}")
            return result
        if restored != latest:
            result.failures.append(
                f"restore_latest returned {restored!r}, index said {latest}"
            )
        for key, want in states[latest].items():
            got = dest["m"].tree[key]
            if not np.array_equal(np.asarray(got), want):
                result.failures.append(
                    f"step {latest} leaf {key!r} not bit-identical "
                    f"after restore"
                )
        if config.tiered:
            # Quiesce the mirror BEFORE the audits: a half-shipped
            # durable copy mid-flight is the mirror working, not store
            # damage, and the per-tier deep checks below must not race
            # it.
            try:
                mgr2.resume_mirrors()
                mgr2.wait_durable(latest, timeout=durable_timeout_s)
                # ... and the crashed take's own orphan job (a
                # committed-but-unindexed step still mirrors) — drain
                # everything so no job races the audits below.
                from ..tiered.mirror import get_mirror

                get_mirror().drain(timeout=durable_timeout_s)
            except BaseException as e:  # noqa: BLE001
                result.failures.append(
                    f"mirror resume/wait_durable({latest}) failed: {e!r}"
                )
        fsck = verify_snapshot(mgr2.step_path(latest), deep=True)
        for prob in fsck.problems:
            result.failures.append(
                f"fsck({latest}): {prob.kind} {prob.location}: {prob.detail}"
            )
        if config.cas:
            cas_report = verify_cas_store(root, deep=True)
            for prob in cas_report.problems:
                result.failures.append(
                    f"fsck --cas: {prob.kind} {prob.location}: "
                    f"{prob.detail}"
                )

        # -- the damaged root must accept a clean retake -----------------
        try:
            mgr2.save(3, {"m": ts.PyTreeState(dict(states[3]))})
            dest3 = {"m": ts.PyTreeState(_zeros_like(states[3]))}
            ts.Snapshot(mgr2.step_path(3)).restore(dest3)
            for key, want in states[3].items():
                if not np.array_equal(
                    np.asarray(dest3["m"].tree[key]), want
                ):
                    result.failures.append(
                        f"post-crash retake leaf {key!r} not bit-identical"
                    )
        except BaseException as e:  # noqa: BLE001
            result.failures.append(f"post-crash retake failed: {e!r}")
    return result


def run_crash_matrix(
    base_dir: str,
    points: Optional[Sequence[str]] = None,
    configs: Sequence[MatrixConfig] = CONFIGS,
    seed: int = 0,
) -> List[CrashCaseResult]:
    """The sweep: every (declared point × configuration) cell. Returns
    every result; :func:`assert_matrix_green` turns violations into one
    failure message carrying each red cell's replay line."""
    results = []
    for config in configs:
        for point in points or declared_crashpoints():
            results.append(
                run_crash_case(base_dir, point, config, seed=seed)
            )
    return results


def assert_matrix_green(results: Sequence[CrashCaseResult]) -> None:
    bad = [r for r in results if not r.ok]
    if bad:
        raise AssertionError(
            f"crash matrix: {len(bad)} of {len(results)} cell(s) red\n"
            + "\n".join(r.describe() for r in bad)
        )
