"""The chaos engine: evaluates a fault plan against injection events.

One engine instance holds one :class:`~torchsnapshot_tpu.chaos.plan.
FaultPlan` and a per-spec trigger state (match counter, seeded RNG,
fires-so-far). Layers hand it events — ``engine.on_event(point, key)``
— and get back ``None`` (proceed) or the :class:`FaultSpec` that fired;
the wrappers below translate a fired spec into the concrete damage
(raise / sleep / flip a byte / tear / drop / simulated crash).

Determinism: per-spec RNGs are seeded ``plan.seed + spec index`` and
advance only on matching events, so the same plan over the same event
stream fires identically — the property the replay workflow (print one
JSON line, re-run) rests on. ``engine.fired`` records every trigger as
``(point, key, mode)`` for tests that pin schedule identity.

Three wrapping surfaces:

- :func:`wrap_plugin` / :func:`chaotic_plugin_type` — any
  :class:`StoragePlugin` (instance wrapper / subclass factory for
  ``patch_storage_plugin``-style class injection).
- :class:`ChaosStore` — any coordination ``Store``.
- :func:`install_wire_chaos` — the shared socket framing
  (``dist_store.send_frame``/``recv_frame``), covering the TCP store
  and the peer transport in one hook.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..io_types import (
    BufferList,
    ReadIO,
    StoragePlugin,
    WriteIO,
    as_bytes_view,
    payload_nbytes,
)
from .crashpoints import SimulatedCrash
from .plan import FaultPlan, FaultSpec


class _SpecState:
    __slots__ = ("spec", "rng", "seen", "fired")

    def __init__(self, spec: FaultSpec, seed: int) -> None:
        import random

        self.spec = spec
        self.rng = random.Random(seed)
        self.seen = 0
        self.fired = 0


class ChaosEngine:
    """Thread-safe trigger evaluation over one fault plan."""

    def __init__(self, plan: FaultPlan) -> None:
        self.plan = plan
        self._lock = threading.Lock()
        self._states = [
            _SpecState(spec, plan.seed + i)
            for i, spec in enumerate(plan.faults)
        ]
        # Every trigger, in order: (point, key, mode) — the replay pin.
        self.fired: List[Tuple[str, str, str]] = []

    def on_event(self, point: str, key: str = "") -> Optional[FaultSpec]:
        """Record one injection event; the first spec that triggers on
        it wins (at most one fault per event)."""
        with self._lock:
            for state in self._states:
                spec = state.spec
                if spec.point != point or not spec.matches(key):
                    continue
                state.seen += 1
                if state.seen <= spec.after:
                    continue
                if spec.times is not None and state.fired >= spec.times:
                    continue
                if spec.prob < 1.0 and state.rng.random() >= spec.prob:
                    continue
                state.fired += 1
                self.fired.append((point, key, spec.mode))
                return spec
            return None

    def raise_for(self, spec: FaultSpec, key: str) -> None:
        if spec.mode == "crash":
            raise SimulatedCrash(f"chaos: simulated crash at {key!r}")
        raise OSError(f"{spec.exc_msg} ({spec.point} {key!r})")


def corrupt_bytes(buf: bytes | bytearray | memoryview) -> bytes:
    """Size-preserving damage: flip one bit of the middle byte (an
    empty payload is returned unchanged — nothing to damage)."""
    data = bytearray(as_bytes_view(buf))
    if data:
        data[len(data) // 2] ^= 0x01
    return bytes(data)


# ---------------------------------------------------------------------------
# Storage plugin surface
# ---------------------------------------------------------------------------


async def _inject_write(
    engine: ChaosEngine,
    write_io: WriteIO,
    inner_write: Callable[[WriteIO], Any],
) -> None:
    spec = engine.on_event("storage-write", write_io.path)
    if spec is None:
        await inner_write(write_io)
        return
    if spec.mode == "delay":
        await asyncio.sleep(spec.delay_s)
        await inner_write(write_io)
        return
    if spec.mode == "drop":
        return  # a lost write: success reported, nothing persisted
    if spec.mode == "corrupt":
        buf = write_io.buf
        if isinstance(buf, BufferList):
            buf = buf.consolidate()
        await inner_write(
            WriteIO(path=write_io.path, buf=corrupt_bytes(buf))
        )
        return
    if spec.mode == "torn":
        buf = write_io.buf
        if isinstance(buf, BufferList):
            buf = buf.consolidate()
        mv = as_bytes_view(buf)
        await inner_write(
            WriteIO(path=write_io.path, buf=bytes(mv[: mv.nbytes // 2]))
        )
        raise OSError(f"{spec.exc_msg} (torn write of {write_io.path!r})")
    if spec.delay_s:  # a slow failure (timeout-shaped), not a fast one
        await asyncio.sleep(spec.delay_s)
    engine.raise_for(spec, write_io.path)


async def _inject_read(
    engine: ChaosEngine,
    read_io: ReadIO,
    inner_read: Callable[[ReadIO], Any],
) -> None:
    spec = engine.on_event("storage-read", read_io.path)
    if spec is None:
        await inner_read(read_io)
        return
    if spec.mode == "delay":
        await asyncio.sleep(spec.delay_s)
        await inner_read(read_io)
        return
    if spec.mode == "corrupt":
        # Read the real bytes, then damage what the caller sees. The
        # read must not land in a caller-owned direct destination
        # un-damaged, so the direct path is disabled for this request.
        shadow = ReadIO(path=read_io.path, byte_range=read_io.byte_range)
        await inner_read(shadow)
        damaged = corrupt_bytes(
            shadow.buf if shadow.buf is not None else b""
        )
        if read_io.dest is not None and len(read_io.dest) == len(damaged):
            read_io.dest[:] = damaged
            read_io.buf = read_io.dest
        else:
            read_io.buf = memoryview(damaged)
        read_io.served_by = shadow.served_by
        return
    if spec.delay_s:  # a slow failure (timeout-shaped), not a fast one
        await asyncio.sleep(spec.delay_s)
    engine.raise_for(spec, read_io.path)


class ChaosStoragePlugin(StoragePlugin):
    """Instance wrapper: every op of ``inner`` rides the engine.

    The fused ``*_with_checksum`` hooks decline (having done nothing):
    the scheduler then computes/verifies digests over the *original*
    bytes and calls the plain ops — which is exactly what makes
    ``corrupt`` injections land as restore-time ``ChecksumError``
    rather than silently poisoning the recorded tables. For the same
    reason the wrapper declares no multibuffer support (the scheduler
    consolidates first; the engine sees one buffer per blob)."""

    supports_multibuffer = False

    def __init__(self, inner: StoragePlugin, engine: ChaosEngine) -> None:
        self.inner = inner
        self.engine = engine

    async def write(self, write_io: WriteIO) -> None:
        await _inject_write(self.engine, write_io, self.inner.write)

    async def read(self, read_io: ReadIO) -> None:
        await _inject_read(self.engine, read_io, self.inner.read)

    async def read_degraded(self, read_io: ReadIO) -> bool:
        # The healing ladder re-reads through the inner plugin directly:
        # the adversary damaged a tier copy; the ladder's whole point is
        # reaching the OTHER tier's bytes.
        return await self.inner.read_degraded(read_io)

    async def delete(self, path: str) -> None:
        spec = self.engine.on_event("storage-delete", path)
        if spec is not None:
            if spec.mode == "delay":
                await asyncio.sleep(spec.delay_s)
            elif spec.mode == "drop":
                return
            else:
                self.engine.raise_for(spec, path)
        await self.inner.delete(path)

    async def close(self) -> None:
        await self.inner.close()


def wrap_plugin(inner: StoragePlugin, engine: ChaosEngine) -> StoragePlugin:
    return ChaosStoragePlugin(inner, engine)


def chaotic_plugin_type(base_cls: type, engine: ChaosEngine) -> type:
    """Subclass factory for class-injection seams (``test_utils.
    patch_storage_plugin`` constructs plugins from a CLASS): a
    ``base_cls`` whose plain ops ride ``engine`` and whose fused
    ``*_with_checksum`` hooks decline, with the same rationale as
    :class:`ChaosStoragePlugin`."""

    class _Chaotic(base_cls):  # type: ignore[misc,valid-type]
        supports_multibuffer = False

        async def write(self, write_io: WriteIO) -> None:
            await _inject_write(engine, write_io, super().write)

        async def write_with_checksum(self, write_io: WriteIO):
            return None  # decline: route through write() + engine

        async def read(self, read_io: ReadIO) -> None:
            await _inject_read(engine, read_io, super().read)

        async def read_with_checksum(self, read_io: ReadIO):
            return None  # decline: route through read() + engine

        async def delete(self, path: str) -> None:
            spec = engine.on_event("storage-delete", path)
            if spec is not None:
                if spec.mode == "delay":
                    await asyncio.sleep(spec.delay_s)
                elif spec.mode == "drop":
                    return
                else:
                    engine.raise_for(spec, path)
            await super().delete(path)

    _Chaotic.__name__ = f"Chaotic{base_cls.__name__}"
    _Chaotic.__qualname__ = _Chaotic.__name__
    return _Chaotic


# ---------------------------------------------------------------------------
# Coordination-store surface
# ---------------------------------------------------------------------------


def _store_base() -> type:
    from ..dist_store import Store

    return Store


class ChaosStore(_store_base()):
    """Delegating ``Store`` wrapper riding the engine on the four
    primitive ops. Subclassing the ABC (the ``ByteCountingStore``
    shape) means every inherited collective — gather, broadcast,
    barriers, the per-key ``multi_*`` fallbacks — runs through the
    wrapped primitives, so one wrapper chaoses all coordination
    traffic."""

    def __init__(self, inner: Any, engine: ChaosEngine) -> None:
        self.inner = inner
        self.engine = engine

    def _gate(self, point: str, key: str) -> Optional[FaultSpec]:
        import time

        spec = self.engine.on_event(point, key)
        if spec is None:
            return None
        if spec.mode == "delay":
            time.sleep(spec.delay_s)
            return None
        if spec.mode == "drop":
            return spec
        if spec.mode == "crash":
            raise SimulatedCrash(f"chaos: simulated crash at {key!r}")
        raise ConnectionError(f"{spec.exc_msg} ({point} {key!r})")

    def set(self, key: str, value: bytes) -> None:
        if self._gate("store-set", key) is not None:
            return  # dropped
        self.inner.set(key, value)

    def try_get(self, key: str):
        if self._gate("store-get", key) is not None:
            return None  # dropped: reads as absent
        return self.inner.try_get(key)

    def add(self, key: str, amount: int) -> int:
        if self._gate("store-add", key) is not None:
            # A "dropped" add has no honest success value: the request
            # (or its response) was lost, and the client cannot know
            # the counter — surface it as the connection error a lost
            # round trip produces.
            raise ConnectionError(
                f"chaos: dropped store-add round trip ({key!r})"
            )
        return self.inner.add(key, amount)

    def delete(self, key: str) -> None:
        if self._gate("store-delete", key) is not None:
            return
        self.inner.delete(key)

    def __getattr__(self, name: str) -> Any:
        return getattr(self.inner, name)


# ---------------------------------------------------------------------------
# Wire surface (send_frame/recv_frame)
# ---------------------------------------------------------------------------


def install_wire_chaos(engine: ChaosEngine) -> None:
    """Route every length-prefixed frame (TCP store + peer transport)
    through ``engine``: ``wire-send``/``wire-recv`` events keyed by the
    frame length. ``fail`` raises ``ConnectionError``, ``delay``
    sleeps, ``corrupt`` flips a payload byte (the receiver's parse /
    digest check catches it), ``drop`` on ``wire-send`` swallows the
    frame so the receiver's timeout/backoff path is what gets
    exercised. Process-local; pair with
    :func:`uninstall_wire_chaos` in a finally block."""
    from .. import dist_store

    dist_store._WIRE_CHAOS = _WireHook(engine)


def uninstall_wire_chaos() -> None:
    from .. import dist_store

    dist_store._WIRE_CHAOS = None


class _WireHook:
    __slots__ = ("engine",)

    def __init__(self, engine: ChaosEngine) -> None:
        self.engine = engine

    def __call__(self, point: str, payload: bytes) -> Optional[bytes]:
        import time

        spec = self.engine.on_event(point, str(len(payload)))
        if spec is None:
            return payload
        if spec.mode == "delay":
            time.sleep(spec.delay_s)
            return payload
        if spec.mode == "corrupt":
            return corrupt_bytes(payload)
        if spec.mode == "crash":
            raise SimulatedCrash("chaos: simulated crash on the wire")
        if spec.mode == "drop":
            if point == "wire-send":
                return None  # frame vanishes; the receiver waits it out
            # A received-then-dropped frame reads as a dead stream on
            # this side — there is no way to "unreceive" bytes.
            raise ConnectionError(f"{spec.exc_msg} (dropped frame)")
        raise ConnectionError(f"{spec.exc_msg} ({point})")


def degraded_summary(pipeline: Optional[Dict[str, Any]]) -> Dict[str, int]:
    """Convenience for tests: the rerouted-read accounting a pipeline
    telemetry dict carries (empty when nothing degraded)."""
    if not pipeline:
        return {}
    return dict(pipeline.get("degraded_reads") or {})
