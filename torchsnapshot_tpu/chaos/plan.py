"""Declarative, seeded fault plans — the one description of an adversary.

A :class:`FaultPlan` is a seed plus an ordered list of :class:`FaultSpec`
entries; it serializes to ONE JSON line (``to_json``/``from_json``) so a
red test run can print the exact adversary needed to replay it. The
engine (chaos/engine.py) evaluates the plan against a stream of
*injection events* — ``(point, key)`` pairs the instrumented layers
emit — and, because the per-spec RNGs are seeded from ``seed`` and
advance only on matching events, the same plan over the same event
stream always produces the same fault schedule.

Injection points (the ``point`` of a spec):

- ``storage-write`` / ``storage-read`` / ``storage-delete`` — a wrapped
  :class:`~torchsnapshot_tpu.io_types.StoragePlugin` op; ``key`` is the
  blob path.
- ``store-set`` / ``store-get`` / ``store-add`` / ``store-delete`` — a
  wrapped coordination :class:`~torchsnapshot_tpu.dist_store.Store` op;
  ``key`` is the store key.
- ``wire-send`` / ``wire-recv`` — one length-prefixed frame crossing
  the shared socket framing (``dist_store.send_frame``/``recv_frame``:
  the TCP store AND the peer transport); ``key`` is the frame length.
- ``crashpoint`` — a named kill point threaded through the take/commit/
  GC/mirror paths; ``key`` is the declared ``CRASH_*`` id
  (telemetry/names.py).

Modes (what happens when a spec triggers):

- ``fail`` — raise ``OSError(exc_msg)`` (storage), ``ConnectionError``
  (store/wire).
- ``delay`` — sleep ``delay_s``, then proceed normally.
- ``corrupt`` — size-preserving bit damage: flip one byte of the
  payload (written bytes, read buffer, or wire frame) — only a digest
  can catch it.
- ``torn`` — storage-write only: persist a strict prefix of the bytes,
  then raise (the kill-mid-write shape).
- ``drop`` — storage-write: report success, write nothing (a lost
  write); store-set: swallow the set.
- ``crash`` — raise :class:`~torchsnapshot_tpu.chaos.SimulatedCrash`
  (a ``BaseException``: best-effort ``except Exception`` recovery
  blocks cannot absorb it, matching a real kill).

Triggering: a spec considers only events whose ``point`` matches and
whose ``key`` contains ``match`` (empty = every key). Of those, the
first ``after`` are skipped, then each fires with probability ``prob``
(spec-seeded RNG; 1.0 = always) until ``times`` triggers have fired
(None = unbounded).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Dict, List, Optional, Sequence

MODES = ("fail", "delay", "corrupt", "torn", "drop", "crash")


@dataclasses.dataclass
class FaultSpec:
    """One fault: where (``point``/``match``), what (``mode``), when
    (``after``/``times``/``prob``). ``predicate`` is a programmatic
    escape hatch (a ``key -> bool`` callable consulted instead of
    ``match``) for in-process harnesses; it does not serialize —
    plans meant for replay use ``match``/``after``/``prob`` only."""

    point: str
    mode: str = "fail"
    match: str = ""
    after: int = 0
    times: Optional[int] = 1
    prob: float = 1.0
    delay_s: float = 0.0
    exc_msg: str = "chaos: injected fault"
    predicate: Optional[Callable[[str], bool]] = dataclasses.field(
        default=None, compare=False
    )

    def __post_init__(self) -> None:
        if self.mode not in MODES:
            raise ValueError(
                f"unknown fault mode {self.mode!r} (one of {MODES})"
            )

    def matches(self, key: str) -> bool:
        if self.predicate is not None:
            return bool(self.predicate(key))
        return self.match in key

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"point": self.point, "mode": self.mode}
        if self.match:
            out["match"] = self.match
        if self.after:
            out["after"] = self.after
        if self.times != 1:
            out["times"] = self.times
        if self.prob != 1.0:
            out["prob"] = self.prob
        if self.delay_s:
            out["delay_s"] = self.delay_s
        if self.exc_msg != "chaos: injected fault":
            out["exc_msg"] = self.exc_msg
        return out

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "FaultSpec":
        known = {f.name for f in dataclasses.fields(cls) if f.name != "predicate"}
        return cls(**{k: v for k, v in data.items() if k in known})


@dataclasses.dataclass
class FaultPlan:
    """A seed plus an ordered fault list; the unit of replay."""

    seed: int = 0
    faults: List[FaultSpec] = dataclasses.field(default_factory=list)

    def to_json(self) -> str:
        """ONE compact line — what a failing harness prints so the red
        run replays from a copy-paste."""
        return json.dumps(
            {
                "seed": self.seed,
                "faults": [f.to_dict() for f in self.faults],
            },
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, line: str) -> "FaultPlan":
        data = json.loads(line)
        return cls(
            seed=int(data.get("seed", 0)),
            faults=[FaultSpec.from_dict(f) for f in data.get("faults", [])],
        )

    @classmethod
    def single(cls, seed: int = 0, **spec_kwargs: Any) -> "FaultPlan":
        return cls(seed=seed, faults=[FaultSpec(**spec_kwargs)])


def crash_plan(
    point_name: str, seed: int = 0, after: int = 0
) -> FaultPlan:
    """The crash-matrix adversary: kill at the ``after+1``-th hit of one
    declared crash point."""
    return FaultPlan(
        seed=seed,
        faults=[
            FaultSpec(
                point="crashpoint",
                mode="crash",
                match=point_name,
                after=after,
            )
        ],
    )


def seeded_failure_plan(
    seed: int,
    point: str,
    fail_at: int,
    mode: str = "fail",
    exc_msg: str = "chaos: injected fault",
    ops: Sequence[str] = (),
    predicate: Optional[Callable[[str], bool]] = None,
    delay_s: float = 0.0,
) -> FaultPlan:
    """The crash-consistency sweep shape: fail every matching op of
    ``point`` (and of every extra point in ``ops``) after skipping the
    first ``fail_at``. Each point carries its OWN skip counter — a
    multi-point plan is N independent adversaries, not one shared "Nth
    storage op overall" counter; callers wanting a shared count across
    op kinds pass a counting ``predicate`` instead."""
    points = [point, *[p for p in ops if p != point]]
    return FaultPlan(
        seed=seed,
        faults=[
            FaultSpec(
                point=p,
                mode=mode,
                after=fail_at,
                times=None,
                exc_msg=exc_msg,
                predicate=predicate,
                delay_s=delay_s,
            )
            for p in points
        ],
    )
