"""Named kill points threaded through the take/commit/GC/mirror paths.

``crashpoint(names.CRASH_...)`` is the production no-op / test kill
switch: instrumented layers call it at the moments a real process kill
would be most damaging (chunk written but unpinned, backup index slot
written but not the primary, commit marker durable but unindexed, ...).
Unarmed, the call costs one global read and a branch. Armed — via
:func:`arm` (one point) or :func:`arm_engine` (a full fault plan whose
``crashpoint``-point specs drive it) — a matching hit raises
:class:`SimulatedCrash`.

``SimulatedCrash`` derives from ``BaseException`` on purpose: the
storage/telemetry layers wrap plenty of best-effort work in ``except
Exception`` blocks, and a simulated kill must not be absorbed by code a
real SIGKILL would never consult. (``finally`` blocks still run —
in-process simulation closes event loops a real kill would leak — so
the crash matrix asserts the *store's* invariants, which are exactly
the ones that must not depend on cleanup code running.)

The declared catalogue is the ``CRASH_*`` registry in
``telemetry/names.py`` (kebab-case, declared once, lint-enforced by
snaplint's ``crashpoint-ids``); :func:`declared_crashpoints` enumerates
it, which is how the crash-matrix harness turns "every declared point"
into a mechanical sweep — declaring a constant IS adding it to the
matrix.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

CRASHPOINT = "crashpoint"  # the injection-point name in fault plans


class SimulatedCrash(BaseException):
    """An armed crash point fired — the in-process stand-in for a kill.

    BaseException, not Exception: best-effort recovery blocks must not
    absorb a simulated kill."""


_LOCK = threading.Lock()
_ENGINE = None  # the armed ChaosEngine (None = every crashpoint no-ops)
_HITS: Dict[str, int] = {}  # per-point hit counts while armed


def crashpoint(name: str) -> None:
    """Declare-and-maybe-die: no-op unless a chaos engine is armed and
    one of its ``crashpoint`` specs triggers on ``name``."""
    engine = _ENGINE
    if engine is None:
        return
    with _LOCK:
        _HITS[name] = _HITS.get(name, 0) + 1
    spec = engine.on_event(CRASHPOINT, name)
    if spec is not None:
        engine.raise_for(spec, name)


def arm_engine(engine) -> None:
    """Arm a full chaos engine; its ``crashpoint``-point specs decide
    which hits kill. Resets the hit counters."""
    global _ENGINE
    with _LOCK:
        _HITS.clear()
        _ENGINE = engine


def arm(name: str, at: int = 1, seed: int = 0):
    """Arm exactly one point: the ``at``-th hit of ``name`` raises.
    Returns the backing engine (its ``fired`` log pins replays)."""
    from .engine import ChaosEngine
    from .plan import crash_plan

    engine = ChaosEngine(crash_plan(name, seed=seed, after=at - 1))
    arm_engine(engine)
    return engine


def disarm() -> None:
    global _ENGINE
    with _LOCK:
        _ENGINE = None


def hits(name: Optional[str] = None):
    """Hit counts recorded since arming (all points, or one)."""
    with _LOCK:
        if name is not None:
            return _HITS.get(name, 0)
        return dict(_HITS)


def declared_crashpoints() -> List[str]:
    """Every declared crash-point id, from the ``CRASH_*`` registry in
    telemetry/names.py — the crash matrix's row set."""
    from ..telemetry import names

    return sorted(
        value
        for const, value in vars(names).items()
        if const.startswith("CRASH_") and isinstance(value, str)
    )
