"""In-memory storage plugin.

No reference counterpart as a shipped plugin; it serves the role the
reference's test-side fake plugins play (tests/test_async_take.py:25-65) and
is handy as a scratch target (``memory://``). A process-wide registry of
named stores lets a writer and a reader in the same process share contents.
"""

from __future__ import annotations

import asyncio
import errno
from typing import Dict

from ..io_types import ReadIO, StoragePlugin, WriteIO

_STORES: Dict[str, Dict[str, bytes]] = {}


class MemoryStoragePlugin(StoragePlugin):
    def __init__(self, name: str = "default") -> None:
        self.name = name
        self._blobs: Dict[str, bytes] = _STORES.setdefault(name, {})

    async def write(self, write_io: WriteIO) -> None:
        self._blobs[write_io.path] = bytes(write_io.buf)
        await asyncio.sleep(0)  # keep scheduling behavior async-plugin-like

    async def read(self, read_io: ReadIO) -> None:
        if read_io.path not in self._blobs:
            raise FileNotFoundError(read_io.path)  # the FS plugin contract
        data = self._blobs[read_io.path]
        if read_io.byte_range is not None:
            start, end = read_io.byte_range
            if start < 0 or start > end or end > len(data):
                # FS-plugin contract (EIO, matching its native pread
                # path): a ranged read outside the blob is corruption,
                # not a partial success.
                raise OSError(
                    errno.EIO,
                    f"ranged read [{start}, {end}) invalid for "
                    f"{len(data)}-byte blob",
                    read_io.path,
                )
            data = data[start:end]
        read_io.buf = memoryview(data)
        await asyncio.sleep(0)

    async def delete(self, path: str) -> None:
        if path not in self._blobs:
            raise FileNotFoundError(path)
        del self._blobs[path]

    async def close(self) -> None:
        pass

    @classmethod
    def drop_store(cls, name: str) -> None:
        _STORES.pop(name, None)
