"""S3 storage plugin.

Reference parity: torchsnapshot/storage_plugins/s3.py:15-70 — aiobotocore
``put_object`` streaming uploads, HTTP Range reads (with the inclusive-end
adjustment S3 requires), per-plugin client session. The dependency is
import-gated: environments without aiobotocore (TPU images ship GCS deps
only) fail with an actionable error at plugin construction, not at import
of the package.
"""

from __future__ import annotations

import asyncio
import errno
import logging
import time

from .. import knobs
from ..io_types import ReadIO, StoragePlugin, WriteIO
from ..telemetry import observe_io
from ..telemetry.trace import io_span
from .retry import CollectiveProgressRetryStrategy

logger = logging.getLogger(__name__)

_TRANSIENT_S3_CODES = frozenset(
    {"SlowDown", "Throttling", "ThrottlingException", "RequestTimeout",
     "RequestLimitExceeded", "InternalError", "ServiceUnavailable"}
)


def _import_aiobotocore():
    try:
        from aiobotocore.session import get_session
    except ImportError as e:
        raise RuntimeError(
            "S3 support requires aiobotocore (pip install aiobotocore)"
        ) from e
    return get_session


def _is_transient_s3(exc: BaseException) -> bool:
    """Throttles (503 SlowDown, 429), 5xx, and connection-level failures are
    retriable; auth/4xx errors are not."""
    import botocore.exceptions as be

    if isinstance(exc, be.ClientError):
        err = exc.response.get("Error", {})
        status = exc.response.get("ResponseMetadata", {}).get("HTTPStatusCode")
        if err.get("Code") in _TRANSIENT_S3_CODES:
            return True
        return status is not None and (status in (408, 429) or status >= 500)
    if isinstance(exc, (be.EndpointConnectionError, be.ConnectionError,
                        be.HTTPClientError, be.ReadTimeoutError,
                        be.ConnectTimeoutError)):
        return True
    if isinstance(exc, FileNotFoundError):
        return False  # normalized missing-key: definitive, never retried
    if isinstance(exc, OSError) and exc.errno == errno.EIO:
        return False  # normalized out-of-range read: definitive truncation
    return isinstance(exc, (OSError, asyncio.TimeoutError))


class _TransientS3Error(Exception):
    pass


class S3StoragePlugin(StoragePlugin):
    def __init__(self, root: str) -> None:
        get_session = _import_aiobotocore()
        bucket, _, prefix = root.partition("/")
        if not bucket:
            raise ValueError(
                f"Invalid S3 root {root!r}; expected 'bucket[/prefix]'"
            )
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self._session = get_session()
        self._client_ctx = None
        self._client = None
        self._client_lock = asyncio.Lock()
        self._retry = CollectiveProgressRetryStrategy(scope="s3")

    def _key(self, path: str) -> str:
        from ..storage_plugin import normalize_object_key

        return normalize_object_key(self.prefix, path)

    async def _get_client(self):
        # Lock so N concurrent first ops don't each enter a client context
        # (all but the last would leak their connector).
        if self._client is None:
            async with self._client_lock:
                if self._client is None:
                    # MinIO CI lanes and private S3-compatible deployments
                    # point this at a non-AWS endpoint; unset = real S3.
                    endpoint = knobs.get_s3_endpoint_url()
                    kwargs = {"endpoint_url": endpoint} if endpoint else {}
                    self._client_ctx = self._session.create_client(
                        "s3", **kwargs
                    )
                    self._client = await self._client_ctx.__aenter__()
        return self._client

    async def _run_retrying(self, op):
        async def guarded():
            try:
                return await op()
            except Exception as e:
                if _is_transient_s3(e):
                    raise _TransientS3Error() from e
                raise

        return await self._retry.run(
            guarded, retriable_exceptions=(_TransientS3Error,)
        )

    async def write(self, write_io: WriteIO) -> None:
        client = await self._get_client()

        async def op() -> None:
            from ..memoryview_stream import MemoryviewStream

            # File-like body: botocore streams it (seek/tell for length and
            # retry rewind) instead of us copying the staged buffer into a
            # bytes — reference memoryview_stream.py:12-81 rationale.
            await client.put_object(
                Bucket=self.bucket,
                Key=self._key(write_io.path),
                Body=MemoryviewStream(memoryview(write_io.buf)),
            )

        nbytes = memoryview(write_io.buf).cast("B").nbytes
        t0 = time.monotonic()
        # Recorder-only span (io_span): this coroutine suspends across
        # the upload, so a thread-local jax annotation would mis-nest.
        with io_span("s3", "write", write_io.path, nbytes):
            await self._run_retrying(op)
        observe_io("s3", "write", nbytes, time.monotonic() - t0)

    async def read(self, read_io: ReadIO) -> None:
        client = await self._get_client()
        kwargs = {}
        if read_io.byte_range is not None:
            start, end = read_io.byte_range
            # S3 Range headers use inclusive ends (reference s3.py:57-60).
            kwargs["Range"] = f"bytes={start}-{end - 1}"

        async def op() -> bytes:
            import botocore.exceptions as be

            try:
                resp = await client.get_object(
                    Bucket=self.bucket, Key=self._key(read_io.path), **kwargs
                )
            except be.ClientError as e:
                code = e.response.get("Error", {}).get("Code")
                status = e.response.get("ResponseMetadata", {}).get(
                    "HTTPStatusCode"
                )
                if code in ("NoSuchKey", "404"):
                    # Normalize to the FS plugin's missing-blob contract so
                    # callers (e.g. checksum-table probing) can distinguish
                    # absent from unreadable.
                    raise FileNotFoundError(read_io.path) from e
                if code == "InvalidRange" or status == 416:
                    # Normalize out-of-range ranged reads to the fs/memory
                    # plugins' EIO contract: a range past the blob is
                    # truncation/corruption, not a partial success —
                    # fsck's and convert --verify's problem taxonomies
                    # depend on it. Definitive: never retried.
                    raise OSError(
                        errno.EIO,
                        f"ranged read {read_io.byte_range} is outside "
                        f"the blob",
                        read_io.path,
                    ) from e
                raise
            async with resp["Body"] as stream:
                return await stream.read()

        t0 = time.monotonic()
        with io_span(
            "s3", "read", read_io.path, byte_range=read_io.byte_range
        ):
            read_io.buf = memoryview(await self._run_retrying(op))
        observe_io(
            "s3", "read", read_io.buf.nbytes, time.monotonic() - t0
        )

    async def delete(self, path: str) -> None:
        client = await self._get_client()

        async def op() -> None:
            await client.delete_object(Bucket=self.bucket, Key=self._key(path))

        await self._run_retrying(op)

    async def close(self) -> None:
        if self._client_ctx is not None:
            await self._client_ctx.__aexit__(None, None, None)
            self._client = None
            self._client_ctx = None
