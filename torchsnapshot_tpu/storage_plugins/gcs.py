"""Google Cloud Storage plugin — the TPU-adjacent object store.

Reference parity: torchsnapshot/storage_plugins/gcs.py:47-211 (resumable
uploads, chunked/ranged downloads, transient-error taxonomy, shared
collective-progress retry). Blocking ``google-resumable-media`` calls are
bridged to asyncio on a dedicated thread pool, sized to the per-rank I/O
concurrency knob so storage writes overlap.

Auth: application-default credentials (the standard on TPU VMs, whose
metadata server grants the attached service account). Bucket paths are
``gs://bucket/prefix`` URLs.
"""

from __future__ import annotations

import asyncio
import io
import logging
import os
import random
import time
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional, Tuple

from .. import knobs, telemetry
from ..io_types import ReadIO, StoragePlugin, WriteIO
from ..telemetry import names as metric_names
from ..telemetry import observe_io
from ..telemetry.trace import get_recorder as _trace_recorder, io_span
from ..utils.tracing import trace_annotation
from .retry import CollectiveProgressRetryStrategy

logger = logging.getLogger(__name__)

_UPLOAD_CHUNK_SIZE = 100 * 1024 * 1024
_DOWNLOAD_CHUNK_SIZE = 100 * 1024 * 1024
# In-thread recover keeps the resumable session alive through brief
# brownouts (losing it forfeits every already-confirmed chunk: the outer
# retry restarts the upload from byte 0). Sleeps are short and capped —
# each blocks a gcs-io executor thread, and with every worker sleeping
# nothing can record progress on the collective deadline — so the total
# in-thread stall is bounded at ~8s before the failure propagates to the
# async retry strategy, whose asyncio.sleep backoff holds no thread.
_MAX_RECOVER_ATTEMPTS = 6
_RECOVER_SLEEP_CAP_SECONDS = 2.0


def _import_gcs_deps():
    try:
        import google.auth  # noqa: F401
        from google.auth.transport.requests import AuthorizedSession  # noqa: F401
        from google.resumable_media import common  # noqa: F401
        from google.resumable_media.requests import (  # noqa: F401
            ChunkedDownload,
            ResumableUpload,
        )
    except ImportError as e:
        raise RuntimeError(
            "GCS support requires google-auth and google-resumable-media "
            "(pip install google-auth google-resumable-media[requests])"
        ) from e
    return google.auth, AuthorizedSession, common, ChunkedDownload, ResumableUpload


def _is_transient(exc: BaseException, common: Any) -> bool:
    """Transient-error taxonomy (reference gcs.py:88-107): HTTP 408/429/5xx,
    connection resets, and invalid-response wrappers are retriable."""
    import requests

    if isinstance(exc, common.InvalidResponse):
        return exc.response.status_code in (408, 429) or (
            500 <= exc.response.status_code < 600
        )
    if isinstance(exc, (requests.ConnectionError, requests.Timeout)):
        return True
    if isinstance(exc, common.DataCorruption):
        return True
    return False


class GCSStoragePlugin(StoragePlugin):
    def __init__(self, root: str) -> None:
        (
            self._google_auth,
            authorized_session_cls,
            self._common,
            self._chunked_download_cls,
            self._resumable_upload_cls,
        ) = _import_gcs_deps()

        bucket, _, prefix = root.partition("/")
        if not bucket:
            raise ValueError(
                f"Invalid GCS root {root!r}; expected 'bucket[/prefix]'"
            )
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        # STORAGE_EMULATOR_HOST (the fake-gcs-server convention) redirects
        # every request to a local emulator with no auth — the CI path for
        # exercising resumable-upload recover and the transient-retry
        # taxonomy against a real HTTP server instead of mocks.
        emulator = os.environ.get("STORAGE_EMULATOR_HOST")
        if emulator:
            if "://" not in emulator:
                emulator = f"http://{emulator}"
            self._base_url = emulator.rstrip("/")
            import requests

            self._session = requests.Session()
        else:
            self._base_url = "https://storage.googleapis.com"
            credentials, _ = self._google_auth.default(
                scopes=["https://www.googleapis.com/auth/devstorage.read_write"]
            )
            self._session = authorized_session_cls(credentials)
        self._executor = ThreadPoolExecutor(
            max_workers=knobs.get_per_rank_io_concurrency(),
            thread_name_prefix="gcs-io",
        )
        self._retry = CollectiveProgressRetryStrategy(scope="gcs")

    # ------------------------------------------------------------------

    def _blob_name(self, path: str) -> str:
        from ..storage_plugin import normalize_object_key

        return normalize_object_key(self.prefix, path)

    def _upload_sync(self, path: str, data: bytes) -> None:
        # Dual annotation (recorder + jax timeline): this runs on a
        # gcs-io executor thread, where the thread-local jax side nests
        # correctly.
        with trace_annotation(
            metric_names.SPAN_STORAGE_WRITE,
            plugin="gcs",
            blob=path,
            bytes=len(data),
        ):
            self._upload_sync_impl(path, data)

    def _upload_sync_impl(self, path: str, data: bytes) -> None:
        blob = self._blob_name(path)
        url = (
            f"{self._base_url}/upload/storage/v1/b/"
            f"{self.bucket}/o?uploadType=resumable"
        )
        # The library's own hidden retry layer (blocking exponential sleeps
        # up to minutes, inside a gcs-io executor thread the collective-
        # progress deadline cannot observe) is disabled: THIS loop's bounded
        # recover plus the async retry strategy are the retry architecture.
        upload = self._resumable_upload_cls(url, _UPLOAD_CHUNK_SIZE)
        # (Constructor takes no retry kwarg in shipped versions; the
        # strategy is an attribute on the transfer object.)
        upload._retry_strategy = self._common.RetryStrategy(max_retries=0)
        stream = io.BytesIO(data)
        upload.initiate(
            self._session,
            stream,
            {"name": blob},
            "application/octet-stream",
            total_bytes=len(data),
        )
        recover_attempts = 0
        while not upload.finished:
            try:
                upload.transmit_next_chunk(self._session)
                recover_attempts = 0
            except Exception as e:
                # Upload-recovery rewind (reference gcs.py:109-122): ask the
                # server how far it got, reposition the stream, continue —
                # bounded and backed off so a sustained brownout propagates
                # out to the collective-progress retry instead of spinning.
                # Covers InvalidResponse AND connection resets/timeouts:
                # with the library's own retry layer disabled, any transient
                # failure that escapes this loop forfeits the resumable
                # session (the outer retry restarts from byte 0).
                if (
                    not _is_transient(e, self._common)
                    or recover_attempts >= _MAX_RECOVER_ATTEMPTS
                ):
                    raise
                time.sleep(
                    min(_RECOVER_SLEEP_CAP_SECONDS, 0.25 * 2**recover_attempts)
                    * (0.5 + random.random())
                )
                upload.recover(self._session)
                recover_attempts += 1
                # Session-recover attempts were previously counted here
                # and dropped; the registry keeps them (they are the
                # leading indicator of a browning-out backend, visible
                # well before the collective deadline trips).
                telemetry.metrics().counter_inc(
                    metric_names.GCS_RECOVER_ATTEMPTS_TOTAL
                )
                # Instant event: places each brownout-recover on the
                # timeline, inside the upload span it interrupted.
                _trace_recorder().instant(
                    metric_names.INSTANT_GCS_RECOVER,
                    blob=blob,
                    attempt=recover_attempts,
                )

    def _download_sync(
        self, path: str, byte_range: Optional[Tuple[int, int]]
    ) -> bytes:
        # Ranged reads were previously invisible to any timeline; the
        # dual annotation covers both whole-blob and ranged downloads.
        args = {"plugin": "gcs", "blob": path}
        if byte_range is not None:
            args["range"] = [int(byte_range[0]), int(byte_range[1])]
        with trace_annotation(metric_names.SPAN_STORAGE_READ, **args):
            return self._download_sync_impl(path, byte_range)

    def _download_sync_impl(
        self, path: str, byte_range: Optional[Tuple[int, int]]
    ) -> bytes:
        blob = urllib.parse.quote(self._blob_name(path), safe="")
        url = (
            f"{self._base_url}/download/storage/v1/b/"
            f"{self.bucket}/o/{blob}?alt=media"
        )
        stream = io.BytesIO()
        if byte_range is not None:
            start, end = byte_range
            download = self._chunked_download_cls(
                url,
                _DOWNLOAD_CHUNK_SIZE,
                stream,
                start=start,
                end=end - 1,  # API takes an inclusive end
            )
        else:
            download = self._chunked_download_cls(
                url, _DOWNLOAD_CHUNK_SIZE, stream
            )
        download._retry_strategy = self._common.RetryStrategy(max_retries=0)
        try:
            while not download.finished:
                download.consume_next_chunk(self._session)
        except self._common.InvalidResponse as e:
            status = getattr(e.response, "status_code", None)
            if status == 404:
                # Normalize to the FS plugin's missing-blob contract so
                # callers (e.g. checksum-table probing) can distinguish
                # absent from unreadable. Definitive: never retried.
                raise FileNotFoundError(path) from e
            if status == 416:
                # Out-of-range ranged read -> the fs/memory plugins' EIO
                # contract (truncation, not partial success); convert
                # --verify and fsck classify on it. Definitive: never
                # retried (OSError is not in the GCS transient taxonomy).
                import errno

                raise OSError(
                    errno.EIO,
                    f"ranged read {byte_range} is outside the blob",
                    path,
                ) from e
            raise
        return stream.getvalue()

    def _delete_sync(self, path: str) -> None:
        blob = urllib.parse.quote(self._blob_name(path), safe="")
        url = (
            f"{self._base_url}/storage/v1/b/"
            f"{self.bucket}/o/{blob}"
        )
        resp = self._session.delete(url)
        if resp.status_code not in (200, 204, 404):
            raise self._common.InvalidResponse(resp, "delete failed")

    # ------------------------------------------------------------------

    async def write(self, write_io: WriteIO) -> None:
        loop = asyncio.get_running_loop()
        data = bytes(write_io.buf)

        async def op() -> None:
            await loop.run_in_executor(
                self._executor, self._upload_sync, write_io.path, data
            )

        t0 = time.monotonic()
        await self._run_retrying(op)
        observe_io("gcs", "write", len(data), time.monotonic() - t0)

    async def read(self, read_io: ReadIO) -> None:
        loop = asyncio.get_running_loop()

        async def op() -> bytes:
            return await loop.run_in_executor(
                self._executor,
                self._download_sync,
                read_io.path,
                read_io.byte_range,
            )

        t0 = time.monotonic()
        read_io.buf = memoryview(await self._run_retrying(op))
        observe_io("gcs", "read", read_io.buf.nbytes, time.monotonic() - t0)

    async def delete(self, path: str) -> None:
        loop = asyncio.get_running_loop()

        async def op() -> None:
            await loop.run_in_executor(self._executor, self._delete_sync, path)

        await self._run_retrying(op)

    async def _run_retrying(self, op):
        """Retry ``op`` on transient GCS errors under the shared
        collective-progress deadline."""

        async def guarded():
            try:
                return await op()
            except Exception as e:
                if _is_transient(e, self._common):
                    raise _TransientGCSError() from e
                raise

        return await self._retry.run(
            guarded, retriable_exceptions=(_TransientGCSError,)
        )

    async def close(self) -> None:
        self._executor.shutdown(wait=False)


class _TransientGCSError(Exception):
    pass
