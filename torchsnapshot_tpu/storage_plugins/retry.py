"""Collective-progress retry strategy for cloud storage plugins.

Reference parity: the ``_RetryStrategy`` in torchsnapshot's GCS plugin
(storage_plugins/gcs.py:214-270): rather than a fixed per-operation retry
count, concurrent transfers share a *deadline* that is refreshed whenever
any of them completes. As long as somebody is making progress, stragglers
keep retrying (with exponential backoff + jitter); when nobody has
progressed for the window, everyone gives up. This matches checkpoint
workloads, where dozens of concurrent writes hit the same degraded backend
and individual retry budgets either trip too early (transient brownout) or
too late (hard outage).
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Awaitable, Callable, Tuple, Type, TypeVar

T = TypeVar("T")

DEFAULT_PROGRESS_WINDOW_SECONDS = 128.0
_BACKOFF_BASE_SECONDS = 1.0
_BACKOFF_MAX_SECONDS = 32.0


class RetriesExhausted(RuntimeError):
    pass


class CollectiveProgressRetryStrategy:
    """Shared-deadline retry coordinator for one storage plugin instance."""

    def __init__(
        self, progress_window_seconds: float = DEFAULT_PROGRESS_WINDOW_SECONDS
    ) -> None:
        self.progress_window_seconds = progress_window_seconds
        # The window only starts ticking at the first observed failure (not
        # at plugin construction): a checkpoint can spend minutes in
        # staging/collectives before its first storage op, and that quiet
        # period must not count against the retry budget.
        self._deadline: "float | None" = None

    def record_progress(self) -> None:
        """Any completed operation pushes the collective deadline out."""
        self._deadline = time.monotonic() + self.progress_window_seconds

    @property
    def deadline_passed(self) -> bool:
        if self._deadline is None:
            return False
        return time.monotonic() > self._deadline

    async def run(
        self,
        op: Callable[[], Awaitable[T]],
        retriable_exceptions: Tuple[Type[BaseException], ...],
    ) -> T:
        """Run ``op``, retrying transient failures until the collective
        deadline lapses with no progress from any concurrent operation."""
        attempt = 0
        while True:
            try:
                result = await op()
            except retriable_exceptions as e:
                if self._deadline is None:
                    self._deadline = (
                        time.monotonic() + self.progress_window_seconds
                    )
                if self.deadline_passed:
                    raise RetriesExhausted(
                        f"No concurrent operation progressed within "
                        f"{self.progress_window_seconds:.0f}s; giving up "
                        f"after {attempt + 1} attempts"
                    ) from e
                backoff = min(
                    _BACKOFF_MAX_SECONDS, _BACKOFF_BASE_SECONDS * (2**attempt)
                )
                await asyncio.sleep(backoff * (0.5 + random.random() / 2))
                attempt += 1
            else:
                self.record_progress()
                return result
