"""Collective-progress retry strategy for cloud storage plugins.

Reference parity: the ``_RetryStrategy`` in torchsnapshot's GCS plugin
(storage_plugins/gcs.py:214-270): rather than a fixed per-operation retry
count, concurrent transfers share a *deadline* that is refreshed whenever
any of them completes. As long as somebody is making progress, stragglers
keep retrying (with exponential backoff + jitter); when nobody has
progressed for the window, everyone gives up. This matches checkpoint
workloads, where dozens of concurrent writes hit the same degraded backend
and individual retry budgets either trip too early (transient brownout) or
too late (hard outage).
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Awaitable, Callable, Tuple, Type, TypeVar

from .. import telemetry
from ..telemetry import names as metric_names
from ..telemetry.trace import get_recorder as _trace_recorder

T = TypeVar("T")

DEFAULT_PROGRESS_WINDOW_SECONDS = 128.0
_BACKOFF_BASE_SECONDS = 1.0
_BACKOFF_MAX_SECONDS = 32.0


class RetriesExhausted(RuntimeError):
    pass


def decorrelated_backoff(
    prev_backoff_s: float,
    rng: "random.Random | None" = None,
    base_s: float = _BACKOFF_BASE_SECONDS,
    max_s: float = _BACKOFF_MAX_SECONDS,
) -> float:
    """Next sleep under *decorrelated* jitter: uniform over
    ``[base, 3 x previous sleep]``, capped. Plain exponential backoff
    with bounded jitter keeps N ranks that lost the backend at the same
    instant retrying in near-lockstep (attempt k lands in the same
    narrow ``[2^k/2, 2^k]`` band everywhere — the synchronized-herd
    pattern that re-knocks over a recovering durable tier). Feeding
    each draw's range from the PREVIOUS draw decorrelates the schedules
    after the first sleep: two processes' sequences diverge and stay
    diverged (AWS architecture-blog construction). Shared by the cloud
    plugins, the tiered mirror, and the peer tier."""
    r: "random.Random | Any" = rng if rng is not None else random
    return min(max_s, r.uniform(base_s, max(base_s, 3.0 * prev_backoff_s)))


class CollectiveProgressRetryStrategy:
    """Shared-deadline retry coordinator for one storage plugin instance."""

    def __init__(
        self,
        progress_window_seconds: float = DEFAULT_PROGRESS_WINDOW_SECONDS,
        scope: str = "",
        rng: "random.Random | None" = None,
    ) -> None:
        self.progress_window_seconds = progress_window_seconds
        # Backoff RNG seam: per-instance generators let tests pin two
        # strategies' schedules and assert they diverge; production
        # uses the module RNG (process-seeded, already uncorrelated
        # ACROSS processes — the decorrelated draw below is what keeps
        # them uncorrelated across attempts too).
        self._rng = rng
        # The window only starts ticking at the first observed failure (not
        # at plugin construction): a checkpoint can spend minutes in
        # staging/collectives before its first storage op, and that quiet
        # period must not count against the retry budget.
        self._deadline: "float | None" = None
        # Telemetry: which subsystem this strategy serves (labels the
        # registry counters: "s3" | "gcs" | "mirror"), plus per-instance
        # totals so a caller holding the strategy can read its own
        # attempt/backoff history without registry arithmetic.
        self.scope = scope
        self.attempts_total = 0
        self.backoff_s_total = 0.0
        self.exhausted_total = 0

    def record_progress(self) -> None:
        """Any completed operation pushes the collective deadline out."""
        self._deadline = time.monotonic() + self.progress_window_seconds

    @property
    def deadline_passed(self) -> bool:
        if self._deadline is None:
            return False
        return time.monotonic() > self._deadline

    async def run(
        self,
        op: Callable[[], Awaitable[T]],
        retriable_exceptions: Tuple[Type[BaseException], ...],
    ) -> T:
        """Run ``op``, retrying transient failures until the collective
        deadline lapses with no progress from any concurrent operation."""
        attempt = 0
        prev_backoff = _BACKOFF_BASE_SECONDS
        while True:
            try:
                result = await op()
            except retriable_exceptions as e:
                registry = telemetry.metrics()
                self.attempts_total += 1
                registry.counter_inc(
                    metric_names.STORAGE_RETRY_ATTEMPTS_TOTAL,
                    scope=self.scope,
                )
                if self._deadline is None:
                    self._deadline = (
                        time.monotonic() + self.progress_window_seconds
                    )
                if self.deadline_passed:
                    self.exhausted_total += 1
                    registry.counter_inc(
                        metric_names.STORAGE_RETRIES_EXHAUSTED_TOTAL,
                        scope=self.scope,
                    )
                    raise RetriesExhausted(
                        f"No concurrent operation progressed within "
                        f"{self.progress_window_seconds:.0f}s; giving up "
                        f"after {attempt + 1} attempts"
                    ) from e
                backoff = decorrelated_backoff(prev_backoff, rng=self._rng)
                prev_backoff = backoff
                self.backoff_s_total += backoff
                registry.counter_inc(
                    metric_names.STORAGE_RETRY_BACKOFF_SECONDS_TOTAL,
                    backoff,
                    scope=self.scope,
                )
                # Instant event: each retry lands on the flight-recorder
                # timeline inside the span of the operation it delays.
                _trace_recorder().instant(
                    metric_names.INSTANT_STORAGE_RETRY,
                    scope=self.scope,
                    attempt=attempt + 1,
                    backoff_s=round(backoff, 3),
                )
                await asyncio.sleep(backoff)
                attempt += 1
            else:
                self.record_progress()
                return result
