"""Local/network filesystem storage plugin.

Reference parity: torchsnapshot/storage_plugins/fs.py:19-54 (async
read/write with ranged reads and a parent-directory cache), with a native
fast path: when the C++ runtime (native/ts_io.cpp) is available, reads and
writes go through ctypes-bound pwrite/pread on executor threads — ctypes
releases the GIL for the whole call, so the scheduler's concurrent I/O ops
become truly parallel kernel I/O streams instead of GIL-serialized Python
writes. Without the native lib, aiofiles provides the same semantics.

fsync is deliberately left to the OS (matching the reference; the commit
protocol tolerates torn data writes because the metadata file is written
only after all data writes return).
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import Set

try:
    import aiofiles
    import aiofiles.os
except ImportError:
    # aiofiles is optional: images without it (some TPU containers ship
    # only the native runtime) fall back to blocking stdlib I/O on
    # executor threads — same semantics, and the native fast path is
    # unaffected either way.
    aiofiles = None

from .. import _native
from ..io_types import ReadIO, StoragePlugin, WriteIO
from ..telemetry import names as metric_names, observe_io
from ..telemetry.trace import io_span
from ..utils.tracing import trace_annotation


class FSStoragePlugin(StoragePlugin):
    def __init__(self, root: str) -> None:
        self.root = root
        self._dir_cache: Set[str] = set()
        self._native = _native.lib() is not None

    def _full_path(self, path: str) -> str:
        return os.path.join(self.root, path)

    async def _ensure_parent_dir(self, full_path: str) -> None:
        parent = os.path.dirname(full_path)
        if parent and parent not in self._dir_cache:
            if aiofiles is not None:
                await aiofiles.os.makedirs(parent, exist_ok=True)
            else:
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(
                    None, lambda: os.makedirs(parent, exist_ok=True)
                )
            self._dir_cache.add(parent)

    async def write(self, write_io: WriteIO) -> None:
        nbytes = memoryview(write_io.buf).cast("B").nbytes
        t0 = time.monotonic()
        with io_span("fs", "write", write_io.path, nbytes):
            await self._write_impl(write_io)
        observe_io("fs", "write", nbytes, time.monotonic() - t0)

    async def _write_impl(self, write_io: WriteIO) -> None:
        full_path = self._full_path(write_io.path)
        await self._ensure_parent_dir(full_path)
        if self._native:
            loop = asyncio.get_running_loop()
            # buf stays referenced by write_io for the call's duration.
            # write_file returns False (wrote nothing) if the native lib
            # became unavailable after construction — fall through then.
            def _write_native() -> bool:
                with trace_annotation(
                    metric_names.SPAN_FS_NATIVE_WRITE, blob=write_io.path
                ):
                    return _native.write_file(full_path, write_io.buf)

            if await loop.run_in_executor(None, _write_native):
                return
        if aiofiles is not None:
            async with aiofiles.open(full_path, "wb") as f:
                await f.write(write_io.buf)
            return

        def _write_blocking() -> None:
            with open(full_path, "wb") as f:
                f.write(write_io.buf)

        await asyncio.get_running_loop().run_in_executor(
            None, _write_blocking
        )

    async def write_with_checksum(self, write_io: WriteIO):
        """Fused write + integrity pass (one cache-hot memory pass, one
        executor hop): returns the checksum-table entry, or None when the
        native runtime is unavailable (the scheduler then runs the
        two-step compute-then-write path)."""
        if not self._native:
            return None
        from ..integrity import PAGE_SIZE, entry_from_page_crcs

        full_path = self._full_path(write_io.path)
        await self._ensure_parent_dir(full_path)
        loop = asyncio.get_running_loop()

        def _write_crc():
            with trace_annotation(
                metric_names.SPAN_FS_NATIVE_WRITE, blob=write_io.path
            ):
                pages = _native.write_file_crc(
                    full_path, write_io.buf, PAGE_SIZE
                )
            if pages is None:
                return None
            return entry_from_page_crcs(
                pages, memoryview(write_io.buf).cast("B").nbytes
            )

        nbytes = memoryview(write_io.buf).cast("B").nbytes
        t0 = time.monotonic()
        with io_span("fs", "write", write_io.path, nbytes):
            entry = await loop.run_in_executor(None, _write_crc)
        if entry is not None:
            # A declined fused write wrote nothing; the scheduler's
            # two-step fallback lands in write(), which accounts itself.
            observe_io("fs", "write", nbytes, time.monotonic() - t0)
        return entry

    async def read(self, read_io: ReadIO) -> None:
        t0 = time.monotonic()
        with io_span("fs", "read", read_io.path, byte_range=read_io.byte_range):
            await self._read_dispatch(read_io)
        observe_io(
            "fs",
            "read",
            memoryview(read_io.buf).nbytes if read_io.buf is not None else 0,
            time.monotonic() - t0,
        )

    async def _read_dispatch(self, read_io: ReadIO) -> None:
        full_path = self._full_path(read_io.path)
        if self._native:
            loop = asyncio.get_running_loop()
            data = await loop.run_in_executor(
                None, self._native_read, full_path, read_io
            )
            if data is not None:
                # Identity matters: the scheduler detects a direct-into-
                # destination read by ``buf is dest``.
                read_io.buf = (
                    data if data is read_io.dest else memoryview(data)
                )
                return
        if aiofiles is not None:
            async with aiofiles.open(full_path, "rb") as f:
                if read_io.byte_range is None:
                    data = await f.read()
                else:
                    start, end = read_io.byte_range
                    await f.seek(start)
                    data = await f.read(end - start)
        else:

            def _read_blocking() -> bytes:
                with open(full_path, "rb") as f:
                    if read_io.byte_range is None:
                        return f.read()
                    start, end = read_io.byte_range
                    f.seek(start)
                    return f.read(end - start)

            data = await asyncio.get_running_loop().run_in_executor(
                None, _read_blocking
            )
        if read_io.byte_range is not None:
            start, end = read_io.byte_range
            if len(data) < end - start:
                # Keep fallback semantics identical to the native path,
                # which fails ranged reads past EOF with EIO: a short
                # blob is corruption, not a partial success.
                raise OSError(
                    5,
                    f"short read: {full_path!r} has fewer than "
                    f"{end} bytes",
                    full_path,
                )
        read_io.buf = memoryview(data)

    async def read_with_checksum(self, read_io: ReadIO):
        """Fused whole-blob read + integrity pass: fills ``read_io.buf``
        and returns the CRC32-C of each integrity page, computed while
        the page is cache-hot from the read. None (nothing read) when the
        native runtime is unavailable or the read is ranged — the
        scheduler then plain-reads and verifies separately."""
        if not self._native or read_io.byte_range is not None:
            return None
        from ..integrity import PAGE_SIZE

        full_path = self._full_path(read_io.path)
        loop = asyncio.get_running_loop()

        def _read_crc():
            with trace_annotation(
                metric_names.SPAN_FS_NATIVE_READ, blob=read_io.path
            ):
                length = _native.file_size(full_path)
                if length is None:
                    return None
                if read_io.dest is not None and read_io.dest.nbytes == length:
                    out = read_io.dest
                else:
                    out = bytearray(length)
                pages = _native.pread_into_crc(full_path, out, PAGE_SIZE)
                if pages is None:
                    return None
                return out, pages

        t0 = time.monotonic()
        with io_span("fs", "read", read_io.path):
            res = await loop.run_in_executor(None, _read_crc)
        if res is None:
            return None
        out, pages = res
        read_io.buf = out if out is read_io.dest else memoryview(out)
        observe_io(
            "fs", "read", memoryview(out).nbytes, time.monotonic() - t0
        )
        return pages

    def _native_read(self, full_path: str, read_io: ReadIO):
        """Read via the native lib; None if it became unavailable."""
        with trace_annotation(
            metric_names.SPAN_FS_NATIVE_READ, blob=read_io.path
        ):
            return self._native_read_impl(full_path, read_io)

    def _native_read_impl(self, full_path: str, read_io: ReadIO):
        if read_io.byte_range is None:
            start = 0
            length = _native.file_size(full_path)
            if length is None:
                return None
        else:
            start, end = read_io.byte_range
            length = end - start
        if read_io.dest is not None and read_io.dest.nbytes == length:
            # Read straight into the consumer's destination memory: no
            # intermediate allocation, no copy in the consume stage.
            # Failure semantics: if the read errors mid-way the destination
            # holds partial bytes. A raised restore already leaves app state
            # undefined at whole-tensor granularity (earlier consumers have
            # completed); direct reads widen that to partial-tensor, which
            # callers must treat the same way — retry or discard.
            if _native.pread_into(full_path, read_io.dest, offset=start):
                return read_io.dest
            return None
        out = bytearray(length)
        if not _native.pread_into(full_path, out, offset=start):
            return None
        return out

    async def delete(self, path: str) -> None:
        if aiofiles is not None:
            await aiofiles.os.remove(self._full_path(path))
            return
        await asyncio.get_running_loop().run_in_executor(
            None, os.remove, self._full_path(path)
        )

    async def close(self) -> None:
        self._dir_cache.clear()
