"""Local/network filesystem storage plugin.

Reference parity: torchsnapshot/storage_plugins/fs.py:19-54 (async
read/write with ranged reads and a parent-directory cache), with a native
fast path: when the C++ runtime (native/ts_io.cpp) is available, reads and
writes go through ctypes-bound pwrite/pread on executor threads — ctypes
releases the GIL for the whole call, so the scheduler's concurrent I/O ops
become truly parallel kernel I/O streams instead of GIL-serialized Python
writes. Without the native lib, aiofiles provides the same semantics.

fsync is deliberately left to the OS (matching the reference; the commit
protocol tolerates torn data writes because the metadata file is written
only after all data writes return).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Set

logger = logging.getLogger(__name__)

try:
    import aiofiles
    import aiofiles.os
except ImportError:
    # aiofiles is optional: images without it (some TPU containers ship
    # only the native runtime) fall back to blocking stdlib I/O on
    # executor threads — same semantics, and the native fast path is
    # unaffected either way.
    aiofiles = None

import errno

from .. import _native, knobs, telemetry
from ..io_types import (
    BufferList,
    ReadIO,
    StoragePlugin,
    WriteIO,
    as_bytes_view,
    payload_nbytes,
)
from ..telemetry import names as metric_names, observe_io
from ..telemetry.trace import io_span
from ..utils.tracing import trace_annotation

# O_DIRECT is for LARGE writes: below this the page-cache copy is noise
# and the alignment bookkeeping isn't worth a syscall pattern change.
_DIRECT_IO_MIN_BYTES = 8 * 1024 * 1024

# errnos that mean "this filesystem / this buffer can't take O_DIRECT"
# (tmpfs fails the open with EINVAL) — a capability signal, not an I/O
# error: decline sticky-per-plugin back to the buffered path, mirroring
# the scheduler's fused_declined pattern.
_DIRECT_DECLINE_ERRNOS = {errno.EINVAL, errno.ENOTSUP, errno.EOPNOTSUPP}


class FSStoragePlugin(StoragePlugin):
    # BufferList payloads are written with one vectorized pwritev kernel
    # (native) or sequential part writes into one fd (fallback) — never
    # consolidated into a pack buffer here.
    supports_multibuffer = True

    def __init__(self, root: str) -> None:
        self.root = root
        self._dir_cache: Set[str] = set()
        self._native = _native.lib() is not None
        # Sticky per-plugin decline for the O_DIRECT variant: the first
        # EINVAL/unsupported-fs error turns it off for this plugin's
        # lifetime (same root = same filesystem), so later writes never
        # re-pay a doomed open.
        self._direct_declined = False

    def _full_path(self, path: str) -> str:
        full = os.path.join(self.root, path)
        if ".." in path:
            # Parent-relative refs (incremental ``../step_*``, CAS
            # ``../chunks/<key>``) resolve lexically, matching the
            # object-store plugins' normalize_object_key: kernel ``..``
            # resolution walks the directory tree, so an un-normalized
            # open would demand this plugin's own root dir EXIST on
            # this tier — which it may not yet (the mirror's durable-
            # side chunk probe runs before the step's first upload
            # creates the step dir there).
            full = os.path.normpath(full)
        return full

    def _direct_eligible(self, buf) -> bool:
        """Whether this single-buffer write qualifies for O_DIRECT:
        knob on, native runtime present, no sticky decline, large
        enough, and 4096-aligned (StagingPool/batcher slabs are
        allocated aligned; incidental alignment also qualifies)."""
        if (
            not self._native
            or self._direct_declined
            or not knobs.is_fs_direct_io_enabled()
            or isinstance(buf, BufferList)
        ):
            return False
        mv = as_bytes_view(buf)
        return (
            mv.nbytes >= _DIRECT_IO_MIN_BYTES and _native.is_direct_aligned(mv)
        )

    def _decline_direct(self, e: OSError, path: str) -> None:
        self._direct_declined = True
        logger.info(
            "O_DIRECT declined for %s (%s); buffered writes for the rest "
            "of this plugin's lifetime",
            path,
            e,
        )

    async def _ensure_parent_dir(self, full_path: str) -> None:
        parent = os.path.dirname(full_path)
        if parent and parent not in self._dir_cache:
            if aiofiles is not None:
                await aiofiles.os.makedirs(parent, exist_ok=True)
            else:
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(
                    None, lambda: os.makedirs(parent, exist_ok=True)
                )
            self._dir_cache.add(parent)

    async def write(self, write_io: WriteIO) -> None:
        nbytes = payload_nbytes(write_io.buf)
        t0 = time.monotonic()
        with io_span("fs", "write", write_io.path, nbytes):
            await self._write_impl(write_io)
        observe_io("fs", "write", nbytes, time.monotonic() - t0)

    async def _write_impl(self, write_io: WriteIO) -> None:
        full_path = self._full_path(write_io.path)
        await self._ensure_parent_dir(full_path)
        buf = write_io.buf
        if self._native:
            loop = asyncio.get_running_loop()
            # buf stays referenced by write_io for the call's duration.
            # The native kernels return None/False (wrote nothing) if the
            # lib became unavailable after construction — fall through.
            if isinstance(buf, BufferList):

                def _writev_native() -> bool:
                    with trace_annotation(
                        metric_names.SPAN_FS_NATIVE_PWRITEV,
                        blob=write_io.path,
                    ):
                        return (
                            _native.pwritev_file_crc(full_path, buf.parts)
                            is not None
                        )

                if await loop.run_in_executor(None, _writev_native):
                    write_io.variant = "vectorized"
                    telemetry.metrics().counter_inc(
                        metric_names.FS_VECTORIZED_WRITE_BYTES_TOTAL,
                        buf.nbytes,
                        plugin="fs",
                    )
                    return
            else:
                if self._direct_eligible(buf):
                    try:
                        if await loop.run_in_executor(
                            None, self._write_direct_kernel, full_path, write_io
                        ):
                            return
                    except OSError as e:
                        if e.errno not in _DIRECT_DECLINE_ERRNOS:
                            raise
                        self._decline_direct(e, write_io.path)

                def _write_native() -> bool:
                    with trace_annotation(
                        metric_names.SPAN_FS_NATIVE_WRITE, blob=write_io.path
                    ):
                        return _native.write_file(full_path, buf)

                if await loop.run_in_executor(None, _write_native):
                    write_io.variant = "buffered"
                    return
        if isinstance(buf, BufferList):
            # Pure-Python zero-pack fallback: sequential part writes into
            # one fd — still no consolidation pass.
            write_io.variant = "buffered"
            if aiofiles is not None:
                async with aiofiles.open(full_path, "wb") as f:
                    for part in buf.parts:
                        await f.write(part)
                return

            def _writev_blocking() -> None:
                with open(full_path, "wb") as f:
                    for part in buf.parts:
                        f.write(part)

            await asyncio.get_running_loop().run_in_executor(
                None, _writev_blocking
            )
            return
        write_io.variant = "buffered"
        if aiofiles is not None:
            async with aiofiles.open(full_path, "wb") as f:
                await f.write(buf)
            return

        def _write_blocking() -> None:
            with open(full_path, "wb") as f:
                f.write(buf)

        await asyncio.get_running_loop().run_in_executor(
            None, _write_blocking
        )

    def _write_direct_kernel(self, full_path: str, write_io: WriteIO) -> bool:
        """Executor-thread O_DIRECT write for the plain (no-checksum)
        path — the CRC pass is skipped outright (``page_size=None`` hands
        the kernel a NULL page array), so a checksums-off run never pays
        a per-byte CRC for a result nobody reads. True on success; raises
        OSError with a decline errno for the caller's sticky fallback."""
        with trace_annotation(
            metric_names.SPAN_FS_NATIVE_DIRECT_WRITE, blob=write_io.path
        ):
            pages = _native.write_file_crc_direct(full_path, write_io.buf)
        if pages is None:
            return False
        write_io.variant = "direct"
        telemetry.metrics().counter_inc(
            metric_names.FS_DIRECT_WRITE_BYTES_TOTAL,
            payload_nbytes(write_io.buf),
            plugin="fs",
        )
        return True

    async def write_with_checksum(self, write_io: WriteIO):
        """Fused write + integrity pass (one cache-hot memory pass, one
        executor hop): returns the checksum-table entry, or None when the
        native runtime is unavailable (the scheduler then runs the
        two-step compute-then-write path). Serves all three native
        variants: vectorized pwritev for BufferList payloads (zero-pack),
        O_DIRECT for large aligned single buffers (knob-gated, sticky
        decline on unsupported filesystems), and the plain fused
        write+CRC otherwise."""
        if not self._native:
            return None
        from ..integrity import PAGE_SIZE, entry_from_page_crcs

        full_path = self._full_path(write_io.path)
        await self._ensure_parent_dir(full_path)
        loop = asyncio.get_running_loop()
        buf = write_io.buf
        nbytes = payload_nbytes(buf)

        def _writev_crc():
            with trace_annotation(
                metric_names.SPAN_FS_NATIVE_PWRITEV, blob=write_io.path
            ):
                pages = _native.pwritev_file_crc(
                    full_path, buf.parts, page_size=PAGE_SIZE
                )
            if pages is None:
                return None
            write_io.variant = "vectorized"
            telemetry.metrics().counter_inc(
                metric_names.FS_VECTORIZED_WRITE_BYTES_TOTAL,
                nbytes,
                plugin="fs",
            )
            return entry_from_page_crcs(pages, nbytes)

        def _direct_crc():
            with trace_annotation(
                metric_names.SPAN_FS_NATIVE_DIRECT_WRITE, blob=write_io.path
            ):
                pages = _native.write_file_crc_direct(
                    full_path, buf, PAGE_SIZE
                )
            if pages is None:
                return None
            write_io.variant = "direct"
            telemetry.metrics().counter_inc(
                metric_names.FS_DIRECT_WRITE_BYTES_TOTAL, nbytes, plugin="fs"
            )
            return entry_from_page_crcs(pages, nbytes)

        def _write_crc():
            with trace_annotation(
                metric_names.SPAN_FS_NATIVE_WRITE, blob=write_io.path
            ):
                pages = _native.write_file_crc(full_path, buf, PAGE_SIZE)
            if pages is None:
                return None
            write_io.variant = "fused"
            return entry_from_page_crcs(pages, nbytes)

        t0 = time.monotonic()
        with io_span("fs", "write", write_io.path, nbytes):
            entry = None
            if isinstance(buf, BufferList):
                entry = await loop.run_in_executor(None, _writev_crc)
            else:
                if self._direct_eligible(buf):
                    try:
                        entry = await loop.run_in_executor(None, _direct_crc)
                    except OSError as e:
                        if e.errno not in _DIRECT_DECLINE_ERRNOS:
                            raise
                        self._decline_direct(e, write_io.path)
                if entry is None:
                    entry = await loop.run_in_executor(None, _write_crc)
        if entry is not None:
            # A declined fused write wrote nothing; the scheduler's
            # two-step fallback lands in write(), which accounts itself.
            observe_io("fs", "write", nbytes, time.monotonic() - t0)
        return entry

    async def read(self, read_io: ReadIO) -> None:
        t0 = time.monotonic()
        with io_span("fs", "read", read_io.path, byte_range=read_io.byte_range):
            await self._read_dispatch(read_io)
        observe_io(
            "fs",
            "read",
            memoryview(read_io.buf).nbytes if read_io.buf is not None else 0,
            time.monotonic() - t0,
        )

    async def _read_dispatch(self, read_io: ReadIO) -> None:
        full_path = self._full_path(read_io.path)
        if self._native:
            loop = asyncio.get_running_loop()
            data = await loop.run_in_executor(
                None, self._native_read, full_path, read_io
            )
            if data is not None:
                # Identity matters: the scheduler detects a direct-into-
                # destination read by ``buf is dest``.
                read_io.buf = (
                    data if data is read_io.dest else memoryview(data)
                )
                return
        if aiofiles is not None:
            async with aiofiles.open(full_path, "rb") as f:
                if read_io.byte_range is None:
                    data = await f.read()
                else:
                    start, end = read_io.byte_range
                    await f.seek(start)
                    data = await f.read(end - start)
        else:

            def _read_blocking() -> bytes:
                with open(full_path, "rb") as f:
                    if read_io.byte_range is None:
                        return f.read()
                    start, end = read_io.byte_range
                    f.seek(start)
                    return f.read(end - start)

            data = await asyncio.get_running_loop().run_in_executor(
                None, _read_blocking
            )
        if read_io.byte_range is not None:
            start, end = read_io.byte_range
            if len(data) < end - start:
                # Keep fallback semantics identical to the native path,
                # which fails ranged reads past EOF with EIO: a short
                # blob is corruption, not a partial success.
                raise OSError(
                    5,
                    f"short read: {full_path!r} has fewer than "
                    f"{end} bytes",
                    full_path,
                )
        read_io.buf = memoryview(data)

    async def read_with_checksum(self, read_io: ReadIO):
        """Fused whole-blob read + integrity pass: fills ``read_io.buf``
        and returns the CRC32-C of each integrity page, computed while
        the page is cache-hot from the read. None (nothing read) when the
        native runtime is unavailable or the read is ranged — the
        scheduler then plain-reads and verifies separately."""
        if not self._native or read_io.byte_range is not None:
            return None
        from ..integrity import PAGE_SIZE

        full_path = self._full_path(read_io.path)
        loop = asyncio.get_running_loop()

        def _read_crc():
            with trace_annotation(
                metric_names.SPAN_FS_NATIVE_READ, blob=read_io.path
            ):
                length = _native.file_size(full_path)
                if length is None:
                    return None
                if read_io.dest is not None and read_io.dest.nbytes == length:
                    out = read_io.dest
                else:
                    out = bytearray(length)
                pages = _native.pread_into_crc(full_path, out, PAGE_SIZE)
                if pages is None:
                    return None
                return out, pages

        t0 = time.monotonic()
        with io_span("fs", "read", read_io.path):
            res = await loop.run_in_executor(None, _read_crc)
        if res is None:
            return None
        out, pages = res
        read_io.buf = out if out is read_io.dest else memoryview(out)
        observe_io(
            "fs", "read", memoryview(out).nbytes, time.monotonic() - t0
        )
        return pages

    def _native_read(self, full_path: str, read_io: ReadIO):
        """Read via the native lib; None if it became unavailable."""
        with trace_annotation(
            metric_names.SPAN_FS_NATIVE_READ, blob=read_io.path
        ):
            return self._native_read_impl(full_path, read_io)

    def _native_read_impl(self, full_path: str, read_io: ReadIO):
        if read_io.byte_range is None:
            start = 0
            length = _native.file_size(full_path)
            if length is None:
                return None
        else:
            start, end = read_io.byte_range
            length = end - start
        if read_io.dest is not None and read_io.dest.nbytes == length:
            # Read straight into the consumer's destination memory: no
            # intermediate allocation, no copy in the consume stage.
            # Failure semantics: if the read errors mid-way the destination
            # holds partial bytes. A raised restore already leaves app state
            # undefined at whole-tensor granularity (earlier consumers have
            # completed); direct reads widen that to partial-tensor, which
            # callers must treat the same way — retry or discard.
            if _native.pread_into(full_path, read_io.dest, offset=start):
                return read_io.dest
            return None
        out = bytearray(length)
        if not _native.pread_into(full_path, out, offset=start):
            return None
        return out

    async def delete(self, path: str) -> None:
        if aiofiles is not None:
            await aiofiles.os.remove(self._full_path(path))
            return
        await asyncio.get_running_loop().run_in_executor(
            None, os.remove, self._full_path(path)
        )

    async def close(self) -> None:
        self._dir_cache.clear()
