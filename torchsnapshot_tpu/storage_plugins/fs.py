"""Local/network filesystem storage plugin.

Reference parity: torchsnapshot/storage_plugins/fs.py:19-54 (aiofiles-based
async read/write with ranged reads and a parent-directory cache). Writes are
dispatched through aiofiles' thread pool so the event loop stays free to
overlap staging, and fsync is deliberately left to the OS (matching the
reference; the commit protocol tolerates torn writes because the metadata
file is written only after all data writes return).
"""

from __future__ import annotations

import os
from typing import Set

import aiofiles
import aiofiles.os

from ..io_types import ReadIO, StoragePlugin, WriteIO


class FSStoragePlugin(StoragePlugin):
    def __init__(self, root: str) -> None:
        self.root = root
        self._dir_cache: Set[str] = set()

    def _full_path(self, path: str) -> str:
        return os.path.join(self.root, path)

    async def _ensure_parent_dir(self, full_path: str) -> None:
        parent = os.path.dirname(full_path)
        if parent and parent not in self._dir_cache:
            await aiofiles.os.makedirs(parent, exist_ok=True)
            self._dir_cache.add(parent)

    async def write(self, write_io: WriteIO) -> None:
        full_path = self._full_path(write_io.path)
        await self._ensure_parent_dir(full_path)
        async with aiofiles.open(full_path, "wb") as f:
            await f.write(write_io.buf)

    async def read(self, read_io: ReadIO) -> None:
        full_path = self._full_path(read_io.path)
        async with aiofiles.open(full_path, "rb") as f:
            if read_io.byte_range is None:
                data = await f.read()
            else:
                start, end = read_io.byte_range
                await f.seek(start)
                data = await f.read(end - start)
        read_io.buf = memoryview(data)

    async def delete(self, path: str) -> None:
        await aiofiles.os.remove(self._full_path(path))

    async def close(self) -> None:
        self._dir_cache.clear()
