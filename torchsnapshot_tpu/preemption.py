"""Preemption-aware checkpointing: save once, consistently, when any host
receives an eviction signal.

TPU pods are preempted routinely (spot capacity, maintenance events),
and the eviction signal (SIGTERM, typically with a short grace window)
may land on only SOME hosts. A rank that checkpoints alone deadlocks its
peers inside the distributed take; ranks that checkpoint at different
steps commit garbage. :class:`PreemptionSaver` turns the signal into a
whole-world agreement to save at one specific step:

    mgr = CheckpointManager(root, pg=pg)
    saver = PreemptionSaver(pg=pg)          # installs SIGTERM handler
    for step in range(start, total):
        state, loss = train_step(state, batch)
        if saver.should_save(step):          # cheap store poll per step
            mgr.save(step, app_state)
            if saver.exit_after_save:
                break
    else:
        if saver.pending_save():             # eviction raced the loop end
            mgr.save(total - 1, app_state)
    saver.close()   # peers racing an eviction notice abandon fast

No reference counterpart (the reference relies on torchelastic restarts,
test_utils.py:193-202 — state since the last periodic snapshot is simply
lost). The TPU incumbent's analog is orbax's preemption checkpointing
over jax's PreemptionSyncManager; this implementation needs only the
snapshot store (TCPStore or the JAX coordination service), so it works
in every deployment the checkpointer itself works in.

Agreement protocol (sound under JAX's async dispatch, where host loops
drift relative to device collectives, so "collectives order the ranks"
arguments do NOT hold):

1. *Flag* (cheap steady-state): a signaled rank sets one store key;
   every rank polls it once per ``should_save`` call.
2. *Rendezvous* (once, after a rank observes the flag): each rank
   publishes its own current step and blocks until all ``world_size``
   ranks have published. Every rank then computes the same
   ``target = max(published) + 1``. Ranks' published steps are frozen
   while they wait, so no rank is past the target when it resumes.
3. Each rank returns True from ``should_save`` exactly at
   ``step >= target`` — the same step everywhere, because steps advance
   by one per loop iteration on every rank.

If the rendezvous does not complete within ``rendezvous_timeout``
(a peer already died), the saver gives up loudly and never triggers —
a rank must not enter a distributed take its peers will never join.
"""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Any, List, Optional

from .pg_wrapper import PGWrapper

logger: logging.Logger = logging.getLogger(__name__)

_PREFIX = "__preemption"


class PreemptionSaver:
    """Coordinates one consistent whole-world save on an eviction signal.

    Args:
        pg: process group (as accepted by :class:`PGWrapper`); ``None``
            for single-process training.
        signals: signal numbers that mean "preemption imminent"
            (default: ``SIGTERM``). Pass ``()`` to install no handler
            and drive :meth:`request_save` manually (e.g. from a cloud
            metadata watcher thread).
        exit_after_save: advisory flag echoed back as
            ``saver.exit_after_save`` for the training loop.
        chain: when True (default), a previously-installed Python-level
            handler for the same signal is invoked after ours.
        rendezvous_timeout: seconds to wait for every rank to join the
            step agreement before giving up (default 120).
        poll_interval: seconds between background flag polls (default 1).
        peer_grace: seconds the final symmetry check sleeps before a
            triggered save, letting a just-timed-out peer's abandoned
            marker land. Defaults to ``max(1, 2 * poll_interval)``;
            deployments with slow coordination stores should widen it
            (the marker publish must fit inside it).
        ledger_root: the CheckpointManager root whose run ledger
            (telemetry/ledger.py) should record this saver's
            preemption events — the step the world was at when the
            notice landed, and the agreed save target (or the
            give-up). The goodput engine's lost-work accounting
            anchors on these records. Rank 0 posts only; None (the
            default) records nothing.
    """

    def __init__(
        self,
        pg: Optional[Any] = None,
        signals: tuple = (signal.SIGTERM,),
        exit_after_save: bool = True,
        chain: bool = True,
        rendezvous_timeout: float = 120.0,
        session: str = "",
        poll_interval: float = 1.0,
        peer_grace: Optional[float] = None,
        ledger_root: Optional[str] = None,
    ) -> None:
        self._pg = PGWrapper(pg)
        self._ledger_root = ledger_root
        # Store keys are namespaced per session: saver lifetimes sharing
        # one persistent store (restarted loops, tests over one
        # coordinator) must not observe each other's stale flag/step
        # keys. Pass a distinct, rank-consistent session per lifetime
        # (e.g. the resume step) when the store outlives the saver.
        self._session = session
        self.exit_after_save = exit_after_save
        self.rendezvous_timeout = rendezvous_timeout
        self.poll_interval = poll_interval
        self.peer_grace = (
            peer_grace
            if peer_grace is not None
            else max(1.0, 2.0 * poll_interval)
        )
        self._flagged = threading.Event()
        self._remote_flagged = threading.Event()
        self._drains: List[Any] = []
        self._stop_poller = threading.Event()
        self._poller: Optional[threading.Thread] = None
        self._flag_published = False
        self._target_step: Optional[int] = None
        self._saved = False
        self._gave_up = False
        self._chain = chain
        self._prev_handlers: List[tuple] = []
        for sig in signals:
            prev = signal.signal(sig, self._on_signal)
            self._prev_handlers.append((sig, prev))
        if signals:
            logger.info(
                "PreemptionSaver armed on %s (rank %d/%d)",
                [signal.Signals(s).name for s in signals],
                self._pg.get_rank(),
                self._pg.get_world_size(),
            )

    def _key(self, suffix: str) -> str:
        return f"{_PREFIX}/{self._session}/{suffix}"

    def _post_ledger(self, **fields: Any) -> None:
        """Record a preemption event in the run ledger (rank 0 only;
        no-op without a ``ledger_root``). Best-effort — ledger posting
        must never perturb the agreement protocol."""
        if self._ledger_root is None or self._pg.get_rank() != 0:
            return
        try:
            from .telemetry import ledger as run_ledger
            from .telemetry import names as event_names

            run_ledger.post_event(
                self._ledger_root, event_names.EVENT_PREEMPTION, **fields
            )
        except Exception as e:  # noqa: BLE001 - ledger is best-effort
            logger.warning("preemption ledger post failed: %r", e)

    def _ensure_poller(self, store) -> None:
        """Background flag watcher: the training loop's should_save does
        no store RPC in the steady state — one daemon thread per rank
        polls the flag key every ``poll_interval`` seconds and flips a
        local Event (store clients serialize requests internally, so the
        poller and a later rendezvous never interleave corruptly)."""
        if self._poller is not None:
            return

        def poll() -> None:
            # Never give up: a poller that exits on a coordinator hiccup
            # leaves this rank blind to remote eviction notices, and a
            # later real preemption then degrades to peers blocking out
            # the full rendezvous timeout. Failures back off
            # exponentially (capped) so an unhealthy store isn't hammered.
            failures = 0
            cap = max(30.0, 16.0 * self.poll_interval)
            delay = self.poll_interval
            while not self._stop_poller.wait(delay):
                try:
                    if store.try_get(self._key("flag")) is not None:
                        self._remote_flagged.set()
                        return
                    failures = 0
                    delay = self.poll_interval
                except Exception as e:  # noqa: BLE001 - transient store hiccup
                    failures += 1
                    delay = min(cap, delay * 2.0)
                    log = logger.error if delay >= cap else logger.warning
                    log(
                        "preemption flag poll failed %d time(s) (%r); "
                        "retrying in %.1fs",
                        failures,
                        e,
                        delay,
                    )

        self._poller = threading.Thread(
            target=poll, name="preemption-flag-poll", daemon=True
        )
        self._poller.start()

    # -- signal side (async-signal-safe: only sets an Event) -------------

    def _on_signal(self, signum, frame) -> None:
        self._flagged.set()
        if self._chain:
            for sig, prev in self._prev_handlers:
                if sig == signum and callable(prev):
                    prev(signum, frame)

    def request_save(self) -> None:
        """Programmatic preemption notice (metadata watchers, tests)."""
        self._flagged.set()

    @property
    def preempted(self) -> bool:
        """True once a signal/request has been observed locally."""
        return self._flagged.is_set()

    def register_drain(self, fn: Any) -> None:
        """Register a zero-arg callable run during :meth:`close` — before
        the done marker publishes — to flush work that must fit the
        eviction grace window. The tiered-checkpoint integration::

            saver.register_drain(
                lambda: tiered.get_mirror().drain(timeout=grace_s)
            )

        pushes in-flight durable-tier uploads out before the host dies;
        whatever misses the window is journaled, so the restarted job's
        ``CheckpointManager.resume_mirrors()`` resumes the upload instead
        of re-sending completed blobs. Drain failures are logged, never
        raised (close() runs on the teardown path).

        The peer tier (tiered/peer.py) needs no registration: ``close``
        always flushes pending peer pushes FIRST — shipping the last
        committed step's delta into the surviving neighbor's host RAM
        is the cheapest work the grace window can buy (host-RAM
        bandwidth, not a durable upload), and it is what bounds the
        replacement rank's restore by RAM copy speed instead of
        storage. An unconfigured peer tier makes that flush a no-op."""
        self._drains.append(fn)

    def uninstall(self) -> None:
        """Restore previously-installed signal handlers."""
        for sig, prev in self._prev_handlers:
            signal.signal(sig, prev if prev is not None else signal.SIG_DFL)
        self._prev_handlers = []

    def close(self) -> None:
        """Call when the training loop exits normally (no more
        ``should_save`` calls coming). Publishes a done marker so a peer
        whose eviction notice raced the end of training abandons its
        rendezvous immediately instead of waiting out the timeout, and
        restores the signal handlers."""
        self._stop_poller.set()
        if self._poller is not None:
            self._poller.join(timeout=self.poll_interval + 1.0)
        # Peer-tier flush FIRST (built-in drain hook): the last
        # committed step's delta ships into the neighbor's host RAM at
        # RAM-copy speed — the cheapest recovery insurance the grace
        # window can buy, and strictly faster than the durable-tier
        # drains registered below. A dead peer cannot wedge this: the
        # push jobs themselves time out and degrade, and the drain wait
        # is bounded. No-op when the tier is unconfigured.
        try:
            from .tiered import peer as peer_tier

            if not peer_tier.maybe_drain(timeout=self.rendezvous_timeout):
                logger.warning(
                    "preemption drain: peer-tier pushes did not settle "
                    "within %.0fs; the restore ladder falls through to "
                    "storage for whatever is missing",
                    self.rendezvous_timeout,
                )
        except Exception as e:  # noqa: BLE001 - teardown path
            logger.warning("preemption peer-tier drain failed: %r", e)
        for fn in self._drains:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 - teardown path
                logger.warning("preemption drain hook failed: %r", e)
        store = self._pg.store
        if store is not None and self._pg.get_world_size() > 1:
            try:
                # Session-namespaced tombstone: it must outlive this
                # process so a straggler's rendezvous can see the peer
                # finished. The session id scopes the whole family; a
                # new job incarnation starts a fresh namespace.
                # snaplint: disable=store-key-leak
                store.set(self._key(f"done/{self._pg.get_rank()}"), b"1")
            except Exception:  # noqa: BLE001 - teardown path
                logger.debug("preemption done-marker publish failed")
        self.uninstall()

    # -- training-loop side ----------------------------------------------

    def should_save(self, step: int) -> bool:
        """Call once per training step with that step's number.

        Returns True on the one step at which every rank must save
        (``step`` itself on single-process worlds)."""
        if self._saved or self._gave_up:
            return False
        store = self._pg.store
        if store is None or self._pg.get_world_size() <= 1:
            if self._flagged.is_set():
                self._saved = True
                self._post_ledger(step=step, target_step=step)
                return True
            return False

        if self._target_step is None:
            # Steady state: NO store RPC on the training loop — the
            # background poller watches the flag; a locally-signaled
            # rank publishes it once.
            self._ensure_poller(store)
            if self._flagged.is_set() and not self._flag_published:
                # One sticky flag per session: deleting it could lose
                # the notice for ranks that have not polled yet.
                # snaplint: disable=store-key-leak
                store.set(self._key("flag"), b"1")
                self._flag_published = True
                self._remote_flagged.set()
                logger.warning(
                    "rank %d received preemption notice at step %d",
                    self._pg.get_rank(),
                    step,
                )
            if not self._remote_flagged.is_set():
                return False
            self._target_step = self._agree_on_target(step)
            if self._target_step is None:
                self._give_up(store)
                return False
            # The lost-work anchor: where this rank was when the world
            # agreed, and the step the save will capture. A crash
            # before that save commits loses target - last_committed
            # steps; a clean save zeroes the loss (the goodput engine
            # compares against the segment's last step-committed).
            self._post_ledger(step=step, target_step=self._target_step)
            logger.warning(
                "preemption agreed: world saves at step %d",
                self._target_step,
            )
        if step >= self._target_step:
            if self._peer_abandoned_after_grace(store):
                self._give_up(store)
                return False
            self._saved = True
            return True
        return False

    def _peer_abandoned_after_grace(self, store) -> bool:
        """Final symmetry check before triggering a save: a peer may have
        timed out of the rendezvous just as ours completed, and saving
        without it would be a lone save (permanent block inside the
        distributed take). The grace sleep outlasts the gap between a
        peer's deadline expiry and its abandoned-marker publish — cheap
        against the checkpoint we are about to write. Residual window: a
        peer whose marker *publish itself* stalls longer than the grace
        (store unreachable during the eviction) can still be missed;
        timeout-based agreement cannot close that without a third phase,
        and a store that broken would fail the save anyway. A raised
        store read is retried within a short window (one hiccup on one
        rank must not abort its save while peers proceed into the take
        and block on its absence); a *persistently* failing store is
        grounds to give up: that is exactly when "no abandon marker
        seen" must not be read as an all-clear for a possibly-lone
        save."""
        time.sleep(self.peer_grace)
        deadline = time.monotonic() + max(2.0, self.peer_grace)
        # The abandoned marker IS this loop's abort channel (there is no
        # round error key — preemption is not a fan-out round), and the
        # loop only spins on *store read failures*, bounded by the
        # deadline above.
        # snaplint: disable=wait-without-error-poll
        while True:
            try:
                return store.try_get(self._key("abandoned")) is not None
            except Exception as e:  # noqa: BLE001 - unhealthy store
                if time.monotonic() >= deadline:
                    logger.error(
                        "preemption symmetry check could not read the "
                        "store (%r); abandoning the coordinated save "
                        "rather than risk a lone take",
                        e,
                    )
                    return True
                logger.warning(
                    "preemption symmetry check read failed (%r); retrying",
                    e,
                )
                time.sleep(0.1)

    def pending_save(self) -> bool:
        """One-shot check for an agreed save the loop never reached.

        The agreed target can exceed the loop's final step (eviction
        landing while the leading rank runs its last steps). Every rank
        exits the loop unsaved in that case — call this after the loop
        and save at the final step if it returns True. Symmetric: a
        completed rendezvous means every rank holds the same target (a
        timed-out or abandoned rendezvous gives up on every rank), so
        either all ranks see True here or none do::

            for step in range(total):
                ...
                if saver.should_save(step):
                    mgr.save(step, app_state); break
            else:
                if saver.pending_save():
                    mgr.save(total - 1, app_state)
            saver.close()
        """
        if (
            self._saved
            or self._gave_up
            or (self._target_step is None and not self._flagged.is_set())
        ):
            return False
        if self._pg.store is None or self._pg.get_world_size() <= 1:
            self._saved = True
            return True
        if self._target_step is None:
            return False  # flagged but never agreed: peers may be done
        if self._peer_abandoned_after_grace(self._pg.store):
            self._give_up(self._pg.store)
            return False
        self._saved = True
        return True

    def _give_up(self, store) -> None:
        """Abandon the coordinated save — and tell peers, so a rank whose
        rendezvous would otherwise complete against this rank's stale
        step key cannot save alone (the asymmetric-deadlock case)."""
        self._gave_up = True
        self._post_ledger(gave_up=True)
        try:
            # Sticky per-session tombstone, same contract as done/:
            # peers must read it after this process is gone.
            # snaplint: disable=store-key-leak
            store.set(self._key("abandoned"), b"1")
        except Exception:  # noqa: BLE001 - already giving up
            logger.debug("preemption abandoned-marker publish failed")
        logger.error(
            "preemption rendezvous abandoned (timeout %.0fs or a peer "
            "finished training); coordinated save will not happen — "
            "periodic checkpoints are the fallback",
            self.rendezvous_timeout,
        )

    def _agree_on_target(self, step: int) -> Optional[int]:
        """Blocking max-step rendezvous; identical result on every rank,
        or None when it must be abandoned (timeout, a finished peer, or
        a peer that already abandoned)."""
        store = self._pg.store
        rank = self._pg.get_rank()
        world = self._pg.get_world_size()
        # The rendezvous happens at most once per session (the process
        # is being evicted); its keys are session-namespaced and must
        # survive until the last straggler reads them — there is no
        # safe point to delete (a late joiner re-reads every step key).
        # snaplint: disable=store-key-leak
        store.set(self._key(f"step/{rank}"), str(step).encode())
        joined = store.add(self._key("step_count"), 1)  # snaplint: disable=store-key-leak
        deadline = time.monotonic() + self.rendezvous_timeout
        # Steady wait costs ONE coordinator RPC per 50ms tick (the join
        # counter); per-rank step keys are read once, after the counter
        # says everyone published. done/abandoned are coarse conditions
        # (a finished or timed-out peer aborts the save either way):
        # checked ~1/s.
        next_abort_check = 0.0
        # abandoned/done ARE the abort channels here (checked ~1/s in
        # the loop body), and the fixed 50ms tick is the documented cost
        # model above — a pacer's backoff would slow the join counter,
        # the thing this loop exists to watch.
        # snaplint: disable=wait-without-error-poll
        while time.monotonic() < deadline:
            if time.monotonic() >= next_abort_check:
                next_abort_check = time.monotonic() + 1.0
                try:
                    if store.try_get(self._key("abandoned")) is not None:
                        logger.error(
                            "a peer abandoned the preemption rendezvous"
                        )
                        return None
                    for r in range(world):
                        if store.try_get(self._key(f"done/{r}")) is not None:
                            # A peer that finished training will never
                            # join; abandon now, not at the timeout.
                            logger.error(
                                "rank %d finished training before joining "
                                "the preemption rendezvous",
                                r,
                            )
                            return None
                except Exception as e:  # noqa: BLE001 - transient store hiccup
                    # Abort checks are best-effort; the deadline bounds a
                    # persistently failing store (rendezvous then gives
                    # up, which is the safe outcome).
                    logger.warning(
                        "preemption abort check failed (%r); retrying", e
                    )
            if joined < world:
                try:
                    joined = store.add(self._key("step_count"), 0)
                except Exception as e:  # noqa: BLE001 - transient store hiccup
                    logger.warning(
                        "preemption join-count poll failed (%r); retrying", e
                    )
            if joined >= world:
                try:
                    steps: List[Optional[bytes]] = [
                        store.try_get(self._key(f"step/{r}"))
                        for r in range(world)
                    ]
                except Exception as e:  # noqa: BLE001 - transient store hiccup
                    # Same best-effort treatment as the abort checks: the
                    # deadline bounds a persistently failing store.
                    logger.warning(
                        "preemption step-key read failed (%r); retrying", e
                    )
                    steps = []
                if steps and all(s is not None for s in steps):
                    return max(int(s.decode()) for s in steps) + 1
            time.sleep(0.05)
        return None
