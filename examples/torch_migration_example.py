"""Migration path for reference (PyTorch) users: checkpoint a torch
training loop with this framework, then read the same snapshot from JAX.

Phase 1 keeps the existing torch trainer and swaps only the
checkpointing layer (TorchStateful exposes tensors as numpy). Phase 2
reads those checkpoints from a pure-JAX process — the manifest records
plain dense arrays, so nothing torch-specific persists on disk.

    python examples/torch_migration_example.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import torchsnapshot_tpu as ts


def main() -> None:
    try:
        import torch
    except ImportError:
        print("torch not installed; this example needs the torch CPU wheel")
        return

    work_dir = tempfile.mkdtemp(prefix="ts_migration_")
    from torchsnapshot_tpu.tricks.torch import TorchStateful

    # ---- Phase 1: the torch trainer saves through this framework ----
    torch.manual_seed(0)
    model = torch.nn.Sequential(torch.nn.Linear(16, 32), torch.nn.Linear(32, 4))
    optim = torch.optim.Adam(model.parameters(), lr=1e-3)
    model(torch.randn(8, 16)).sum().backward()
    optim.step()

    path = os.path.join(work_dir, "step-100")
    ts.Snapshot.take(
        path,
        {
            "model": TorchStateful(model),
            "optim": TorchStateful(optim),
            "progress": ts.StateDict(step=100),
        },
    )
    print(f"torch trainer saved {path}")

    # Restoring into a fresh torch model works as in the reference.
    fresh = torch.nn.Sequential(torch.nn.Linear(16, 32), torch.nn.Linear(32, 4))
    ts.Snapshot(path).restore({"model": TorchStateful(fresh)})
    assert torch.equal(fresh[0].weight, model[0].weight)
    print("torch -> torch restore verified")

    # ---- Phase 2: the ported JAX trainer reads the same snapshot ----
    import jax.numpy as jnp

    w0 = ts.Snapshot(path).read_object("0/model/0.weight")
    jax_params = {"layer0": {"w": jnp.asarray(np.asarray(w0))}}
    np.testing.assert_array_equal(
        np.asarray(jax_params["layer0"]["w"]), model[0].weight.detach().numpy()
    )
    print("torch -> jax migration verified; step =",
          ts.Snapshot(path).read_object("0/progress/step"))


if __name__ == "__main__":
    main()
