"""Migration path for reference (PyTorch) users: checkpoint a torch
training loop with this framework, then read the same snapshot from JAX.

Phase 1 keeps the existing torch trainer and swaps only the
checkpointing layer (TorchStateful exposes tensors as numpy). Phase 2
reads those checkpoints from a pure-JAX process — the manifest records
plain dense arrays, so nothing torch-specific persists on disk.

    python examples/torch_migration_example.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import torchsnapshot_tpu as ts


def main() -> None:
    try:
        import torch
    except ImportError:
        print("torch not installed; this example needs the torch CPU wheel")
        return

    work_dir = tempfile.mkdtemp(prefix="ts_migration_")
    from torchsnapshot_tpu.tricks.torch import TorchStateful

    # ---- Phase 1: the torch trainer saves through this framework ----
    torch.manual_seed(0)
    model = torch.nn.Sequential(torch.nn.Linear(16, 32), torch.nn.Linear(32, 4))
    optim = torch.optim.Adam(model.parameters(), lr=1e-3)
    model(torch.randn(8, 16)).sum().backward()
    optim.step()

    path = os.path.join(work_dir, "step-100")
    ts.Snapshot.take(
        path,
        {
            "model": TorchStateful(model),
            "optim": TorchStateful(optim),
            "progress": ts.StateDict(step=100),
        },
    )
    print(f"torch trainer saved {path}")

    # Restoring into a fresh torch model works as in the reference.
    fresh = torch.nn.Sequential(torch.nn.Linear(16, 32), torch.nn.Linear(32, 4))
    ts.Snapshot(path).restore({"model": TorchStateful(fresh)})
    assert torch.equal(fresh[0].weight, model[0].weight)
    print("torch -> torch restore verified")

    # ---- Phase 2: the ported JAX trainer reads the same snapshot ----
    import jax.numpy as jnp

    w0 = ts.Snapshot(path).read_object("0/model/0.weight")
    jax_params = {"layer0": {"w": jnp.asarray(np.asarray(w0))}}
    np.testing.assert_array_equal(
        np.asarray(jax_params["layer0"]["w"]), model[0].weight.detach().numpy()
    )
    print("torch -> jax migration verified; step =",
          ts.Snapshot(path).read_object("0/progress/step"))

    # ---- Phase 0 (retroactive): EXISTING reference-format checkpoints ----
    # Checkpoints written by the reference library itself load directly
    # (tricks.torchsnapshot_reader) or convert once to the native format
    # (tricks.convert). Demonstrated here with a reference-format
    # snapshot produced by the export bridge, so the example is
    # self-contained; a real torchsnapshot-written directory reads the
    # same way.
    from torchsnapshot_tpu.tricks.convert import main as convert_main
    from torchsnapshot_tpu.tricks.torchsnapshot_reader import (
        read_reference_snapshot,
    )
    from torchsnapshot_tpu.tricks.torchsnapshot_writer import (
        write_reference_snapshot,
    )

    old_ckpt = os.path.join(work_dir, "reference_format")
    write_reference_snapshot(
        old_ckpt,
        {
            "model": {"w": model[0].weight.detach().numpy()},
            "progress": {"step": 100},
        },
    )
    old_state = read_reference_snapshot(old_ckpt)
    np.testing.assert_array_equal(
        old_state["model"]["w"], model[0].weight.detach().numpy()
    )
    native_ckpt = os.path.join(work_dir, "converted_native")
    assert convert_main([old_ckpt, native_ckpt, "--verify"]) == 0
    print("reference-format checkpoint read + converted to native format")

    # ---- Phase 3 (escape hatch): export back to the reference format ----
    # Anything exported this way restores through the actual reference
    # library (torchsnapshot.Snapshot(path).restore) — see
    # docs/migration.md.
    export = os.path.join(work_dir, "export_for_torch")
    write_reference_snapshot(export, {"model": {"w": jax_params["layer0"]["w"]}})
    print(f"jax state exported for torch tooling at {export}")


if __name__ == "__main__":
    main()
