"""Checkpoint-managed training loop: resume-if-exists, periodic saves,
retention.

The packaged version of the reference's hand-rolled loop
(examples/simple_example.py:59-76). Run it twice to see the resume:

    python examples/manager_example.py --work-dir /tmp/mgr_example
    python examples/manager_example.py --work-dir /tmp/mgr_example  # resumes
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import optax

import torchsnapshot_tpu as ts

TOTAL_STEPS = 10
SAVE_EVERY = 3


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--work-dir", default="/tmp/ts_manager_example")
    args = parser.parse_args()

    params = {"w": jnp.zeros((32, 32)), "b": jnp.zeros(32)}
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)
    app_state = {
        "params": ts.PyTreeState(params),
        "opt": ts.PyTreeState(opt_state),
        "progress": ts.StateDict(step=0),
        "rng": ts.RngState(jax.random.key(0)),
    }

    mgr = ts.CheckpointManager(args.work_dir, keep_last_n=2)
    resumed = mgr.restore_latest(app_state)
    start = app_state["progress"]["step"]
    print(
        f"resumed from step {resumed}" if resumed is not None else "fresh run",
        f"(starting at step {start})",
    )

    @jax.jit
    def train_step(params, opt_state, key):
        x = jax.random.normal(key, (16, 32))

        def loss_fn(p):
            return jnp.mean((x @ p["w"] + p["b"]) ** 2)

        grads = jax.grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    params = app_state["params"].tree
    opt_state = app_state["opt"].tree
    key = app_state["rng"].keys
    for step in range(start, TOTAL_STEPS):
        key, sub = jax.random.split(key)
        params, opt_state = train_step(params, opt_state, sub)
        if (step + 1) % SAVE_EVERY == 0 or step + 1 == TOTAL_STEPS:
            app_state["params"].tree = params
            app_state["opt"].tree = opt_state
            app_state["progress"]["step"] = step + 1
            app_state["rng"].keys = key
            pending = mgr.async_save(step + 1, app_state)
            pending.wait()
            print(f"step {step + 1}: saved (steps on disk: {mgr.all_steps()})")

    print(f"done at step {TOTAL_STEPS}; retained steps: {mgr.all_steps()}")


if __name__ == "__main__":
    main()
