"""Pipeline-parallel training step + per-stage checkpoint round-trip.

The schedule the GSPMD flagship model never exercises: stage-stacked
params sharded over a ``pp`` mesh axis run a GPipe schedule
(parallel/pipeline.py), train one step, checkpoint, and restore — then
restore AGAIN into a different pp degree (elastic stage resharding).

Run (CPU, 4 virtual devices):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python examples/pipeline_example.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# Honor JAX_PLATFORMS=cpu even when the environment pre-pins a platform
# (some dev setups pre-import jax with a platform set in jax.config).
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.parallel import (
    pipeline_stage_shardings,
    pipelined_apply,
    stack_stage_params,
)

D, N_STAGES, N_MICRO, BATCH = 32, 4, 4, 16


def stage_fn(params, x):
    return x + jnp.tanh(x @ params["w"] + params["b"])


def main() -> None:
    devices = jax.devices()[:N_STAGES]
    if len(devices) < N_STAGES:
        raise SystemExit(f"need {N_STAGES} devices, have {len(devices)}")
    mesh = Mesh(np.asarray(devices).reshape(N_STAGES), ("pp",))

    rng = np.random.default_rng(0)
    per_stage = [
        {
            "w": jnp.asarray(rng.standard_normal((D, D)) * 0.1, jnp.float32),
            "b": jnp.zeros((D,), jnp.float32),
        }
        for _ in range(N_STAGES)
    ]
    params = stack_stage_params(per_stage, mesh=mesh)
    x = jnp.asarray(rng.standard_normal((BATCH, D)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((BATCH, D)), jnp.float32)

    @jax.jit
    def train_step(params):
        def loss_fn(p):
            out = pipelined_apply(
                stage_fn, p, x, mesh=mesh, n_microbatches=N_MICRO
            )
            return jnp.mean((out - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return (
            jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, grads),
            loss,
        )

    params, loss = train_step(params)
    print(f"pipelined train step: loss={float(loss):.4f}")

    with tempfile.TemporaryDirectory() as tmp:
        ts.Snapshot.take(tmp, {"pp": ts.PyTreeState(params)})

        # Restore into the same pp degree.
        dest = jax.tree_util.tree_map(
            lambda l: jax.device_put(jnp.zeros_like(l), l.sharding), params
        )
        wrapped = ts.PyTreeState(dest)
        ts.Snapshot(tmp).restore({"pp": wrapped})
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            wrapped.tree,
            params,
        )
        print("restored per-stage state byte-identically")

        # Elastic: a 2-stage relaunch reads the same snapshot.
        mesh2 = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("pp",))
        dest2 = jax.tree_util.tree_map(
            lambda l, s: jax.device_put(jnp.zeros_like(l), s),
            params,
            pipeline_stage_shardings(params, mesh2),
        )
        wrapped2 = ts.PyTreeState(dest2)
        ts.Snapshot(tmp).restore({"pp": wrapped2})
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            wrapped2.tree,
            params,
        )
        print("elastic restore into pp=2: ok")


if __name__ == "__main__":
    main()
