"""Minimal end-to-end training loop with checkpoint/resume.

Reference parity: examples/simple_example.py:50-82 — app_state dict, restore
if a snapshot exists, train, take. Here the state is a pure JAX pytree:
params + optax optimizer state + progress counters + an explicit PRNG key.

Run:  python examples/simple_example.py /tmp/simple_snapshot
Kill it mid-run and re-run: it resumes from the last committed snapshot.
"""

import sys

import jax
import jax.numpy as jnp
import optax

import os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import torchsnapshot_tpu as ts

NUM_EPOCHS = 4
STEPS_PER_EPOCH = 8


def loss_fn(params, x, y):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def main(path: str) -> None:
    params = {
        "w": jnp.zeros((16, 1), jnp.float32),
        "b": jnp.zeros((1,), jnp.float32),
    }
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    app_state = {
        "params": ts.PyTreeState(params),
        "opt": ts.PyTreeState(opt_state),
        "progress": ts.StateDict(epoch=0),
        "rng": ts.RngState(jax.random.PRNGKey(0)),
    }

    try:
        snapshot = ts.Snapshot(path)
        snapshot.restore(app_state)
        print(f"resumed from epoch {app_state['progress']['epoch']}")
    except FileNotFoundError:
        print("no snapshot found; starting fresh")

    @jax.jit
    def step(params, opt_state, key):
        kx, ky = jax.random.split(key)
        x = jax.random.normal(kx, (32, 16))
        y = x @ jnp.arange(16.0).reshape(16, 1) + jax.random.normal(ky, (32, 1)) * 0.01
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    while app_state["progress"]["epoch"] < NUM_EPOCHS:
        params = app_state["params"].tree
        opt_state = app_state["opt"].tree
        key = app_state["rng"].keys
        for _ in range(STEPS_PER_EPOCH):
            key, sub = jax.random.split(key)
            params, opt_state, loss = step(params, opt_state, sub)
        app_state["params"].tree = params
        app_state["opt"].tree = opt_state
        app_state["rng"].keys = key
        app_state["progress"]["epoch"] += 1
        print(f"epoch {app_state['progress']['epoch']}: loss={float(loss):.5f}")
        ts.Snapshot.take(path, app_state)

    print("done")


if __name__ == "__main__":
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("path", nargs="?", default=None)
    p.add_argument("--work-dir", default="/tmp/simple_snapshot")
    args = p.parse_args()
    main(args.path or args.work_dir)
