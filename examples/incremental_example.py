"""Incremental checkpointing + async restore: a LoRA-style fine-tune.

A large frozen base plus small trainable adapters — the state shape where
incremental saves shine: every save after the first rewrites only the
adapter/optimizer chunks and *references* the frozen base (no
device→host transfer, no storage write for unchanged bytes). Resume uses
async restore so the reads stream in while the train step compiles.

    python examples/incremental_example.py --work-dir /tmp/ts_incr_example
    python examples/incremental_example.py --work-dir /tmp/ts_incr_example  # resumes
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import optax

import torchsnapshot_tpu as ts

TOTAL_STEPS = 9
SAVE_EVERY = 3


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--work-dir", default="/tmp/ts_incr_example")
    args = parser.parse_args()

    # Frozen base (never trained) + trainable low-rank adapters.
    key = jax.random.key(0)
    k_base, k_a, k_b = jax.random.split(key, 3)
    base = {"w": jax.random.normal(k_base, (512, 512), jnp.float32)}
    adapters = {
        "lora_a": jax.random.normal(k_a, (512, 8), jnp.float32) * 0.01,
        "lora_b": jnp.zeros((8, 512), jnp.float32),
    }
    tx = optax.adam(1e-3)
    opt_state = tx.init(adapters)

    app_state = {
        "base": ts.PyTreeState(base),
        "adapters": ts.PyTreeState(adapters),
        "opt": ts.PyTreeState(opt_state),
        "progress": ts.StateDict(step=0),
    }

    mgr = ts.CheckpointManager(
        args.work_dir, keep_last_n=2, incremental=True
    )

    @jax.jit
    def train_step(adapters, opt_state, base, x):
        def loss_fn(ad):
            h = x @ (base["w"] + ad["lora_a"] @ ad["lora_b"])
            return jnp.mean(h**2)

        loss, grads = jax.value_and_grad(loss_fn)(adapters)
        updates, opt_state = tx.update(grads, opt_state, adapters)
        return optax.apply_updates(adapters, updates), opt_state, loss

    # Async resume: restore reads stream in the background while the
    # train step compiles (on real states, minutes of overlap).
    out = mgr.async_restore_latest(app_state)
    x = jax.random.normal(jax.random.key(1), (16, 512), jnp.float32)
    compiled = train_step.lower(
        app_state["adapters"].tree, app_state["opt"].tree, base, x
    ).compile()
    if out is not None:
        step_resumed, pending = out
        pending.wait()
        print(f"resumed from step {step_resumed}")
    else:
        print("fresh run")

    adapters = app_state["adapters"].tree
    opt_state = app_state["opt"].tree
    base = app_state["base"].tree
    start = app_state["progress"]["step"]

    for step in range(start, TOTAL_STEPS):
        adapters, opt_state, loss = compiled(adapters, opt_state, base, x)
        print(f"step {step}: loss {float(loss):.5f}")
        if (step + 1) % SAVE_EVERY == 0:
            app_state["adapters"] = ts.PyTreeState(adapters)
            app_state["opt"] = ts.PyTreeState(opt_state)
            app_state["progress"]["step"] = step + 1
            t0 = time.perf_counter()
            mgr.save(step + 1, app_state)
            dt = time.perf_counter() - t0
            snap_dir = mgr.step_path(step + 1)
            nbytes = sum(
                os.path.getsize(os.path.join(d, f))
                for d, _, fs in os.walk(snap_dir)
                for f in fs
            )
            print(
                f"  saved step {step + 1} in {dt:.2f}s "
                f"({nbytes / 1e6:.2f} MB on disk — the frozen base is "
                f"referenced, not rewritten)"
            )

    print("done; steps on disk:", mgr.all_steps())


if __name__ == "__main__":
    main()
