"""Sharded-transformer checkpointing over a device mesh, with async take.

Reference parity: the role of examples/ddp_example.py + examples/torchrec
(replicated and sharded state) — TPU-native: one (dp, sp, tp) mesh, GSPMD
shardings, ``Snapshot.async_take`` so the loop resumes while storage I/O
drains.

Run (any host; uses all visible devices, or a virtual mesh):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/sharded_example.py /tmp/sharded_snapshot
"""

import sys
import time

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# Honor JAX_PLATFORMS=cpu even when the environment pre-pins a platform
# (jax reads the config, not the env, once imported).
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import torchsnapshot_tpu as ts
from torchsnapshot_tpu.models import (
    TransformerConfig,
    init_train_state,
    make_mesh,
    make_train_step,
)


def main(path: str) -> None:
    cfg = TransformerConfig(
        vocab_size=512, d_model=128, n_heads=8, n_layers=4, d_ff=256,
        n_experts=4,
    )
    mesh = make_mesh()
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")
    state = init_train_state(cfg, seed=0, mesh=mesh)
    step_fn = make_train_step(cfg, mesh=mesh)

    app_state = {"train": ts.PyTreeState(state.as_pytree())}
    try:
        ts.Snapshot(path).restore(app_state)
        from torchsnapshot_tpu.models.transformer import TrainState

        t = app_state["train"].tree
        state = TrainState(
            params=t["params"], opt_state=t["opt_state"],
            step=t["step"], rng=t["rng"],
        )
        print(f"resumed at step {int(state.step)}")
    except FileNotFoundError:
        print("starting fresh")

    rng = np.random.default_rng(0)
    for _ in range(3):
        tokens = jax.device_put(
            rng.integers(0, cfg.vocab_size, (8, 64)).astype(np.int32),
            NamedSharding(mesh, P("dp", None)),
        )
        state, loss = step_fn(state, tokens)
        print(f"step {int(state.step)}: loss={float(loss):.4f}")

    # Async take: control returns after staging; I/O drains in background.
    t0 = time.perf_counter()
    pending = ts.Snapshot.async_take(
        path, {"train": ts.PyTreeState(state.as_pytree())}
    )
    print(f"unblocked after {time.perf_counter() - t0:.3f}s (staging only)")
    # ... more training steps would run here, overlapped with I/O ...
    snapshot = pending.wait()
    print(f"committed: {snapshot.path}")


if __name__ == "__main__":
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("path", nargs="?", default=None)
    p.add_argument("--work-dir", default="/tmp/sharded_snapshot")
    args = p.parse_args()
    main(args.path or args.work_dir)
