"""Preemption-aware training: a SIGTERM mid-run becomes one consistent
checkpoint, and the next run resumes from it.

Single process (a timer delivers a real SIGTERM to this process):

    python examples/preemption_example.py --work-dir /tmp/ts_preempt_example
    python examples/preemption_example.py --work-dir /tmp/ts_preempt_example  # resumes

Two processes (the notice lands on rank 1 ONLY; the whole world still
saves the same step — the agreement docs/preemption.md describes):

    python examples/preemption_example.py --nproc 2
"""

import argparse
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

import torchsnapshot_tpu as ts  # noqa: E402

TOTAL_STEPS = 500


def train(pg, work_dir: str, evict_rank: int, evict_after_s: float):
    rank = getattr(pg, "rank", 0)
    mgr = ts.CheckpointManager(work_dir, pg=pg)
    saver = ts.PreemptionSaver(
        pg=pg, signals=(signal.SIGTERM,), poll_interval=0.1
    )
    if rank == evict_rank:
        # Stand-in for the cloud's eviction notice: a real SIGTERM to
        # this process, mid-training.
        threading.Timer(
            evict_after_s, lambda: os.kill(os.getpid(), signal.SIGTERM)
        ).start()

    state = {"w": jnp.zeros((128,)), "lr": 1e-3}
    app_state = lambda step: {  # noqa: E731
        "train": ts.PyTreeState(state),
        "progress": ts.StateDict(step=step),
    }
    start = mgr.restore_latest(
        {"train": ts.PyTreeState(state), "progress": ts.StateDict(step=-1)}
    )
    first = 0 if start is None else start + 1
    if rank == 0:
        print(f"starting at step {first}" + (" (resumed)" if start else ""))

    for step in range(first, TOTAL_STEPS):
        time.sleep(0.02)  # the "train step"
        state = {"w": state["w"] + 1.0, "lr": state["lr"]}
        if saver.should_save(step):
            mgr.save(step, app_state(step))
            if rank == 0:
                print(f"preemption save committed at step {step}; exiting")
            saver.close()
            return step
    else:
        if saver.pending_save():
            mgr.save(TOTAL_STEPS - 1, app_state(TOTAL_STEPS - 1))
    saver.close()
    if rank == 0:
        print("training finished without preemption")
    return None


def _worker(pg, work_dir: str):
    return train(pg, work_dir, evict_rank=1, evict_after_s=1.0)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--work-dir", default="/tmp/ts_preempt_example")
    p.add_argument("--nproc", type=int, default=1)
    args = p.parse_args()

    if args.nproc == 1:
        train(None, args.work_dir, evict_rank=0, evict_after_s=1.0)
        return
    from torchsnapshot_tpu.test_utils import run_multiprocess

    saved = run_multiprocess(_worker, args.nproc, args=(args.work_dir,))
    assert len(set(saved)) == 1, saved
    print(f"all {args.nproc} ranks saved the same step: {saved[0]}")


if __name__ == "__main__":
    main()
